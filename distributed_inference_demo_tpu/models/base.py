"""Core model abstractions: configs, KV cache, pipeline-stage parameter slices.

Design notes (TPU-first, not a port):

The reference splits an HF torch model into ``split_size`` sequential ONNX
"modules", one per device (reference ``server.py:831-832,893-905``).  Here a
model is a pure function over a parameter pytree whose per-layer weights are
*stacked* along a leading ``layer`` axis.  A pipeline stage ("module") is then
just ``jax.tree.map(lambda x: x[lo:hi], params.layers)`` — a zero-copy array
slice — and the per-stage forward is a single ``lax.scan`` over the stacked
layers, which XLA compiles into one fused loop that keeps the MXU busy.

The KV cache is first-class (the reference has none — SURVEY.md §2.7): a
preallocated head-major ``[layers, batch, kv_heads, max_seq, head_dim]`` pair
(see ``KVCache`` for why head-major) updated in place via
``lax.dynamic_update_slice`` with donated buffers, so decode steps are O(1)
in allocation and fully jit-compatible (static shapes).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp


@partial(jax.tree_util.register_dataclass,
         data_fields=[],
         meta_fields=["family", "vocab_size", "hidden_size", "num_layers",
                      "num_heads", "num_kv_heads", "intermediate_size",
                      "max_seq_len", "rope_theta", "norm_eps", "dtype_name",
                      "tie_embeddings", "use_alibi", "use_rope",
                      "attn_layernorm", "attn_qkv_bias", "num_experts",
                      "experts_per_token", "moe_capacity_factor",
                      "quantization", "head_dim_override", "embed_scale",
                      "mlp_act"])
@dataclass(frozen=True)
class ModelConfig:
    """Static, hashable architecture description shared by all model families.

    ``family`` selects the block flavor ("llama", "bloom", "mixtral", ...).
    The feature flags (rope/alibi/gated-mlp) let one decoder implementation
    cover the whole catalog the reference supports (bloom560m..7b1,
    reference ``data/Data.kt:19-33``) plus the BASELINE.json targets
    (TinyLlama, Llama-3-8B, Mixtral-8x7B).
    """

    family: str = "llama"
    vocab_size: int = 32000
    hidden_size: int = 2048
    num_layers: int = 22
    num_heads: int = 32
    num_kv_heads: int = 4
    intermediate_size: int = 5632
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype_name: str = "bfloat16"
    tie_embeddings: bool = False
    # bloom-style ALiBi positional bias vs llama-style RoPE
    use_alibi: bool = False
    use_rope: bool = True
    # bloom uses LayerNorm (with bias); llama uses RMSNorm
    attn_layernorm: bool = False
    # qwen2-style: q/k/v projections carry biases (RMSNorm model, so
    # independent of attn_layernorm, which implies ALL attention biases)
    attn_qkv_bias: bool = False
    # gemma: head_dim decoupled from hidden/heads (0 = derive), embedding
    # scaled by sqrt(hidden), and a non-silu gated-MLP activation
    head_dim_override: int = 0
    embed_scale: bool = False
    mlp_act: str = "silu"      # "silu" | "gelu_tanh" (gemma)
    # MoE (mixtral): 0 experts means dense MLP
    num_experts: int = 0
    experts_per_token: int = 2
    # expert-parallel dispatch capacity: slots per expert =
    # ceil(tokens * k / num_experts * factor); over-capacity tokens drop
    moe_capacity_factor: float = 2.0
    # weight-only quantization: "none" | "int8" | "int4" (ops/quant.py)
    quantization: str = "none"

    @property
    def dtype(self) -> jnp.dtype:
        return jnp.dtype(self.dtype_name)

    @property
    def head_dim(self) -> int:
        return self.head_dim_override or self.hidden_size // self.num_heads

    def replace(self, **kw) -> "ModelConfig":
        return dataclasses.replace(self, **kw)


@dataclass(frozen=True)
class StageSpec:
    """A contiguous layer range assigned to one pipeline stage/worker.

    Mirrors the role of a reference "module" (``server.py:893-905``): the
    first stage owns the embedding, the last owns the final norm + LM head.
    """

    stage_id: int
    num_stages: int
    layer_start: int
    layer_end: int  # exclusive

    @property
    def is_first(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last(self) -> bool:
        return self.stage_id == self.num_stages - 1

    @property
    def num_layers(self) -> int:
        return self.layer_end - self.layer_start


@partial(jax.tree_util.register_dataclass,
         data_fields=["keys", "values", "length"], meta_fields=[])
@dataclass
class KVCache:
    """Per-stage KV cache: stacked over the stage's layers.

    keys/values: ``[num_layers, batch, num_kv_heads, max_seq, head_dim]``
    — **head-major**, so each kv head's cache is a contiguous ``[seq, hd]``
    plane: the layout the Pallas flash kernel streams HBM→VMEM per head,
    and the one XLA tiles best (the trailing ``[seq, hd]`` dims map onto
    (sublane, lane) without a relayout).
    ``length`` is a scalar int32 tracking how many positions are filled.

    Capacity is NOT checked inside traced code (``dynamic_update_slice``
    clamps silently) — the engine layer enforces
    ``prompt_len + new_tokens <= max_seq`` host-side, where both are static.
    """

    keys: jax.Array
    values: jax.Array
    length: jax.Array

    @staticmethod
    def create(cfg: ModelConfig, num_layers: int, batch: int,
               max_seq: Optional[int] = None, dtype=None) -> "KVCache":
        # requested capacity is a lower bound: the buffer is padded to the
        # sublane granule HERE, at the one choke point, so no engine can
        # reintroduce the flash kernel's divisible-by-8 crash by forgetting
        # to pad (see pad_cache_capacity below)
        max_seq = pad_cache_capacity(max_seq or cfg.max_seq_len)
        dtype = dtype or cfg.dtype
        shape = (num_layers, batch, cfg.num_kv_heads, max_seq, cfg.head_dim)
        return KVCache(
            keys=jnp.zeros(shape, dtype),
            values=jnp.zeros(shape, dtype),
            length=jnp.zeros((), jnp.int32),
        )

    @property
    def max_seq(self) -> int:
        return self.keys.shape[3]


def pad_cache_capacity(n: int) -> int:
    """KV buffer capacity rounded up to the TPU sublane granule (8).

    The flash kernel streams [block_k, head_dim] K/V tiles whose sublane
    dimension must divide into the cache's sequence axis in multiples of 8
    (``ops/flash_attention.py:_pick_block``), so every engine allocates its
    cache a few slots larger than the user-facing ``max_seq`` bound when
    that bound isn't already aligned.  Purely a buffer-shape concern: the
    extra slots sit beyond every valid length and stay masked (the same
    stale-slot invariant that covers speculative rollback and batching
    admission), and the capacity CHECK (``check_capacity``) still enforces
    the caller's ``max_seq``."""
    return -(-n // 8) * 8


@partial(jax.tree_util.register_dataclass,
         data_fields=["layers", "embed", "final_norm", "lm_head"],
         meta_fields=[])
@dataclass
class StageParams:
    """Parameters owned by one pipeline stage.

    ``layers`` is a dict of stacked arrays with leading dim = stage layer
    count.  ``embed`` / ``final_norm`` / ``lm_head`` are present only on the
    stages that own them (first / last), else None.
    """

    layers: dict
    embed: Optional[dict] = None
    final_norm: Optional[dict] = None
    lm_head: Optional[dict] = None

    def nbytes(self) -> int:
        return sum(x.nbytes for x in jax.tree.leaves(
            (self.layers, self.embed, self.final_norm, self.lm_head)))


def slice_stage(full: StageParams, cfg: ModelConfig, spec: StageSpec) -> StageParams:
    """Cut a full-model StageParams into the slice owned by ``spec``.

    This is the TPU-native equivalent of the reference's per-module ONNX
    export + zip + ship (``server.py:910-957``): shard manifests instead of
    ONNX zips, realized as array slices.
    """
    layers = jax.tree.map(lambda x: x[spec.layer_start:spec.layer_end], full.layers)
    # Tied embeddings: the last stage needs the token table for the LM head.
    needs_embed = spec.is_first or (spec.is_last and cfg.tie_embeddings)
    return StageParams(
        layers=layers,
        embed=full.embed if needs_embed else None,
        final_norm=full.final_norm if spec.is_last else None,
        lm_head=full.lm_head if spec.is_last else None,
    )


def split_layer_ranges(num_layers: int, num_stages: int,
                       weights: Optional[list] = None) -> list:
    """Partition ``num_layers`` into ``num_stages`` contiguous ranges.

    With ``weights`` (per-layer cost, e.g. FLOPs from the cost model), uses a
    balanced greedy prefix split; otherwise an even split.  Returns a list of
    StageSpec.  Replaces the reference's round_robin_module_arrangement
    (``server.py:893-905``).
    """
    if num_stages > num_layers:
        raise ValueError(
            f"cannot split {num_layers} layers into {num_stages} stages")
    if weights is None:
        weights = [1.0] * num_layers
    if len(weights) != num_layers:
        raise ValueError("weights must have one entry per layer")

    # Dynamic programming over cut points minimizing the max stage cost
    # (the pipeline's throughput is set by its slowest stage).  O(S * L^2)
    # with L = model depth — trivial at planning time.
    prefix = [0.0]
    for w in weights:
        prefix.append(prefix[-1] + float(w))

    def cost(i, j):  # cost of layers [i, j)
        return prefix[j] - prefix[i]

    INF = float("inf")
    # best[s][j] = minimal max-stage-cost splitting layers [0, j) into s
    # stages of >= 1 layer each; cut[s][j] = the last cut position.
    best = [[INF] * (num_layers + 1) for _ in range(num_stages + 1)]
    cut = [[0] * (num_layers + 1) for _ in range(num_stages + 1)]
    best[0][0] = 0.0
    for s in range(1, num_stages + 1):
        for j in range(s, num_layers + 1):
            for i in range(s - 1, j):
                c = max(best[s - 1][i], cost(i, j))
                if c < best[s][j]:
                    best[s][j] = c
                    cut[s][j] = i
    bounds = [num_layers]
    j = num_layers
    for s in range(num_stages, 0, -1):
        j = cut[s][j]
        bounds.append(j)
    bounds.reverse()

    specs = []
    for s in range(num_stages):
        specs.append(StageSpec(stage_id=s, num_stages=num_stages,
                               layer_start=bounds[s], layer_end=bounds[s + 1]))
    assert all(sp.num_layers >= 1 for sp in specs)
    return specs
