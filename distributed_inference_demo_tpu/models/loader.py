"""Weight loading from HF safetensors checkpoints into StageParams pytrees.

TPU-native replacement for the reference's missing ``util.model_card``
ModelCard (load HF torch model -> split -> ONNX export -> int8 quantize ->
zip; SURVEY.md §2.2): here we map safetensors names directly onto the stacked
layer layout, optionally casting to bf16 or int8-per-channel, with no export
step — a stage's weights are an array slice of the full stack
(``base.slice_stage``).

Zero-egress environment: loading requires a *local* checkpoint directory.
Tests use random init instead.
"""

import json
import os
import re
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from .base import ModelConfig, StageParams
from .decoder import init_full_params


# safetensors name -> (our key, transpose?); attention/norm subset is shared
# by every rope-family mapper (llama dense MLP adds the mlp.* entries,
# mixtral swaps them for per-expert blocks).
_ATTN_NORM_MAP = {
    "input_layernorm.weight": ("attn_norm_w", False),
    "self_attn.q_proj.weight": ("wq", True),
    "self_attn.k_proj.weight": ("wk", True),
    "self_attn.v_proj.weight": ("wv", True),
    "self_attn.o_proj.weight": ("wo", True),
    "post_attention_layernorm.weight": ("mlp_norm_w", False),
}

_LLAMA_LAYER_MAP = {
    **_ATTN_NORM_MAP,
    "mlp.gate_proj.weight": ("w_gate", True),
    "mlp.up_proj.weight": ("w_up", True),
    "mlp.down_proj.weight": ("w_down", True),
}


def load_safetensors_dir(path: str, key_filter=None) -> Dict[str, np.ndarray]:
    """Read *.safetensors files in a checkpoint directory.  With a
    ``key_filter`` predicate only matching tensors are materialized
    (``safe_open`` lists keys lazily — a caller extracting one submodule
    from a large bundle never copies the rest into host RAM)."""
    from safetensors import safe_open
    tensors: Dict[str, np.ndarray] = {}
    files = sorted(f for f in os.listdir(path) if f.endswith(".safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors files under {path}")
    for fname in files:
        with safe_open(os.path.join(path, fname), framework="np") as f:
            for key in f.keys():
                if key_filter is None or key_filter(key):
                    tensors[key] = f.get_tensor(key)
    return tensors


def _get(raw: Dict[str, np.ndarray], name: str,
         prefixes=("model.", "transformer.", "")) -> np.ndarray:
    for prefix in prefixes:
        if prefix + name in raw:
            return np.asarray(raw[prefix + name])
    raise KeyError(name)


def llama_params_from_state_dict(raw: Dict[str, np.ndarray],
                                 cfg: ModelConfig) -> StageParams:
    """Map a llama-family HF state dict (``model.layers.{i}.*`` names) onto
    the stacked layout.  HF stores linears as [out, in]; ours are [in, out]
    einsum operands, hence the transposes.  Also serves qwen2 (identical
    names + ``self_attn.{q,k,v}_proj.bias`` under ``attn_qkv_bias``)."""
    dt = cfg.dtype
    layer_map = dict(_LLAMA_LAYER_MAP)
    if cfg.attn_qkv_bias:
        layer_map.update({
            "self_attn.q_proj.bias": ("bq", False),
            "self_attn.k_proj.bias": ("bk", False),
            "self_attn.v_proj.bias": ("bv", False)})
    layers: Dict[str, list] = {}
    for i in range(cfg.num_layers):
        for hf_name, (ours, transpose) in layer_map.items():
            w = _get(raw, f"layers.{i}.{hf_name}")
            if transpose:
                w = w.T
            layers.setdefault(ours, []).append(w)
    stacked = {k: jnp.asarray(np.stack(v), dt) for k, v in layers.items()}

    embed = {"tokens": jnp.asarray(_get(raw, "embed_tokens.weight"), dt)}
    final_norm = {"w": jnp.asarray(_get(raw, "norm.weight"), dt)}
    if cfg.tie_embeddings:
        lm_head = {}
    else:
        lm_head = {"w": jnp.asarray(_get(raw, "lm_head.weight", ("",)).T, dt)}
    return StageParams(layers=stacked, embed=embed, final_norm=final_norm,
                       lm_head=lm_head)


def bloom_params_from_state_dict(raw: Dict[str, np.ndarray],
                                 cfg: ModelConfig) -> StageParams:
    """Map a BloomForCausalLM state dict onto the stacked layout.

    The fused ``query_key_value`` weight is **per-head interleaved**:
    [nh, 3, hd, H] after reshape (q/k/v planes alternate within each head),
    not three contiguous blocks — the one genuinely tricky mapping in the
    family (reference ships pre-exported ONNX instead, SURVEY.md §2.2).
    """
    dt = cfg.dtype
    H, nh, hd = cfg.hidden_size, cfg.num_heads, cfg.head_dim
    layers: Dict[str, list] = {}

    def push(key, val):
        layers.setdefault(key, []).append(val)

    for i in range(cfg.num_layers):
        p = f"h.{i}."
        push("attn_norm_w", _get(raw, p + "input_layernorm.weight"))
        push("attn_norm_b", _get(raw, p + "input_layernorm.bias"))
        qkv_w = _get(raw, p + "self_attention.query_key_value.weight")
        qkv_b = _get(raw, p + "self_attention.query_key_value.bias")
        w = qkv_w.reshape(nh, 3, hd, H)
        b = qkv_b.reshape(nh, 3, hd)
        # [H, nh*hd] per projection (transpose of HF's [out, in])
        push("wq", w[:, 0].reshape(nh * hd, H).T)
        push("wk", w[:, 1].reshape(nh * hd, H).T)
        push("wv", w[:, 2].reshape(nh * hd, H).T)
        push("bq", b[:, 0].reshape(nh * hd))
        push("bk", b[:, 1].reshape(nh * hd))
        push("bv", b[:, 2].reshape(nh * hd))
        push("wo", _get(raw, p + "self_attention.dense.weight").T)
        push("bo", _get(raw, p + "self_attention.dense.bias"))
        push("mlp_norm_w", _get(raw, p + "post_attention_layernorm.weight"))
        push("mlp_norm_b", _get(raw, p + "post_attention_layernorm.bias"))
        push("w_up", _get(raw, p + "mlp.dense_h_to_4h.weight").T)
        push("b_up", _get(raw, p + "mlp.dense_h_to_4h.bias"))
        push("w_down", _get(raw, p + "mlp.dense_4h_to_h.weight").T)
        push("b_down", _get(raw, p + "mlp.dense_4h_to_h.bias"))
    stacked = {k: jnp.asarray(np.stack(v), dt) for k, v in layers.items()}

    embed = {
        "tokens": jnp.asarray(_get(raw, "word_embeddings.weight"), dt),
        "norm_w": jnp.asarray(
            _get(raw, "word_embeddings_layernorm.weight"), dt),
        "norm_b": jnp.asarray(
            _get(raw, "word_embeddings_layernorm.bias"), dt),
    }
    final_norm = {"w": jnp.asarray(_get(raw, "ln_f.weight"), dt),
                  "b": jnp.asarray(_get(raw, "ln_f.bias"), dt)}
    return StageParams(layers=stacked, embed=embed, final_norm=final_norm,
                       lm_head={})  # bloom ties the head to the embedding


def mixtral_params_from_state_dict(raw: Dict[str, np.ndarray],
                                   cfg: ModelConfig) -> StageParams:
    """Map a MixtralForCausalLM state dict onto the stacked layout.

    Per-expert linears (``block_sparse_moe.experts.{e}.w1/w2/w3``) stack into
    [L, E, in, out] blocks: w1 -> w_gate, w3 -> w_up, w2 -> w_down.
    """
    dt = cfg.dtype
    E = cfg.num_experts
    layers: Dict[str, list] = {}

    def push(key, val):
        layers.setdefault(key, []).append(val)

    for i in range(cfg.num_layers):
        p = f"layers.{i}."
        for hf_name, (ours, transpose) in _ATTN_NORM_MAP.items():
            w = _get(raw, p + hf_name)
            push(ours, w.T if transpose else w)
        push("router", _get(raw, p + "block_sparse_moe.gate.weight").T)
        push("w_gate", np.stack([
            _get(raw, p + f"block_sparse_moe.experts.{e}.w1.weight").T
            for e in range(E)]))
        push("w_up", np.stack([
            _get(raw, p + f"block_sparse_moe.experts.{e}.w3.weight").T
            for e in range(E)]))
        push("w_down", np.stack([
            _get(raw, p + f"block_sparse_moe.experts.{e}.w2.weight").T
            for e in range(E)]))
    stacked = {k: jnp.asarray(np.stack(v), dt) for k, v in layers.items()}

    embed = {"tokens": jnp.asarray(_get(raw, "embed_tokens.weight"), dt)}
    final_norm = {"w": jnp.asarray(_get(raw, "norm.weight"), dt)}
    lm_head = ({} if cfg.tie_embeddings else
               {"w": jnp.asarray(_get(raw, "lm_head.weight", ("",)).T, dt)})
    return StageParams(layers=stacked, embed=embed, final_norm=final_norm,
                       lm_head=lm_head)


def gemma_params_from_state_dict(raw: Dict[str, np.ndarray],
                                 cfg: ModelConfig) -> StageParams:
    """Gemma: llama names end to end, but every RMSNorm applies
    ``(1 + w)`` — fold the +1 into the stored weights HERE so the
    decoder keeps one rms_norm rule for all families (a random-init
    gemma's ones-init norms equal HF w=0, the checkpoint identity)."""
    p = llama_params_from_state_dict(raw, cfg)
    layers = dict(p.layers)
    # fold in FLOAT32 and keep the folded vectors f32: HF computes
    # (1 + w.float()) exactly, and a bf16 re-round of the sum would lose
    # mantissa bits on every norm weight (norm vectors are tiny — the
    # f32 residency costs nothing; rms_norm consumes any dtype)
    for key in ("attn_norm_w", "mlp_norm_w"):
        layers[key] = layers[key].astype(jnp.float32) + 1.0
    final_norm = dict(p.final_norm)
    final_norm["w"] = final_norm["w"].astype(jnp.float32) + 1.0
    return StageParams(layers=layers, embed=p.embed,
                       final_norm=final_norm, lm_head=p.lm_head)


_SD_MAPPERS = {
    "llama": llama_params_from_state_dict,
    "qwen2": llama_params_from_state_dict,   # same names + qkv biases
    "gemma": gemma_params_from_state_dict,
    "bloom": bloom_params_from_state_dict,
    "mixtral": mixtral_params_from_state_dict,
}


def params_from_state_dict(raw: Dict[str, np.ndarray],
                           cfg: ModelConfig) -> StageParams:
    """Family dispatch for HF-layout state dicts (numpy leaves)."""
    if cfg.family not in _SD_MAPPERS:
        raise NotImplementedError(f"no state-dict mapper for {cfg.family!r}")
    return _SD_MAPPERS[cfg.family](raw, cfg)


def load_llama_params(path: str, cfg: ModelConfig) -> StageParams:
    """Assemble a llama-family HF checkpoint into stacked StageParams."""
    return llama_params_from_state_dict(load_safetensors_dir(path), cfg)


def stage_params_to_bytes(params: StageParams) -> bytes:
    """Serialize a StageParams tree for the control plane's artifact channel
    (the reference ships per-device ONNX zips, ``server.py:910-957``; we
    ship weight blobs in the versioned wire codec + a JSON manifest).
    Layout: ``<u32 manifest_len><manifest JSON><wire tensor message>``."""
    import struct

    from ..comm import wire

    from ..ops.quant import QuantizedArray

    flat = {}
    for section in ("layers", "embed", "final_norm", "lm_head"):
        d = getattr(params, section)
        if d is None:
            continue
        for k, v in d.items():
            if isinstance(v, QuantizedArray):
                # ship weights pre-quantization; the receiving stage applies
                # its own config's quantization (ops/quant.maybe_quantize)
                raise TypeError(
                    f"{section}/{k} is quantized; serialize the float "
                    "params and quantize at the consumer")
            flat[f"{section}/{k}"] = np.asarray(v)
    names = sorted(flat)
    manifest = json.dumps({"names": names,
                           "present": {
                               s: getattr(params, s) is not None
                               for s in ("embed", "final_norm", "lm_head")}
                           }).encode("utf-8")
    blob = wire.serialize_tensors([flat[n] for n in names])
    return struct.pack("<I", len(manifest)) + manifest + blob


def stage_params_from_bytes(data: bytes) -> StageParams:
    """Inverse of :func:`stage_params_to_bytes`."""
    import struct

    from ..comm import wire

    (mlen,) = struct.unpack_from("<I", data, 0)
    manifest = json.loads(data[4:4 + mlen].decode("utf-8"))
    tensors = wire.deserialize_tensors(data[4 + mlen:]).tensors
    sections: Dict[str, dict] = {"layers": {}, "embed": {},
                                 "final_norm": {}, "lm_head": {}}
    for name, arr in zip(manifest["names"], tensors):
        sec, _, key = name.partition("/")
        sections[sec][key] = jnp.asarray(arr)
    present = manifest["present"]
    return StageParams(
        layers=sections["layers"],
        embed=sections["embed"] if present["embed"] else None,
        final_norm=sections["final_norm"] if present["final_norm"] else None,
        lm_head=sections["lm_head"] if present["lm_head"] else None)


def load_or_init(model_name: str, cfg: ModelConfig,
                 checkpoint_dir: Optional[str] = None,
                 seed: int = 0, quantize: bool = True) -> StageParams:
    """Load from a local checkpoint if given/found, else random-init.

    The random path keeps every test and benchmark runnable with zero
    network egress; the bench harness measures throughput, which is
    weight-value independent.  ``quantize=False`` returns the float tree
    even for ``-int8`` configs — used by the server app, whose artifact
    channel ships float weights and lets each stage quantize locally.
    """
    import jax
    if checkpoint_dir and os.path.isdir(checkpoint_dir):
        from ..checkpoint import _META
        if os.path.exists(os.path.join(checkpoint_dir, _META)):
            # our own orbax checkpoint format (checkpoint.save_params) —
            # already quantized as saved, so return directly.
            from ..checkpoint import load_params
            params, _ = load_params(checkpoint_dir, cfg,
                                    model_name=model_name)
            return params
        params = params_from_state_dict(load_safetensors_dir(checkpoint_dir),
                                        cfg)
    else:
        # random path: quantize during init (layer-chunked) so peak HBM
        # stays near the quantized footprint — an 8B -int8/-int4 config
        # must be initializable on exactly the chips its bf16 tree would
        # not fit.
        return init_full_params(
            jax.random.PRNGKey(seed), cfg,
            quantize=quantize and cfg.quantization in ("int8", "int4"))
    if not quantize:
        return params
    from ..ops.quant import maybe_quantize
    return maybe_quantize(params, cfg)


# ---------------------------------------------------------------------------
# vision tower (CLIP-ViT / LLaVA checkpoints)

def vision_params_from_clip_state_dict(raw: Dict[str, np.ndarray], vcfg,
                                       decoder_hidden: int,
                                       seed: int = 0) -> dict:
    """Map an HF CLIP vision tower (``vision_model.*`` names — standalone
    ``CLIPVisionModel`` exports and LLaVA bundles alike) onto the stacked
    ``models/vision.py`` layout.  Requires ``vcfg.clip_arch`` (the class
    token / pre-layernorm / projection-bias geometry those checkpoints
    ship).  The LLaVA ``multi_modal_projector`` weights are mapped when
    present; otherwise the projector stays seed-initialized (a plain CLIP
    export has no projector into the decoder's space).

    HF stores linears as [out, in]; ours are [in, out] matmul operands,
    hence the transposes.  The patch "conv" [H, C, p, p] flattens to our
    patchify order (row-in-patch, col-in-patch, channel) via
    ``transpose(2, 3, 1, 0)``.
    """
    import jax as _jax

    from .vision import init_vision_params

    if not vcfg.clip_arch:
        raise ValueError(
            "CLIP checkpoints need VisionConfig(clip_arch=True) — the "
            "plain tower has no class token / pre-layernorm to load into")

    def get(name):
        return _get(raw, name, prefixes=(
            "vision_model.",                         # CLIPVisionModel
            "vision_tower.vision_model.",            # LLaVA bundles
            "model.vision_tower.vision_model.", ""))

    dt = vcfg.dtype
    L = vcfg.num_layers
    layer_map = {
        "layer_norm1.weight": ("norm1_w", False),
        "layer_norm1.bias": ("norm1_b", False),
        "self_attn.q_proj.weight": ("wq", True),
        "self_attn.q_proj.bias": ("bq", False),
        "self_attn.k_proj.weight": ("wk", True),
        "self_attn.k_proj.bias": ("bk", False),
        "self_attn.v_proj.weight": ("wv", True),
        "self_attn.v_proj.bias": ("bv", False),
        "self_attn.out_proj.weight": ("wo", True),
        "self_attn.out_proj.bias": ("bo", False),
        "layer_norm2.weight": ("norm2_w", False),
        "layer_norm2.bias": ("norm2_b", False),
        "mlp.fc1.weight": ("w_up", True),
        "mlp.fc1.bias": ("b_up", False),
        "mlp.fc2.weight": ("w_down", True),
        "mlp.fc2.bias": ("b_down", False),
    }
    layers: Dict[str, list] = {}
    for i in range(L):
        for hf_name, (ours, transpose) in layer_map.items():
            w = get(f"encoder.layers.{i}.{hf_name}")
            layers.setdefault(ours, []).append(w.T if transpose else w)
    stacked = {k: jnp.asarray(np.stack(v), dt) for k, v in layers.items()}

    patch = get("embeddings.patch_embedding.weight")     # [H, C, p, p]
    p_ = vcfg.patch_size
    patch = patch.transpose(2, 3, 1, 0).reshape(
        p_ * p_ * vcfg.channels, vcfg.hidden_size)

    # projector seed-init as the fallback; checkpoint weights overwrite
    out = init_vision_params(_jax.random.PRNGKey(seed), vcfg,
                             decoder_hidden)
    out.update({
        "patch_embed": jnp.asarray(patch, dt),
        "pos_embed": jnp.asarray(
            get("embeddings.position_embedding.weight"), dt),
        "cls_embed": jnp.asarray(
            get("embeddings.class_embedding").reshape(-1), dt),
        "pre_norm_w": jnp.asarray(get("pre_layrnorm.weight"), dt),
        "pre_norm_b": jnp.asarray(get("pre_layrnorm.bias"), dt),
        "post_norm_w": jnp.asarray(get("post_layernorm.weight"), dt),
        "post_norm_b": jnp.asarray(get("post_layernorm.bias"), dt),
        "layers": stacked,
    })
    for hf_name, ours, transpose in (
            ("multi_modal_projector.linear_1.weight", "proj_w1", True),
            ("multi_modal_projector.linear_1.bias", "proj_b1", False),
            ("multi_modal_projector.linear_2.weight", "proj_w2", True),
            ("multi_modal_projector.linear_2.bias", "proj_b2", False)):
        for prefix in ("", "model."):
            if prefix + hf_name in raw:
                w = np.asarray(raw[prefix + hf_name])
                out[ours] = jnp.asarray(w.T if transpose else w, dt)
                break
    if out["pos_embed"].shape[0] != vcfg.num_positions:
        raise ValueError(
            f"checkpoint position table has {out['pos_embed'].shape[0]} "
            f"rows; VisionConfig expects {vcfg.num_positions} "
            f"(image {vcfg.image_size} / patch {vcfg.patch_size} + cls)")
    # a projector sized for a different decoder must fail HERE with the
    # shapes spelled out, not as an XLA dot error on the first request
    want1 = (vcfg.hidden_size, decoder_hidden)
    want2 = (decoder_hidden, decoder_hidden)
    if (out["proj_w1"].shape != want1 or out["proj_w2"].shape != want2):
        raise ValueError(
            f"checkpoint projector maps {out['proj_w1'].shape} -> "
            f"{out['proj_w2'].shape}; this tower/decoder pairing needs "
            f"{want1} -> {want2} (decoder hidden {decoder_hidden})")
    return out


_VISION_KEY_PREFIXES = ("vision_model.", "vision_tower.",
                        "model.vision_tower.", "multi_modal_projector.",
                        "model.multi_modal_projector.")


def load_vision_params(path: str, vcfg, decoder_hidden: int,
                       seed: int = 0) -> dict:
    """CLIP/LLaVA vision weights from a safetensors checkpoint dir.

    Only vision-tower / projector keys are materialized — pointing this
    at a full LLaVA bundle must not copy the language model's weights
    into host RAM just to extract the tower."""
    tensors = load_safetensors_dir(
        path, key_filter=lambda k: k.startswith(_VISION_KEY_PREFIXES))
    return vision_params_from_clip_state_dict(tensors, vcfg,
                                              decoder_hidden, seed=seed)
