"""Weight loading from HF safetensors checkpoints into StageParams pytrees.

TPU-native replacement for the reference's missing ``util.model_card``
ModelCard (load HF torch model -> split -> ONNX export -> int8 quantize ->
zip; SURVEY.md §2.2): here we map safetensors names directly onto the stacked
layer layout, optionally casting to bf16 or int8-per-channel, with no export
step — a stage's weights are an array slice of the full stack
(``base.slice_stage``).

Zero-egress environment: loading requires a *local* checkpoint directory.
Tests use random init instead.
"""

import json
import os
import re
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from .base import ModelConfig, StageParams
from .decoder import init_full_params


# safetensors name -> (our key, transpose?) per family
_LLAMA_LAYER_MAP = {
    "input_layernorm.weight": ("attn_norm_w", False),
    "self_attn.q_proj.weight": ("wq", True),
    "self_attn.k_proj.weight": ("wk", True),
    "self_attn.v_proj.weight": ("wv", True),
    "self_attn.o_proj.weight": ("wo", True),
    "post_attention_layernorm.weight": ("mlp_norm_w", False),
    "mlp.gate_proj.weight": ("w_gate", True),
    "mlp.up_proj.weight": ("w_up", True),
    "mlp.down_proj.weight": ("w_down", True),
}


def load_safetensors_dir(path: str) -> Dict[str, np.ndarray]:
    """Read all *.safetensors files in a checkpoint directory."""
    from safetensors import safe_open
    tensors: Dict[str, np.ndarray] = {}
    files = sorted(f for f in os.listdir(path) if f.endswith(".safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors files under {path}")
    for fname in files:
        with safe_open(os.path.join(path, fname), framework="np") as f:
            for key in f.keys():
                tensors[key] = f.get_tensor(key)
    return tensors


def load_llama_params(path: str, cfg: ModelConfig) -> StageParams:
    """Assemble a llama-family HF checkpoint into stacked StageParams."""
    raw = load_safetensors_dir(path)
    dt = cfg.dtype
    L = cfg.num_layers

    def get(name):
        for prefix in ("model.", ""):
            if prefix + name in raw:
                return raw[prefix + name]
        raise KeyError(name)

    layers: Dict[str, list] = {}
    for i in range(L):
        for hf_name, (ours, transpose) in _LLAMA_LAYER_MAP.items():
            w = get(f"layers.{i}.{hf_name}")
            if transpose:
                w = w.T
            layers.setdefault(ours, []).append(w)
    stacked = {k: jnp.asarray(np.stack(v), dt) for k, v in layers.items()}

    embed = {"tokens": jnp.asarray(get("embed_tokens.weight"), dt)}
    final_norm = {"w": jnp.asarray(get("norm.weight"), dt)}
    if cfg.tie_embeddings:
        lm_head = {}
    else:
        lm_head = {"w": jnp.asarray(raw["lm_head.weight"].T, dt)}
    return StageParams(layers=stacked, embed=embed, final_norm=final_norm,
                       lm_head=lm_head)


def load_or_init(model_name: str, cfg: ModelConfig,
                 checkpoint_dir: Optional[str] = None,
                 seed: int = 0) -> StageParams:
    """Load from a local checkpoint if given/found, else random-init.

    The random path keeps every test and benchmark runnable with zero
    network egress; the bench harness measures throughput, which is
    weight-value independent.
    """
    import jax
    if checkpoint_dir and os.path.isdir(checkpoint_dir):
        from ..checkpoint import _META
        if os.path.exists(os.path.join(checkpoint_dir, _META)):
            # our own orbax checkpoint format (checkpoint.save_params) —
            # already quantized as saved, so return directly.
            from ..checkpoint import load_params
            params, _ = load_params(checkpoint_dir, cfg,
                                    model_name=model_name)
            return params
        if cfg.family in ("llama",):
            params = load_llama_params(checkpoint_dir, cfg)
        else:
            raise NotImplementedError(
                f"checkpoint loading for family {cfg.family!r} lands with the "
                "model-card subsystem; use random init")
    else:
        params = init_full_params(jax.random.PRNGKey(seed), cfg)
    from ..ops.quant import maybe_quantize
    return maybe_quantize(params, cfg)
