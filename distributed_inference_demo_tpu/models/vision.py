"""Vision encoder + projector: the LLaVA-style multimodal stage 0.

BASELINE.json config #5: "LLaVA-1.5 multimodal: vision encoder on an edge
client, LLM decoder shard on TPU".  The reference's closest concept is
heterogeneous per-device module placement (``server.py:831-832`` — a
ModelCard splitting arbitrary HF models into per-device modules); it ships
no vision path, so this is a from-scratch TPU-first design:

- ViT encoder as pure functions over stacked-layer weights (same design as
  ``models/decoder.py``): patchify = reshape + one [p*p*c, H] matmul (an
  MXU-shaped "conv"), learned position embeddings, pre-norm bidirectional
  attention blocks in a single ``lax.scan``, GELU MLP.
- A 2-layer projector mapping vision hidden size to the decoder's hidden
  size (LLaVA-1.5's mlp2x_gelu projector shape).

``vision_forward`` emits ``[batch, num_patches, decoder_hidden]`` ready to
be concatenated with token embeddings and fed into any decoder stage as a
pre-embedded prefix (``decoder.stage_forward`` accepts float inputs on the
first stage).
"""

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from ..ops.norms import layer_norm
from .decoder import _dense_init


@partial(jax.tree_util.register_dataclass, data_fields=[],
         meta_fields=["image_size", "patch_size", "channels", "hidden_size",
                      "num_layers", "num_heads", "intermediate_size",
                      "norm_eps", "dtype_name", "clip_arch",
                      "feature_layer", "hidden_act"])
@dataclass(frozen=True)
class VisionConfig:
    """ViT architecture description (defaults ≈ a small CLIP-style tower;
    llava-1.5 scale would be image 336 / patch 14 / hidden 1024 / 24
    layers).

    ``clip_arch``: the CLIP-ViT-faithful variant — a learned class token
    prepended to the patch sequence (position embeddings gain one row),
    a layernorm over the embeddings before the encoder (CLIP's
    ``pre_layrnorm``), and q/k/v/out projection biases.  This is the
    geometry HF CLIP checkpoints ship, so weights load without
    reinterpretation (``loader.vision_params_from_clip_state_dict``).

    ``feature_layer``: which encoder output feeds the projector.  -1 =
    all layers + the final layernorm (the plain tower).  -2 = LLaVA-1.5
    feature select: stop one encoder layer EARLY, no final layernorm,
    and (under ``clip_arch``) drop the class token from the features —
    the projector still sees ``num_patches`` positions either way."""

    image_size: int = 64
    patch_size: int = 16
    channels: int = 3
    hidden_size: int = 128
    num_layers: int = 4
    num_heads: int = 4
    intermediate_size: int = 512
    norm_eps: float = 1e-5
    dtype_name: str = "float32"
    clip_arch: bool = False
    feature_layer: int = -1
    hidden_act: str = "gelu"   # "gelu" | "quick_gelu" (original CLIP)

    def __post_init__(self):
        if self.hidden_act not in ("gelu", "quick_gelu"):
            raise ValueError(f"unknown hidden_act {self.hidden_act!r}")
        if self.feature_layer not in (-1, -2):
            raise ValueError("feature_layer must be -1 (full tower) or "
                             "-2 (LLaVA-1.5 feature select)")
        if self.feature_layer == -2 and self.num_layers < 2:
            raise ValueError("feature_layer=-2 needs >= 2 encoder layers")

    @property
    def dtype(self):
        return jnp.dtype(self.dtype_name)

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def num_positions(self) -> int:
        """Rows of the position-embedding table (the class token adds
        one under ``clip_arch``)."""
        return self.num_patches + (1 if self.clip_arch else 0)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def init_vision_params(rng: jax.Array, vcfg: VisionConfig,
                       decoder_hidden: int) -> dict:
    """Stacked-layer ViT weights + the LLaVA mlp2x projector into the
    decoder's embedding space."""
    H, I, L = vcfg.hidden_size, vcfg.intermediate_size, vcfg.num_layers
    p, c = vcfg.patch_size, vcfg.channels
    dt = vcfg.dtype
    ks = jax.random.split(rng, 12)
    layers = {
        "norm1_w": jnp.ones((L, H), dt), "norm1_b": jnp.zeros((L, H), dt),
        "wq": _dense_init(ks[0], (L, H, H), dt),
        "wk": _dense_init(ks[1], (L, H, H), dt),
        "wv": _dense_init(ks[2], (L, H, H), dt),
        "wo": _dense_init(ks[3], (L, H, H), dt),
        # projection biases: zeros in the plain tower (a no-op there),
        # loaded from the checkpoint under clip_arch
        "bq": jnp.zeros((L, H), dt), "bk": jnp.zeros((L, H), dt),
        "bv": jnp.zeros((L, H), dt), "bo": jnp.zeros((L, H), dt),
        "norm2_w": jnp.ones((L, H), dt), "norm2_b": jnp.zeros((L, H), dt),
        "w_up": _dense_init(ks[4], (L, H, I), dt),
        "b_up": jnp.zeros((L, I), dt),
        "w_down": _dense_init(ks[5], (L, I, H), dt),
        "b_down": jnp.zeros((L, H), dt),
    }
    out = {
        "patch_embed": _dense_init(ks[6], (p * p * c, H), dt),
        "pos_embed": _dense_init(ks[7], (vcfg.num_positions, H), dt,
                                 scale=0.02),
        "layers": layers,
        "post_norm_w": jnp.ones((H,), dt),
        "post_norm_b": jnp.zeros((H,), dt),
        # LLaVA-1.5 projector: Linear -> GELU -> Linear into decoder space
        "proj_w1": _dense_init(ks[8], (H, decoder_hidden), dt),
        "proj_b1": jnp.zeros((decoder_hidden,), dt),
        "proj_w2": _dense_init(ks[9], (decoder_hidden, decoder_hidden), dt),
        "proj_b2": jnp.zeros((decoder_hidden,), dt),
    }
    if vcfg.clip_arch:
        out["cls_embed"] = _dense_init(ks[10], (H,), dt, scale=0.02)
        out["pre_norm_w"] = jnp.ones((H,), dt)
        out["pre_norm_b"] = jnp.zeros((H,), dt)
    return out


def _patchify(images: jnp.ndarray, vcfg: VisionConfig) -> jnp.ndarray:
    """[b, H, W, C] -> [b, num_patches, p*p*C] (row-major patch order)."""
    b = images.shape[0]
    p = vcfg.patch_size
    n = vcfg.image_size // p
    x = images.reshape(b, n, p, n, p, vcfg.channels)
    x = x.transpose(0, 1, 3, 2, 4, 5)          # [b, n, n, p, p, c]
    return x.reshape(b, n * n, p * p * vcfg.channels)


def _encoder_layer(vcfg: VisionConfig, lp: dict, x: jnp.ndarray):
    b, s, H = x.shape
    nh, hd = vcfg.num_heads, vcfg.head_dim
    h = layer_norm(x, lp["norm1_w"], lp["norm1_b"], vcfg.norm_eps)
    q = (h @ lp["wq"] + lp["bq"]).reshape(b, s, nh, hd)
    k = (h @ lp["wk"] + lp["bk"]).reshape(b, s, nh, hd)
    v = (h @ lp["wv"] + lp["bv"]).reshape(b, s, nh, hd)
    # bidirectional attention: no mask, f32 softmax
    s_ = jnp.einsum("bqnd,bknd->bnqk", q, k).astype(jnp.float32)
    s_ = s_ / jnp.sqrt(jnp.asarray(hd, jnp.float32))
    a = jax.nn.softmax(s_, axis=-1).astype(x.dtype)
    o = jnp.einsum("bnqk,bknd->bqnd", a, v).reshape(b, s, nh * hd)
    x = x + o @ lp["wo"] + lp["bo"]
    h = layer_norm(x, lp["norm2_w"], lp["norm2_b"], vcfg.norm_eps)
    h = (h @ lp["w_up"] + lp["b_up"]).astype(jnp.float32)
    # original CLIP towers ship quick_gelu (x * sigmoid(1.702 x)); exact
    # gelu everywhere else
    h = (h * jax.nn.sigmoid(1.702 * h) if vcfg.hidden_act == "quick_gelu"
         else jax.nn.gelu(h))
    return x + (h.astype(x.dtype) @ lp["w_down"] + lp["b_down"]), None


def vision_forward(params: dict, vcfg: VisionConfig,
                   images: jnp.ndarray) -> jnp.ndarray:
    """ViT + projector: [b, H, W, C] images -> [b, num_patches, decoder_H]
    hidden states ready for the decoder's pre-embedded input path.

    Under ``clip_arch`` the class token is prepended before the encoder
    and dropped from the features (LLaVA's "default" select strategy),
    so the output sequence length is ``num_patches`` regardless of
    architecture.  ``feature_layer=-2`` skips the LAST encoder layer and
    the final layernorm entirely (LLaVA-1.5 reads the penultimate
    hidden state — HF ``hidden_states[-2]``)."""
    x = _patchify(images.astype(vcfg.dtype), vcfg)
    x = x @ params["patch_embed"]
    if vcfg.clip_arch:
        cls = jnp.broadcast_to(params["cls_embed"],
                               (x.shape[0], 1, vcfg.hidden_size))
        x = jnp.concatenate([cls.astype(x.dtype), x], axis=1)
    x = x + params["pos_embed"][None]
    if vcfg.clip_arch:
        x = layer_norm(x, params["pre_norm_w"], params["pre_norm_b"],
                       vcfg.norm_eps)

    def body(x, lp):
        return _encoder_layer(vcfg, lp, x)

    layers = params["layers"]
    if vcfg.feature_layer == -2:
        # run all but the last encoder layer; its weights stay loaded
        # (checkpoint-faithful) but never execute
        layers = jax.tree.map(lambda a: a[:-1], layers)
    x, _ = jax.lax.scan(body, x, layers)
    if vcfg.feature_layer == -1:
        x = layer_norm(x, params["post_norm_w"], params["post_norm_b"],
                       vcfg.norm_eps)
    if vcfg.clip_arch:
        x = x[:, 1:]                   # drop the class token's feature
    h = jax.nn.gelu((x @ params["proj_w1"] + params["proj_b1"]
                     ).astype(jnp.float32)).astype(x.dtype)
    return h @ params["proj_w2"] + params["proj_b2"]
