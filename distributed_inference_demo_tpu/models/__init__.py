from .base import ModelConfig, KVCache, StageParams, StageSpec
from .registry import MODEL_REGISTRY, get_model_config, get_model_family

__all__ = [
    "ModelConfig",
    "KVCache",
    "StageParams",
    "StageSpec",
    "MODEL_REGISTRY",
    "get_model_config",
    "get_model_family",
]
