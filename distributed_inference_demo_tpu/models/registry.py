"""Model catalog: every family the reference supports plus BASELINE targets.

Replaces the reference's hardcoded catalog (``data/Data.kt:19-33``:
bloom560m/1b1/1b7/3b/7b each +- int8) and the per-model branches in
``server.py:796-801`` / ``init_server.py:131-136``.  Quantized variants are a
runtime dtype choice here (``-int8`` suffix), not separate exports.

Also provides tiny "-test" configs for fast unit tests and virtual-mesh
dry runs.
"""

from .base import ModelConfig


def _bloom(hidden, layers, heads, vocab=250880) -> ModelConfig:
    return ModelConfig(
        family="bloom", vocab_size=vocab, hidden_size=hidden, num_layers=layers,
        num_heads=heads, num_kv_heads=heads, intermediate_size=4 * hidden,
        max_seq_len=2048, use_alibi=True, use_rope=False, attn_layernorm=True,
        tie_embeddings=True, norm_eps=1e-5)


MODEL_REGISTRY = {
    # --- bloom family (reference parity: data/Data.kt:19-33) ---
    "bloom560m": _bloom(1024, 24, 16),
    "bloom1b1": _bloom(1536, 24, 16),
    "bloom1b7": _bloom(2048, 24, 16),
    "bloom3b": _bloom(2560, 30, 32),
    "bloom7b1": _bloom(4096, 30, 32),
    # --- llama family (BASELINE.json configs 1-3) ---
    "tinyllama-1.1b": ModelConfig(
        family="llama", vocab_size=32000, hidden_size=2048, num_layers=22,
        num_heads=32, num_kv_heads=4, intermediate_size=5632,
        max_seq_len=2048, rope_theta=10000.0),
    "llama-3-8b": ModelConfig(
        family="llama", vocab_size=128256, hidden_size=4096, num_layers=32,
        num_heads=32, num_kv_heads=8, intermediate_size=14336,
        max_seq_len=8192, rope_theta=500000.0),
    # --- qwen2 family (llama block + qkv biases; beyond-reference
    # breadth: the catalog pattern extends to new HF families without a
    # new decoder) ---
    "qwen2.5-7b": ModelConfig(
        family="qwen2", vocab_size=152064, hidden_size=3584, num_layers=28,
        num_heads=28, num_kv_heads=4, intermediate_size=18944,
        max_seq_len=32768, rope_theta=1000000.0, norm_eps=1e-6,
        attn_qkv_bias=True),
    "qwen2.5-0.5b": ModelConfig(
        family="qwen2", vocab_size=151936, hidden_size=896, num_layers=24,
        num_heads=14, num_kv_heads=2, intermediate_size=4864,
        max_seq_len=32768, rope_theta=1000000.0, norm_eps=1e-6,
        attn_qkv_bias=True, tie_embeddings=True),
    # --- gemma family (RMSNorm(1+w) folded at load, sqrt(H) embedding
    # scale, GeGLU, decoupled head_dim; gemma-2b is MQA) ---
    "gemma-7b": ModelConfig(
        family="gemma", vocab_size=256000, hidden_size=3072, num_layers=28,
        num_heads=16, num_kv_heads=16, intermediate_size=24576,
        max_seq_len=8192, rope_theta=10000.0, norm_eps=1e-6,
        tie_embeddings=True, head_dim_override=256, embed_scale=True,
        mlp_act="gelu_tanh"),
    "gemma-2b": ModelConfig(
        family="gemma", vocab_size=256000, hidden_size=2048, num_layers=18,
        num_heads=8, num_kv_heads=1, intermediate_size=16384,
        max_seq_len=8192, rope_theta=10000.0, norm_eps=1e-6,
        tie_embeddings=True, head_dim_override=256, embed_scale=True,
        mlp_act="gelu_tanh"),
    # --- mixtral MoE (BASELINE.json config 4) ---
    "mixtral-8x7b": ModelConfig(
        family="mixtral", vocab_size=32000, hidden_size=4096, num_layers=32,
        num_heads=32, num_kv_heads=8, intermediate_size=14336,
        max_seq_len=8192, rope_theta=1000000.0, num_experts=8,
        experts_per_token=2),
    # --- chip-fitting MoE bench pair (BASELINE.json config 4 at a scale
    # a single 16 GB chip holds: ~0.8 B params bf16).  The -dense twin
    # has the SAME active FLOPs per token (top-2 of 8 experts = 2x the
    # expert intermediate, dense I = 2 x 3584) so moe-vs-dense decode
    # tok/s isolates the routing/dispatch cost. ---
    "mixtral-tpu-1b": ModelConfig(
        family="mixtral", vocab_size=32000, hidden_size=1024, num_layers=8,
        num_heads=16, num_kv_heads=4, intermediate_size=3584,
        max_seq_len=2048, rope_theta=1000000.0, num_experts=8,
        experts_per_token=2),
    "mixtral-tpu-1b-dense": ModelConfig(
        family="llama", vocab_size=32000, hidden_size=1024, num_layers=8,
        num_heads=16, num_kv_heads=4, intermediate_size=7168,
        max_seq_len=2048, rope_theta=1000000.0),
    # --- tiny configs for tests and virtual-mesh dry runs ---
    "llama-test": ModelConfig(
        family="llama", vocab_size=256, hidden_size=64, num_layers=4,
        num_heads=4, num_kv_heads=2, intermediate_size=128, max_seq_len=128,
        dtype_name="float32"),
    "qwen2-test": ModelConfig(
        family="qwen2", vocab_size=256, hidden_size=64, num_layers=4,
        num_heads=4, num_kv_heads=2, intermediate_size=128, max_seq_len=128,
        attn_qkv_bias=True, dtype_name="float32"),
    "gemma-test": ModelConfig(
        family="gemma", vocab_size=256, hidden_size=64, num_layers=4,
        num_heads=4, num_kv_heads=1, intermediate_size=128, max_seq_len=128,
        tie_embeddings=True, head_dim_override=32, embed_scale=True,
        mlp_act="gelu_tanh", norm_eps=1e-6, dtype_name="float32"),
    "bloom-test": ModelConfig(
        family="bloom", vocab_size=256, hidden_size=64, num_layers=4,
        num_heads=4, num_kv_heads=4, intermediate_size=256, max_seq_len=128,
        use_alibi=True, use_rope=False, attn_layernorm=True,
        tie_embeddings=True, dtype_name="float32"),
    "mixtral-test": ModelConfig(
        family="mixtral", vocab_size=256, hidden_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, intermediate_size=128, max_seq_len=128,
        num_experts=4, experts_per_token=2, dtype_name="float32"),
}


def get_model_config(name: str) -> ModelConfig:
    """Resolve a model name; an ``-int8`` / ``-int4`` suffix selects
    weight-only quantization (the reference's quantized exports,
    ``data/Data.kt:19-33``, as a runtime transform — ops/quant.py; int4
    is group-wise and packs two weights per byte)."""
    base = name
    quant = "none"
    for suffix in ("-int8", "-int4"):
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            quant = suffix[1:]
            break
    if base not in MODEL_REGISTRY:
        raise KeyError(f"unknown model {name!r}; known: {sorted(MODEL_REGISTRY)}")
    cfg = MODEL_REGISTRY[base]
    if quant != "none":
        cfg = cfg.replace(quantization=quant)
    return cfg


def get_model_family(name: str) -> str:
    return get_model_config(name).family
