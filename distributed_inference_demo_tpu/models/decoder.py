"""One unified decoder implementation for every supported model family.

Instead of per-family ONNX exports (reference ``util.model_card.ModelCard``,
inferred at SURVEY.md §2.2), a single pure ``stage_forward`` covers:

- **llama family** (TinyLlama-1.1B, Llama-3-8B): RMSNorm, RoPE, GQA, SwiGLU.
- **bloom family** (bloom560m..7b1, reference ``data/Data.kt:19-33``):
  LayerNorm+bias, ALiBi, fused dense MLP with GELU.
- **mixtral family** (Mixtral-8x7B): llama blocks with top-k routed MoE MLP.

The per-stage forward is a single ``lax.scan`` over stacked layer weights —
XLA compiles one loop body reused across layers, keeping compile time flat in
depth and the MXU saturated.  The KV cache threads through the scan as
per-layer xs/ys so each layer updates its slice functionally.
"""

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from .._jax_compat import axis_size

from ..ops.attention import alibi_slopes, attention, update_kv_cache
from ..ops.quant import dense
from ..ops.norms import layer_norm, rms_norm
from ..ops.rope import apply_rope
from .base import KVCache, ModelConfig, StageParams, StageSpec


# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("shape", "dtype"))
def _dense_init_jit(rng, scale, shape, dtype):
    # f32 sampling + scale + convert fuse into one XLA kernel under jit:
    # only the target-dtype output is ever materialized in HBM.
    return (jax.random.normal(rng, shape, jnp.float32) * scale).astype(dtype)


def _dense_init(rng, shape, dtype, scale=None):
    fan_in = shape[-2] if len(shape) >= 2 else shape[-1]
    scale = scale if scale is not None else float(fan_in) ** -0.5
    return _dense_init_jit(rng, jnp.float32(scale), tuple(shape),
                           jnp.dtype(dtype))


@partial(jax.jit, static_argnames=("shape", "dtype", "mode"))
def _init_quantized_layer(rng, scale, shape, dtype, mode="int8"):
    from ..ops.quant import quantize_array, quantize_array4
    w = _dense_init_jit(rng, scale, shape, dtype)
    if mode == "int4":
        qa = quantize_array4(w)
        return qa.q, qa.scale
    qa = quantize_array(w, stacked=False)
    return qa.q, qa.scale


def _init_quantized(rng, shape, dtype, scale=None, mode="int8"):
    """Init + quantize (int8 or int4) one layer slice at a time.

    Peak HBM stays at the accumulating quantized footprint plus ONE
    layer's float transient — never the full tensor at float width.
    This is what lets an int8 Llama-3-8B be random-initialized on a
    16 GB chip whose bf16 variant would not fit (the reference ships
    pre-quantized exports instead, ``data/Data.kt:19-33``); int4 halves
    the footprint again.
    """
    from ..ops.quant import QuantizedArray, QuantizedArray4
    L = shape[0]
    fan_in = shape[-2] if len(shape) >= 2 else shape[-1]
    scale = jnp.float32(scale if scale is not None else float(fan_in) ** -0.5)
    keys = jax.random.split(rng, L)
    qs, scales = [], []
    for i in range(L):
        q, s = _init_quantized_layer(keys[i], scale, tuple(shape[1:]),
                                     jnp.dtype(dtype), mode)
        qs.append(q)
        scales.append(s)
    if mode == "int4":
        from ..ops.quant import int4_group_for
        return QuantizedArray4(q=jnp.stack(qs), scale=jnp.stack(scales),
                               group=int4_group_for(shape[-2]))
    return QuantizedArray(q=jnp.stack(qs), scale=jnp.stack(scales))


def init_layer_params(rng: jax.Array, cfg: ModelConfig, num_layers: int,
                      quantize=False) -> dict:
    """Stacked per-layer weights, leading dim = num_layers.

    With ``quantize`` (True = "int8", or an explicit "int8"/"int4"
    mode), each big matmul operand is generated and quantized
    layer-by-layer (``_init_quantized``), so peak memory stays near the
    quantized footprint instead of materializing the whole tensor at
    the float dtype first — this is what lets an int8 8B model be
    random-initialized on a chip the bf16 variant would not fit on
    (int4 halves it again).
    """
    H, nh, nkv, hd = cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    I, L = cfg.intermediate_size, num_layers
    dt = cfg.dtype

    mode = "int8" if quantize is True else quantize
    big = (partial(_init_quantized, mode=mode) if mode else _dense_init)

    keys = jax.random.split(rng, 16)
    p = {
        "attn_norm_w": jnp.ones((L, H), dt),
        "wq": big(keys[0], (L, H, nh * hd), dt),
        "wk": big(keys[1], (L, H, nkv * hd), dt),
        "wv": big(keys[2], (L, H, nkv * hd), dt),
        "wo": big(keys[3], (L, nh * hd, H), dt),
        "mlp_norm_w": jnp.ones((L, H), dt),
    }
    if cfg.attn_layernorm:  # bloom: LayerNorm has bias; linears have bias
        p["attn_norm_b"] = jnp.zeros((L, H), dt)
        p["mlp_norm_b"] = jnp.zeros((L, H), dt)
        p["bo"] = jnp.zeros((L, H), dt)
    if cfg.attn_layernorm or cfg.attn_qkv_bias:  # + qwen2: qkv-only bias
        p["bq"] = jnp.zeros((L, nh * hd), dt)
        p["bk"] = jnp.zeros((L, nkv * hd), dt)
        p["bv"] = jnp.zeros((L, nkv * hd), dt)
    if cfg.num_experts > 0:  # mixtral MoE
        E = cfg.num_experts
        p["router"] = _dense_init(keys[4], (L, H, E), dt)
        p["w_gate"] = big(keys[5], (L, E, H, I), dt)
        p["w_up"] = big(keys[6], (L, E, H, I), dt)
        p["w_down"] = big(keys[7], (L, E, I, H), dt)
    elif cfg.family == "bloom":  # dense 4H GELU MLP with bias
        p["w_up"] = big(keys[5], (L, H, I), dt)
        p["b_up"] = jnp.zeros((L, I), dt)
        p["w_down"] = big(keys[7], (L, I, H), dt)
        p["b_down"] = jnp.zeros((L, H), dt)
    else:  # llama SwiGLU
        p["w_gate"] = big(keys[5], (L, H, I), dt)
        p["w_up"] = big(keys[6], (L, H, I), dt)
        p["w_down"] = big(keys[7], (L, I, H), dt)
    return p


def init_full_params(rng: jax.Array, cfg: ModelConfig,
                     quantize=False) -> StageParams:
    """Random-init full model as a single StageParams (stage 0 of 1).

    ``quantize=True`` resolves to the config's own quantization mode
    (int8 or int4), so ``get_model_config("x-int4")`` + ``quantize=True``
    does the right thing without every caller re-deriving the mode."""
    if quantize is True and cfg.quantization in ("int8", "int4"):
        quantize = cfg.quantization
    k_emb, k_layers, k_head = jax.random.split(rng, 3)
    dt = cfg.dtype
    embed = {"tokens": _dense_init(k_emb, (cfg.vocab_size, cfg.hidden_size), dt,
                                   scale=0.02)}
    if cfg.family == "bloom":  # bloom applies LayerNorm right after embedding
        embed["norm_w"] = jnp.ones((cfg.hidden_size,), dt)
        embed["norm_b"] = jnp.zeros((cfg.hidden_size,), dt)
    final_norm = {"w": jnp.ones((cfg.hidden_size,), dt)}
    if cfg.attn_layernorm:
        final_norm["b"] = jnp.zeros((cfg.hidden_size,), dt)
    if cfg.tie_embeddings:
        lm_head = {}  # reuse embed["tokens"]
    else:
        lm_head = {"w": _dense_init(k_head, (cfg.hidden_size, cfg.vocab_size), dt)}
    return StageParams(
        layers=init_layer_params(k_layers, cfg, cfg.num_layers,
                                 quantize=quantize),
        embed=embed, final_norm=final_norm, lm_head=lm_head)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def embed_tokens(params: StageParams, cfg: ModelConfig,
                 ids: jnp.ndarray) -> jnp.ndarray:
    """Token ids -> [b, s, H] through the full embedding pipeline (table
    lookup + bloom's embedding LayerNorm).  The single source shared by the
    ids path of ``stage_forward`` and multimodal prefix construction."""
    x = params.embed["tokens"][ids]
    if cfg.embed_scale:
        # gemma scales embeddings by sqrt(H), with the normalizer cast to
        # the activation dtype FIRST (HF semantics — the rounding is part
        # of the checkpoint's numerics)
        x = x * jnp.asarray(cfg.hidden_size ** 0.5, x.dtype)
    if "norm_w" in params.embed:  # bloom embedding LayerNorm
        x = layer_norm(x, params.embed["norm_w"], params.embed["norm_b"],
                       cfg.norm_eps)
    return x

def _mlp(cfg: ModelConfig, lp: dict, x: jnp.ndarray,
         tp_axis: Optional[str] = None,
         ep_axis: Optional[str] = None) -> jnp.ndarray:
    """MLP block.  Under manual TP (``tp_axis`` set inside shard_map),
    w_gate/w_up arrive column-sliced and w_down row-sliced: the partial
    products are summed with an explicit psum (Megatron layout); biases are
    added once, after the reduction.  ``ep_axis`` selects the expert-
    parallel all_to_all dispatch path for MoE layers."""
    if cfg.num_experts > 0:
        if ep_axis is not None:
            return _moe_mlp_ep(cfg, lp, x, ep_axis)
        return _moe_mlp(cfg, lp, x, tp_axis)
    if cfg.family == "bloom":
        # under manual TP, b_up arrives column-sliced (P(None, "tp")) to
        # match w_up's local columns, so a plain add is correct either way.
        h = dense(x, lp["w_up"], "bsh,hi->bsi") + lp["b_up"]
        h = jax.nn.gelu(h.astype(jnp.float32)).astype(x.dtype)
        out = dense(h, lp["w_down"], "bsi,ih->bsh")
        if tp_axis is not None:
            out = jax.lax.psum(out, tp_axis)
        return out + lp["b_down"]
    gate = dense(x, lp["w_gate"], "bsh,hi->bsi")
    up = dense(x, lp["w_up"], "bsh,hi->bsi")
    gate = gate.astype(jnp.float32)
    act = (jax.nn.gelu(gate, approximate=True)
           if cfg.mlp_act == "gelu_tanh" else jax.nn.silu(gate))
    h = (act * up.astype(jnp.float32)).astype(x.dtype)
    out = dense(h, lp["w_down"], "bsi,ih->bsh")
    if tp_axis is not None:
        out = jax.lax.psum(out, tp_axis)
    return out


def _moe_mlp(cfg: ModelConfig, lp: dict, x: jnp.ndarray,
             tp_axis: Optional[str] = None) -> jnp.ndarray:
    """Top-k routed MoE (mixtral).

    Round-1 strategy: compute all experts batched on the MXU and combine with
    the (sparse) routing weights.  For small decode batches this trades FLOPs
    for zero gather/scatter overhead and static shapes; a capacity-based
    dispatch kernel is the later optimization.  Expert parallelism shards the
    leading E axis of w_gate/w_up/w_down over the "ep"/"tp" mesh axis.
    """
    E, k = cfg.num_experts, cfg.experts_per_token
    logits = jnp.einsum("bsh,he->bse", x, lp["router"]).astype(jnp.float32)
    topv, topi = jax.lax.top_k(logits, k)                      # [b,s,k]
    weights = jax.nn.softmax(topv, axis=-1)                    # [b,s,k]
    # dense routing matrix [b,s,E] with top-k softmax weights, zeros elsewhere
    route = jnp.zeros_like(logits).at[
        jnp.arange(x.shape[0])[:, None, None],
        jnp.arange(x.shape[1])[None, :, None],
        topi].set(weights)
    if tp_axis is not None:
        # expert parallelism: this rank holds E_local experts; select its
        # slice of the routing matrix and psum partial outputs across ranks.
        e_local = lp["w_gate"].shape[0]  # QuantizedArray exposes .shape
        e0 = jax.lax.axis_index(tp_axis) * e_local
        route = jax.lax.dynamic_slice_in_dim(route, e0, e_local, axis=-1)
    gate = dense(x, lp["w_gate"], "bsh,ehi->besi")
    up = dense(x, lp["w_up"], "bsh,ehi->besi")
    h = (jax.nn.silu(gate.astype(jnp.float32)) * up.astype(jnp.float32)).astype(x.dtype)
    out = dense(h, lp["w_down"], "besi,eih->besh")        # [b,E,s,h]
    out = jnp.einsum("besh,bse->bsh", out, route.astype(x.dtype))
    if tp_axis is not None:
        out = jax.lax.psum(out, tp_axis)
    return out


def _default_attn(q, k, v, k_cache, v_cache, positions, cache_start, slopes):
    """Default attention path: insert chunk into cache, attend to cache.

    ``attn_impl`` hooks in ``_layer``/``stage_forward`` share this signature;
    the sequence-parallel path (parallel/sequence.py) substitutes ring /
    sharded-cache attention without duplicating the decoder block.
    """
    k_cache, v_cache = update_kv_cache(k_cache, v_cache, k, v, cache_start)
    new_len = cache_start + q.shape[1]
    out = attention(q, k_cache, v_cache, positions, new_len, slopes)
    return out, k_cache, v_cache


def _moe_mlp_ep(cfg: ModelConfig, lp: dict, x: jnp.ndarray,
                ep_axis: str) -> jnp.ndarray:
    """Expert-parallel MoE: GShard-style capacity dispatch + all_to_all.

    BASELINE.json config #4 ("per-expert shard placement") done the TPU
    way: experts live sharded over the ``ep`` mesh axis (this rank holds
    ``E/n`` experts' weights — ``lp["w_*"]`` arrive E-sliced inside
    shard_map), tokens are data-parallel over the same axis.  Each rank
    routes its tokens into per-expert capacity slots
    (``C = ceil(T*k/E * moe_capacity_factor)``, over-capacity tokens drop
    — exactness for tests comes from a generous factor), one
    ``all_to_all`` ships slot buffers to the expert owners, the expert
    MLPs run batched on the MXU ([e_loc, n*C, H] x [e_loc, H, I]), and a
    reverse ``all_to_all`` brings outputs home for the weighted combine.

    Dispatch/combine are one-hot einsums (dense [T, E, C] masks): static
    shapes, no gather/scatter — the XLA-friendly formulation.
    """
    import math
    b, s, H = x.shape
    T = b * s
    E, k = cfg.num_experts, cfg.experts_per_token
    n = axis_size(ep_axis)
    e_loc = lp["w_gate"].shape[0]       # E-sliced inside shard_map
    assert e_loc * n == E, (e_loc, n, E)
    xt = x.reshape(T, H)

    logits = dense(xt, lp["router"], "th,he->te").astype(jnp.float32)
    topv, topi = jax.lax.top_k(logits, k)                  # [T, k]
    weights = jax.nn.softmax(topv, axis=-1)                # [T, k]

    C = int(math.ceil(T * k / E * cfg.moe_capacity_factor))
    onehot = jax.nn.one_hot(topi, E, dtype=jnp.int32)      # [T, k, E]
    flat = onehot.reshape(T * k, E)
    pos = jnp.cumsum(flat, axis=0) - 1                     # slot per expert
    keep = (flat > 0) & (pos < C)
    slot = jnp.where(keep, pos, C)                         # C -> dropped
    disp = jax.nn.one_hot(slot, C, dtype=jnp.float32)      # [T*k, E, C]
    disp_t = disp.reshape(T, k, E, C).sum(1)               # [T, E, C]
    comb = (disp * weights.reshape(T * k)[:, None, None]
            ).reshape(T, k, E, C).sum(1)                   # [T, E, C]

    expert_in = jnp.einsum("tec,th->ech", disp_t,
                           xt.astype(jnp.float32))         # [E, C, H]
    ein = expert_in.reshape(n, e_loc, C, H)
    ein = jax.lax.all_to_all(ein, ep_axis, split_axis=0, concat_axis=0)
    h_in = ein.transpose(1, 0, 2, 3).reshape(e_loc, n * C, H)
    h_in = h_in.astype(x.dtype)

    gate = dense(h_in, lp["w_gate"], "ech,ehi->eci")
    up = dense(h_in, lp["w_up"], "ech,ehi->eci")
    hh = (jax.nn.silu(gate.astype(jnp.float32))
          * up.astype(jnp.float32)).astype(x.dtype)
    out = dense(hh, lp["w_down"], "eci,eih->ech")          # [e_loc, n*C, H]

    out = out.reshape(e_loc, n, C, H).transpose(1, 0, 2, 3)
    out = jax.lax.all_to_all(out, ep_axis, split_axis=0, concat_axis=0)
    expert_out = out.reshape(E, C, H).astype(jnp.float32)
    y = jnp.einsum("tec,ech->th", comb, expert_out)
    return y.reshape(b, s, H).astype(x.dtype)


def _layer(cfg: ModelConfig, lp: dict, x: jnp.ndarray,
           k_cache: jnp.ndarray, v_cache: jnp.ndarray,
           positions: jnp.ndarray, cache_start: jnp.ndarray,
           slopes: Optional[jnp.ndarray],
           tp_axis: Optional[str] = None,
           attn_impl=None,
           ep_axis: Optional[str] = None
           ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One decoder block. x: [b, s, H]. Returns (x', k_cache', v_cache').

    Head counts derive from the weight shards, not the config, so the same
    code runs full-model (GSPMD) and per-TP-rank (manual shard_map) — under
    TP this rank sees nh/tp query heads and nkv/tp kv heads.
    """
    b, s, H = x.shape
    hd = cfg.head_dim
    wq_shape = lp["wq"].shape  # QuantizedArray exposes .shape too
    nh = wq_shape[-1] // hd
    nkv = lp["wk"].shape[-1] // hd

    if cfg.attn_layernorm:
        h = layer_norm(x, lp["attn_norm_w"], lp["attn_norm_b"], cfg.norm_eps)
    else:
        h = rms_norm(x, lp["attn_norm_w"], cfg.norm_eps)

    q = dense(h, lp["wq"], "bsh,hd->bsd")
    k = dense(h, lp["wk"], "bsh,hd->bsd")
    v = dense(h, lp["wv"], "bsh,hd->bsd")
    if cfg.attn_layernorm or cfg.attn_qkv_bias:
        # bq/bk/bv are column-sharded with their weights under TP
        q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    q = q.reshape(b, s, nh, hd)
    k = k.reshape(b, s, nkv, hd)
    v = v.reshape(b, s, nkv, hd)

    if cfg.use_rope:
        q = apply_rope(q, positions, cfg.rope_theta)
        k = apply_rope(k, positions, cfg.rope_theta)

    attn_fn = attn_impl if attn_impl is not None else _default_attn
    attn, k_cache, v_cache = attn_fn(
        q, k, v, k_cache, v_cache, positions, cache_start, slopes)
    attn = attn.reshape(b, s, nh * hd)
    attn = dense(attn, lp["wo"], "bsd,dh->bsh")
    if tp_axis is not None:
        attn = jax.lax.psum(attn, tp_axis)
    if cfg.attn_layernorm:
        attn = attn + lp["bo"]
    x = x + attn

    if cfg.attn_layernorm:
        h = layer_norm(x, lp["mlp_norm_w"], lp["mlp_norm_b"], cfg.norm_eps)
    else:
        h = rms_norm(x, lp["mlp_norm_w"], cfg.norm_eps)
    x = x + _mlp(cfg, lp, h, tp_axis, ep_axis)
    return x, k_cache, v_cache


def stage_forward(
    params: StageParams,
    cfg: ModelConfig,
    spec: StageSpec,
    inputs: jnp.ndarray,        # [b, s] int32 ids (first stage) or [b, s, H] hidden
    cache: KVCache,             # this stage's cache (num_layers = spec.num_layers)
    positions: jnp.ndarray,     # [b, s] absolute positions of the chunk
    tp_axis: Optional[str] = None,  # set inside shard_map for manual TP
    attn_impl=None,             # attention hook (see _default_attn)
    ep_axis: Optional[str] = None,  # expert-parallel MoE axis (shard_map)
    last_logits_only: bool = False,  # head over the final position only
    cache_in_carry: bool = True,  # in-place cache (inference) vs ys (train)
) -> Tuple[jnp.ndarray, KVCache]:
    """Run this stage's layer range. Returns (hidden or logits, updated cache).

    ``last_logits_only`` narrows the LM-head matmul to the chunk's final
    position (shape [b, 1, V]) — prefill only samples from the last token,
    and a full [b, s, V] logits tensor at long prompts is GBs of HBM for
    nothing.  Training and scoring paths keep the default (all positions).

    The stage seam replaces the reference's ``run_inference`` module boundary
    (``cpp/inference.cpp:145-218``): first stage embeds ids, last stage
    applies final norm + LM head.  Residual/skip routing between stages
    (reference ``LoadBalance.java:37-88`` dependencyMap machinery) is
    dissolved by construction — stages own whole decoder blocks, so the only
    inter-stage tensor is the [b, s, H] hidden state.
    """
    if spec.is_first:
        if jnp.issubdtype(inputs.dtype, jnp.floating):
            # pre-embedded [b, s, H] prefix (multimodal: projected vision
            # patches ++ token embeddings — models/vision.py); assumed to
            # be past the embedding pipeline incl. any bloom embed-norm.
            x = inputs.astype(cfg.dtype)
        else:
            x = embed_tokens(params, cfg, inputs)  # [b, s, H]
    else:
        x = inputs.astype(cfg.dtype)

    slopes = alibi_slopes(cfg.num_heads) if cfg.use_alibi else None
    if slopes is not None and tp_axis is not None:
        nh_local = params.layers["wq"].shape[-1] // cfg.head_dim
        slopes = jax.lax.dynamic_slice_in_dim(
            slopes, jax.lax.axis_index(tp_axis) * nh_local, nh_local, axis=0)
    cache_start = cache.length

    if cache_in_carry:
        # Inference layout: the full stacked cache rides the scan CARRY and
        # each iteration dynamic-slices its layer plane in/out — XLA keeps
        # the carry buffer in place, so a decode step writes one token
        # column instead of re-materializing every layer's whole
        # [b, nkv, max_seq, hd] plane as a stacked ys output.  Measured on
        # v5e (tinyllama, max_seq=2048): +16% decode tok/s at batch 8,
        # +57% at batch 64 over the ys layout.
        # The cache planes are pytrees, not bare arrays, when the pool
        # is quantized (ops.quant.QuantizedKVPages: narrow data + scale
        # leaves share the leading layer axis) — index/update per leaf.
        def body(carry, scanned):
            x, K, V = carry
            lp, li = scanned
            kc = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, li, 0, keepdims=False), K)
            vc = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, li, 0, keepdims=False), V)
            x, kc, vc = _layer(cfg, lp, x, kc, vc, positions, cache_start,
                               slopes, tp_axis, attn_impl, ep_axis)
            K = jax.tree.map(
                lambda a, c: jax.lax.dynamic_update_index_in_dim(
                    a, c, li, 0), K, kc)
            V = jax.tree.map(
                lambda a, c: jax.lax.dynamic_update_index_in_dim(
                    a, c, li, 0), V, vc)
            return (x, K, V), None

        n_layers = jax.tree.leaves(cache.keys)[0].shape[0]
        (x, new_k, new_v), _ = jax.lax.scan(
            body, (x, cache.keys, cache.values),
            (params.layers, jnp.arange(n_layers)))
    else:
        # Training layout: per-layer cache planes as xs/ys.  Under
        # differentiation a big carry would be saved per scan iteration by
        # the VJP; ys keeps residuals at one cache's worth.
        def body(x, scanned):
            lp, kc, vc = scanned
            x, kc, vc = _layer(cfg, lp, x, kc, vc, positions, cache_start,
                               slopes, tp_axis, attn_impl, ep_axis)
            return x, (kc, vc)

        x, (new_k, new_v) = jax.lax.scan(
            body, x, (params.layers, cache.keys, cache.values))
    new_cache = KVCache(new_k, new_v, cache_start + inputs.shape[1])

    if spec.is_last:
        if last_logits_only:
            x = x[:, -1:, :]
        if cfg.attn_layernorm:
            x = layer_norm(x, params.final_norm["w"], params.final_norm["b"],
                           cfg.norm_eps)
        else:
            x = rms_norm(x, params.final_norm["w"], cfg.norm_eps)
        head = (params.embed["tokens"].T if cfg.tie_embeddings
                else params.lm_head["w"])
        x = jnp.einsum("bsh,hv->bsv", x, head)
        if tp_axis is not None and x.shape[-1] != cfg.vocab_size:
            # vocab-parallel head: gather the logit shards so every rank
            # sees full logits at the sampling boundary.  Skipped when the
            # head was replicated (e.g. tied embeddings) and logits are
            # already full-width.
            x = jax.lax.all_gather(x, tp_axis, axis=-1, tiled=True)
    return x, new_cache
