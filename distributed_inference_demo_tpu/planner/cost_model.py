"""Analytic per-layer cost model: FLOPs, parameter bytes, activation bytes.

The reference's (missing) ``ModelCard.prepare_optimization_info`` computed
per-module FLOPs / memory / output-size maps for the planner
(``server.py:834-835``, SURVEY.md §2.2).  Here the same quantities come from
the architecture description (ModelConfig) analytically — no probe model or
ONNX export needed, and the numbers are exact for the decoder math we run.

Conventions:
- FLOPs are per generated token (decode step, batch 1, KV-cached attention
  over ``ctx`` cached positions).  Multiply by batch for batched decode;
  prefill FLOPs are per prompt token with ``ctx`` ≈ seq/2 on average.
- Bytes are weight-resident bytes (what must fit in device memory, before
  the 0.7 headroom factor the reference applies, ``server.py:860-862``).
- Activation bytes are what crosses a pipeline cut after the layer
  (hidden-state row per token), i.e. the wire payload between stages.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

from ..models.base import ModelConfig


@dataclass(frozen=True)
class LayerCost:
    flops: float           # per-token decode FLOPs
    param_bytes: int       # resident weight bytes
    act_bytes: int         # activation bytes crossing a cut after this layer
    kv_bytes_per_tok: int  # KV-cache growth per token (resident, per layer)


@dataclass(frozen=True)
class ModelCostProfile:
    """Costs for embedding, each decoder layer, and the head."""

    embed: LayerCost
    layers: List[LayerCost]
    head: LayerCost
    dtype_bytes: int

    @property
    def total_param_bytes(self) -> int:
        return (self.embed.param_bytes + self.head.param_bytes
                + sum(c.param_bytes for c in self.layers))

    @property
    def total_flops(self) -> float:
        return (self.embed.flops + self.head.flops
                + sum(c.flops for c in self.layers))


def _dtype_bytes(cfg: ModelConfig) -> float:
    if cfg.quantization == "int8":
        return 1
    if cfg.quantization == "int4":
        # nibble-packed weights + f32 group scales
        # (ops/quant.DEFAULT_INT4_GROUP) — mis-costing int4 at float
        # width would make the planner reject placements that fit
        from ..ops.quant import DEFAULT_INT4_GROUP
        return 0.5 + 4.0 / DEFAULT_INT4_GROUP
    return {"float32": 4, "bfloat16": 2, "float16": 2}.get(cfg.dtype_name, 2)


def model_cost_profile(cfg: ModelConfig, ctx: int = 1024) -> ModelCostProfile:
    """Cost profile at a representative KV context length ``ctx``."""
    h = cfg.hidden_size
    inter = cfg.intermediate_size
    kvh = cfg.num_kv_heads
    hd = cfg.head_dim
    wb = _dtype_bytes(cfg)
    act = 2 * h  # bf16 hidden row on the wire per token

    # attention weights: q (h * nh*hd), k,v (h * kvh*hd each),
    # o (nh*hd * h) — nh*hd != h when head_dim is decoupled (gemma)
    qo = h * cfg.num_heads * hd
    attn_params = qo + 2 * h * kvh * hd + qo
    # mlp weights: 2 matrices for bloom's dense GELU MLP, 3 for every
    # gated family (llama/qwen2/gemma SwiGLU-or-GeGLU, mixtral experts)
    # — mirrors decoder._mlp's branch exactly
    gated = cfg.family != "bloom"
    mlp_params_dense = (3 if gated else 2) * h * inter
    if cfg.num_experts > 0:
        mlp_params = cfg.num_experts * mlp_params_dense + h * cfg.num_experts
        # only experts_per_token experts run per token
        mlp_flops = 2 * cfg.experts_per_token * mlp_params_dense \
            + 2 * h * cfg.num_experts
    else:
        mlp_params = mlp_params_dense
        mlp_flops = 2 * mlp_params_dense
    norm_params = 2 * h * (2 if cfg.attn_layernorm else 1)

    # decode-step attention FLOPs: projections + scores/values over ctx
    attn_flops = 2 * attn_params + 2 * 2 * cfg.num_heads * hd * ctx

    layer = LayerCost(
        flops=float(attn_flops + mlp_flops),
        param_bytes=(attn_params + mlp_params + norm_params) * wb,
        act_bytes=act,
        kv_bytes_per_tok=2 * kvh * hd * 2,   # k+v, bf16
    )
    embed = LayerCost(
        flops=0.0,  # gather
        param_bytes=cfg.vocab_size * h * wb,
        act_bytes=act,
        kv_bytes_per_tok=0,
    )
    head = LayerCost(
        flops=float(2 * h * cfg.vocab_size),
        param_bytes=(0 if cfg.tie_embeddings else cfg.vocab_size * h * wb)
        + h * wb,
        act_bytes=4,  # a sampled token id
        kv_bytes_per_tok=0,
    )
    return ModelCostProfile(embed=embed, layers=[layer] * cfg.num_layers,
                            head=head, dtype_bytes=wb)
