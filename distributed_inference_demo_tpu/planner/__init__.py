"""Partition planner: cost model + device assignment.

Re-implements and completes the reference's planning pipeline
(``server.py:794-957``): the per-module cost info the missing ``ModelCard``
produced (``prepare_optimization_info``, ``server.py:834-835``), the
round-robin arrangement actually used (``server.py:893-905``), and the
cost-model ``Optimizer`` the reference left commented out
(``server.py:879-891``) — here a real bottleneck-minimizing DP over layer
cuts with memory-headroom constraints and inter-device comm costs, also
emitting TPU mesh axes per stage.
"""

from .cost_model import LayerCost, ModelCostProfile, model_cost_profile
from .planner import (SKETCH_REQUIRED_KEYS, SKETCH_SCHEMA_VERSION,
                      DeviceProfile, PartitionPlan, PlanError, SketchError,
                      WorkloadSketch, load_cached_plan,
                      load_workload_sketch, plan_from_sketch,
                      plan_partition, round_robin_plan, save_plan_cache)

__all__ = [
    "LayerCost", "ModelCostProfile", "model_cost_profile",
    "DeviceProfile", "PartitionPlan", "PlanError",
    "plan_partition", "round_robin_plan",
    "load_cached_plan", "save_plan_cache",
    "SKETCH_SCHEMA_VERSION", "SKETCH_REQUIRED_KEYS",
    "SketchError", "WorkloadSketch",
    "load_workload_sketch", "plan_from_sketch",
]
