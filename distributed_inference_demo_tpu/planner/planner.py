"""Device assignment: round-robin baseline + bottleneck-minimizing optimizer.

The reference planned with ``round_robin_module_arrangement``
(``server.py:893-905``) — an even split ignoring device speed — and left its
cost-model LP ``Optimizer`` commented out (``server.py:879-891``,
``init_server.py:219-232``).  This module provides both:

- ``round_robin_plan``: the even split, for parity and as a fallback;
- ``plan_partition``: dynamic programming over contiguous layer cuts along
  the fixed ring order (header first, tail last — the order the device pool
  allocates, ``server.py:261-267``), minimizing the pipeline bottleneck
  ``max_i(compute_i + comm_i)`` subject to a 0.7 memory-headroom constraint
  per device (``server.py:860-862``).  Inputs are the analytic model costs
  (cost_model.py) and per-device monitor measurements (flops/s, memory,
  p2p bandwidth/latency — the tuple of ``server.py:858``).

Plans are cacheable to JSON, mirroring the reference's ``ip_module.json`` /
``session.json`` reload path (``server.py:805-820``).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..models.base import ModelConfig, StageSpec
from .cost_model import ModelCostProfile, model_cost_profile

MEMORY_HEADROOM = 0.7  # reference server.py:860-862


class PlanError(RuntimeError):
    """No feasible partition under the given constraints."""


@dataclass(frozen=True)
class DeviceProfile:
    """Planner view of one device (the monitor tuple, ``server.py:858``)."""

    device_id: str
    address: str
    flops_per_sec: float = 1e12
    memory_bytes: int = 16 << 30
    platform: str = "cpu"              # cpu | tpu
    chips: int = 1                     # TPU chips for intra-stage tp
    # bandwidth to the NEXT device in ring order, bytes/sec; latency sec
    egress_bandwidth: float = 1e9
    egress_latency: float = 1e-3


@dataclass
class StageAssignment:
    device_id: str
    address: str
    layer_start: int
    layer_end: int
    est_compute_sec: float
    est_comm_sec: float
    est_param_bytes: int
    mesh_axes: Dict[str, int] = field(default_factory=dict)

    @property
    def est_step_sec(self) -> float:
        return self.est_compute_sec + self.est_comm_sec


@dataclass
class PartitionPlan:
    model: str
    num_layers: int
    stages: List[StageAssignment]
    est_bottleneck_sec: float
    plan_version: int = 0

    @property
    def stage_ranges(self) -> Dict[str, List[int]]:
        return {s.device_id: [s.layer_start, s.layer_end]
                for s in self.stages}

    @property
    def device_graph(self) -> List[str]:
        return [s.address for s in self.stages]

    @property
    def device_ids(self) -> List[str]:
        return [s.device_id for s in self.stages]

    def stage_specs(self) -> List[StageSpec]:
        return [StageSpec(i, len(self.stages), s.layer_start, s.layer_end)
                for i, s in enumerate(self.stages)]

    def to_json(self) -> dict:
        return {
            "model": self.model, "num_layers": self.num_layers,
            "plan_version": self.plan_version,
            "est_bottleneck_sec": self.est_bottleneck_sec,
            "stages": [{
                "device_id": s.device_id, "address": s.address,
                "layers": [s.layer_start, s.layer_end],
                "est_compute_sec": s.est_compute_sec,
                "est_comm_sec": s.est_comm_sec,
                "est_param_bytes": s.est_param_bytes,
                "mesh_axes": s.mesh_axes,
            } for s in self.stages],
        }

    @staticmethod
    def from_json(d: dict) -> "PartitionPlan":
        return PartitionPlan(
            model=d["model"], num_layers=d["num_layers"],
            plan_version=d.get("plan_version", 0),
            est_bottleneck_sec=d.get("est_bottleneck_sec", 0.0),
            stages=[StageAssignment(
                device_id=s["device_id"], address=s["address"],
                layer_start=s["layers"][0], layer_end=s["layers"][1],
                est_compute_sec=s.get("est_compute_sec", 0.0),
                est_comm_sec=s.get("est_comm_sec", 0.0),
                est_param_bytes=s.get("est_param_bytes", 0),
                mesh_axes=dict(s.get("mesh_axes", {})),
            ) for s in d["stages"]])


def _mesh_axes_for(dev: DeviceProfile) -> Dict[str, int]:
    """TPU stages shard intra-stage over their chips (tp innermost — ICI);
    CPU/edge stages run unsharded (the heterogeneous boundary)."""
    if dev.platform == "tpu" and dev.chips > 1:
        return {"dp": 1, "tp": dev.chips, "sp": 1}
    return {"dp": 1, "tp": 1, "sp": 1}


def _stage_costs(profile: ModelCostProfile, devs: Sequence[DeviceProfile],
                 cfg: ModelConfig, i: int, a: int, b: int, num_devices: int,
                 batch: int, ctx: int):
    """(compute_sec, comm_sec, param_bytes, kv_bytes) for layers [a,b) on
    device i.  TP over a TPU stage's chips divides per-chip FLOPs."""
    dev = devs[i]
    flops = sum(c.flops for c in profile.layers[a:b]) * batch
    params = sum(c.param_bytes for c in profile.layers[a:b])
    kv = sum(c.kv_bytes_per_tok for c in profile.layers[a:b]) * batch * ctx
    if i == 0:
        flops += profile.embed.flops * batch
        params += profile.embed.param_bytes
    if i == num_devices - 1:
        flops += profile.head.flops * batch
        params += profile.head.param_bytes
        if cfg.tie_embeddings and num_devices > 1:
            # slice_stage gives the tail its own copy of the token table
            # for the tied LM head (models/base.py needs_embed) — charge it.
            params += profile.embed.param_bytes
    eff_flops = dev.flops_per_sec * (dev.chips if dev.platform == "tpu"
                                     else 1)
    compute = flops / eff_flops
    if i == num_devices - 1:
        # the tail sends a sampled token id back to the header, not a
        # hidden row
        act = profile.head.act_bytes * batch
    else:
        act = profile.layers[b - 1].act_bytes * batch if b > a else 0
    comm = (dev.egress_latency + act / dev.egress_bandwidth
            if num_devices > 1 else 0.0)
    return compute, comm, params, kv


def plan_partition(cfg: ModelConfig, model_name: str,
                   devices: Sequence[DeviceProfile],
                   batch: int = 1, ctx: Optional[int] = None,
                   profile: Optional[ModelCostProfile] = None,
                   plan_version: int = 0) -> PartitionPlan:
    """Optimal contiguous split along the ring order: minimize the pipeline
    bottleneck, respecting per-device memory headroom.

    DP over (devices used, layers consumed): O(D * L^2)."""
    ctx = ctx or min(cfg.max_seq_len, 1024)
    profile = profile or model_cost_profile(cfg, ctx=ctx)
    L, D = cfg.num_layers, len(devices)
    if D < 1:
        raise PlanError("no devices")
    if D > L:
        raise PlanError(f"more devices ({D}) than layers ({L})")

    def feasible(i, a, b):
        _, _, params, kv = _stage_costs(profile, devices, cfg, i, a, b, D,
                                        batch, ctx)
        return params + kv <= MEMORY_HEADROOM * devices[i].memory_bytes

    def stage_time(i, a, b):
        comp, comm, _, _ = _stage_costs(profile, devices, cfg, i, a, b, D,
                                        batch, ctx)
        return comp + comm

    INF = float("inf")
    # best[i][j]: minimal bottleneck assigning first j layers to devices 0..i-1
    best = [[INF] * (L + 1) for _ in range(D + 1)]
    cut = [[-1] * (L + 1) for _ in range(D + 1)]
    best[0][0] = 0.0
    for i in range(1, D + 1):
        for j in range(i, L + 1):
            for k in range(i - 1, j):   # each device gets >= 1 layer
                if best[i - 1][k] == INF:
                    continue
                if not feasible(i - 1, k, j):
                    continue
                c = max(best[i - 1][k], stage_time(i - 1, k, j))
                if c < best[i][j]:
                    best[i][j] = c
                    cut[i][j] = k
    if best[D][L] == INF:
        raise PlanError(
            f"no feasible partition of {L} layers over {D} devices "
            f"(memory headroom {MEMORY_HEADROOM})")

    bounds = [L]
    j = L
    for i in range(D, 0, -1):
        j = cut[i][j]
        bounds.append(j)
    bounds.reverse()

    stages = []
    for i, dev in enumerate(devices):
        a, b = bounds[i], bounds[i + 1]
        comp, comm, params, _ = _stage_costs(profile, devices, cfg, i, a, b,
                                             D, batch, ctx)
        stages.append(StageAssignment(
            device_id=dev.device_id, address=dev.address,
            layer_start=a, layer_end=b, est_compute_sec=comp,
            est_comm_sec=comm, est_param_bytes=params,
            mesh_axes=_mesh_axes_for(dev)))
    return PartitionPlan(model=model_name, num_layers=L, stages=stages,
                         est_bottleneck_sec=best[D][L],
                         plan_version=plan_version)


def round_robin_plan(cfg: ModelConfig, model_name: str,
                     devices: Sequence[DeviceProfile],
                     plan_version: int = 0) -> PartitionPlan:
    """Even split ignoring device speed — the arrangement the reference
    actually shipped (``round_robin_module_arrangement``,
    ``server.py:893-905``)."""
    L, D = cfg.num_layers, len(devices)
    if D < 1 or D > L:
        raise PlanError(f"cannot split {L} layers over {D} devices")
    base, extra = divmod(L, D)
    stages, start = [], 0
    for i, dev in enumerate(devices):
        n = base + (1 if i < extra else 0)
        stages.append(StageAssignment(
            device_id=dev.device_id, address=dev.address,
            layer_start=start, layer_end=start + n,
            est_compute_sec=0.0, est_comm_sec=0.0, est_param_bytes=0,
            mesh_axes=_mesh_axes_for(dev)))
        start += n
    return PartitionPlan(model=model_name, num_layers=L, stages=stages,
                         est_bottleneck_sec=0.0, plan_version=plan_version)


# -- workload sketch input (telemetry/profiling.py, docs/DESIGN.md §20) ------

#: pinned with ``telemetry.profiling.SKETCH_SCHEMA_VERSION`` by
#: ``tools/check_sketch_schema.py`` — bump BOTH together.  Deliberately a
#: LITERAL copy, not an import: the planner parses committed sketch
#: artifacts without loading the serving stack.
SKETCH_SCHEMA_VERSION = 1

#: top-level keys every consumable artifact carries (same lint pins the
#: recorder's copy; ``load_workload_sketch`` enforces presence).
SKETCH_REQUIRED_KEYS = ("schema_version", "window_s", "requests",
                        "tenants", "prompt_tokens", "decode_tokens",
                        "interarrival_s", "prefix_hit")


class SketchError(ValueError):
    """A workload-sketch artifact the planner refuses to consume."""


def _hist_percentile(frag: dict, p: float) -> float:
    """Planner-side mirror of the recorder's fixed-edge histogram read:
    the upper edge of the bucket holding the p-quantile (conservative);
    the overflow bin reports the max seen."""
    edges = frag.get("edges") or []
    counts = [int(c) for c in (frag.get("counts") or [])]
    total = sum(counts)
    if not total:
        return 0.0
    target = p * total
    seen = 0
    for i, c in enumerate(counts):
        seen += c
        if seen >= target:
            return (float(edges[i]) if i < len(edges)
                    else float(frag.get("max", 0.0)))
    return float(frag.get("max", 0.0))


@dataclass(frozen=True)
class WorkloadSketch:
    """Planner view of one measured workload (the §20 sketch artifact):
    exactly the knobs ROADMAP item 3 names — ctx length, arrival rate,
    prefix share — distilled from the recorder's histograms."""

    requests: int
    window_s: float
    arrival_rate: float            # requests/sec over the window (0 = n/a)
    prompt_p50: float
    prompt_p95: float
    decode_p50: float
    decode_p95: float
    prefix_share: float
    tenants: Dict[str, int] = field(default_factory=dict)

    @property
    def ctx_tokens(self) -> int:
        """Context budget a plan should assume: p95 prompt + p95 decode
        (conservative bucket-edge reads, so a plan sized from this never
        under-reserves KV for the sketched traffic)."""
        return int(self.prompt_p95 + self.decode_p95)


def load_workload_sketch(src) -> WorkloadSketch:
    """Parse a sketch artifact into the planner's workload input.

    ``src``: a dict (already-parsed artifact), a JSON string, or a path
    to a JSON file (``tools/sketch.py`` writes both forms).  Raises
    :class:`SketchError` on a schema-version mismatch or missing keys —
    a mis-sized plan must fail loudly at planning time."""
    obj = src
    if isinstance(obj, str):
        if obj.lstrip().startswith("{"):
            obj = json.loads(obj)
        else:
            with open(obj) as f:
                obj = json.load(f)
    if not isinstance(obj, dict):
        raise SketchError(f"sketch artifact must be a JSON object, "
                          f"got {type(obj).__name__}")
    if obj.get("schema_version") != SKETCH_SCHEMA_VERSION:
        raise SketchError(
            f"sketch schema_version {obj.get('schema_version')!r} != "
            f"planner's pinned {SKETCH_SCHEMA_VERSION} — regenerate the "
            "artifact (or update BOTH pinned versions together)")
    missing = [k for k in SKETCH_REQUIRED_KEYS if k not in obj]
    if missing:
        raise SketchError(f"sketch artifact missing keys: {missing}")
    window = float(obj["window_s"])
    requests = int(obj["requests"])
    prefix = obj["prefix_hit"] or {}
    return WorkloadSketch(
        requests=requests,
        window_s=window,
        arrival_rate=(requests / window if window > 0 else 0.0),
        prompt_p50=_hist_percentile(obj["prompt_tokens"], 0.50),
        prompt_p95=_hist_percentile(obj["prompt_tokens"], 0.95),
        decode_p50=_hist_percentile(obj["decode_tokens"], 0.50),
        decode_p95=_hist_percentile(obj["decode_tokens"], 0.95),
        prefix_share=float(prefix.get("share", 0.0)),
        tenants={str(k): int(v)
                 for k, v in (obj.get("tenants") or {}).items()})


def plan_from_sketch(cfg: ModelConfig, model_name: str,
                     devices: Sequence[DeviceProfile], sketch,
                     batch: int = 1,
                     profile: Optional[ModelCostProfile] = None,
                     plan_version: int = 0) -> PartitionPlan:
    """:func:`plan_partition` driven by a measured workload sketch
    instead of a hand-picked ctx: the context budget is the sketch's
    p95 prompt + p95 decode (clamped to the model's window), discounted
    by the measured prefix-hit share — shared prefixes don't re-prefill,
    so the KV feasibility constraint should not charge them twice."""
    if not isinstance(sketch, WorkloadSketch):
        sketch = load_workload_sketch(sketch)
    ctx = sketch.ctx_tokens or min(cfg.max_seq_len, 1024)
    # prefix-shared tokens are resident once per tree, not once per
    # request: discount the per-request ctx the memory constraint sees
    ctx = int(ctx - sketch.prompt_p95 * min(1.0, max(0.0,
                                                     sketch.prefix_share)))
    ctx = max(1, min(cfg.max_seq_len, ctx))
    return plan_partition(cfg, model_name, devices, batch=batch, ctx=ctx,
                          profile=profile, plan_version=plan_version)


# -- plan caching (reference ip_module.json/session.json, server.py:805-820)

def save_plan_cache(path: str, plan: PartitionPlan) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(plan.to_json(), f, indent=2)
    os.replace(tmp, path)


def load_cached_plan(path: str, model: str,
                     device_ids: Sequence[str]) -> Optional[PartitionPlan]:
    """Reload a cached plan when it still matches the model AND the exact
    device set (the reference reloads blindly; a changed fleet must replan)."""
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            plan = PartitionPlan.from_json(json.load(f))
    except (ValueError, KeyError, IndexError, TypeError):
        return None  # corrupt/stale cache: fall back to replanning
    if plan.model != model or plan.device_ids != list(device_ids):
        return None
    return plan
