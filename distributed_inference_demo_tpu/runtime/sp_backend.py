"""``serve --sp N``: long-context sequence-parallel serving backend.

The reference has no long-context serving story (its max context is a
single device's attention; SURVEY §5.7 names sequence parallelism a
framework goal).  This backend puts the ring-attention / Ulysses
generate fns (``parallel/sequence.py``, ``parallel/ulysses.py``) behind
the same HTTP surface every other serve mode uses, so a ≥32k-token
request is one POST /generate like any other.

Design notes:

- The sp generate fns bake ``num_new_tokens`` into the jitted program
  (fixed-trip decode scan inside ``shard_map``); the backend caches one
  built fn per requested ``max_new_tokens`` and lets jit re-specialize
  per prompt-length bucket as usual.  Long-context clients typically
  reuse one ``max_new_tokens``, so the cache stays tiny.
- Prompts must arrive padded to a multiple of sp.  That is the same
  rule ``generate --sp`` enforces: silent server-side padding would
  change what the model attends, so a bad length is an HTTP 400
  (``validate_sp_prompt``'s ValueError), never a silent fix-up.
- One request runs at a time (lock): the sp mesh owns every device in
  the group, so concurrent requests would interleave collectives from
  two programs on the same chips.
- The line behind that lock is BOUNDED and VISIBLE: ``/stats`` reports
  ``queue_depth``/``busy``, and a request arriving past
  ``max_queue_depth`` waiting requests is rejected with 429 +
  Retry-After (``SchedulerOverloaded``) instead of blocking silently
  for potentially minutes at 32k context (``DWT_SP_QUEUE_DEPTH`` /
  ``serve --sp-queue-depth``; 0 = unbounded, the old behavior).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Optional

import numpy as np

from ..models.base import ModelConfig
from ..ops.sampling import SamplingParams
from ..parallel.sequence import make_sp_generate_fn, validate_sp_prompt
from ..parallel.ulysses import make_ulysses_generate_fn
from .engine import GenerationResult
from .overload import SchedulerOverloaded

STRATEGIES = ("ring", "ulysses")


class SequenceParallelBackend:
    """Engine-like backend over a local sp mesh for InferenceHTTPServer."""

    def __init__(self, cfg: ModelConfig, params, mesh, *, max_seq: int,
                 strategy: str = "ring",
                 sampling: Optional[SamplingParams] = None,
                 kv_cache_dtype: Optional[str] = None,
                 eos_id: Optional[int] = None,
                 max_queue_depth: Optional[int] = None,
                 kv_layout: Optional[str] = None):
        """``max_queue_depth``: how many requests may WAIT behind the
        one running (the sp mesh serializes requests); one more and the
        arrival is rejected with 429 + Retry-After instead of blocking
        on the device lock unboundedly.  ``None`` defers to
        ``DWT_SP_QUEUE_DEPTH`` (default 8); 0 = unbounded.

        ``kv_layout``: accepted for the universal-paged contract
        (docs/DESIGN.md §14) and surfaced on ``/stats``.  The sp cache
        is per-request scratch INSIDE the fused sequence-sharded
        program — allocated at dispatch, freed when the program
        returns, each chip holding its own ``max_seq/sp`` shard — so
        there is no standing ``batch x max_seq`` reservation for the
        paged layout to convert: both layouts run the same sharded
        program, and the flag records intent instead of being
        rejected."""
        if strategy not in STRATEGIES:
            raise ValueError(f"unknown sp strategy {strategy!r}; "
                             f"known: {STRATEGIES}")
        from .kvcache import resolve_kv_layout
        self.kv_layout = resolve_kv_layout(kv_layout)
        self.cfg = cfg
        self.params = params
        self.mesh = mesh
        self.max_seq = max_seq
        self.strategy = strategy
        self.sampling = sampling
        self.kv_cache_dtype = kv_cache_dtype
        self.eos_id = eos_id
        self.sp = int(mesh.shape["sp"])
        self._fns: "OrderedDict" = OrderedDict()
        self._stream_pair = None
        self._lock = threading.Lock()
        # counters + fn-cache bookkeeping get their OWN lock: generate()
        # holds _lock for the whole device computation (minutes at 32k
        # context), and GET /stats must answer DURING a request, not
        # after it
        self._stats_lock = threading.Lock()
        self._served = 0
        self._decode_seconds = 0.0
        self._tokens_out = 0
        if max_queue_depth is None:
            from ..telemetry._env import env_int
            max_queue_depth = env_int("DWT_SP_QUEUE_DEPTH", 8)
        self.max_queue_depth = max(0, int(max_queue_depth))
        # requests admitted and not yet finished (running + waiting on
        # the device lock) — the /stats queue picture and the 429 bound
        self._active = 0
        # fail at CONSTRUCTION, not at the first request: the generate
        # fns' build-time checks (max_seq % sp, Ulysses head
        # divisibility) run here, so a misconfigured server errors
        # before it ever prints HTTP_READY — a launch mistake must not
        # surface as HTTP 400s blaming the clients
        self._build(1)

    def _build(self, num_new: int):
        make = (make_sp_generate_fn if self.strategy == "ring"
                else make_ulysses_generate_fn)
        return make(self.cfg, self.mesh, max_seq=self.max_seq,
                    num_new_tokens=num_new, sampling=self.sampling,
                    kv_cache_dtype=self.kv_cache_dtype)

    # each distinct max_new_tokens is its own jitted program (the decode
    # scan's trip count is baked in); the cache is LRU-bounded so a
    # client scanning max_new values can't grow compiled programs
    # without limit — evicted variants just recompile on next use
    MAX_COMPILED_VARIANTS = 8

    def _fn(self, num_new: int):
        # called with _lock held (one build at a time); the cache dict
        # itself mutates under _stats_lock so stats() can snapshot it
        # without waiting out a whole generation
        with self._stats_lock:
            fn = self._fns.get(num_new)
            if fn is not None:
                self._fns.move_to_end(num_new)
                return fn
        fn = self._build(num_new)
        with self._stats_lock:
            self._fns[num_new] = fn
            while len(self._fns) > self.MAX_COMPILED_VARIANTS:
                self._fns.popitem(last=False)
        return fn

    def _admit(self):
        """Bounded admission to the one-request-at-a-time queue: past
        ``max_queue_depth`` WAITING requests, reject NOW with 429 +
        Retry-After (estimated from this backend's own measured
        seconds/request) — a client must never discover saturation by
        silently blocking on the device lock for minutes.  Callers pair
        this with ``_leave`` in a finally."""
        with self._stats_lock:
            if (self.max_queue_depth
                    and self._active >= 1 + self.max_queue_depth):
                per_req = (self._decode_seconds / self._served
                           if self._served else 30.0)
                retry = min(600.0, max(1.0, per_req * self._active))
                raise SchedulerOverloaded(
                    f"sp queue full: {self._active - 1} request(s) "
                    f"already waiting behind the running one (bound "
                    f"{self.max_queue_depth}); retry later",
                    retry_after_s=retry, http_code=429)
            self._active += 1

    def _leave(self):
        with self._stats_lock:
            self._active -= 1

    def generate(self, prompt_ids: np.ndarray, max_new_tokens: int,
                 seed: int = 0) -> GenerationResult:
        ids = np.asarray(prompt_ids, dtype=np.int32)
        num_new = int(max_new_tokens)
        # ValueError renders as HTTP 400 with the rule spelled out
        validate_sp_prompt(ids.shape[1], self.sp, self.max_seq, num_new)
        self._admit()
        try:
            return self._generate_admitted(ids, num_new, seed)
        finally:
            self._leave()

    def _generate_admitted(self, ids: np.ndarray, num_new: int,
                           seed: int) -> GenerationResult:
        import jax

        if self.eos_id is not None:
            # eos early stop rides the step-split stream programs (the
            # fused fn has a baked trip count and no eos plumbing):
            # rows past their eos pad with eos, and decode dispatches
            # STOP once every row finished — at long context that skips
            # real compute, not just output.  Stats are recorded by the
            # stream itself.
            box = [0.0]
            steps = list(self._stream(ids, num_new, seed, box))
            toks = np.full((ids.shape[0], num_new), self.eos_id, np.int32)
            toks[:, :len(steps)] = np.stack(steps, axis=1)
            # device-only seconds, like the fused path (wall-clock would
            # fold in lock waits from interleaved streams)
            return GenerationResult(tokens=toks, prompt_len=ids.shape[1],
                                    num_new=num_new, seconds=box[0])
        with self._lock:
            fn = self._fn(num_new)
            t0 = time.perf_counter()
            with self.mesh:
                toks = np.asarray(
                    fn(self.params, ids, jax.random.PRNGKey(seed)))
            dt = time.perf_counter() - t0
        with self._stats_lock:
            self._served += 1
            self._decode_seconds += dt
            self._tokens_out += int(toks.size)
        return GenerationResult(tokens=toks, prompt_len=ids.shape[1],
                                num_new=num_new, seconds=dt)

    # tokens per streaming decode dispatch: large enough to amortize
    # dispatch latency (the block runs as one fused scan), small enough
    # that chunks reach the client every few steps
    STREAM_BLOCK = 8

    def _stream_fns(self):
        """The step-split (prefill_fn, decode_fn) pair — built once; ONE
        compiled pair serves every max_new_tokens (unlike the fused fns,
        which bake their trip count)."""
        if self._stream_pair is None:
            from ..parallel.sequence import make_sp_stream_fns
            from ..parallel.ulysses import make_ulysses_stream_fns
            make = (make_sp_stream_fns if self.strategy == "ring"
                    else make_ulysses_stream_fns)
            self._stream_pair = make(
                self.cfg, self.mesh, max_seq=self.max_seq,
                block=self.STREAM_BLOCK, sampling=self.sampling,
                kv_cache_dtype=self.kv_cache_dtype)
        return self._stream_pair

    def generate_stream(self, prompt_ids: np.ndarray, max_new_tokens: int,
                        seed: int = 0):
        """TRUE incremental sp streaming: one prefill dispatch yields
        token #1 immediately, then each STREAM_BLOCK-token decode
        dispatch yields as it lands — first-token latency is the prefill,
        not the whole generation.  The device lock is taken per DISPATCH
        and released before every yield, so a slow or stalled client
        never blocks other requests; concurrent streams interleave their
        block dispatches (each stream's state buffers are its own).
        Greedy streams are bit-identical to ``generate``; sampled streams
        are equally distributed but draw per-block sub-rngs (the engines'
        streaming contract).  Validation errors surface on the first
        ``next()`` (a clean 400), like every other backend — and so does
        the bounded-queue rejection (a clean 429, still pre-headers)."""
        yield from self._stream(np.asarray(prompt_ids, np.int32),
                                int(max_new_tokens), seed, [0.0],
                                admit=True)

    def _stream(self, ids: np.ndarray, num_new: int, seed: int,
                device_s_box: list, admit: bool = False):
        """generate_stream's body; ``device_s_box[0]`` accumulates pure
        device-dispatch seconds so the eos ``generate()`` path can report
        the same device-only timing the fused path does (wall-clock would
        fold in lock contention from interleaved streams)."""
        import jax

        validate_sp_prompt(ids.shape[1], self.sp, self.max_seq, num_new)
        if admit:
            # generate_stream entry: the generate() path admitted before
            # calling in (one admission per REQUEST, not per surface)
            self._admit()
        emitted, device_s = 0, 0.0
        try:
            # the device lock is held per DISPATCH, never across a yield:
            # a client that stops reading suspends the generator with the
            # lock RELEASED, so other requests (and streams) keep serving
            # — their programs touch none of this stream's state buffers
            eos = self.eos_id
            done = np.zeros((ids.shape[0],), bool)

            def mask_row_eos(tok):
                """The engines' row-wise eos rule (engine._mask_eos),
                applied host-side between dispatches: finished rows pad
                with eos; returns (masked tok, all rows finished)."""
                nonlocal done
                if eos is None:
                    return tok, False
                tok = np.where(done, eos, tok)
                done = done | (tok == eos)
                return tok, bool(done.all())

            with self._lock:
                pf, dec = self._stream_fns()
                t0 = time.perf_counter()
                with self.mesh:
                    out = pf(self.params, ids, jax.random.PRNGKey(seed))
                device_s += time.perf_counter() - t0
                # flush prefill time immediately: a generation that ends
                # at (or right after) prefill — num_new=1, instant eos —
                # must not report seconds=0 / tokens_per_second NaN
                device_s_box[0] = device_s
            state, rng = list(out[:-1]), out[-1]
            tok, stop = mask_row_eos(np.asarray(state[-1]))
            yield tok                               # token #1
            emitted = 1
            while emitted < num_new and not stop:
                rng, sub = jax.random.split(rng)
                with self._lock:
                    t0 = time.perf_counter()
                    with self.mesh:
                        out = dec(self.params, *state, sub)
                    device_s += time.perf_counter() - t0
                    device_s_box[0] = device_s
                state, toks = list(out[:-1]), np.asarray(out[-1])
                # per-dispatch width comes from the COMPILED program's
                # output, not the mutable STREAM_BLOCK attribute (the
                # cached pair keeps its build-time block forever)
                take = min(toks.shape[1], num_new - emitted)
                for j in range(take):
                    tok, stop = mask_row_eos(toks[:, j])
                    yield tok
                    emitted += 1
                    if stop:
                        break
        finally:
            # an abandoned stream (client disconnect, gen.close()) still
            # spent device time and emitted tokens: count what happened.
            # A stream that failed before its first token counts nothing,
            # matching generate()'s success-only accounting.  The box is
            # flushed here too so the caller's timing is complete however
            # the generator exits (eos mid-block, close, failure).
            device_s_box[0] = device_s
            if admit:
                self._leave()
            if emitted:
                with self._stats_lock:
                    self._served += 1
                    self._decode_seconds += device_s
                    self._tokens_out += emitted * ids.shape[0]

    def stats(self) -> dict:
        # _stats_lock only: /stats must answer WHILE a long-context
        # request holds the generation lock — that is exactly when a
        # client needs the queue picture
        with self._stats_lock:
            return {
                "mode": "sequence_parallel",
                "strategy": self.strategy,
                "sp": self.sp,
                "max_seq": self.max_seq,
                # per-request sequence-sharded scratch either way (see
                # __init__): recorded so a fleet scrape can assert the
                # resolved layout uniformly across serve modes
                "kv_layout": self.kv_layout,
                "requests_served": self._served,
                "tokens_out": self._tokens_out,
                "seconds_generating": round(self._decode_seconds, 3),
                "compiled_max_new_variants": sorted(self._fns),
                # the line behind the one-request-at-a-time device lock:
                # how deep it is, whether a request is running, and the
                # bound past which arrivals get 429 (0 = unbounded)
                "queue_depth": max(0, self._active - 1),
                "busy": self._lock.locked(),
                "queue_bound": self.max_queue_depth,
            }

    def reset_stats(self) -> None:
        with self._stats_lock:
            self._served = 0
            self._decode_seconds = 0.0
            self._tokens_out = 0
