"""The gateway HTTP process: cache-aware proxy over N engine replicas.

A standalone :class:`GatewayHTTPServer` (docs/DESIGN.md §16) speaking
the same surface as ``runtime/http_server.py`` — ``/health``,
``/stats``, ``/metrics``, ``/debugz``, ``/trace`` — plus the one route
that matters: ``/generate``, proxied to the replica the
:class:`~.router.PrefixAwareRouter` picks.  Being the fleet's front
door, it also serves the fleet-wide observability surfaces
(docs/DESIGN.md §7):

- ``GET /metrics/fleet`` — every replica's ``/metrics`` re-labeled
  with ``replica="host:port"`` and merged with the gateway's own
  registry (:class:`~.federation.FleetScraper`: debounced, bounded
  staleness);
- ``GET /trace/fleet`` — every replica's ``/trace`` export stitched
  with the gateway's proxy spans into ONE Chrome trace; a request's
  gateway ``route``/``proxy`` spans, its engine spans, and any
  migration spans share the ``X-DWT-Trace-Id`` the gateway minted, so
  Perfetto shows the whole cross-process story on one lane.

Proxy contract (the hard-won parts):

- **one-shot body read, streamed response**: the request body is read
  once and replayed verbatim on retry; the replica's response streams
  through line-by-line (replicas emit chunked JSONL), so the gateway
  adds one line of latency, not one response of buffering.
- **retry before first token only**: a replica that dies (connect
  refused, socket reset, anything but a clean HTTP status) before the
  gateway has forwarded ANY body byte is struck in the registry and
  the request is replayed on the next candidate — bounded by
  ``retry_limit``.  The instant one byte has been forwarded the
  gateway never REPLAYS: the client has seen output, and a verbatim
  replay could duplicate it.
- **resume after first token**: a mid-stream death (severed chunked
  stream, transport error, or the replica's own ``{"error": ...}``
  line) is instead RESUMED on a survivor (docs/DESIGN.md §23): the
  gateway journals every *complete* delivered JSONL line, re-routes
  through the prefix-aware router, and re-POSTs the original body
  plus ``{"resume": {"delivered_tokens": [...], "rng_step_offset":
  N}}`` so the survivor replays the delivered prefix silently and
  streams the suffix bit-identically — bounded by ``resume_limit``
  (0 disables).  Only when resume is exhausted (or the request shape
  is ineligible: multi-row, logprobs, stop, image, resume disabled)
  does the client get the ``{"error": ...}`` JSONL line + clean
  termination (the exact contract engines use for their own
  mid-stream failures) — the documented post-resume fallback, never
  a hang.  A torn trailing fragment (line without ``\n``) is never
  forwarded: the client and the journal both end at the last
  complete line.
- **federated admission**: a replica's own ``503/429 + Retry-After``
  (runtime/overload.py) propagates to the client verbatim — the
  replica already said precisely what the client should do.  Every
  candidate down → the gateway's own
  :class:`~..overload.GatewayOverloaded` 503.
- **tracing**: every proxied request carries ``X-DWT-Trace-Id``; the
  replica echoes it and logs it to its flight recorder
  (runtime/http_server.py), and the gateway records ``route`` +
  ``proxy`` spans under the same id — one trace id covers
  gateway→replica, exported at ``GET /trace`` (and stitched with the
  replicas' engine/migration spans at ``GET /trace/fleet``).
- **tenant identity**: a ``tenant`` body field or ``X-DWT-Tenant``
  header rides the proxy hop as ``X-DWT-Tenant`` so the replica's SLO
  ledger (telemetry/slo.py) attributes the request's goodput to the
  right tenant.
"""

from __future__ import annotations

import json
import threading
import time
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ...telemetry import catalog as _catalog
from ...telemetry import metrics as _m
from ...telemetry import profiling as _profiling
from ...telemetry.flightrecorder import get_flight_recorder
from ...telemetry.tracing import (SpanClock, TraceRecorder,
                                  merge_chrome_traces, new_trace_id,
                                  to_chrome_trace)
from ..overload import GatewayOverloaded, SchedulerOverloaded
from .federation import FleetScraper

_HOP_HEADERS = {"transfer-encoding", "connection", "keep-alive",
                "content-length"}


class _ReplicaDied(RuntimeError):
    """The replica failed without producing a clean HTTP response (or
    its stream broke before the first body byte was forwarded)."""


class GatewayHTTPServer:
    """Threaded HTTP gateway over a registry + router pair."""

    def __init__(self, registry, router, host: str = "127.0.0.1",
                 port: int = 0, *, retry_limit: int = 1,
                 resume_limit: int = 1,
                 proxy_timeout_s: Optional[float] = None,
                 fleet_scrape_interval_s: float = 1.0,
                 fleet_max_stale_s: float = 30.0,
                 metrics_fetcher=None, sketch_fetcher=None):
        """``retry_limit``: additional replicas tried after the routed
        one dies before first token.  ``resume_limit``: mid-stream
        failover attempts after the first token (each re-routes the
        journaled request to a survivor with a ``resume`` payload;
        0 disables and restores the error-line-only contract).
        ``proxy_timeout_s``: per-socket
        timeout on replica connections (None = no deadline; streams
        with long decode gaps need None or a generous value).
        ``fleet_scrape_interval_s`` / ``fleet_max_stale_s`` /
        ``metrics_fetcher``: the ``/metrics/fleet`` federation knobs
        (see :class:`~.federation.FleetScraper`).  ``sketch_fetcher``:
        injectable ``(rid, host, port) -> dict`` for the federated
        ``GET /sketch`` (tests run it socket-free; None = HTTP)."""
        self.registry = registry
        self.router = router
        self.retry_limit = max(0, int(retry_limit))
        self.resume_limit = max(0, int(resume_limit))
        self.proxy_timeout_s = proxy_timeout_s
        self._sketch_fetcher = sketch_fetcher
        self.tracer = TraceRecorder("gateway")
        self.fleet = FleetScraper(
            registry, min_interval_s=fleet_scrape_interval_s,
            max_stale_s=fleet_max_stale_s, fetcher=metrics_fetcher)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):   # quiet by default
                pass

            # bounded route labels, same rule as the replica server
            _ROUTES = frozenset((
                "/health", "/stats", "/metrics", "/metrics/fleet",
                "/trace", "/trace/fleet", "/debugz", "/sketch",
                "/generate", "/drain"))

            def _json(self, code: int, obj: dict,
                      headers: Optional[dict] = None) -> None:
                route = self.path.split("?")[0]
                if route not in self._ROUTES:
                    route = "other"
                _catalog.HTTP_REQUESTS.inc(route=route, code=str(code))
                body = json.dumps(obj).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _shed(self, e: SchedulerOverloaded) -> None:
                _catalog.GATEWAY_SHED.inc()
                get_flight_recorder().record("gateway_shed",
                                             reason=str(e)[:256])
                self._json(getattr(e, "http_code", 503),
                           {"error": str(e)},
                           headers={"Retry-After":
                                    str(max(1, int(e.retry_after_s)))})

            def _text(self, code: int, text: str) -> None:
                route = self.path.split("?")[0]
                if route not in self._ROUTES:
                    route = "other"
                _catalog.HTTP_REQUESTS.inc(route=route, code=str(code))
                body = text.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; "
                                 "charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?")[0]
                if path == "/metrics":
                    try:
                        self._text(200, _catalog.scrape())
                    except Exception as e:
                        self._text(500, f"# scrape error: {e}\n")
                elif path == "/metrics/fleet":
                    try:
                        self._text(200, outer.fleet.scrape_fleet(
                            _catalog.scrape))
                    except Exception as e:
                        self._text(500, f"# fleet scrape error: {e}\n")
                elif path == "/trace/fleet":
                    try:
                        self._json(200, outer._fleet_trace())
                    except Exception as e:
                        self._json(500, {"error": str(e)})
                elif path == "/sketch":
                    # federated workload sketch (§20): merged across up
                    # replicas, served as CANONICAL bytes (re-dumping
                    # through _json would break byte-determinism)
                    try:
                        body = _profiling.render_sketch(
                            outer._fleet_sketch()).encode("utf-8")
                        _catalog.HTTP_REQUESTS.inc(route="/sketch",
                                                   code="200")
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/json")
                        self.send_header("Content-Length",
                                         str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    except Exception as e:
                        self._json(500, {"error": str(e)})
                elif path == "/health":
                    ups = outer.registry.up_replicas()
                    routable = outer.registry.routable_replicas()
                    self._json(200, {
                        "status": "ok" if routable else "degraded",
                        "role": "gateway",
                        "replicas_up": len(ups),
                        "replicas_routable": len(routable),
                        "replicas": outer.registry.replica_ids(),
                    })
                elif path == "/stats":
                    self._json(200, outer.stats())
                elif path == "/trace":
                    self._json(200, to_chrome_trace(outer.tracer.drain()))
                elif path == "/debugz":
                    try:
                        self._json(200, outer._debugz())
                    except Exception as e:
                        self._json(500, {"error": str(e)})
                else:
                    self._json(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path not in ("/generate", "/drain"):
                    self._json(404, {"error": f"no route {self.path}"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    raw = self.rfile.read(n) or b"{}"
                    req = json.loads(raw)
                except (ValueError, KeyError) as e:
                    self._json(400, {"error": str(e)})
                    return
                if self.path == "/drain":
                    self._json(*outer._handle_drain(req))
                    return
                try:
                    outer._proxy_generate(self, raw, req)
                except SchedulerOverloaded as e:
                    self._shed(e)
                except Exception as e:
                    self._json(500, {"error": str(e)})

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self.httpd.server_address
        self._thread: Optional[threading.Thread] = None

    # -- the proxy ---------------------------------------------------------

    @staticmethod
    def _routing_tokens(req: dict):
        """The token key the router matches on: the first prompt row.
        Text prompts have no gateway-side tokens (no tokenizer here) —
        they ride the hash fallback keyed on the text bytes."""
        ids = req.get("prompt_ids")
        if ids is None:
            prompt = req.get("prompt")
            if isinstance(prompt, str) and prompt:
                # stable per-byte pseudo-tokens: equal texts share hash
                # and prefix keys without a tokenizer
                return [b for b in prompt.encode("utf-8")[:256]]
            return None
        try:
            row = ids[0] if ids and isinstance(ids[0], list) else ids
            return [int(t) for t in row]
        except (TypeError, ValueError):
            return None

    @staticmethod
    def _make_journal(req: dict, tokens, tenant) -> Optional[dict]:
        """Arm a resume journal iff the request shape supports
        bit-identical resumption: a streaming single-row request with
        no logprobs/stop/image sidecars (those change the line schema
        or the replica-side replay contract).  Ineligible shapes keep
        today's error-line-only mid-stream semantics."""
        if not req.get("stream"):
            return None
        if req.get("logprobs") or req.get("stop") or \
                req.get("image") is not None:
            return None
        ids = req.get("prompt_ids")
        if isinstance(ids, list) and ids and isinstance(ids[0], list) \
                and len(ids) > 1:
            return None     # multi-row batch: one journal can't split it
        return {"body": dict(req), "tokens": [],
                "routing_tokens": tokens, "tenant": tenant,
                "dead": set(), "eligible": True}

    def _proxy_generate(self, handler, raw: bytes, req: dict) -> None:
        tokens = self._routing_tokens(req)
        trace_id = new_trace_id()
        tenant = req.get("tenant") or handler.headers.get("X-DWT-Tenant")
        tenant = str(tenant) if tenant else None
        journal = (self._make_journal(req, tokens, tenant)
                   if self.resume_limit > 0 else None)
        get_flight_recorder().record(
            "gateway_admit", trace_id=f"{trace_id:016x}",
            tenant=tenant or "default")
        route_clock = SpanClock()
        decision = self.router.route(tokens)    # raises GatewayOverloaded
        get_flight_recorder().record(
            "gateway_route", replica=decision.rid,
            policy=decision.policy, match_tokens=decision.match_tokens,
            trace_id=f"{trace_id:016x}")
        route_span = self.tracer.record(
            "gateway.route", trace_id, clock=route_clock,
            replica=decision.rid, policy=decision.policy,
            match_tokens=decision.match_tokens)

        candidates = [decision.rid] + decision.candidates[:self.retry_limit]
        ttft_clock = SpanClock()
        last_err: Optional[Exception] = None
        for attempt, rid in enumerate(candidates):
            if attempt > 0:
                if not self.registry.is_up(rid):
                    continue
                _catalog.GATEWAY_RETRIED.inc()
                get_flight_recorder().record(
                    "gateway_retry", replica=rid, attempt=attempt,
                    trace_id=f"{trace_id:016x}")
            self.router.acquire(rid)
            proxy_clock = SpanClock()
            try:
                done = self._proxy_once(handler, rid, raw, trace_id,
                                        ttft_clock, decision, attempt,
                                        tenant=tenant, journal=journal)
            except _ReplicaDied as e:
                last_err = e
                self.registry.record_failure(rid, reason=str(e))
                continue
            finally:
                self.router.release(rid)
                self.tracer.record(
                    "gateway.proxy", trace_id, parent_id=route_span,
                    clock=proxy_clock, replica=rid, attempt=attempt)
            if done and tokens and decision.policy in (
                    "prefix", "host_tier", "hash"):
                # the replica now holds this prompt's blocks: teach the
                # index so the NEXT request sharing the prefix sticks
                # (a host_tier route lands here too — the promote puts
                # the prefix back in the replica's DEVICE tree, so the
                # next hit is an ordinary prefix route)
                self.router.record(rid, tokens)
            return
        raise GatewayOverloaded(
            "request failed on every candidate replica before first "
            f"token (tried {len(candidates)}; last error: {last_err})",
            retry_after_s=self.registry.retry_after_hint())

    @staticmethod
    def _journal_line(journal: dict, line: bytes) -> bool:
        """Fold one complete forwarded JSONL line into the resume
        journal.  Returns False when the line is the replica's own
        ``{"error": ...}`` report — the caller treats that as a
        mid-stream death (resume seam #3) instead of forwarding it.
        A line the journal cannot account for (unparseable, batched
        multi-token) permanently disarms resume for this request."""
        try:
            obj = json.loads(line)
        except Exception:
            journal["eligible"] = False
            return True
        if not isinstance(obj, dict):
            journal["eligible"] = False
            return True
        if "error" in obj:
            return False
        toks = obj.get("tokens")
        if isinstance(toks, list):
            if len(toks) == 1:
                try:
                    journal["tokens"].append(int(toks[0]))
                except (TypeError, ValueError):
                    journal["eligible"] = False
            elif len(toks) > 1:
                journal["eligible"] = False
        return True

    def _forward_stream(self, resp, chunkfn, journal, rid: str):
        """Forward JSONL lines from ``resp`` through ``chunkfn`` until
        the stream ends.  Returns ``(status, detail)``: ``"done"``
        (clean terminating chunk), ``"client_gone"`` (OUR client
        closed — nothing left to do), or ``"died"`` (severed stream,
        transport error, torn trailing fragment, or — when a journal
        is armed and eligible — the replica's own error line, which
        is intercepted so a resume can replace it)."""
        while True:
            try:
                line = resp.readline()
            except Exception as e:
                return "died", f"stream error: {e}"
            if not line:
                # readline() reports a SEVERED chunked stream as a
                # clean EOF: http.client's peek swallows the
                # IncompleteRead AND closes the response, so read()
                # cannot re-raise either.  The one surviving signal is
                # chunk_left — a clean termination walks through the
                # 0-chunk and leaves it None; a replica that died
                # without it leaves 0 (or the unread remainder)
                if resp.chunk_left is not None:
                    return "died", ("chunked stream severed before "
                                    "the terminating chunk")
                return "done", None
            if not line.endswith(b"\n"):
                # torn fragment: never forward a partial JSONL line —
                # the client and the journal both end at the last
                # COMPLETE line (resume's correctness precondition)
                return "died", "stream severed mid-line"
            if journal is not None and journal["eligible"] and \
                    not self._journal_line(journal, line):
                return "died", f"replica {rid} reported mid-stream error"
            try:
                chunkfn(line)
            except OSError:
                return "client_gone", None

    def _proxy_once(self, handler, rid: str, raw: bytes, trace_id: int,
                    ttft_clock: SpanClock, decision, attempt: int,
                    tenant: Optional[str] = None,
                    journal: Optional[dict] = None) -> bool:
        """Proxy one attempt to ``rid``.  Returns True on a 2xx the
        client fully received; raises :class:`_ReplicaDied` when safe
        to retry (no body byte forwarded); propagates replica HTTP
        errors (including 503/429 shedding) as final answers.  A
        mid-stream death with ``journal`` armed hands off to
        :meth:`_resume_stream` before falling back to the error
        line."""
        host, port = self.registry.endpoint(rid)
        conn = HTTPConnection(host, port, timeout=self.proxy_timeout_s)
        try:
            headers = {
                "Content-Type": "application/json",
                "X-DWT-Trace-Id": f"{trace_id:016x}",
            }
            if tenant:
                # tenant rides the hop so the replica's SLO ledger
                # books this request under the right tenant even when
                # the body carried it as a header-only hint
                headers["X-DWT-Tenant"] = tenant[:64]
            try:
                conn.request("POST", "/generate", body=raw,
                             headers=headers)
                resp = conn.getresponse()
            except Exception as e:
                raise _ReplicaDied(f"{rid}: {e}") from e

            if resp.status in (503, 429):
                # federated admission: the replica's shed is the
                # answer — propagate its Retry-After verbatim
                _catalog.GATEWAY_SHED.inc()
                get_flight_recorder().record(
                    "gateway_shed", reason=f"replica {rid} shed "
                    f"({resp.status})", trace_id=f"{trace_id:016x}")
                body = resp.read()
                retry_after = resp.getheader("Retry-After") or "1"
                handler._json(resp.status,
                              _safe_json(body),
                              headers={"Retry-After": retry_after})
                return False
            if resp.status != 200:
                handler._json(resp.status, _safe_json(resp.read()))
                return False

            self.registry.record_success(rid)
            chunked = (resp.getheader("Transfer-Encoding", "")
                       .lower() == "chunked")
            if not chunked:
                body = resp.read()
                _catalog.GATEWAY_PROXY_TTFT_SECONDS.observe(
                    ttft_clock.seconds)
                _catalog.HTTP_REQUESTS.inc(route="/generate", code="200")
                handler.send_response(200)
                ct = resp.getheader("Content-Type", "application/json")
                handler.send_header("Content-Type", ct)
                handler.send_header("Content-Length", str(len(body)))
                handler.send_header("X-DWT-Replica", rid)
                handler.end_headers()
                handler.wfile.write(body)
                return True

            # streaming: forward JSONL lines as our own chunked body.
            # Pull the FIRST line before committing to 200 (a replica
            # that dies pre-first-token must stay retryable).
            try:
                first = resp.readline()
            except Exception as e:
                raise _ReplicaDied(f"{rid}: stream died before first "
                                   f"token: {e}") from e
            if not first:
                raise _ReplicaDied(f"{rid}: empty stream before first "
                                   "token")
            if not first.endswith(b"\n"):
                # torn before the first complete line: nothing has
                # been forwarded, so this stays an ordinary retry
                raise _ReplicaDied(f"{rid}: stream severed mid-line "
                                   "before first token")
            if journal is not None and not self._journal_line(journal,
                                                              first):
                # the replica's FIRST line is already an error report:
                # zero tokens delivered, nothing to resume — forward
                # it verbatim like any other line
                journal["eligible"] = False
            _catalog.GATEWAY_PROXY_TTFT_SECONDS.observe(ttft_clock.seconds)
            _catalog.HTTP_REQUESTS.inc(route="/generate", code="200")
            handler.send_response(200)
            handler.send_header("Content-Type", "application/jsonl")
            handler.send_header("Transfer-Encoding", "chunked")
            handler.send_header("X-DWT-Replica", rid)
            handler.end_headers()

            def chunk(data: bytes) -> None:
                handler.wfile.write(f"{len(data):x}\r\n".encode())
                handler.wfile.write(data + b"\r\n")

            try:
                chunk(first)
            except OSError:
                return True      # our client went away; nothing to do
            status, detail = self._forward_stream(resp, chunk, journal,
                                                  rid)
            if status == "died":
                # replica died MID-stream, after first token: never
                # replayed verbatim (the client saw output).  Resume
                # on a survivor when the journal allows it
                # (docs/DESIGN.md §23); the error line is the
                # post-resume fallback
                self.registry.record_failure(rid, reason="mid-stream")
                resumed = False
                if journal is not None and journal["eligible"] and \
                        journal["tokens"]:
                    journal["dead"].add(rid)
                    resumed = self._resume_stream(chunk, journal,
                                                  trace_id)
                    if not resumed:
                        _catalog.GATEWAY_RESUME_EXHAUSTED.inc()
                if not resumed:
                    try:
                        chunk((json.dumps(
                            {"error": f"replica {rid} died mid-stream: "
                                      f"{detail}"}) + "\n").encode())
                    except OSError:
                        return True
            elif status == "client_gone":
                return True
            try:
                chunk(b"")
                handler.wfile.flush()
            except OSError:
                pass
            return True
        finally:
            conn.close()

    # -- mid-stream failover (docs/DESIGN.md §23) --------------------------

    def _resume_stream(self, chunkfn, journal: dict,
                       trace_id: int) -> bool:
        """Bounded mid-stream failover: re-route the journaled request
        and re-POST it with a ``resume`` payload so a survivor replays
        the delivered prefix silently and streams the suffix
        bit-identically.  Returns True when a survivor finished the
        stream (the client saw delivered prefix + resumed suffix, no
        repeats, gaps, or torn lines); False when attempts are
        exhausted and the caller falls back to the error line."""
        flight = get_flight_recorder()
        for attempt in range(1, self.resume_limit + 1):
            if not (journal["eligible"] and journal["tokens"]):
                return False
            _catalog.GATEWAY_RESUME_ATTEMPTS.inc()
            ttf_clock = SpanClock()
            try:
                decision = self.router.route(journal["routing_tokens"])
            except Exception:
                return False    # nothing routable: fall back now
            cands = [r for r in [decision.rid] + decision.candidates
                     if r not in journal["dead"]
                     and self.registry.is_up(r)]
            if not cands:
                return False
            rid = cands[0]
            flight.record(
                "gateway_resume", replica=rid, attempt=attempt,
                delivered=len(journal["tokens"]),
                trace_id=f"{trace_id:016x}")
            body = dict(journal["body"])
            body["resume"] = {
                "delivered_tokens": [int(t) for t in journal["tokens"]],
                "rng_step_offset": len(journal["tokens"]),
            }
            raw = json.dumps(body).encode("utf-8")
            self.router.acquire(rid)
            span_clock = SpanClock()
            try:
                ok = self._resume_once(rid, raw, chunkfn, journal,
                                       trace_id, ttf_clock)
            finally:
                self.router.release(rid)
                self.tracer.record(
                    "gateway.resume", trace_id, clock=span_clock,
                    replica=rid, attempt=attempt)
            if ok:
                _catalog.GATEWAY_RESUME_SUCCEEDED.inc()
                if journal["routing_tokens"]:
                    # the survivor now holds prompt + stream blocks
                    self.router.record(rid, journal["routing_tokens"])
                flight.record("gateway_resume_done", replica=rid,
                              trace_id=f"{trace_id:016x}")
                return True
            journal["dead"].add(rid)
            self.registry.record_failure(
                rid, reason=f"resume attempt {attempt} failed")
        return False

    def _resume_once(self, rid: str, raw: bytes, chunkfn,
                     journal: dict, trace_id: int,
                     ttf_clock: SpanClock) -> bool:
        """One resume attempt against ``rid``.  The client's 200 +
        chunked framing is already committed, so every failure mode
        here returns False (try the next survivor / fall back) rather
        than raising — nothing may reach the client except complete
        resumed JSONL lines."""
        host, port = self.registry.endpoint(rid)
        conn = HTTPConnection(host, port, timeout=self.proxy_timeout_s)
        try:
            headers = {
                "Content-Type": "application/json",
                "X-DWT-Trace-Id": f"{trace_id:016x}",
            }
            if journal["tenant"]:
                headers["X-DWT-Tenant"] = journal["tenant"][:64]
            try:
                conn.request("POST", "/generate", body=raw,
                             headers=headers)
                resp = conn.getresponse()
            except Exception:
                return False
            if resp.status != 200:
                resp.read()
                return False
            if (resp.getheader("Transfer-Encoding", "")
                    .lower() != "chunked"):
                return False    # resume is a streaming-only contract
            self.registry.record_success(rid)
            try:
                first = resp.readline()
            except Exception:
                return False
            if not first or not first.endswith(b"\n"):
                return False
            _catalog.GATEWAY_RESUME_TTF_SECONDS.observe(ttf_clock.seconds)
            if not self._journal_line(journal, first):
                return False    # survivor's replay failed loudly
            try:
                chunkfn(first)
            except OSError:
                return True     # our client went away; nothing to do
            status, _detail = self._forward_stream(resp, chunkfn,
                                                   journal, rid)
            return status in ("done", "client_gone")
        finally:
            conn.close()

    # -- drain control -----------------------------------------------------

    def _handle_drain(self, req: dict) -> tuple:
        """``POST /drain {"replica": rid, "draining": bool}``: flip the
        registry's drain flag.  Routing changes take effect on the next
        :meth:`~.router.PrefixAwareRouter.route` call; in-flight
        proxies are untouched.  Moving the replica's requests off is
        the migration controller's job, not the gateway's."""
        rid = req.get("replica")
        if not isinstance(rid, str) or rid not in self.registry.replica_ids():
            return 400, {"error": f"unknown replica {rid!r}",
                         "replicas": self.registry.replica_ids()}
        flag = bool(req.get("draining", True))
        get_flight_recorder().record("gateway_drain", replica=rid,
                                     draining=flag)
        self.registry.set_draining(rid, flag)
        return 200, {"replica": rid, "draining": flag,
                     "routable": self.registry.routable_replicas()}

    # -- fleet observability -----------------------------------------------

    def _fleet_trace(self) -> dict:
        """``GET /trace/fleet``: drain the gateway's own spans, drain
        every up replica's ``/trace`` export, and stitch them into one
        Chrome trace (``merge_chrome_traces`` renumbers pids so each
        process keeps its own track).  A replica that fails to export
        just misses from this stitch — its spans survive locally until
        its next ``/trace`` drain, so nothing is lost, only deferred."""
        traces = [to_chrome_trace(self.tracer.drain())]
        for rid in self.registry.up_replicas():
            host, port = self.registry.endpoint(rid)
            conn = HTTPConnection(host, port,
                                  timeout=self.proxy_timeout_s or 5.0)
            try:
                conn.request("GET", "/trace")
                resp = conn.getresponse()
                body = resp.read()
                if resp.status != 200:
                    continue
                t = json.loads(body)
                if isinstance(t, dict):
                    traces.append(t)
            except Exception:
                continue
            finally:
                conn.close()
        return merge_chrome_traces(traces)

    def _fleet_sketch(self) -> dict:
        """``GET /sketch``: every up replica's workload-sketch artifact,
        merged deterministically (``profiling.merge_sketches`` sorts by
        replica id and sums fixed-edge histograms bin-wise).  A replica
        that fails to serve — or serves a foreign schema version — is
        listed in ``dropped_replicas`` instead of poisoning the merge."""
        sections = []
        for rid in self.registry.up_replicas():
            try:
                host, port = self.registry.endpoint(rid)
                if self._sketch_fetcher is not None:
                    obj = self._sketch_fetcher(rid, host, port)
                else:
                    conn = HTTPConnection(
                        host, port, timeout=self.proxy_timeout_s or 5.0)
                    try:
                        conn.request("GET", "/sketch")
                        resp = conn.getresponse()
                        body = resp.read()
                        if resp.status != 200:
                            continue
                        obj = json.loads(body)
                    finally:
                        conn.close()
            except Exception:
                continue
            if isinstance(obj, dict):
                sections.append((rid, obj))
        return _profiling.merge_sketches(sections)

    def _fleet_slo(self) -> dict:
        """Per-replica SLO summaries, as last reported over the health
        probe (engine ``stats()`` includes its SLO ledger summary, and
        the prober stores the whole stats dict)."""
        out = {}
        for rid in self.registry.replica_ids():
            try:
                slo = self.registry.get(rid).last_stats.get("slo")
            except KeyError:
                continue
            if isinstance(slo, dict):
                out[rid] = slo
        return out

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        ups = self.registry.up_replicas()
        return {
            "role": "gateway",
            "replicas_up": len(ups),
            "replicas": self.registry.debug_state()["replicas"],
            "routing": self.router.routing_table(),
        }

    def _debugz(self) -> dict:
        from ...telemetry import flightrecorder, postmortem
        return {
            "flight": flightrecorder.debug_state(),
            "registry": self.registry.debug_state(),
            "routing": self.router.routing_table(),
            "postmortem": postmortem.debug_state(),
            "fleet_slo": self._fleet_slo(),
            "federation": self.fleet.debug_state(),
        }

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self.registry.start()
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def serve_forever(self) -> None:
        self.registry.start()
        try:
            self.httpd.serve_forever()
        finally:
            self.registry.stop()

    def shutdown(self) -> None:
        self.registry.stop()
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=10)
            self._thread = None


def _safe_json(body: bytes) -> dict:
    try:
        out = json.loads(body)
        return out if isinstance(out, dict) else {"error": str(out)}
    except Exception:
        return {"error": body.decode("utf-8", "replace")[:512]}


# re-exported for callers that only import the server module
__all__ = ["GatewayHTTPServer", "GatewayOverloaded"]
