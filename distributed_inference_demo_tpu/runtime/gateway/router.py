"""Prefix-aware request routing over the replica fleet.

The router answers ONE question per request — which replica most
likely already holds this prompt's prefix in its radix tree — using
only gateway-side state (docs/DESIGN.md §16):

- **routing-history index**: per replica, a bounded block-granular
  token-prefix index built from what the gateway itself routed there.
  Recording a prompt inserts one key per ``block_tokens``-sized prefix
  (the same granularity the replicas' radix trees match at, so the
  gateway's estimate and the replica's actual hit agree structurally);
  matching walks the prompt's block prefixes longest-first.  LRU
  bounded per replica — the index is a ROUTING HINT, not a mirror of
  the replica's cache: a dropped entry costs one hashed route, never a
  wrong answer.
- **decision**: route to the replica with the longest match at or
  above ``min_prefix_tokens`` (ties break toward the lighter replica);
  otherwise fall back to rendezvous (highest-random-weight) hashing of
  the first prefix block with BOUNDED LOAD — a hashed pick whose
  in-flight count exceeds ``load_factor`` x the fleet mean skips to
  the next candidate in rendezvous order, so one hot key cannot bury
  one replica while others idle.
- **reconciliation**: replica-reported ``dwt_kvcache_*`` stats (riding
  the registry's ``/stats`` probes) guard the estimate — a replica
  whose radix tree emptied (restart, eviction storm) gets its index
  flushed instead of attracting traffic for prefixes it no longer
  holds.  A readmitted replica is flushed the same way.
- **host-tier second chance** (docs/DESIGN.md §21): when no replica's
  device-tier index matches enough prefix, the router consults the
  demoted-prefix digests replicas publish in ``/stats`` (the tiered-KV
  host ring's newest chain digests, 64-bit-truncated).  A replica
  whose HOST tier holds the prefix promotes it back for one h2d adopt
  instead of re-prefilling — cheaper than the hash fallback's cold
  replica.  Unlike the routing-history index this is replica-REPORTED
  state (probe-fresh, capped), so it sits strictly between the prefix
  policy and the hash fallback, never above the device-tier estimate.

Everything is in-process state under one lock; the router never opens
a socket (the registry probes, the server proxies).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

from ...telemetry import catalog as _catalog
from ..overload import GatewayOverloaded


def _digest(key: bytes) -> int:
    return int.from_bytes(hashlib.sha1(key).digest()[:8], "big")


class RouteDecision:
    """Why a request went where it went (surfaced on /debugz and in
    trace span args)."""

    __slots__ = ("rid", "policy", "match_tokens", "candidates")

    def __init__(self, rid: str, policy: str, match_tokens: int,
                 candidates: List[str]):
        self.rid = rid
        self.policy = policy            # "prefix" | "host_tier" | "hash"
        self.match_tokens = match_tokens
        # alternates for retry-before-first-token, preference order
        self.candidates = candidates


class PrefixAwareRouter:
    """Cache-aware routing with consistent-hash fallback (see module
    docstring)."""

    def __init__(self, registry, *, min_prefix_tokens: int = 16,
                 block_tokens: int = 16, max_index_entries: int = 4096,
                 max_key_tokens: int = 512, load_factor: float = 2.0,
                 prefill_token_weight: int = 256,
                 spec_token_weight: int = 256):
        if min_prefix_tokens < 1:
            raise ValueError("min_prefix_tokens must be >= 1")
        if block_tokens < 1:
            raise ValueError("block_tokens must be >= 1")
        if prefill_token_weight < 0:
            raise ValueError("prefill_token_weight must be >= 0")
        if spec_token_weight < 0:
            raise ValueError("spec_token_weight must be >= 0")
        self.registry = registry
        self.min_prefix_tokens = min_prefix_tokens
        self.block_tokens = block_tokens
        self.max_index_entries = max_index_entries
        self.max_key_tokens = max_key_tokens
        self.load_factor = load_factor
        # prefill-backlog weighting: N pending prompt tokens count as
        # one queued request in the bounded-load check (0 = ignore the
        # backlog, depth-only load as before ISSUE-15)
        self.prefill_token_weight = prefill_token_weight
        # speculative-backlog weighting (docs/DESIGN.md §22): the same
        # scale for the replica-reported Σ (K_row + 1) · decode_block
        # per-iteration spec spend — a replica mid-speculation has less
        # budget headroom than its queue depth shows (0 = ignore)
        self.spec_token_weight = spec_token_weight
        self._lock = threading.Lock()
        # rid -> OrderedDict[prefix-key-bytes, n_tokens] (LRU: move on
        # touch, evict oldest past the cap)
        self._index: Dict[str, "OrderedDict[bytes, int]"] = {}
        self._inflight: Dict[str, int] = {}
        self._routed: Dict[str, int] = {}
        self._prefix_hits: Dict[str, int] = {}
        # last replica-reported radix occupancy, for reconciliation
        self._replica_nodes: Dict[str, int] = {}
        # last replica-reported demoted-prefix digest (§21 host tier):
        # rid -> {"block_tokens": int, "digests": frozenset of 64-bit
        # hex strings} — replica-owned truth, replaced wholesale on
        # every /stats probe, so it never needs LRU bookkeeping here
        self._tier_index: Dict[str, dict] = {}
        registry.on_readmit = self.flush_replica
        registry.on_stats = self.reconcile

    # -- index plumbing ----------------------------------------------------

    def _keys(self, tokens: Sequence[int]) -> List[Tuple[bytes, int]]:
        """Block-granular prefix keys for ``tokens``: one ``(digest,
        n_tokens)`` per whole leading block, longest first, capped at
        ``max_key_tokens``."""
        toks = [int(t) for t in tokens[:self.max_key_tokens]]
        bt = self.block_tokens
        out = []
        h = hashlib.sha1()
        bound = (len(toks) // bt) * bt
        # build incrementally (one pass), then reverse for longest-first
        pos = 0
        for end in range(bt, bound + 1, bt):
            for t in toks[pos:end]:
                h.update(t.to_bytes(8, "big", signed=True))
            pos = end
            out.append((h.digest(), end))
        out.reverse()
        return out

    def record(self, rid: str, tokens: Sequence[int]) -> None:
        """Learn that ``tokens`` was served by ``rid`` (called after a
        successful proxy: the replica now holds the prefix)."""
        keys = self._keys(tokens)
        if not keys:
            return
        with self._lock:
            idx = self._index.setdefault(rid, OrderedDict())
            # shortest-first so the LONGEST (most specific) keys are the
            # newest entries and survive the LRU trim below
            for key, n in reversed(keys):
                if key in idx:
                    idx.move_to_end(key)
                else:
                    idx[key] = n
            while len(idx) > self.max_index_entries:
                idx.popitem(last=False)
            n_entries = len(idx)
        _catalog.GATEWAY_INDEX_ENTRIES.set(n_entries, replica=rid)

    def match_tokens(self, rid: str, tokens: Sequence[int]) -> int:
        """Longest indexed prefix of ``tokens`` on ``rid``, in tokens."""
        with self._lock:
            idx = self._index.get(rid)
            if not idx:
                return 0
            for key, n in self._keys(tokens):
                if key in idx:
                    idx.move_to_end(key)
                    return n
        return 0

    def tier_match_tokens(self, rid: str, tokens: Sequence[int]) -> int:
        """Longest prefix of ``tokens`` whose chain digest appears in
        ``rid``'s reported demoted-prefix digest, in tokens.

        Recomputed at the REPLICA's block granularity (its pool may run
        a different ``block_tokens`` than the router default) with the
        replica's 64-bit truncation — byte-compatible with
        ``kvcache.tiered.chain_digests`` / ``TieredKVStore.digest()``.
        Deepest boundary wins; the run needn't be contiguous here (the
        replica's promote walks contiguity itself; a gap just means a
        shorter actual promote — a hint being optimistic is fine)."""
        with self._lock:
            info = self._tier_index.get(rid)
        if not info:
            return 0
        bt = info["block_tokens"]
        digests = info["digests"]
        toks = [int(t) for t in tokens[:self.max_key_tokens]]
        best = 0
        h = hashlib.sha1()
        pos = 0
        for end in range(bt, (len(toks) // bt) * bt + 1, bt):
            for t in toks[pos:end]:
                h.update(t.to_bytes(8, "big", signed=True))
            pos = end
            if h.hexdigest()[:16] in digests:
                best = end
        return best

    def flush_replica(self, rid: str) -> None:
        """Drop the routing history for ``rid`` (readmission after an
        outage: its cache state is unknown — re-learn from scratch)."""
        with self._lock:
            self._index.pop(rid, None)
            self._replica_nodes.pop(rid, None)
            self._tier_index.pop(rid, None)
        _catalog.GATEWAY_INDEX_ENTRIES.set(0, replica=rid)

    def reconcile(self, rid: str, stats: dict) -> None:
        """Guard the estimate against replica-side cache resets: if the
        replica's reported radix tree shrank to (near) nothing while
        the gateway still holds history for it, flush the history —
        routing on prefixes the replica evicted would send traffic to
        a cold cache on purpose."""
        kv = stats.get("kvcache") or {}
        tier = kv.get("tier") or {}
        digests = tier.get("digest")
        if digests is not None:
            bt = int(tier.get("block_tokens", self.block_tokens))
            with self._lock:
                if digests:
                    self._tier_index[rid] = {
                        "block_tokens": max(1, bt),
                        "digests": frozenset(str(d) for d in digests)}
                else:
                    # empty digest = nothing demoted (or tier closed):
                    # stop second-chancing onto this replica
                    self._tier_index.pop(rid, None)
        nodes = kv.get("nodes", kv.get("tree_nodes"))
        if nodes is None:
            return
        nodes = int(nodes)
        with self._lock:
            prev = self._replica_nodes.get(rid)
            self._replica_nodes[rid] = nodes
            has_history = bool(self._index.get(rid))
        if (has_history and prev is not None and nodes == 0 and prev > 0):
            self.flush_replica(rid)
            self._replica_nodes[rid] = nodes

    # -- load accounting ---------------------------------------------------

    def acquire(self, rid: str) -> None:
        with self._lock:
            self._inflight[rid] = self._inflight.get(rid, 0) + 1

    def release(self, rid: str) -> None:
        with self._lock:
            self._inflight[rid] = max(0, self._inflight.get(rid, 0) - 1)

    def _load(self, rid: str) -> float:
        """In-flight proxies plus the replica's last reported queue
        depth — the gateway's own concurrency signal reacts instantly,
        the probed depth covers traffic from other gateways — plus the
        reported prefill backlog scaled to request units: two replicas
        at equal depth are NOT equally loaded when one still has tens
        of thousands of prompt tokens to chew through before its queue
        moves (docs/DESIGN.md §19)."""
        load = (self._inflight.get(rid, 0)
                + self.registry.queue_depth(rid))
        if self.prefill_token_weight:
            load += (self.registry.pending_prefill_tokens(rid)
                     / float(self.prefill_token_weight))
        if self.spec_token_weight:
            # spec backlog (§22): speculating rows eat the replica's
            # per-iteration token budget the same way a prefill backlog
            # does — fold it in at its own scale
            load += (self.registry.spec_backlog_tokens(rid)
                     / float(self.spec_token_weight))
        return load

    # -- the decision ------------------------------------------------------

    def route(self, tokens: Optional[Sequence[int]]) -> RouteDecision:
        """Pick a replica for ``tokens`` (None/empty = no routing key:
        straight to the hash fallback with an empty key).  Raises
        :class:`GatewayOverloaded` when no replica is admitted."""
        ups = self.registry.routable_replicas()
        if not ups:
            raise GatewayOverloaded(
                "no replica is admitted to routing (all evicted by the "
                "health debounce or draining)", retry_after_s=2.0)
        toks = list(tokens) if tokens is not None else []

        best_rid, best_len = None, 0
        with self._lock:
            loads = {rid: self._inflight.get(rid, 0) for rid in ups}
        for rid in ups:
            n = self.match_tokens(rid, toks)
            if n > best_len or (n == best_len and n > 0 and best_rid
                                and loads[rid] < loads[best_rid]):
                best_rid, best_len = rid, n

        # rendezvous order over the first prefix block: stable under
        # membership churn (only keys owned by a removed replica move)
        key = b"".join(int(t).to_bytes(8, "big", signed=True)
                       for t in toks[:self.block_tokens])
        ranked = sorted(
            ups, key=lambda rid: _digest(key + rid.encode()), reverse=True)

        # host-tier second chance: no device-tier estimate is good
        # enough, but some replica REPORTS the prefix demoted in its
        # host ring — promotion beats the hash pick's re-prefill
        tier_rid, tier_len = None, 0
        if best_len < self.min_prefix_tokens and toks:
            for rid in ups:
                n = self.tier_match_tokens(rid, toks)
                if n > tier_len or (n == tier_len and n > 0 and tier_rid
                                    and loads[rid] < loads[tier_rid]):
                    tier_rid, tier_len = rid, n

        if best_rid is not None and best_len >= self.min_prefix_tokens:
            chosen, policy, match = best_rid, "prefix", best_len
            _catalog.GATEWAY_PREFIX_ROUTED.inc()
        elif tier_rid is not None and tier_len >= self.min_prefix_tokens:
            chosen, policy, match = tier_rid, "host_tier", tier_len
            _catalog.GATEWAY_TIER_ROUTED.inc()
        else:
            chosen, policy, match = ranked[0], "hash", 0
            # bounded load: a hashed pick may be busy while the fleet
            # idles — skip down the rendezvous order past overloaded
            # candidates (never past the last one: SOME replica serves)
            with self._lock:
                mean = (sum(self._load(r) for r in ups) / len(ups))
                bound = self.load_factor * (1.0 + mean)
                for rid in ranked:
                    if self._load(rid) <= bound:
                        chosen = rid
                        break
            _catalog.GATEWAY_HASHED.inc()

        with self._lock:
            self._routed[chosen] = self._routed.get(chosen, 0) + 1
            if policy == "prefix":
                self._prefix_hits[chosen] = (
                    self._prefix_hits.get(chosen, 0) + 1)
            routed = self._routed[chosen]
            hits = self._prefix_hits.get(chosen, 0)
        _catalog.GATEWAY_PREFIX_HIT_RATIO.set(
            hits / routed if routed else 0.0, replica=chosen)

        alternates = [r for r in ranked if r != chosen]
        return RouteDecision(chosen, policy, match, alternates)

    # -- introspection -----------------------------------------------------

    def routing_table(self) -> dict:
        """The /debugz dump: per-replica index occupancy + decision
        counters (bounded: sizes and counts, never the keys)."""
        with self._lock:
            rids = set(self._index) | set(self._routed) | set(
                self.registry.replica_ids())
            return {
                "min_prefix_tokens": self.min_prefix_tokens,
                "block_tokens": self.block_tokens,
                "load_factor": self.load_factor,
                "prefill_token_weight": self.prefill_token_weight,
                "spec_token_weight": self.spec_token_weight,
                "replicas": {
                    rid: {
                        "up": self.registry.is_up(rid),
                        "draining": self.registry.is_draining(rid),
                        "index_entries": len(self._index.get(rid, ())),
                        "routed": self._routed.get(rid, 0),
                        "prefix_routed": self._prefix_hits.get(rid, 0),
                        "inflight": self._inflight.get(rid, 0),
                        "pending_prefill_tokens":
                            self.registry.pending_prefill_tokens(rid),
                        "spec_backlog_tokens":
                            self.registry.spec_backlog_tokens(rid),
                        "replica_tree_nodes":
                            self._replica_nodes.get(rid),
                        "tier_digest_entries": len(
                            (self._tier_index.get(rid) or {})
                            .get("digests", ())),
                    } for rid in sorted(rids)},
            }
