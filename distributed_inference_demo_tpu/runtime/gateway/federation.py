"""Gateway metrics federation: one fleet-wide ``/metrics`` page.

Each replica already serves its own Prometheus text exposition
(``runtime/http_server.py`` → ``telemetry/catalog.scrape``).  The
gateway is the one process that knows the whole fleet, so it federates:
:class:`FleetScraper` pulls every registered replica's ``/metrics``
over the same host:port channel the registry's health prober uses,
re-labels every sample with ``replica="host:port"``, and merges the
sections with the gateway's own registry into a single page served at
``GET /metrics/fleet``.

The operational contract (docs/DESIGN.md §7):

- **debounced**: replica fetches are rate-limited to ``min_interval_s``
  per replica — a dashboard refreshing ``/metrics/fleet`` at 10 Hz must
  not turn the gateway into a load generator against its own fleet.
- **bounded staleness**: a failed fetch serves the replica's last good
  text for up to ``max_stale_s`` (counted on
  ``dwt_gateway_fleet_failed_scrapes_total``); beyond that the section
  degrades to an explanatory comment — silently-frozen counters from a
  dead replica are worse than a visible hole.
- **age is a metric**: ``dwt_gateway_fleet_scrape_age_seconds`` says
  how stale each replica's section is, so the staleness itself is
  alertable.
- **family-merged output**: sections are parsed into metric families
  and merged so each ``# HELP``/``# TYPE`` header appears once and all
  of a family's samples (gateway's own, un-relabeled, plus every
  replica's) stay contiguous — strict exposition parsers accept the
  result.

The fetcher is injectable (same pattern as the registry's ``prober``)
so federation is testable without sockets.
"""

from __future__ import annotations

import threading
import time
from http.client import HTTPConnection
from typing import Callable, Dict, List, Optional, Tuple

from ...telemetry import catalog as _catalog


def http_metrics_fetcher(timeout_s: float = 2.0):
    """Default fetcher: ``GET /metrics`` on the replica, decoded text.
    Raises on transport errors or non-200 (the scraper counts the
    raise, not the reason — same rule as ``http_stats_prober``)."""

    def fetch(host: str, port: int) -> str:
        conn = HTTPConnection(host, port, timeout=timeout_s)
        try:
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                raise RuntimeError(f"/metrics returned {resp.status}")
            return body.decode("utf-8", "replace")
        finally:
            conn.close()

    return fetch


# -- exposition text surgery -----------------------------------------------

def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"')


def relabel_sample(line: str, rid: str) -> str:
    """Inject ``replica="rid"`` into one sample line.

    ``name{a="b"} v`` gains a leading label; ``name v`` gains a label
    set.  The injected label goes FIRST so it cannot land inside an
    existing label's (escaped-quote-bearing) value — everything after
    the first ``{`` is untouched."""
    tag = f'replica="{_escape_label(rid)}"'
    brace = line.find("{")
    space = line.find(" ")
    if brace != -1 and (space == -1 or brace < space):
        rest = line[brace + 1:]
        sep = "" if rest.startswith("}") else ","
        return f"{line[:brace]}{{{tag}{sep}{rest}"
    if space == -1:
        return line          # malformed; pass through untouched
    return f"{line[:space]}{{{tag}}}{line[space:]}"


def parse_families(text: str) -> "List[Tuple[str, dict]]":
    """Parse one exposition page into ordered ``(family_name, fam)``
    pairs, ``fam = {"help": line|None, "type": line|None,
    "samples": [line, ...]}``.

    Family attribution follows the renderer's grouping: samples after a
    ``# HELP``/``# TYPE`` header belong to that family until the next
    header (histogram ``_bucket``/``_sum``/``_count`` children resolve
    to their base family for free).  A headerless sample keys on its
    own metric name — good enough to merge foreign exporters."""
    fams: Dict[str, dict] = {}
    order: List[str] = []
    current: Optional[str] = None

    def fam(name: str) -> dict:
        if name not in fams:
            fams[name] = {"help": None, "type": None, "samples": []}
            order.append(name)
        return fams[name]

    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) < 3:
                continue
            name = parts[2]
            f = fam(name)
            key = "help" if parts[1] == "HELP" else "type"
            if f[key] is None:
                f[key] = line
            current = name
        elif line.startswith("#"):
            continue                      # other comments don't merge
        else:
            if current is not None:
                name = current
            else:
                end = min(x for x in (line.find("{"), line.find(" "))
                          if x != -1) if ("{" in line or " " in line) \
                    else len(line)
                name = line[:end]
            fam(name)["samples"].append(line)
    return [(n, fams[n]) for n in order]


def merge_exposition(sections: "List[Tuple[Optional[str], str]]") -> str:
    """Merge ``(replica_id_or_None, exposition_text)`` sections into one
    page.  ``None`` marks the gateway's own section (samples pass
    through un-relabeled); every other section's samples gain
    ``replica="rid"``.  Headers dedup first-wins; families keep the
    order of first appearance; each family's samples stay contiguous."""
    merged: Dict[str, dict] = {}
    order: List[str] = []
    for rid, text in sections:
        for name, f in parse_families(text):
            if name not in merged:
                merged[name] = {"help": None, "type": None, "samples": []}
                order.append(name)
            m = merged[name]
            m["help"] = m["help"] or f["help"]
            m["type"] = m["type"] or f["type"]
            if rid is None:
                m["samples"].extend(f["samples"])
            else:
                m["samples"].extend(relabel_sample(s, rid)
                                    for s in f["samples"])
    out: List[str] = []
    for name in order:
        m = merged[name]
        if m["help"]:
            out.append(m["help"])
        if m["type"]:
            out.append(m["type"])
        out.extend(m["samples"])
    return "\n".join(out) + ("\n" if out else "")


# -- the scraper -----------------------------------------------------------

class _Cached:
    __slots__ = ("text", "checked_at", "fetched_at")

    def __init__(self) -> None:
        self.text: Optional[str] = None    # last GOOD exposition text
        self.checked_at = -1e18            # last fetch ATTEMPT (debounce)
        self.fetched_at = -1e18            # last fetch SUCCESS (staleness)


class FleetScraper:
    """Debounced, staleness-bounded per-replica ``/metrics`` cache (see
    module docstring).  One instance lives on the gateway server and is
    hit from its request-handler threads — all cache state is under one
    lock, but fetches happen OUTSIDE it so one slow replica cannot
    serialize the others' cache hits."""

    def __init__(self, registry, *, min_interval_s: float = 1.0,
                 max_stale_s: float = 30.0, timeout_s: float = 2.0,
                 clock: Callable[[], float] = time.monotonic,
                 fetcher: Optional[Callable[[str, int], str]] = None):
        self.registry = registry
        self.min_interval_s = min_interval_s
        self.max_stale_s = max_stale_s
        self._clock = clock
        self._fetch = fetcher or http_metrics_fetcher(timeout_s)
        self._lock = threading.Lock()
        self._cache: Dict[str, _Cached] = {}

    def scrape_fleet(self, own_text) -> str:
        """One federated page: the gateway's ``own_text`` plus every
        registered replica's section (fresh, debounce-cached, stale, or
        a hole comment).  ``own_text`` may be a callable rendering the
        gateway's registry — it runs AFTER the replica pulls so the
        fleet scrape/failure counters this very render just moved are
        already visible in the gateway section."""
        replica_sections: List[Tuple[Optional[str], str]] = []
        holes: List[str] = []
        for rid in self.registry.replica_ids():
            text = self._replica_text(rid)
            if text is None:
                holes.append(f"# replica {rid}: no scrape within "
                             f"{self.max_stale_s:g}s (section dropped)")
            else:
                replica_sections.append((rid, text))
        own = own_text() if callable(own_text) else own_text
        page = merge_exposition([(None, own)] + replica_sections)
        if holes:
            page += "\n".join(holes) + "\n"
        return page

    def _replica_text(self, rid: str) -> Optional[str]:
        now = self._clock()
        with self._lock:
            c = self._cache.setdefault(rid, _Cached())
            fresh = now - c.checked_at < self.min_interval_s
            if not fresh:
                c.checked_at = now       # claim the slot: concurrent
                # handler threads inside the debounce window reuse the
                # cache instead of dogpiling the replica
        if not fresh:
            try:
                host, port = self.registry.endpoint(rid)
                text = self._fetch(host, port)
            except Exception:
                _catalog.GATEWAY_FLEET_SCRAPE_FAILURES.inc(replica=rid)
            else:
                _catalog.GATEWAY_FLEET_SCRAPES.inc(replica=rid)
                with self._lock:
                    c.text, c.fetched_at = text, self._clock()
        with self._lock:
            if c.text is None:
                return None              # never scraped: no age to report
            age = max(0.0, now - c.fetched_at)
            _catalog.GATEWAY_FLEET_SCRAPE_AGE.set(round(age, 3),
                                                  replica=rid)
            return c.text if age <= self.max_stale_s else None

    def debug_state(self) -> dict:
        now = self._clock()
        with self._lock:
            return {rid: {"age_s": (round(now - c.fetched_at, 3)
                                    if c.text is not None else None),
                          "cached": c.text is not None}
                    for rid, c in self._cache.items()}
