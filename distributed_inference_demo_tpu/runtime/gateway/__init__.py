"""Prefix-aware replicated serving gateway (docs/DESIGN.md §16).

A standalone process (``cli.py gateway``) spreading ``/generate``
traffic across N independent engine replicas, each a full
``runtime/http_server.py`` stack.  Three pieces:

- :class:`ReplicaRegistry` — health-checked membership with
  sustain+cooldown debounce (``registry.py``);
- :class:`PrefixAwareRouter` — cache-aware routing from the gateway's
  own routing history, rendezvous-hash-with-bounded-load fallback
  (``router.py``);
- :class:`GatewayHTTPServer` — the HTTP process and streaming proxy
  with retry-before-first-token (``server.py``).

The gateway holds no engine and no jax: it imports only the telemetry
layer and ``runtime/overload.py``, so it runs anywhere a socket does.
"""

from .registry import Replica, ReplicaRegistry, http_stats_prober
from .router import PrefixAwareRouter, RouteDecision
from .server import GatewayHTTPServer

__all__ = [
    "Replica",
    "ReplicaRegistry",
    "http_stats_prober",
    "PrefixAwareRouter",
    "RouteDecision",
    "GatewayHTTPServer",
]
