"""Replica registry: health-checked membership for the serving gateway.

One :class:`ReplicaRegistry` owns the gateway's view of the replica
fleet (docs/DESIGN.md §16).  Each replica is an independent engine
process speaking the ``runtime/http_server.py`` surface; the registry
probes its ``/stats`` endpoint (queue depth + kvcache occupancy ride
along free) and debounces membership the same way the anomaly detector
debounces breaches (telemetry/anomaly.py):

- **eviction**: ``sustain`` CONSECUTIVE probe (or proxy) failures evict
  a replica from routing — one dropped connection is a blip, a streak
  is an outage.  Eviction bumps ``dwt_gateway_replica_down_total`` and
  the flight recorder.
- **readmission**: a probe success readmits an evicted replica only
  after ``readmit_cooldown_s`` has elapsed since eviction — a flapping
  process must prove a quiet period, not a single lucky accept.  The
  readmission hook lets the router drop its routing-history index for
  the replica when the replica's own cache came back empty.

Failures come from two doors with ONE streak: the background prober and
``record_failure`` calls from the proxy path (a replica that hangs up
mid-handshake is evidence exactly like a failed probe).  The clock and
the prober are injectable so the debounce is testable without sockets
or sleeps.

Orthogonal to health is **draining** (docs/DESIGN.md §18): an operator
(or the migration controller) marks a replica draining and it leaves
:meth:`ReplicaRegistry.routable_replicas` — no NEW request routes to it
— without burning an eviction strike or touching its in-flight streams.
Surfaced on ``/debugz`` and the ``dwt_gateway_draining_replicas``
gauge.
"""

from __future__ import annotations

import json
import threading
import time
from http.client import HTTPConnection
from typing import Callable, Dict, List, Optional

from ...telemetry import catalog as _catalog
from ...telemetry.flightrecorder import get_flight_recorder

#: bounded failure-reason vocabulary for
#: ``dwt_gateway_replica_failures_total{reason=...}`` — free-text
#: reasons (exception strings) collapse onto these so the label set
#: cannot grow with error-message cardinality
FAILURE_REASONS = ("probe", "proxy", "mid-stream", "resume", "other")


def classify_failure_reason(reason: str) -> str:
    """Collapse a free-text failure reason onto the bounded vocabulary:
    the prober passes ``probe: ...``, the resume loop ``resume``/
    ``resume: ...``, the mid-stream seam ``mid-stream``; any other
    non-empty text is a pre-first-token proxy failure."""
    r = (reason or "").lower()
    if r.startswith("probe"):
        return "probe"
    if "mid-stream" in r:
        return "mid-stream"
    if r.startswith("resume"):
        return "resume"
    if r:
        return "proxy"
    return "other"


def http_stats_prober(timeout_s: float = 2.0):
    """Default prober: ``GET /stats`` on the replica, parsed JSON.
    Raises on any transport error or non-200 — the registry counts the
    raise, not the reason (a refused connect and a wedged accept loop
    are the same outage to a router)."""

    def probe(host: str, port: int) -> dict:
        conn = HTTPConnection(host, port, timeout=timeout_s)
        try:
            conn.request("GET", "/stats")
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                raise RuntimeError(f"/stats returned {resp.status}")
            return json.loads(body)
        finally:
            conn.close()

    return probe


class Replica:
    """One replica's registry row (mutated only under the registry
    lock)."""

    __slots__ = ("rid", "host", "port", "up", "draining", "fail_streak",
                 "down_at", "last_stats", "probes", "failures")

    def __init__(self, rid: str, host: str, port: int):
        self.rid = rid
        self.host = host
        self.port = int(port)
        self.up = True
        self.draining = False
        self.fail_streak = 0
        self.down_at: Optional[float] = None
        self.last_stats: dict = {}
        self.probes = 0
        self.failures = 0

    @property
    def queue_depth(self) -> int:
        return int(self.last_stats.get("queue_depth", 0))

    @property
    def pending_prefill_tokens(self) -> int:
        return int(self.last_stats.get("pending_prefill_tokens", 0))

    @property
    def spec_backlog_tokens(self) -> int:
        """Per-iteration speculative token cost of the replica's active
        rows — Σ (K_row + 1) · decode_block (docs/DESIGN.md §22); 0 on
        replicas with no speculative proposer armed."""
        return int(self.last_stats.get("spec_backlog_tokens", 0))

    @property
    def kv_tier(self) -> dict:
        """The replica's last-reported §21 tier fragment (empty dict
        when the replica runs no host tier) — occupancy for /debugz,
        demoted-prefix digest for the router's second chance."""
        kv = self.last_stats.get("kvcache") or {}
        return kv.get("tier") or {}


class ReplicaRegistry:
    """Debounced replica membership (see module docstring)."""

    def __init__(self, replicas: List[tuple], *, sustain: int = 3,
                 readmit_cooldown_s: float = 5.0,
                 probe_interval_s: float = 1.0,
                 probe_timeout_s: float = 2.0,
                 clock: Callable[[], float] = time.monotonic,
                 prober: Optional[Callable[[str, int], dict]] = None):
        """``replicas``: ``[(host, port), ...]``.  ``prober(host, port)``
        returns the replica's ``/stats`` dict or raises; ``clock`` is
        monotonic seconds.  Both default to the real thing and are
        injectable for deterministic tests."""
        if not replicas:
            raise ValueError("gateway needs at least one replica")
        if sustain < 1:
            raise ValueError("sustain must be >= 1")
        self.sustain = sustain
        self.readmit_cooldown_s = readmit_cooldown_s
        self.probe_interval_s = probe_interval_s
        self._clock = clock
        self._prober = prober or http_stats_prober(probe_timeout_s)
        self._lock = threading.Lock()
        self._replicas: Dict[str, Replica] = {}
        for host, port in replicas:
            rid = f"{host}:{port}"
            self._replicas[rid] = Replica(rid, host, port)
        # called under no lock after a replica is readmitted — the
        # router hooks this to reconcile/flush its prefix index
        self.on_readmit: Optional[Callable[[str], None]] = None
        # called under no lock after each successful probe with
        # (rid, stats) — the router hooks this for load + kvcache
        # reconciliation
        self.on_stats: Optional[Callable[[str, dict], None]] = None
        # registry-wide failure counts by bounded reason (satellite of
        # docs/DESIGN.md §23): the /debugz twin of
        # dwt_gateway_replica_failures_total
        self.failure_reasons: Dict[str, int] = {
            k: 0 for k in FAILURE_REASONS}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        _catalog.GATEWAY_UP_REPLICAS.set(len(self._replicas))

    # -- membership views --------------------------------------------------

    def replica_ids(self) -> List[str]:
        with self._lock:
            return list(self._replicas)

    def up_replicas(self) -> List[str]:
        with self._lock:
            return [r.rid for r in self._replicas.values() if r.up]

    def routable_replicas(self) -> List[str]:
        """Replicas NEW requests may be routed to: up and not draining.
        Health (``up``) and drain intent are orthogonal — a draining
        replica still probes, still proxies its in-flight streams, and
        still accepts migration traffic; it just stops attracting new
        work."""
        with self._lock:
            return [r.rid for r in self._replicas.values()
                    if r.up and not r.draining]

    def is_up(self, rid: str) -> bool:
        with self._lock:
            r = self._replicas.get(rid)
            return bool(r and r.up)

    def is_draining(self, rid: str) -> bool:
        with self._lock:
            r = self._replicas.get(rid)
            return bool(r and r.draining)

    def set_draining(self, rid: str, flag: bool = True) -> None:
        """Mark/unmark ``rid`` as draining.  NOT a failure strike: the
        replica keeps its health state, keeps probing, and keeps
        serving in-flight requests — it only leaves
        :meth:`routable_replicas` so no NEW request lands on it."""
        with self._lock:
            r = self._replicas.get(rid)
            if r is None or r.draining == flag:
                return
            r.draining = flag
            n_draining = sum(
                1 for x in self._replicas.values() if x.draining)
        _catalog.GATEWAY_DRAINING.set(n_draining)
        get_flight_recorder().record(
            "gateway_replica_draining" if flag
            else "gateway_replica_undrained", replica=rid)

    def get(self, rid: str) -> Replica:
        with self._lock:
            return self._replicas[rid]

    def endpoint(self, rid: str) -> tuple:
        with self._lock:
            r = self._replicas[rid]
            return r.host, r.port

    def queue_depth(self, rid: str) -> int:
        with self._lock:
            return self._replicas[rid].queue_depth

    def pending_prefill_tokens(self, rid: str) -> int:
        with self._lock:
            r = self._replicas.get(rid)
            return r.pending_prefill_tokens if r is not None else 0

    def spec_backlog_tokens(self, rid: str) -> int:
        with self._lock:
            r = self._replicas.get(rid)
            return r.spec_backlog_tokens if r is not None else 0

    # -- the debounce ------------------------------------------------------

    def record_failure(self, rid: str, reason: str = "") -> None:
        """One failure strike (probe or proxy).  At ``sustain``
        consecutive strikes an up replica is evicted."""
        evicted = False
        label = classify_failure_reason(reason)
        with self._lock:
            r = self._replicas.get(rid)
            if r is None:
                return
            r.failures += 1
            r.fail_streak += 1
            self.failure_reasons[label] += 1
            if r.up and r.fail_streak >= self.sustain:
                r.up = False
                r.down_at = self._clock()
                evicted = True
                n_up = sum(1 for x in self._replicas.values() if x.up)
        _catalog.GATEWAY_REPLICA_FAILURES.inc(reason=label)
        if evicted:
            _catalog.GATEWAY_REPLICA_DOWN.inc()
            _catalog.GATEWAY_UP_REPLICAS.set(n_up)
            get_flight_recorder().record(
                "gateway_replica_down", replica=rid,
                reason=reason or "failure streak")

    def record_success(self, rid: str, stats: Optional[dict] = None) -> None:
        """A successful probe: clears the streak; readmits a down
        replica once the cooldown has elapsed."""
        readmitted = False
        with self._lock:
            r = self._replicas.get(rid)
            if r is None:
                return
            r.fail_streak = 0
            if stats is not None:
                r.last_stats = stats
            if (not r.up and r.down_at is not None
                    and self._clock() - r.down_at
                    >= self.readmit_cooldown_s):
                r.up = True
                r.down_at = None
                readmitted = True
            n_up = sum(1 for x in self._replicas.values() if x.up)
        if readmitted:
            _catalog.GATEWAY_REPLICA_UP.inc()
            _catalog.GATEWAY_UP_REPLICAS.set(n_up)
            get_flight_recorder().record("gateway_replica_up", replica=rid)
            if self.on_readmit is not None:
                self.on_readmit(rid)
        if stats is not None:
            _catalog.GATEWAY_QUEUE_DEPTH.set(
                int(stats.get("queue_depth", 0)), replica=rid)
            if self.on_stats is not None:
                self.on_stats(rid, stats)

    def retry_after_hint(self, default_s: float = 2.0,
                         floor_s: float = 1.0) -> float:
        """How long a shed client should back off: the smallest
        readmit-cooldown remainder over the DOWN replicas (floored at
        ``floor_s`` — a sub-second hint rounds to an instant hammer),
        or ``default_s`` when nothing is down (the shed was load, not
        membership, and no cooldown clock says otherwise)."""
        now = self._clock()
        with self._lock:
            remains = [
                max(0.0, self.readmit_cooldown_s - (now - r.down_at))
                for r in self._replicas.values()
                if not r.up and r.down_at is not None]
        if not remains:
            return default_s
        return max(floor_s, min(remains))

    def probe_all(self) -> None:
        """One probe round over every replica (up AND down — a down
        replica's successful probes are what readmit it)."""
        for rid in self.replica_ids():
            with self._lock:
                r = self._replicas.get(rid)
                if r is None:
                    continue
                host, port = r.host, r.port
                r.probes += 1
            try:
                stats = self._prober(host, port)
            except Exception as e:
                self.record_failure(rid, reason=f"probe: {e}")
            else:
                self.record_success(rid, stats)

    # -- background prober -------------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._probe_loop,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            self.probe_all()
            self._stop.wait(self.probe_interval_s)

    # -- introspection -----------------------------------------------------

    def debug_state(self) -> dict:
        with self._lock:
            return {
                "sustain": self.sustain,
                "readmit_cooldown_s": self.readmit_cooldown_s,
                "failure_reasons": dict(self.failure_reasons),
                "replicas": {
                    r.rid: {"up": r.up, "draining": r.draining,
                            "fail_streak": r.fail_streak,
                            "probes": r.probes, "failures": r.failures,
                            "queue_depth": r.queue_depth,
                            "down_for_s": (round(self._clock() - r.down_at, 3)
                                           if r.down_at is not None else None),
                            # §21 tier occupancy (bounded: counts and
                            # bytes, never the digest list itself)
                            "kv_tier": ({k: r.kv_tier.get(k, 0)
                                         for k in ("host_blocks",
                                                   "host_resident_bytes",
                                                   "disk_blocks",
                                                   "disk_resident_bytes")}
                                        if r.kv_tier else None)}
                    for r in self._replicas.values()},
            }
