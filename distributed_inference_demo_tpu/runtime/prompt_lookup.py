"""Prompt-lookup (n-gram) speculative decoding: draft-FREE speculation.

Speculative decoding needs a proposer that is much cheaper than the target
model.  A small draft model (runtime/speculative.py) is one choice; this
module uses an even cheaper one: **the text itself**.  Generated text
constantly re-uses spans of its own context — quoted input, repeated
entities, code identifiers, summarized passages — so "find where the
current n-gram last occurred and propose the tokens that followed it"
(prompt lookup / PLD) gets high acceptance on exactly the workloads where
decode throughput matters, at zero extra weights and zero extra HBM
traffic for the proposer.

TPU-first shape of the idea:

- The token history (prompt + emitted) lives on device as a fixed
  ``[b, cap]`` buffer riding the round scan's carry; matching is a masked
  vectorized compare + argmax over positions — pure VPU work, fused into
  the same compiled program as the verify forward.  No host round-trip
  per round.
- Proposal scoring prefers a bigram match over a unigram match, and the
  latest occurrence within each class (score = 2*bigram + unigram,
  tie-broken by position, one argmax).
- Verification / lockstep advance / cache rollback are exactly the
  draft-model machinery: ONE prefill-shaped target forward over the K
  proposals, the standard rejection rule with the proposer treated as a
  one-hot distribution (accept d with prob p(d); on rejection resample
  from p with d masked out — the max(p - q, 0) rule specialized to
  q = one-hot), bonus token after K accepts.  Greedy mode is bit-exact
  vs target-only decode (pinned by tests).

The reference has no analog (one token per ring trip); this composes with
the same engine surface as everything else (``generate`` /
``generate_stream``, ``serve --prompt-lookup``).
"""

import time
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.base import (KVCache, ModelConfig, StageParams,
                           StageSpec, pad_cache_capacity)
from ..models.decoder import stage_forward
from ..ops.flash_attention import make_flash_attn_impl
from ..ops.sampling import SamplingParams, sample_logits
from .engine import GenerationResult, check_capacity
from .speculative import (SpecStats, drain_round_blocks, emit_stream_block,
                          init_done, mask_after_eos, pad_to_width,
                          verify_emit)


def ngram_propose(history: jnp.ndarray, hist_len: jnp.ndarray,
                  num_draft: int) -> jnp.ndarray:
    """[b, K] proposals from the latest bigram/unigram match over a
    [b, cap] token-history buffer with per-row valid lengths.

    For each row: score position j by 2*(bigram match ending at j) +
    (history[j] == last token), require j < hist_len - 1 (the match must
    have a following token inside the valid region), take the
    highest-scoring latest j, and propose the K tokens after it.  Score 0
    everywhere degenerates to j = cap-1, whose clamped gather proposes
    the last token repeated — verification makes any bad proposal merely
    useless, never wrong.  Shared by PromptLookupEngine's round scan and
    the continuous-batching slot loop (prompt_lookup=True)."""
    cap, K = history.shape[1], num_draft
    pos = jnp.arange(cap)[None, :]                    # [1, cap]
    last = jnp.take_along_axis(
        history, (hist_len - 1)[:, None], axis=1)     # [b, 1]
    prev = jnp.take_along_axis(
        history, jnp.maximum(hist_len - 2, 0)[:, None], axis=1)
    uni = history == last                             # [b, cap]
    prev_hist = jnp.roll(history, 1, axis=1)
    bi = uni & (prev_hist == prev) & (pos > 0)
    valid = pos < (hist_len - 1)[:, None]
    score = (2 * bi + uni) * valid
    # lexicographic (score, position) argmax via score*cap + pos
    j = jnp.argmax(score * cap + pos, axis=1)         # [b]
    idx = j[:, None] + 1 + jnp.arange(K)[None, :]     # [b, K]
    idx = jnp.minimum(idx, hist_len[:, None] - 1)
    return jnp.take_along_axis(history, idx, axis=1).astype(jnp.int32)


class PromptLookupEngine:
    """Draft-free speculative generation over a single-stage model."""

    def __init__(self, cfg: ModelConfig, params: StageParams,
                 max_seq: Optional[int] = None,
                 sampling: SamplingParams = SamplingParams(),
                 num_draft: int = 4,
                 attn_backend: str = "auto",
                 mesh=None,
                 eos_id: Optional[int] = None,
                 kv_cache_dtype=None,
                 prefill_chunk: Optional[int] = None,
                 kv_cache_blocks: Optional[int] = None,
                 kv_block_tokens: Optional[int] = None,
                 kv_layout: Optional[str] = None,
                 kv_dtype: Optional[str] = None):
        """``mesh``: tp mesh — the target forward runs sharded (see
        InferenceEngine); proposal matching stays replicated VPU work.
        ``kv_cache_dtype``: reduced-precision cache storage, same
        contract as InferenceEngine (insert rounds, attention upcasts,
        jnp path forced).  ``prefill_chunk``: C-token chunked prefill
        (engine.run_chunked_prefill semantics; the proposer's history
        buffer is host-seeded from the ids and unaffected).

        ``kv_cache_blocks`` / ``kv_block_tokens`` / ``kv_layout``: the
        block-level KV prefix pool behind the backend seam
        (docs/DESIGN.md §14), batch 1: a prompt sharing whole leading
        blocks with an earlier prefill seeds its cache and prefills only
        the suffix — exactness is a prefill-side property, so it
        composes with the n-gram proposer untouched (the history buffer
        still seeds from the full ids).  Default off (0 blocks); the
        pool is device-resident ("paged" is the only layout — "dense"
        was removed, docs/DESIGN.md §14)."""
        if num_draft < 1:
            raise ValueError("num_draft must be >= 1")
        from .kvcache import resolve_kv_layout
        self.kv_layout = resolve_kv_layout(kv_layout)
        self.cfg, self.params = cfg, params
        self.max_seq = max_seq or cfg.max_seq_len
        self.sampling = sampling
        self.num_draft = num_draft
        self.eos_id = eos_id
        self.spec = StageSpec(0, 1, 0, cfg.num_layers)
        self.mesh = mesh
        from .engine import validate_prefill_chunk
        self.prefill_chunk = validate_prefill_chunk(prefill_chunk,
                                                    self.max_seq)

        from ..parallel.tensor import resolve_tp_attn_backend
        from .engine import resolve_cache_dtype_backend
        tp = mesh.shape.get("tp", 1) if mesh is not None else 1
        attn_backend = resolve_tp_attn_backend(tp, attn_backend)
        self.kv_cache_dtype, attn_backend = resolve_cache_dtype_backend(
            kv_cache_dtype, attn_backend)
        if attn_backend == "auto":
            attn_backend = ("flash" if jax.default_backend() == "tpu"
                            else "jnp")
        attn_impl = (make_flash_attn_impl() if attn_backend == "flash"
                     else None)

        cfg_, spec_, samp_, K = cfg, self.spec, sampling, num_draft
        # history/cache slack per round, sublane-aligned for flash
        cap = pad_cache_capacity(self.max_seq + num_draft + 2)

        from ..parallel.tensor import make_forward_seam
        fwd, self._cache_sharding = make_forward_seam(
            cfg, self.spec, mesh, params, attn_impl=attn_impl)

        @jax.jit
        def prefill(params, ids, cache):
            b, s = ids.shape
            pos = jnp.broadcast_to(jnp.arange(s), (b, s))
            logits, cache = fwd(params, ids, cache, pos, True)
            return logits[:, -1], cache

        def one_round(params, last_tok, cache, history, hist_len, rng):
            b = last_tok.shape[0]
            n = cache.length

            drafts = ngram_propose(history, hist_len, K)   # [b, K]

            verify_in = jnp.concatenate([last_tok[:, None], drafts], axis=1)
            pos = n + jnp.broadcast_to(jnp.arange(K + 1), (b, K + 1))
            t_logits, cache = fwd(params, verify_in, cache, pos,
                                  False)                      # [b, K+1, V]

            # shared rejection rule; q_logits=None = one-hot proposer
            rng, sub_u, sub_x = jax.random.split(rng, 3)
            emitted, m, new_last = verify_emit(t_logits, drafts, None,
                                               samp_, sub_u, sub_x)
            cache = KVCache(cache.keys, cache.values, n + m)
            # history gains the emitted block at positions n+1..; entries
            # past m are garbage that next round's write overlaps, and
            # `propose` masks reads beyond hist_len
            history = jax.lax.dynamic_update_slice(
                history, emitted, (jnp.int32(0), n + 1))
            hist_len = hist_len + m
            return emitted, m, new_last, cache, history, hist_len, rng

        @partial(jax.jit, donate_argnums=(2, 3), static_argnums=(6,))
        def rounds(params, last_tok, cache, history, hist_len, rng,
                   num_rounds):
            def body(carry, _):
                last_tok, cache, history, hist_len, rng = carry
                emitted, m, last_tok, cache, history, hist_len, rng = \
                    one_round(params, last_tok, cache, history, hist_len,
                              rng)
                return (last_tok, cache, history, hist_len, rng), \
                    (emitted, m)

            (last_tok, cache, history, hist_len, rng), (em, ms) = \
                jax.lax.scan(body, (last_tok, cache, history, hist_len,
                                    rng), None, length=num_rounds)
            return em, ms, last_tok, cache, history, hist_len, rng

        self._prefill, self._rounds, self._cap = prefill, rounds, cap
        from .engine import make_chunk_programs
        self._chunk_mid, self._chunk_last = make_chunk_programs(fwd)

        from .kvcache import make_kv_backend
        self.kv_cache = make_kv_backend(
            cfg, kv_cache_blocks, kv_block_tokens, layout=self.kv_layout,
            dtype=self.kv_cache_dtype, kv_dtype=kv_dtype,
            default_blocks=0)

    # ------------------------------------------------------------------

    def _init_state(self, ids: jnp.ndarray, rng):
        """Prefill + first target-sampled token + seeded history buffer —
        the state both generate paths start every run from.  A KV-cache
        hit (backend seam) seeds the cache's leading columns and
        prefills only the suffix; the full prompt is stored back before
        the rounds program donates the cache."""
        b, plen = ids.shape
        cache = KVCache.create(self.cfg, self.cfg.num_layers, b, self._cap,
                               dtype=self.kv_cache_dtype)
        if self._cache_sharding is not None:
            cache = jax.device_put(cache, self._cache_sharding)
        start = 0
        if self.kv_cache is not None:
            start, cache = self.kv_cache.seed(ids, cache)
        from .engine import run_seeded_prefill
        last_logits, cache = run_seeded_prefill(
            self.params, ids, cache, self.prefill_chunk, self.max_seq,
            self._prefill, self._chunk_mid,
            self._chunk_last, start=start)
        if self.kv_cache is not None:
            self.kv_cache.store(ids, cache)
        rng, sub = jax.random.split(rng)
        last_tok = sample_logits(last_logits, sub, self.sampling)
        history = jnp.zeros((b, self._cap), jnp.int32)
        history = jax.lax.dynamic_update_slice(history, ids, (0, 0))
        history = jax.lax.dynamic_update_slice(
            history, last_tok[:, None], (jnp.int32(0), jnp.int32(plen)))
        hist_len = jnp.full((b,), plen + 1, jnp.int32)
        return last_tok, cache, history, hist_len, rng

    def generate(self, prompt_ids: np.ndarray, max_new_tokens: int,
                 seed: int = 0,
                 rounds_per_dispatch: Optional[int] = None
                 ) -> "tuple[GenerationResult, SpecStats]":
        ids = jnp.asarray(prompt_ids, jnp.int32)
        b, plen = ids.shape
        check_capacity(self.max_seq, plen, max_new_tokens)
        R = rounds_per_dispatch or min(8, max(1, max_new_tokens))
        rng = jax.random.PRNGKey(seed)

        t0 = time.perf_counter()
        last_tok, cache, history, hist_len, rng = self._init_state(ids, rng)

        stats = SpecStats()
        first = np.asarray(last_tok)
        out = [first[:, None]]
        done = init_done(first, self.eos_id)
        total = 1
        while total < max_new_tokens and not done.all():
            em, ms, last_tok, cache, history, hist_len, rng = self._rounds(
                self.params, last_tok, cache, history, hist_len, rng, R)
            total = drain_round_blocks(np.asarray(em), np.asarray(ms), out,
                                       stats, self.num_draft, total,
                                       max_new_tokens, self.eos_id, done)

        toks = np.concatenate(out, axis=1)[:, :max_new_tokens]
        toks = mask_after_eos(pad_to_width(toks, max_new_tokens,
                                           self.eos_id), self.eos_id)
        dt = time.perf_counter() - t0
        # actual emitted count, not the eos-padded width (keeps
        # tokens_per_round honest and matches the stream path)
        stats.emitted = min(total, max_new_tokens)
        return (GenerationResult(tokens=toks.astype(np.int32),
                                 prompt_len=plen,
                                 num_new=toks.shape[1], seconds=dt),
                stats)

    def generate_stream(self, prompt_ids: np.ndarray, max_new_tokens: int,
                        seed: int = 0,
                        stats_out: Optional[SpecStats] = None):
        """Yield [batch] token arrays per emitted token; tokens arrive in
        per-round bursts (the speculation win showing through the
        stream).  ``stats_out``, if given, is updated in place."""
        if max_new_tokens <= 0:
            return
        ids = jnp.asarray(prompt_ids, jnp.int32)
        b, plen = ids.shape
        check_capacity(self.max_seq, plen, max_new_tokens)
        rng = jax.random.PRNGKey(seed)
        stats = stats_out if stats_out is not None else SpecStats()
        last_tok, cache, history, hist_len, rng = self._init_state(ids, rng)

        first = np.asarray(last_tok)
        yield first
        done = init_done(first, self.eos_id)
        total = stats.emitted = 1
        while total < max_new_tokens and not done.all():
            em, ms, last_tok, cache, history, hist_len, rng = self._rounds(
                self.params, last_tok, cache, history, hist_len, rng, 1)
            m = int(np.asarray(ms)[0])
            block = np.asarray(em)[0]
            stats.rounds += 1
            stats.drafted += self.num_draft
            stats.accepted += m - 1
            for tok, all_done in emit_stream_block(
                    block, m, done, total, max_new_tokens, self.eos_id,
                    stats):
                yield tok
                if all_done:
                    return
            total += m
            stats.emitted = min(total, max_new_tokens)
