"""Standalone worker process entry point: pipeline stage, or a
disaggregated prefill/decode role.

``--role stage`` (the default) launches one pipeline stage over the
socket transport — the role of the reference's on-device worker runtime
(``BackgroundService`` driving ``Communication.running``, SURVEY.md
§3.2/§3.3) as a plain CLI process.  Used by the multi-process
integration tests and the ``worker`` CLI.

``--role prefill`` / ``--role decode`` launch the disaggregated serving
roles (docs/DESIGN.md §15, runtime/disagg.py): a prefill worker runs
chunked prefill and migrates KV pages over the transport; a decode
worker adopts migrated pages into its continuous-batching engine and
streams tokens back.  Peers (the decode worker / prefill workers / the
coordinator) are dialed with repeatable ``--peer id@host:port`` flags.

Weights come either from a seed (every process derives the same full
parameter set deterministically, then slices its own stage — the test
path, replacing the reference's ONNX-zip shipping) or, in the full
deployment path, from the control plane's artifact channel (cli.py).
"""

from __future__ import annotations

import argparse
import sys


def build_worker(args):
    import jax

    from ..comm.transport import ZmqTransport
    from ..models.base import StageSpec, slice_stage
    from ..models.decoder import init_full_params
    from ..models.registry import get_model_config
    from ..ops.sampling import SamplingParams
    from .distributed import PipelineWorker, StageRuntime

    cfg = get_model_config(args.model)
    if args.dtype:
        cfg = cfg.replace(dtype_name=args.dtype)
    spec = StageSpec(args.stage_id, args.num_stages,
                     args.layer_start, args.layer_end)
    full = init_full_params(jax.random.PRNGKey(args.weights_seed), cfg)
    params = slice_stage(full, cfg, spec)
    sampling = SamplingParams(greedy=True) if args.greedy else \
        SamplingParams(temperature=args.temperature, top_k=args.top_k,
                       min_p=args.min_p)
    # pipeline x tensor parallelism: this stage runs tp-sharded over its
    # host's first N local devices; the wire stays [b, s, H]
    from ..parallel.mesh import local_tp_mesh
    runtime = StageRuntime(cfg, spec, params, max_seq=args.max_seq,
                           sampling=sampling, seed=args.seed,
                           mesh=local_tp_mesh(getattr(args, "tp", 1)),
                           kv_cache_dtype=getattr(args, "kv_cache_dtype",
                                                  "") or None,
                           kv_layout=getattr(args, "kv_layout",
                                             None) or None)

    from ..comm.faults import load_fault_plan, maybe_wrap
    transport = maybe_wrap(
        ZmqTransport(args.device_id, bind_host=args.bind_host,
                     port=args.port),
        load_fault_plan(getattr(args, "fault_plan", ""),
                        getattr(args, "chaos", False)))
    next_id = None
    if args.next:
        next_id, next_addr = args.next.split("@", 1)
        transport.connect(next_id, next_addr)
    header_id, header_addr = args.header.split("@", 1)
    transport.connect(header_id, header_addr)
    worker = PipelineWorker(runtime, transport, next_id=next_id,
                            header_id=header_id,
                            step_timeout=args.step_timeout)
    return worker, transport


def build_role_worker(args):
    """Build a disaggregated-role worker (``--role prefill|decode``) on
    a ZMQ transport with its ``--peer`` connections dialed."""
    import jax

    from ..comm.faults import load_fault_plan, maybe_wrap
    from ..comm.transport import ZmqTransport
    from ..models.decoder import init_full_params
    from ..models.registry import get_model_config
    from ..ops.sampling import SamplingParams
    from .disagg import DecodeWorker, PrefillWorker

    cfg = get_model_config(args.model)
    if args.dtype:
        cfg = cfg.replace(dtype_name=args.dtype)
    params = init_full_params(jax.random.PRNGKey(args.weights_seed), cfg)
    transport = maybe_wrap(
        ZmqTransport(args.device_id, bind_host=args.bind_host,
                     port=args.port),
        load_fault_plan(getattr(args, "fault_plan", ""),
                        getattr(args, "chaos", False)))
    for peer in args.peer or ():
        pid, addr = peer.split("@", 1)
        transport.connect(pid, addr)
    if args.role == "prefill":
        worker = PrefillWorker(
            cfg, params, transport, max_seq=args.max_seq,
            prefill_chunk=args.prefill_chunk or 32,
            kv_cache_blocks=args.kv_cache_blocks,
            kv_block_tokens=args.kv_block_tokens,
            ack_timeout=args.migration_ack_timeout,
            migration_retries=args.migration_retries)
        return worker, transport, None
    from .batching import ContinuousBatchingEngine
    sampling = SamplingParams(greedy=True) if args.greedy else \
        SamplingParams(temperature=args.temperature, top_k=args.top_k,
                       min_p=args.min_p)
    engine = ContinuousBatchingEngine(
        cfg, params, max_seq=args.max_seq, max_batch=args.batch_slots,
        sampling=sampling, seed=args.seed, eos_id=args.eos_id,
        decode_block=args.decode_block,
        kv_cache_blocks=args.kv_cache_blocks,
        kv_block_tokens=args.kv_block_tokens,
        kv_cache_dtype=getattr(args, "kv_cache_dtype", "") or None)
    worker = DecodeWorker(engine, transport)
    if getattr(args, "live_migration", False):
        # co-serve the §18 live decode-to-decode migration protocol on
        # the same transport; both protocols share ONE PageStager so
        # their pg:/pgx: frames resolve to the same staging records
        from .migration import CoServingWorker, MigrationWorker
        mig = MigrationWorker(engine, transport,
                              ack_timeout=args.migration_ack_timeout,
                              retries=args.migration_retries,
                              stager=worker.stager)
        worker = CoServingWorker(worker, mig)
    return worker, transport, engine


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="pipeline stage / "
                                 "disaggregated-role worker")
    ap.add_argument("--model", required=True)
    ap.add_argument("--role", default="stage",
                    choices=["stage", "prefill", "decode"],
                    help="stage = one pipeline stage (default); "
                         "prefill/decode = the disaggregated serving "
                         "roles (docs/DESIGN.md §15): prefill runs "
                         "chunked prefill and migrates KV pages to its "
                         "decode peer; decode adopts migrated pages "
                         "into a continuous-batching engine")
    ap.add_argument("--stage-id", type=int, default=None)
    ap.add_argument("--num-stages", type=int, default=None)
    ap.add_argument("--layer-start", type=int, default=None)
    ap.add_argument("--layer-end", type=int, default=None)
    ap.add_argument("--device-id", required=True)
    ap.add_argument("--bind-host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--next", default="",
                    help="next stage as id@host:port (empty on the tail)")
    ap.add_argument("--header", default="",
                    help="header as id@host:port (token return edge; "
                         "required for --role stage)")
    ap.add_argument("--peer", action="append", default=[],
                    help="disagg roles: connect a peer as id@host:port "
                         "(repeatable) — the prefill role dials its "
                         "decode worker + coordinator; the decode role "
                         "dials its prefill workers + coordinator")
    ap.add_argument("--batch-slots", type=int, default=8,
                    help="--role decode: continuous-batching slots")
    ap.add_argument("--decode-block", type=int, default=1,
                    help="--role decode: fuse N decode steps per "
                         "dispatch when no admission could land")
    ap.add_argument("--prefill-chunk", type=int, default=32,
                    help="--role prefill: chunk size for the chunked "
                         "prefill whose chunk boundaries the page "
                         "migration streams on")
    ap.add_argument("--migration-ack-timeout", type=float, default=None,
                    help="--role prefill (or decode --live-migration): "
                         "seconds to wait for a migration ack before "
                         "retransmitting (default "
                         "DWT_DISAGG_ACK_TIMEOUT_S, else 2.0)")
    ap.add_argument("--migration-retries", type=int, default=None,
                    help="--role prefill (or decode --live-migration): "
                         "bounded end/retransmit rounds before the "
                         "handoff is reported failed (default "
                         "DWT_DISAGG_MIGRATION_RETRIES, else 5)")
    ap.add_argument("--live-migration", action="store_true",
                    help="--role decode: co-serve the live decode-to-"
                         "decode migration protocol (docs/DESIGN.md "
                         "§18) on this worker's transport, so the "
                         "replica can export and import mid-flight "
                         "requests for rebalance/drain/defragment")
    ap.add_argument("--eos-id", type=int, default=None)
    ap.add_argument("--max-seq", type=int, default=256)
    ap.add_argument("--dtype", default="",
                    help="override model dtype (e.g. float32 for CPU runs)")
    ap.add_argument("--weights-seed", type=int, default=0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--greedy", action="store_true")
    ap.add_argument("--temperature", type=float, default=0.7)
    ap.add_argument("--top-k", type=int, default=7)
    ap.add_argument("--min-p", type=float, default=0.0)
    ap.add_argument("--step-timeout", type=float, default=120.0)
    ap.add_argument("--kv-cache-dtype", default="",
                    help="reduced-precision KV cache storage for this "
                         "stage, e.g. float8_e4m3fn")
    ap.add_argument("--kv-layout", default=None,
                    choices=["paged"],
                    help="this stage's request-cache layout (paged is "
                         "the only layout: every rid backed by one "
                         "per-stage page pool — blocks reserved per "
                         "chunk actually run, freed on end:{rid}; "
                         "'dense' was removed — docs/DESIGN.md §14)")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor parallelism over this host's first N "
                         "local devices (pipeline x tp)")
    ap.add_argument("--metrics-port", type=int, default=-1,
                    help="serve Prometheus GET /metrics on this port "
                         "(0 = ephemeral, -1 = disabled); the header's "
                         "main HTTP server has its own /metrics")
    ap.add_argument("--kv-cache-blocks", type=int, default=None,
                    help="block-level KV prefix cache (runtime/kvcache): "
                         "pool size for the prefill/decode roles; "
                         "REJECTED on pipeline stage workers — a stage "
                         "sees upstream activations, not token ids, so "
                         "there is no key to match cached blocks by; "
                         "the flag exists for CLI parity with serve and "
                         "errors loudly instead of silently ignoring")
    ap.add_argument("--kv-block-tokens", type=int, default=None,
                    help="tokens per KV cache block (see "
                         "--kv-cache-blocks; rejected on stage workers)")
    ap.add_argument("--fault-plan", default="",
                    help="CHAOS TESTING ONLY: JSON fault-plan spec (path "
                         "or inline) injected into this stage's "
                         "transport; requires --chaos (docs/DESIGN.md "
                         "§12; env DWT_FAULT_PLAN)")
    ap.add_argument("--chaos", action="store_true",
                    help="explicitly acknowledge fault injection; "
                         "--fault-plan/DWT_FAULT_PLAN are rejected "
                         "without it")
    args = ap.parse_args(argv)
    from ..comm.faults import FaultConfigError, load_fault_plan
    try:
        load_fault_plan(args.fault_plan, args.chaos)  # validate EARLY:
    except FaultConfigError as e:   # a leaked env plan must not reach
        print(str(e), file=sys.stderr)     # the serve loop
        return 1
    if args.live_migration and args.role != "decode":
        print("--live-migration requires --role decode (live handoffs "
              "move mid-flight requests between decode replicas)",
              file=sys.stderr)
        return 1
    if args.role == "stage":
        if args.kv_cache_blocks or args.kv_block_tokens:
            print("--kv-cache-blocks/--kv-block-tokens are not supported "
                  "on pipeline stage workers (stages see activations, "
                  "not tokens; block KV reuse lives in the engine-backed "
                  "serve modes — serve --batch-slots, the plain engine, "
                  "or the disagg --role prefill/decode workers)",
                  file=sys.stderr)
            return 1
        missing = [f for f, v in (("--stage-id", args.stage_id),
                                  ("--num-stages", args.num_stages),
                                  ("--layer-start", args.layer_start),
                                  ("--layer-end", args.layer_end),
                                  ("--header", args.header))
                   if v in (None, "")]
        if missing:
            print(f"--role stage requires {'/'.join(missing)}",
                  file=sys.stderr)
            return 1

    # black-box capture: the flight ring is labeled with this worker's
    # identity, and an unhandled crash dumps a postmortem bundle (when
    # DWT_POSTMORTEM_DIR is configured) before the process dies
    from ..telemetry import flightrecorder, postmortem
    flightrecorder.get_flight_recorder().proc = args.device_id
    postmortem.install_crash_handler(config=vars(args))

    engine = None
    if args.role == "stage":
        worker, transport = build_worker(args)
    else:
        worker, transport, engine = build_role_worker(args)
    metrics_srv = None
    if args.metrics_port >= 0:
        from ..telemetry import MetricsHTTPServer
        from ..telemetry import catalog as _catalog

        def _debugz() -> dict:
            out = {
                "device_id": args.device_id,
                "flight": flightrecorder.debug_state(),
                "postmortem": postmortem.debug_state(),
            }
            if args.role == "stage":
                out["stats"] = worker.stats.snapshot()
            else:
                # the disagg /debugz satellite: a wedged handoff is
                # observable from a scrape on EITHER role — in-flight
                # handoffs/staged migrations, adopted pages, last
                # migration latency
                out["disagg"] = worker.debug_state()
            return out

        if args.role == "stage":
            def _render():
                return _catalog.render_worker(worker.stats,
                                              args.device_id)
        else:
            def _render():
                return _catalog.scrape(engine if engine is not None
                                       else worker)
        metrics_srv = MetricsHTTPServer(
            _render, host=args.bind_host, port=args.metrics_port,
            debug_provider=_debugz)
        metrics_srv.start()
        print(f"METRICS_READY http://{metrics_srv.host}:"
              f"{metrics_srv.port}/metrics", flush=True)
    print(f"WORKER_READY {args.device_id} {transport.address}", flush=True)
    # no explicit except-and-trigger here: a serve-loop crash propagates
    # to the sys.excepthook installed above, which writes the ONE crash
    # bundle (an extra trigger in an except clause would double-capture
    # the same exception and halve the pruned bundle history)
    try:
        worker.serve_forever()
    finally:
        if metrics_srv is not None:
            metrics_srv.shutdown()
        if engine is not None:
            engine.close()
        transport.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
