"""Hot-loop observability: per-stage comm/compute timers and byte counts.

The reference accumulates per-token communication and inference time in
``commutimeArraySum`` / ``infertimeArraySum`` / ``byteArraySum``
(``Communication.java:104-107,859-896``) and prints the sums at the end of a
run (``:650-661``).  This module is the structured equivalent: every pipeline
role owns a ``StageStats``, the ring loop feeds it, and a ``snapshot()``
dict flows to the ``/stats`` HTTP endpoint, the bench harness, and the
cross-process stats collection (header polls workers with a ``statsreq``
control message — the GET_STATUS idea applied to the data plane).

Latency percentiles come from bounded reservoirs of per-event samples, so
long runs keep O(1) memory.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque

_MAX_SAMPLES = 4096


def _percentile(samples, q: float) -> float:
    """Nearest-rank percentile: smallest x with cdf(x) >= q/100."""
    if not samples:
        return float("nan")
    xs = sorted(samples)
    idx = min(len(xs) - 1, max(0, math.ceil(q / 100.0 * len(xs)) - 1))
    return xs[idx]


class StageStats:
    """Counters + latency reservoirs for one pipeline role.

    Phases mirror the reference's OneStep timers (SURVEY.md §3.3):
    ``recv_wait`` (commu1), ``compute`` (infer), ``send`` (commu2), plus
    header-side ``ring_rtt`` (commu3: send hidden -> token back).
    """

    def __init__(self, role: str = "stage"):
        self.role = role
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.started_at = time.time()
            self.steps = 0
            self.recv_wait_s = 0.0
            self.compute_s = 0.0
            self.send_s = 0.0
            self.bytes_in = 0
            self.bytes_out = 0
            self.messages_in = 0
            self.messages_out = 0
            self._compute_samples = deque(maxlen=_MAX_SAMPLES)
            self._rtt_samples = deque(maxlen=_MAX_SAMPLES)

    # -- recording ---------------------------------------------------------

    def record_recv(self, wait_s: float, nbytes: int) -> None:
        with self._lock:
            self.recv_wait_s += wait_s
            self.bytes_in += nbytes
            self.messages_in += 1

    def record_compute(self, seconds: float) -> None:
        with self._lock:
            self.compute_s += seconds
            self.steps += 1
            self._compute_samples.append(seconds)

    def record_send(self, seconds: float, nbytes: int) -> None:
        with self._lock:
            self.send_s += seconds
            self.bytes_out += nbytes
            self.messages_out += 1

    def record_rtt(self, seconds: float) -> None:
        """Header only: hidden-out -> token-back ring round trip."""
        with self._lock:
            self._rtt_samples.append(seconds)

    # -- reading -----------------------------------------------------------

    def snapshot(self, include_samples: bool = False) -> dict:
        """``include_samples`` adds the raw per-step reservoirs (FIFO
        order): with one request in flight the header's rtt sample i and
        the tail's compute sample i belong to the same token step, so a
        consumer can estimate the per-hop network latency as the PAIRED
        residual ``(rtt_i - tail_compute_i)/2`` — aggregate percentiles
        can't (compute variance swamps the hop when the tail is slow)."""
        with self._lock:
            rtt = list(self._rtt_samples)
            comp = list(self._compute_samples)
            out = {
                "role": self.role,
                "uptime_s": round(time.time() - self.started_at, 3),
                "steps": self.steps,
                "recv_wait_s": round(self.recv_wait_s, 6),
                "compute_s": round(self.compute_s, 6),
                "send_s": round(self.send_s, 6),
                "bytes_in": self.bytes_in,
                "bytes_out": self.bytes_out,
                "messages_in": self.messages_in,
                "messages_out": self.messages_out,
            }
        if comp:
            xs = sorted(comp)    # one sort; _percentile re-sorts in O(n)
            out["compute_p50_ms"] = round(_percentile(xs, 50) * 1e3, 3)
            out["compute_p95_ms"] = round(_percentile(xs, 95) * 1e3, 3)
            out["compute_p99_ms"] = round(_percentile(xs, 99) * 1e3, 3)
        if rtt:
            xs = sorted(rtt)
            out["ring_rtt_p50_ms"] = round(_percentile(xs, 50) * 1e3, 3)
            out["ring_rtt_p95_ms"] = round(_percentile(xs, 95) * 1e3, 3)
            out["ring_rtt_p99_ms"] = round(_percentile(xs, 99) * 1e3, 3)
        if include_samples:
            out["compute_samples_ms"] = [round(s * 1e3, 3) for s in comp]
            out["rtt_samples_ms"] = [round(s * 1e3, 3) for s in rtt]
        return out


# span timing lives in telemetry.tracing.SpanClock (wall-clock start
# captured at open + perf_counter duration) — the old duration-only
# ``timer()`` helper was removed with the SpanClock migration so future
# instrumentation cannot reintroduce the wall/perf clock mixing.
