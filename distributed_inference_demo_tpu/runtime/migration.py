"""Live KV migration between decode replicas (docs/DESIGN.md §18).

PR 8's disaggregation moves a request exactly once, at admission time:
the prefill worker streams ``pg:`` page frames and the decode worker
joins the request before its first token.  This module moves a request
that is ALREADY DECODING — the rebalance/drain/defragment primitive: a
hot replica sheds mid-flight work to a light one, a draining replica
empties itself without waiting out its longest request, and the freed
source pages return to the pool in one release (defragmenting it).

Two-phase protocol over the §12 transport, reusing the §15 page codec
(CRC, (rid, attempt, seq) dedup, go-back-n retransmit) unchanged:

- **Phase 1 — bulk checkpoint.**  The source snapshots the row between
  two steps (``ContinuousBatchingEngine.export_request``: used KV pages
  verbatim, emitted tokens/logprobs, the sampler rng key, length/budget
  counters, kv_dtype tag) and streams the pages as ``pg:`` frames plus
  an ``rs:`` state frame, while the row KEEPS DECODING.  The target
  stages everything on the HOST (zero pool pages held — crash cleanup
  is structural, the §15 property) and acks.
- **Phase 2 — atomic handoff.**  On the ack the source re-exports with
  ``detach=True`` — the freeze point: the row decoded up to some step
  T' and never steps again — and ships only the DELTA blocks (the
  partial tail block re-ships) plus an ``rsd:`` frame carrying the
  final state.  The target adopts the checkpoint
  (``import_request`` → the §11 ``adopt_blocks_into_pages`` +
  ``store_shared`` join: prompt blocks tree-owned, generated blocks
  request-private, decode-side h2d stays 0) and resumes AT T' exactly.
  The relayed stream dedups by the existing ``(rid, step)`` rule — at
  most the one in-flight step replays, and no step can be skipped: the
  target's first token is step T', the source's last was T' - 1.

The client-visible stream never breaks: the source keeps the original
``Request`` object (stream open, ``done`` unset) and feeds it from the
target's ``tok:`` frames; ``fin:`` carries the authoritative token list
(late/dropped relay frames reconcile there).  If phase 2 cannot be
acked, the source re-imports its own detached checkpoint locally — the
request continues where it froze and the target's staging is aborted
(``pgx:``).  If the SOURCE dies after phase 1, the target can
``promote_staged``: resume from the bulk checkpoint at step T — steps
the source emitted in (T, T'] replay and dedup downstream; none skip.

Frame tags (extends the §15 table; rids must not contain ``:``):

    pg:{rid}:{attempt}:{n}  source → target   page payload (§15 codec)
    rs:{rid}:{attempt}      source → target   phase-1 state manifest
    pga:{rid}:{attempt}     target → source   phase-1 ack (status, expected)
    rsd:{rid}:{attempt}     source → target   phase-2 handoff (delta manifest
                                              + final state)
    rsa:{rid}:{attempt}     target → source   phase-2 ack (status, expected)
    pgx:{rid}               source → target   abort a staged migration
    mcx:{rid}               source → target   cancel a handed-off request
    tok:{rid}:{i}           target → source   one relayed token
    fin:{rid}               target → source   final tokens / error

:class:`MigrationController` is the policy layer: driven by the gateway
registry's load view it picks hot-source → light-target rebalances and
drives a ``draining`` replica empty (ROADMAP's scale-down primitive).
The mechanism (``mover``) is injected — in-process deployments call the
replicas' :meth:`MigrationWorker.migrate_out` directly.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..comm import wire
from ..comm.transport import (TransportError, TransportTimeout,
                              record_corrupt_frame)
from ..telemetry import profiling as _profiling
from ..telemetry._env import env_float, env_int
from ..telemetry.flightrecorder import get_flight_recorder
from ..telemetry.tracing import SpanClock, TraceRecorder, new_trace_id
from .disagg import (_meta_frame, _page_frame, _parse_meta_frame,
                     MigrationError, PageStager)

log = logging.getLogger(__name__)

# live-migration knobs (docs/DESIGN.md §18 table)
DEFAULT_ACK_TIMEOUT_S = env_float("DWT_MIGRATION_ACK_TIMEOUT_S", 2.0)
DEFAULT_RETRIES = env_int("DWT_MIGRATION_RETRIES", 5)
DEFAULT_PAGE_FRAME_BLOCKS = env_int("DWT_MIGRATION_FRAME_BLOCKS", 4)


def _migration_metrics():
    """The dwt_migration_* series, resolved lazily and never fatally (a
    metrics regression must not take down the data plane) — the
    transport's pattern."""
    try:
        from ..telemetry import catalog
        return catalog
    except Exception:           # pragma: no cover - defensive
        return None


def _state_tensors(ckpt: dict):
    """The rs:/rsd: frames' data tensors: prompt, emitted tokens,
    logprobs, rng key words (empty when the checkpoint carries none)."""
    rng = ckpt.get("rng")
    return (np.asarray(ckpt["prompt"], np.int32),
            np.asarray(ckpt["tokens"], np.int32),
            np.asarray(ckpt["lps"], np.float32),
            np.zeros(0, np.uint32) if rng is None
            else np.asarray(rng, np.uint32))


def _state_meta(ckpt: dict, *, rid: str, attempt: int, n_frames: int,
                n_blocks: int, source_id: str, reply_to: str) -> dict:
    return {"rid": rid, "attempt": attempt, "n_frames": n_frames,
            "n_blocks": n_blocks, "max_new": int(ckpt["max_new"]),
            "length": int(ckpt["length"]),
            "last_tok": int(ckpt["last_tok"]),
            "kv_dtype": ckpt["kv_dtype"],
            "block_tokens": int(ckpt["block_tokens"]),
            "source_id": source_id, "reply_to": reply_to,
            # observability identity (docs/DESIGN.md §7): the manifest is
            # all the target sees, so tenant/trace must ride it or the
            # adopted request would lose its attribution mid-fleet
            "tenant": ckpt.get("tenant", "default"),
            "trace_id": int(ckpt.get("trace_id") or 0),
            "t_submit_wall": float(ckpt.get("t_submit_wall") or 0.0),
            "migration_pause": float(ckpt.get("migration_pause") or 0.0),
            # §22 verify-boundary freeze: adaptive-K state rides the
            # manifest (scalars); draft scratch / n-gram history do NOT
            # ship — the importer rebuilds them from prompt + tokens
            "spec_k": int(ckpt.get("spec_k") or 0),
            "spec_ewma": float(ckpt.get("spec_ewma") or 0.0)}


def _ckpt_from_staged(stager: PageStager, st: dict, meta: dict) -> dict:
    """Rebuild an ``import_request`` checkpoint from a staged frame set
    + a state manifest (rs or rsd).  Frames apply in seq order at their
    ``first_block`` offsets, so a phase-2 delta OVERWRITES the partial
    tail block phase 1 shipped."""
    prompt, tokens, lps, rng = st["state_tensors"]
    k_blocks, v_blocks = stager.concat_blocks(st, int(meta["n_blocks"]))
    return {"rid": meta["rid"],
            "prompt": np.asarray(prompt, np.int32),
            "max_new": int(meta["max_new"]),
            "tokens": [int(t) for t in tokens],
            "lps": [float(x) for x in lps],
            "length": int(meta["length"]),
            "last_tok": int(meta["last_tok"]),
            "kv_dtype": meta.get("kv_dtype", st["kv_dtype"]),
            "block_tokens": int(meta["block_tokens"]),
            "tenant": meta.get("tenant", "default"),
            "trace_id": int(meta.get("trace_id") or 0),
            "t_submit_wall": float(meta.get("t_submit_wall") or 0.0),
            "migration_pause": float(meta.get("migration_pause") or 0.0),
            "spec_k": int(meta.get("spec_k") or 0),
            "spec_ewma": float(meta.get("spec_ewma") or 0.0),
            "k": k_blocks, "v": v_blocks,
            "rng": (np.asarray(rng, np.uint32) if len(rng) else None)}


class MigrationWorker:
    """One decode replica's live-migration endpoint — BOTH roles: the
    source (:meth:`migrate_out`) and the target (frame handlers +
    :meth:`import_request` adoption + token relay back).

    Sits beside a :class:`ContinuousBatchingEngine` and a §12 transport;
    in the worker roles it co-serves on the DecodeWorker's loop (the
    ``migration=`` co-handler seam), in-process it gets its own
    :meth:`serve_forever` thread."""

    def __init__(self, engine, transport,
                 ack_timeout: Optional[float] = None,
                 retries: Optional[int] = None,
                 page_frame_blocks: Optional[int] = None,
                 stager: Optional[PageStager] = None):
        self.engine = engine
        self.transport = transport
        self.device_id = transport.device_id
        self.ack_timeout = (DEFAULT_ACK_TIMEOUT_S if ack_timeout is None
                            else float(ack_timeout))
        self.retries = (DEFAULT_RETRIES if retries is None
                        else int(retries))
        self.page_frame_blocks = max(1, int(
            DEFAULT_PAGE_FRAME_BLOCKS if page_frame_blocks is None
            else page_frame_blocks))
        self.tracer = TraceRecorder(f"migration:{self.device_id}")
        # replica /trace drains the ENGINE's recorder: register ours so
        # the export (and the gateway's /trace/fleet stitch) carries
        # migration spans on the same page as prefill/decode spans
        reg = getattr(engine, "register_aux_tracer", None)
        if callable(reg):
            reg(self.tracer)
        # target side: (rid, attempt) page staging (host-only; zero pool
        # pages) — pass the DecodeWorker's stager to co-serve one
        # transport with the §15 admission join
        self.stager = stager or PageStager(
            self.device_id, on_evict=self._evicted)
        # rid -> attempt that was adopted (imported + decoding here):
        # re-ack + duplicate suppression, BOUNDED like
        # DecodeWorker._joined
        self._adopted: "OrderedDict[str, int]" = OrderedDict()
        # rid -> adopted Request (cancel forwarding + drain bookkeeping)
        self._imported: Dict[str, object] = {}
        # source side: rid -> (original Request, target_id) being relayed
        self._relays: Dict[str, tuple] = {}
        self._attempts: Dict[str, int] = {}
        self.stats = {"migrated_out": 0, "migrated_in": 0,
                      "failed_migrations": 0, "aborted_migrations": 0,
                      "replayed_steps": 0, "moved_pages": 0,
                      "moved_bytes": 0, "promoted_requests": 0,
                      "healed_requests": 0, "last_migration_ms": None}
        # acks the serve loop's recv_any consumed on behalf of a
        # concurrent migrate_out (same transport, two threads): the
        # migrating thread waits here FIRST, then on the transport
        self._ack_stash: Dict[str, list] = {}
        self._ack_cv = threading.Condition()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._flight = get_flight_recorder()

    _MARK_CAP = 4096

    @property
    def staged_bytes(self) -> int:
        return self.stager.staged_bytes

    def _evicted(self, rid: str) -> None:
        self.stats["aborted_migrations"] += 1

    # -- serve loop (in-process deployments; worker roles co-serve on
    # the DecodeWorker loop) ----------------------------------------------

    def stop(self) -> None:
        self._stop.set()

    def serve_forever(self, idle_timeout: Optional[float] = None) -> None:
        idle_since = time.monotonic()
        while not self._stop.is_set():
            try:
                tag, payload = self.transport.recv_any(timeout=0.1)
            except TransportTimeout:
                if (idle_timeout is not None
                        and time.monotonic() - idle_since > idle_timeout):
                    return
                continue
            idle_since = time.monotonic()
            try:
                self.handle_message(tag, payload)
            except Exception:
                # one malformed frame must not take the replica (and
                # every future migration) down with it
                log.exception("%s: migration frame %r failed",
                              self.device_id, tag)

    # -- message handling --------------------------------------------------

    def handle_message(self, tag: str, payload: bytes) -> bool:
        """Dispatch one inbound frame; returns True when the tag was a
        live-migration frame this worker owns (co-handler seam)."""
        parts = tag.split(":")
        kind = parts[0]
        if kind == "pg":
            self._on_page(parts[1], int(parts[2]), int(parts[3]),
                          payload, tag)
        elif kind == "rs":
            self._on_state(parts[1], int(parts[2]), payload, tag)
        elif kind == "rsd":
            self._on_handoff(parts[1], int(parts[2]), payload, tag)
        elif kind == "pgx":
            self._on_abort(parts[1])
        elif kind == "mcx":
            self._on_cancel(parts[1])
        elif kind == "tok":
            self._on_tok(parts[1], int(parts[2]), payload)
        elif kind == "fin":
            self._on_fin(parts[1], payload)
        elif kind in ("pga", "rsa"):
            with self._ack_cv:
                self._ack_stash.setdefault(tag, []).append(payload)
                self._ack_cv.notify_all()
        else:
            return False
        return True

    def _drop(self, tag: str, why: str) -> None:
        self._flight.record("migration_frame_dropped", tag=tag, why=why)

    def _mark_adopted(self, rid: str, attempt: int) -> None:
        self._adopted[rid] = max(attempt, self._adopted.get(rid, 0))
        self._adopted.move_to_end(rid)
        while len(self._adopted) > self._MARK_CAP:
            self._adopted.popitem(last=False)

    def _is_adopted(self, rid: str, attempt: int) -> bool:
        """True when ``attempt`` was already resolved here.  The gate is
        attempt-AWARE, not rid-keyed: a request can legally migrate
        away and bounce back later under a HIGHER attempt (the importer
        seeds its own counter from the adopted attempt, so attempts
        increase along the whole migration chain), and that newer
        attempt must stage fresh."""
        return attempt <= self._adopted.get(rid, 0)

    def _ack(self, peer: str, tag: str, complete: bool,
             expected: int) -> None:
        body = wire.serialize_tensors(
            [np.asarray([0 if complete else 1, expected], np.int32)])
        try:
            self.transport.send(peer, tag, body)
        except TransportError:
            pass                 # sender timeout/retry path recovers

    # -- target: staging ---------------------------------------------------

    def _on_page(self, rid: str, attempt: int, seq: int, payload: bytes,
                 tag: str) -> None:
        if self._is_adopted(rid, attempt):
            self._drop(tag, "already_adopted")
            return
        status = self.stager.stage_page(rid, attempt, seq, payload, tag)
        # staged frames are the migration path's only host-buffer growth:
        # feed the §20 watermark ledger here (peaks are what it keeps)
        _profiling.get_hbm_watermarks().sample(
            "migration_staged", self.stager.staged_bytes)
        if status in ("stale_attempt", "dedup"):
            self._drop(tag, status)

    def _on_state(self, rid: str, attempt: int, payload: bytes,
                  tag: str) -> None:
        """Phase-1 manifest: validate the staged frame set, stash the
        request state, ack.  NOTHING imports here — the row is still
        decoding on the source; staging stays host-only."""
        try:
            meta, tensors, ctx = _parse_meta_frame(payload)
        except wire.WireError as e:
            record_corrupt_frame(self.device_id, tag, len(payload), e)
            return
        source = meta.get("source_id", "")
        ack_tag = f"pga:{rid}:{attempt}"
        if self._is_adopted(rid, attempt):
            self._ack(source, ack_tag, True, 0)
            return
        st = self.stager.staging(rid, attempt)
        if st is None:
            self._drop(tag, "stale_attempt")
            return
        if st["expected"] < int(meta["n_frames"]):
            self._ack(source, ack_tag, False, st["expected"])
            return
        st["state_meta"] = meta
        st["state_tensors"] = tensors
        st["ctx"] = ctx
        self._flight.record("migration_staged", rid=rid, attempt=attempt,
                            frames=st["expected"], bytes=st["bytes"])
        self._ack(source, ack_tag, True, st["expected"])

    def _on_handoff(self, rid: str, attempt: int, payload: bytes,
                    tag: str) -> None:
        """Phase-2 handoff: the source froze the row at its final state;
        adopt the complete checkpoint and resume decoding HERE."""
        try:
            meta, tensors, ctx = _parse_meta_frame(payload)
        except wire.WireError as e:
            record_corrupt_frame(self.device_id, tag, len(payload), e)
            return
        source = meta.get("source_id", "")
        ack_tag = f"rsa:{rid}:{attempt}"
        # the same lock promote_staged holds across its staging check +
        # adopt: a delayed rsd frame racing an operator/policy promote
        # must not both pass the adopted gate and double-import one
        # (rid, attempt) into two engine slots
        with self._lock:
            if self._is_adopted(rid, attempt):
                # retransmitted handoff for a request already decoding
                # here: idempotent re-ack, never a second import
                self._ack(source, ack_tag, True, 0)
                return
            st = self.stager.staging(rid, attempt)
            if st is None:
                self._drop(tag, "stale_attempt")
                return
            if st["expected"] < int(meta["n_frames"]):
                self._ack(source, ack_tag, False, st["expected"])
                return
            st["state_meta"] = meta
            st["state_tensors"] = tensors
            self._adopt(rid, attempt, st, meta, ctx, source, ack_tag)

    def _adopt(self, rid: str, attempt: int, st: dict, meta: dict, ctx,
               source: str, ack_tag: Optional[str]) -> Optional[object]:
        try:
            ckpt = _ckpt_from_staged(self.stager, st, meta)
            req = self.engine.import_request(ckpt)
        except Exception as e:
            # an admission rejection (capacity, dtype mismatch) is a
            # per-REQUEST failure, never a dead replica: ack complete
            # (retransmitting cannot fix admission) and surface the
            # error through the fin path so the source unblocks the
            # client with a terminal error instead of a hang
            self.stager.clear(rid)
            # the aborted marker lives in the SHARED stager: when a
            # DecodeWorker co-serves this transport, a late retransmit
            # of this attempt must drop no matter whose _on_page sees it
            self.stager.mark_aborted(rid, attempt)
            self._mark_adopted(rid, attempt)
            self.stats["failed_migrations"] += 1
            self._flight.record("migration_adopt_rejected", rid=rid,
                                error=type(e).__name__, detail=str(e))
            if ack_tag is not None:
                self._ack(source, ack_tag, True, st["expected"])
            try:
                self.transport.send(
                    meta["reply_to"], f"fin:{rid}",
                    _meta_frame({"rid": rid, "ok": False,
                                 "error": f"{type(e).__name__}: {e}"},
                                (np.zeros(0, np.int32),)))
            except TransportError:
                pass
            return None
        n_blocks = int(meta["n_blocks"])
        dt = time.perf_counter() - st["t0"]
        self._mark_adopted(rid, attempt)
        self._imported[rid] = req
        self.stager.clear(rid)
        # shared-stager gate (see the rejection path): a co-serving
        # DecodeWorker must not re-stage late frames of this attempt
        self.stager.mark_aborted(rid, attempt)
        # chain the attempt counter: if THIS replica later re-exports
        # the request (bounce migration), its attempt must exceed every
        # attempt any replica has already seen for this rid
        self._attempts[rid] = max(self._attempts.get(rid, 0), attempt)
        self.stats["migrated_in"] += 1
        self.stats["moved_pages"] += n_blocks
        self.stats["last_migration_ms"] = round(dt * 1e3, 3)
        cat = _migration_metrics()
        if cat is not None:
            try:
                cat.MIGRATION_IMPORTED.inc()
                cat.MIGRATION_HANDOFF_SECONDS.observe(dt)
            except Exception:            # pragma: no cover - defensive
                pass
        if ctx is not None:
            self.tracer.record("migration_adopt", ctx[0], ctx[1],
                               ts=time.time() - dt, dur=dt, rid=rid,
                               blocks=n_blocks)
        self._flight.record("migration_adopt", rid=rid, attempt=attempt,
                            blocks=n_blocks,
                            resumed_at=len(req.tokens))
        if ack_tag is not None:
            self._ack(source, ack_tag, True, st["expected"])
        t = threading.Thread(
            target=self._relay_out,
            args=(req, rid, meta["reply_to"], len(req.tokens)),
            daemon=True, name=f"migration-relay-{rid}")
        t.start()
        return req

    def promote_staged(self, rid: str) -> Optional[object]:
        """Resume a phase-1-complete staged checkpoint whose SOURCE died
        before the handoff: adopt it at step T (the bulk snapshot) and
        stream to the manifest's ``reply_to``.  Steps the dead source
        emitted after T replay and dedup at the collector ((rid, step));
        none skip.  Returns the resumed Request, or None when nothing
        promotable is staged."""
        with self._lock:
            st = self.stager._staged.get(rid)
            if st is None or st["state_meta"] is None:
                return None
            meta = st["state_meta"]
            if st["expected"] < int(meta["n_frames"]):
                return None
            self.stats["promoted_requests"] += 1
            self._flight.record("migration_promote", rid=rid,
                                attempt=st["attempt"])
            return self._adopt(rid, st["attempt"], st, meta,
                               st.get("ctx"), meta.get("source_id", ""),
                               None)

    def _on_abort(self, rid: str) -> None:
        """Abort a staged migration: host buffers AND their byte
        accounting clear, and the attempt marker ensures late frames of
        the aborted attempt drop instead of restaging a leak."""
        st = self.stager._staged.get(rid)
        if st is None or self._is_adopted(rid, st["attempt"]):
            return               # nothing staged, or too late: adopted
        st = self.stager.clear(rid)
        if st is not None:
            self.stager.mark_aborted(rid, st["attempt"])
            self.stats["aborted_migrations"] += 1
            cat = _migration_metrics()
            if cat is not None:
                try:
                    cat.MIGRATION_ABORTED.inc()
                except Exception:        # pragma: no cover - defensive
                    pass
            self._flight.record("migration_abort", rid=rid,
                                attempt=st["attempt"])

    def _on_cancel(self, rid: str) -> None:
        """The source relayed a client cancel for a handed-off request:
        cancel it here — the engine sweep frees its slot/pages and the
        relay's fin reports the clean termination back."""
        req = self._imported.get(rid)
        if req is not None:
            req.cancel()
            self._flight.record("migration_cancel_forwarded", rid=rid)

    def _relay_out(self, req, rid: str, reply_to: str,
                   start_idx: int) -> None:
        """Forward an adopted request's NEW tokens to the source (its
        own thread, like the §15 drain).  ``start_idx`` continues the
        source's numbering — the stream only yields tokens decoded
        here, so index i on the wire is always absolute step i."""
        idx = start_idx
        while True:
            item = req.stream.get()
            if item is None:
                break
            try:
                self.transport.send(reply_to, f"tok:{rid}:{idx}",
                                    wire.serialize_token(int(item)))
            except TransportError:
                pass             # fin carries the authoritative tokens
            idx += 1
        self._imported.pop(rid, None)
        err = req.error
        meta = {"rid": rid,
                "ok": err is None and not req.cancelled,
                "cancelled": bool(req.cancelled),
                "error": None if err is None else
                f"{type(err).__name__}: {err}"}
        body = _meta_frame(meta, (np.asarray(req.tokens, np.int32),))
        try:
            self.transport.send(reply_to, f"fin:{rid}", body)
        except TransportError:
            pass

    # -- source: relay consumption ----------------------------------------

    @staticmethod
    def _end_pause(req) -> None:
        """Close the freeze→resume gap the detaching export opened: the
        accumulated pause is the timeline ledger's migration_pause field
        (first relayed/healed token, or fin, whichever lands first)."""
        t0 = getattr(req, "_pause_t0", None)
        if t0 is not None:
            req.migration_pause += time.perf_counter() - t0
            req._pause_t0 = None

    def _on_tok(self, rid: str, idx: int, payload: bytes) -> None:
        ent = self._relays.get(rid)
        if ent is None:
            return
        req, target = ent
        if req.cancelled:
            # forward the client's cancel to the replica that now owns
            # the row; its fin terminates the stream cleanly
            try:
                self.transport.send(target, f"mcx:{rid}",
                                    _meta_frame({"rid": rid}))
            except TransportError:
                pass
        try:
            tok = wire.deserialize_token(payload)
        except wire.WireError as e:
            record_corrupt_frame(self.device_id, f"tok:{rid}", len(payload),
                                 e)
            return
        # the (rid, step) dedup: exactly the §15 collector rule — the
        # one replayed boundary step appends nowhere, a skipped step is
        # structurally impossible (idx == len(tokens) or it drops)
        if idx == len(req.tokens):
            self._end_pause(req)
            req.tokens.append(tok)
            req.stream.put(tok)
        elif idx < len(req.tokens):
            self.stats["replayed_steps"] += 1
            cat = _migration_metrics()
            if cat is not None:
                try:
                    cat.MIGRATION_REPLAYED.inc()
                except Exception:        # pragma: no cover - defensive
                    pass

    def _on_fin(self, rid: str, payload: bytes) -> None:
        ent = self._relays.pop(rid, None)
        if ent is None:
            return
        req, _target = ent
        try:
            meta, tensors, _ = _parse_meta_frame(payload)
        except wire.WireError as e:
            record_corrupt_frame(self.device_id, f"fin:{rid}",
                                 len(payload), e)
            req.error = MigrationError(
                f"relay fin for {rid!r} was corrupt")
            self._end_pause(req)
            req.stream.put(None)
            req.done.set()
            try:
                self.engine._close_timeline(req, error="MigrationError")
            except Exception:        # pragma: no cover - defensive
                pass
            return
        if meta.get("ok"):
            # the authoritative token list reconciles any relay frames
            # the wire lost (fin rides the reliable send-retry path)
            final = [int(t) for t in tensors[0]]
            for tok in final[len(req.tokens):]:
                self._end_pause(req)
                req.tokens.append(tok)
                req.stream.put(tok)
        elif not meta.get("cancelled"):
            req.error = MigrationError(
                meta.get("error") or f"migrated request {rid!r} failed "
                "on the target replica")
        self._end_pause(req)
        req.t_done = time.perf_counter()
        req.stream.put(None)
        req.done.set()
        self._flight.record("migration_relay_done", rid=rid,
                            ok=bool(meta.get("ok")),
                            tokens=len(req.tokens))
        # the SOURCE closes the user-visible timeline: it held the
        # client connection across the handoff, so its clocks cover the
        # whole request (pause included) — the target never closes one
        try:
            self.engine._close_timeline(
                req, error=(None if meta.get("ok") else
                            ("cancelled" if meta.get("cancelled")
                             else "MigrationError")))
        except Exception:            # pragma: no cover - defensive
            pass

    # -- source: migrate out ----------------------------------------------

    def pick_migratable(self, n: int, min_remaining: int = 2) -> List[str]:
        """Up to ``n`` rids worth moving: actively decoding here, with
        at least ``min_remaining`` tokens of budget left (moving a row
        about to finish costs more than it frees)."""
        out = []
        for rid, _emitted, remaining in self.engine.active_requests():
            if remaining >= min_remaining and rid not in self._relays:
                out.append(rid)
            if len(out) >= n:
                break
        return out

    def migrate_out(self, rid: str, target_id: str,
                    trace: Optional[Tuple[int, int]] = None) -> bool:
        """Move one decoding request to ``target_id``.  Returns True on
        a completed handoff; False when the request resolved locally
        first (finished/cancelled before the freeze).  Raises
        :class:`MigrationError` when the target cannot be reached —
        after SELF-HEALING: the detached checkpoint (if any) re-imports
        locally, so the request survives a dead target."""
        t_all = SpanClock()
        # attempts start at 1: the adopted/aborted gates treat 0 as
        # "never seen", so attempt numbers must stay strictly positive
        attempt = self._attempts.get(rid, 0) + 1
        self._attempts[rid] = attempt
        req = self.engine.get_request(rid)
        if req is None:
            raise KeyError(f"unknown request id {rid!r}")
        if trace is None:
            # join the request's own trace when it carries one (the
            # gateway-propagated id), so /trace/fleet stitches the
            # migration spans into the same request lane as the proxy
            # and engine spans; a fresh id otherwise
            trace = (getattr(req, "trace_id", 0) or new_trace_id(), 0)
        cat = _migration_metrics()
        if cat is not None:
            try:
                cat.MIGRATION_INFLIGHT.inc()
            except Exception:            # pragma: no cover - defensive
                pass
        try:
            return self._migrate_out(rid, attempt, req, target_id, trace,
                                     t_all)
        finally:
            if cat is not None:
                try:
                    cat.MIGRATION_INFLIGHT.dec()
                except Exception:        # pragma: no cover - defensive
                    pass

    def _migrate_out(self, rid: str, attempt: int, req, target_id: str,
                     trace, t_all: SpanClock) -> bool:
        bt = self.engine.kv_cache.block_tokens
        # ---- phase 1: bulk checkpoint, row keeps decoding ----
        with SpanClock() as t_exp:
            try:
                ckpt1 = self.engine.export_request(rid)
            except (KeyError, ValueError):
                # finished/cancelled between pick and export: no-op
                return False
            except TimeoutError as e:
                # stalled scheduler: the mailbox was abandoned (the
                # late export is a no-op) and the row keeps decoding
                # locally — loud failure, nothing to heal
                self.stats["failed_migrations"] += 1
                raise MigrationError(
                    f"phase-1 export of {rid!r} timed out on the "
                    f"scheduler mailbox: {e}") from e
        span1 = self.tracer.record("migration_export", trace[0], trace[1],
                                   clock=t_exp, rid=rid,
                                   tokens=len(ckpt1["tokens"]))
        import jax
        frames: List[Tuple[str, bytes]] = []

        def add_block_frames(ckpt, lo: int) -> None:
            n = (0 if ckpt["k"] is None
                 else jax.tree.leaves(ckpt["k"])[0].shape[0])
            step = self.page_frame_blocks
            for first in range(lo, n, step):
                sl = slice(first, min(first + step, n))
                kb = jax.tree.map(lambda a: a[sl], ckpt["k"])
                vb = jax.tree.map(lambda a: a[sl], ckpt["v"])
                frames.append(
                    (f"pg:{rid}:{attempt}:{len(frames)}",
                     _page_frame(kb, vb, first,
                                 trace=(trace[0], span1))))

        add_block_frames(ckpt1, 0)
        n1 = 0 if ckpt1["k"] is None else -(-ckpt1["length"] // bt)
        state1 = _meta_frame(
            _state_meta(ckpt1, rid=rid, attempt=attempt,
                        n_frames=len(frames), n_blocks=n1,
                        source_id=self.device_id,
                        reply_to=self.device_id),
            _state_tensors(ckpt1), trace=(trace[0], span1))
        try:
            for tag, body in frames:
                self.transport.send(target_id, tag, body)
            acked1 = self._await_ack(rid, attempt, target_id, frames,
                                     f"rs:{rid}:{attempt}", state1,
                                     f"pga:{rid}:{attempt}")
        except TransportError:
            # dead/unconnected peer mid-bulk: the row never froze, same
            # recovery as an unacked phase 1
            acked1 = False
        if not acked1:
            self._abort_target(rid, target_id)
            self.stats["failed_migrations"] += 1
            raise MigrationError(
                f"phase-1 checkpoint of {rid!r} to {target_id} was not "
                f"acked within {self.retries} retries")
        # ---- phase 2: freeze, ship the delta, hand off ----
        with SpanClock() as t_frz:
            try:
                ckpt2 = self.engine.export_request(rid, detach=True)
            except (KeyError, ValueError):
                # the row finished or was cancelled while phase 1
                # shipped: it resolved locally — abort the staging
                self._abort_target(rid, target_id)
                return False
            except TimeoutError as e:
                # the abandoned mailbox guarantees the freeze did NOT
                # happen — the row keeps decoding locally
                self._abort_target(rid, target_id)
                self.stats["failed_migrations"] += 1
                raise MigrationError(
                    f"freeze of {rid!r} timed out on the scheduler "
                    "mailbox; request keeps decoding locally") from e
        span2 = self.tracer.record("migration_freeze", trace[0], span1,
                                   clock=t_frz, rid=rid,
                                   tokens=len(ckpt2["tokens"]))
        # delta: blocks from the (re-shipped) partial tail of phase 1 on
        # — phase 2's version of that block supersedes phase 1's
        lo = (ckpt1["length"] // bt) if ckpt1["k"] is not None else 0
        n_phase1 = len(frames)
        add_block_frames(ckpt2, lo)
        n2 = -(-ckpt2["length"] // bt)
        state2 = _meta_frame(
            _state_meta(ckpt2, rid=rid, attempt=attempt,
                        n_frames=len(frames), n_blocks=n2,
                        source_id=self.device_id,
                        reply_to=self.device_id),
            _state_tensors(ckpt2), trace=(trace[0], span2))
        self._relays[rid] = (req, target_id)
        # EVERYTHING between the detach and the ack must funnel into the
        # self-heal: the row already froze, so a raw TransportError here
        # (dead peer — no retry/timeout softens a hard send failure)
        # would otherwise orphan a request whose pages are released and
        # whose stream nobody owns
        try:
            for tag, body in frames[n_phase1:]:
                self.transport.send(target_id, tag, body)
            acked2 = self._await_ack(rid, attempt, target_id, frames,
                                     f"rsd:{rid}:{attempt}", state2,
                                     f"rsa:{rid}:{attempt}")
        except TransportError:
            acked2 = False
        if not acked2:
            # target unreachable AFTER the freeze: self-heal — the
            # checkpoint re-imports locally and a local relay pump keeps
            # the original stream alive; the request never drops.  The
            # ack may also have been lost after a successful adopt,
            # which pgx: deliberately ignores — mcx: rides along so an
            # adopted target cancels its duplicate row instead of
            # decoding it to completion (its fin finds no relay entry
            # here and drops)
            self._relays.pop(rid, None)
            self._abort_target(rid, target_id)
            self._cancel_target(rid, target_id)
            self.stats["failed_migrations"] += 1
            self._heal_local(rid, req, ckpt2)
            raise MigrationError(
                f"handoff of {rid!r} to {target_id} was not acked; "
                "request re-imported locally")
        nbytes = sum(len(b) for _, b in frames)
        self.stats["migrated_out"] += 1
        self.stats["moved_pages"] += n2
        self.stats["moved_bytes"] += nbytes
        self.stats["last_migration_ms"] = round(t_all.seconds * 1e3, 3)
        cat = _migration_metrics()
        if cat is not None:
            try:
                cat.MIGRATION_EXPORTED.inc()
                cat.MIGRATION_MOVED_PAGES.inc(n2)
                cat.MIGRATION_MOVED_BYTES.inc(nbytes)
            except Exception:            # pragma: no cover - defensive
                pass
        self.tracer.record("migration_handoff", trace[0], span2,
                           ts=t_all.ts, dur=t_all.seconds, rid=rid,
                           target=target_id, blocks=n2, bytes=nbytes)
        self._flight.record("migration_out", rid=rid, attempt=attempt,
                            target=target_id, blocks=n2, bytes=nbytes,
                            ms=self.stats["last_migration_ms"])
        return True

    def _await_ack(self, rid: str, attempt: int, target_id: str,
                   frames: List[Tuple[str, bytes]], end_tag: str,
                   end_body: bytes, ack_tag: str) -> bool:
        """§15 go-back-n: send the end/manifest frame, wait for its ack,
        retransmit the tail from the receiver's expected seq on a
        nack, under the bounded retry budget."""
        for _round in range(self.retries + 1):
            try:
                self.transport.send(target_id, end_tag, end_body)
            except TransportError:
                return False
            try:
                payload = self._recv_ack(ack_tag)
            except TransportTimeout:
                continue
            except TransportError:
                return False
            try:
                status = np.asarray(
                    wire.deserialize_tensors(payload).tensors[0])
            except wire.WireError:
                continue
            if int(status[0]) == 0:
                return True
            expected = int(status[1])
            for tag, body in frames[expected:]:
                try:
                    self.transport.send(target_id, tag, body)
                except TransportError:
                    return False
            self._flight.record("migration_retransmit", rid=rid,
                                attempt=attempt, from_seq=expected)
        return False

    def _recv_ack(self, ack_tag: str) -> bytes:
        """One ack payload within ``ack_timeout`` — from the worker ack
        stash (a concurrent serve loop routed it there) or straight off
        the transport (no serve loop running), whichever lands first."""
        deadline = time.monotonic() + self.ack_timeout
        while True:
            with self._ack_cv:
                items = self._ack_stash.get(ack_tag)
                if items:
                    payload = items.pop(0)
                    if not items:
                        del self._ack_stash[ack_tag]
                    return payload
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TransportTimeout(
                    f"{self.device_id}: no {ack_tag!r} within "
                    f"{self.ack_timeout}s")
            try:
                return self.transport.recv(
                    ack_tag, timeout=min(0.05, remaining))
            except TransportTimeout:
                continue

    def _abort_target(self, rid: str, target_id: str) -> None:
        try:
            self.transport.send(target_id, f"pgx:{rid}",
                                _meta_frame({"rid": rid}))
        except TransportError:
            pass

    def _cancel_target(self, rid: str, target_id: str) -> None:
        """mcx: the target — if the handoff DID land there (the phase-2
        ack was lost after a successful adopt), the duplicate row
        cancels instead of burning a slot decoding to completion; on a
        never-adopted target it is a no-op."""
        try:
            self.transport.send(target_id, f"mcx:{rid}",
                                _meta_frame({"rid": rid}))
        except TransportError:
            pass

    def _heal_local(self, rid: str, req, ckpt: dict) -> None:
        """Re-import a detached checkpoint into the local engine and
        pump the resumed request's stream into the ORIGINAL Request —
        the client's stream survives a failed handoff untouched."""
        try:
            healed = self.engine.import_request(ckpt, request_id=None)
        except Exception as e:
            req.error = MigrationError(
                f"handoff failed and local re-import failed too: "
                f"{type(e).__name__}: {e}")
            self._end_pause(req)
            req.stream.put(None)
            req.done.set()
            try:
                self.engine._close_timeline(req, error="MigrationError")
            except Exception:        # pragma: no cover - defensive
                pass
            return
        self.stats["healed_requests"] += 1
        self._flight.record("migration_healed", rid=rid,
                            resumed_at=len(healed.tokens))

        def pump():
            while True:
                item = healed.stream.get()
                if item is None:
                    break
                self._end_pause(req)
                req.tokens.append(int(item))
                req.stream.put(int(item))
            req.error = healed.error
            self._end_pause(req)
            req.t_done = time.perf_counter()
            req.stream.put(None)
            req.done.set()
            try:
                self.engine._close_timeline(
                    req, error=(None if healed.error is None else
                                type(healed.error).__name__))
            except Exception:        # pragma: no cover - defensive
                pass

        threading.Thread(target=pump, daemon=True,
                         name=f"migration-heal-{rid}").start()

    # -- observability -----------------------------------------------------

    def debug_state(self) -> dict:
        return {"staged_migrations": self.stager.debug_state(),
                "staged_bytes": self.stager.staged_bytes,
                "relaying": sorted(self._relays),
                "imported": sorted(self._imported),
                "migration": dict(self.stats)}


# ---------------------------------------------------------------------------
# co-serving: one transport, two protocols
# ---------------------------------------------------------------------------


class CoServingWorker:
    """One recv loop over a transport shared by a §15
    :class:`~.disagg.DecodeWorker` (prefill->decode admission joins) and
    a §18 :class:`MigrationWorker` (decode->decode live handoffs).

    The two protocols share the ``pg:``/``pgx:`` tags, so they MUST
    share one :class:`~.disagg.PageStager` (pass
    ``decode_worker.stager`` into the MigrationWorker): whichever
    completion frame arrives — ``pge:`` (admission join) or ``rsd:``
    (live handoff) — claims the staged record, and the stager's aborted
    markers make late retransmits drop no matter whose ``_on_page``
    sees them.  Dispatch tries the decode worker first (it owns
    pg/pge/pgx), then the migration worker (rs/rsd/mcx/tok/fin + acks).
    """

    def __init__(self, decode, migration):
        if migration.stager is not decode.stager:
            raise ValueError(
                "co-serving workers must share one PageStager "
                "(MigrationWorker(..., stager=decode_worker.stager))")
        self.decode = decode
        self.migration = migration
        self.transport = decode.transport
        self.device_id = decode.device_id

    def serve_forever(self, idle_timeout: Optional[float] = None) -> None:
        idle_since = time.monotonic()
        while not (self.decode._stop.is_set()
                   or self.migration._stop.is_set()):
            try:
                tag, payload = self.transport.recv_any(timeout=0.1)
            except TransportTimeout:
                if (idle_timeout is not None
                        and time.monotonic() - idle_since > idle_timeout):
                    return
                continue
            idle_since = time.monotonic()
            try:
                if not self.decode.handle_message(tag, payload):
                    self.migration.handle_message(tag, payload)
            except Exception:
                # one malformed frame must not take the replica down
                log.exception("%s: co-served frame %r failed",
                              self.device_id, tag)

    def stop(self) -> None:
        self.decode.stop()
        self.migration.stop()

    def debug_state(self) -> dict:
        out = self.decode.debug_state()
        out["live_migration"] = self.migration.debug_state()
        return out


# ---------------------------------------------------------------------------
# controller
# ---------------------------------------------------------------------------


class MigrationController:
    """Rebalance/drain policy over the gateway registry's load view.

    ``mover(src_rid, dst_rid, n) -> int`` is the injected mechanism (how
    many requests actually moved) — in-process deployments resolve the
    replica's :class:`MigrationWorker` and call ``migrate_out`` per
    picked rid; a remote control plane would RPC the source replica.

    Load = ``active_slots + queue_depth`` from each replica's last
    ``/stats`` probe (the same numbers the router's least-loaded
    tiebreak consumes)."""

    def __init__(self, registry, mover: Callable[[str, str, int], int],
                 *, load_gap: int = 2, max_moves_per_round: int = 1):
        self.registry = registry
        self.mover = mover
        self.load_gap = max(1, int(load_gap))
        self.max_moves = max(1, int(max_moves_per_round))
        self.stats = {"rebalances": 0, "moved_requests": 0,
                      "drained_requests": 0}
        self._flight = get_flight_recorder()

    def load(self, rid: str) -> int:
        r = self.registry.get(rid)
        st = (r.last_stats or {}) if r is not None else {}
        return int(st.get("active_slots", 0)) + int(
            st.get("queue_depth", 0))

    def pick_rebalance(self) -> Optional[Tuple[str, str, int]]:
        """(hot_source, light_target, n) — or None when the fleet is
        balanced.  Sources include draining replicas (their load must
        go somewhere); targets only routable (up, not draining) ones."""
        targets = [r for r in self.registry.routable_replicas()]
        sources = [r for r in self.registry.replica_ids()
                   if self.registry.is_up(r)]
        if not targets or not sources:
            return None
        src = max(sources, key=self.load)
        dst = min(targets, key=self.load)
        if src == dst:
            return None
        gap = self.load(src) - self.load(dst)
        if gap < self.load_gap and not self.registry.is_draining(src):
            return None
        n = (self.load(src) if self.registry.is_draining(src)
             else max(1, gap // 2))
        return src, dst, min(n, self.max_moves)

    def rebalance_once(self) -> int:
        pick = self.pick_rebalance()
        if pick is None:
            return 0
        src, dst, n = pick
        moved = int(self.mover(src, dst, n))
        if moved:
            self.stats["rebalances"] += 1
            self.stats["moved_requests"] += moved
            self._flight.record("migration_rebalance", source=src,
                                target=dst, moved=moved)
        return moved

    def drain(self, rid: str, *, deadline_s: float = 30.0,
              poll_s: float = 0.05) -> int:
        """Drive ``rid`` empty: mark it draining (no new routes, no
        eviction strike) and migrate its in-flight requests to the
        lightest routable peers until none remain or the deadline
        passes.  Returns how many requests moved; requests that finish
        on their own while draining count as drained too (they just
        needed no move)."""
        self.registry.set_draining(rid, True)
        moved = 0
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            targets = [t for t in self.registry.routable_replicas()
                       if t != rid]
            if not targets:
                break
            dst = min(targets, key=self.load)
            n = int(self.mover(rid, dst, self.max_moves))
            if n:
                moved += n
                self.stats["drained_requests"] += n
                continue
            # nothing migratable right now: done, or mid-admission rows
            # need a beat to become movable
            r = self.registry.get(rid)
            st = (r.last_stats or {}) if r is not None else {}
            if (int(st.get("active_slots", 0))
                    + int(st.get("queue_depth", 0))) == 0:
                break
            time.sleep(poll_s)
        self._flight.record("migration_drain", replica=rid, moved=moved)
        return moved
