"""Paged KV cache manager: bookkeeping for a DEVICE-resident block pool.

The dense-era :class:`KVCacheManager` owns host numpy blocks and moves
bytes (H2D on hit, D2H on store).  This manager owns NO data at all —
the K/V pages live on device in the engine's preallocated
``[L, num_blocks, H, block_tokens, D]`` pool arrays (see
``ops/paged_attention.py``), and what lives here is everything the
device cannot do for itself:

- a free list over page ids (``alloc``/``free``), with LRU leaf eviction
  of unpinned radix-tree entries under pressure;
- the same block-keyed :class:`~.radix.RadixTree` as the dense manager,
  giving longest-partial-prefix matches — but a hit now returns page
  IDS for the caller's block table, not bytes (``dwt_kvcache_h2d_bytes``
  stays 0 by construction);
- copy-FREE stores: :meth:`store_shared` adopts a request's
  already-on-device full-prompt pages into the tree (ownership
  transfer, no copy), so the next shared-prefix request references the
  very same pages.

Ownership rule (the one invariant everything else hangs off): every
allocated page has exactly one owner — the radix tree (freed only by
eviction) or one request (freed at completion).  A request's table may
REFERENCE tree pages (its matched prefix, its adopted stores); those
references are protected by node pins (leases), never by ownership.
Tree pages are immutable: decode writes only land at positions >= the
prompt length, which sit in the request's own private pages.

Thread-safety matches the dense manager: one lock, mutations on the
scheduler thread, ``snapshot``/``debug_state`` from scrape threads.
"""

from __future__ import annotations

import threading
from typing import List, Optional

import numpy as np

from ...telemetry.flightrecorder import get_flight_recorder
from .manager import apply_byte_budget
from .radix import RadixTree


class PagedBlockLease:
    """A pin on a radix node protecting the pages a block table
    references — matched prefixes and adopted stores.  Unlike the dense
    lease (released the moment bytes are copied out), a paged lease
    lives as long as the referencing table does: release at request
    completion, or the evictor may hand the pages to someone else
    mid-decode."""

    def __init__(self, mgr: "PagedKVCacheManager", node,
                 block_ids: List[int], tokens: int):
        self._mgr = mgr
        self._node = node
        self.block_ids = block_ids
        self.tokens = tokens
        self._released = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._mgr._release(self._node)


class PagedKVCacheManager:
    """Radix-tree prefix sharing + page-id allocation, zero data moved."""

    def __init__(self, num_layers: int, num_kv_heads: int, head_dim: int,
                 num_blocks: int, block_tokens: int, dtype,
                 kv_dtype: Optional[str] = None):
        from ...ops.quant import (kv_scale_token_head_bytes,
                                  kv_token_head_bytes, resolve_kv_dtype)
        bt = int(block_tokens)
        self.kv_dtype = resolve_kv_dtype(kv_dtype)
        # block_bytes accounts the ACTUAL page width incl. the quantized
        # layouts' scale sidecar — one owner (ops/quant.py) shared with
        # make_kv_backend's byte-budget admission
        token_heads = 2 * int(num_layers) * int(num_kv_heads) * bt
        self.block_bytes = token_heads * kv_token_head_bytes(
            int(head_dim), self.kv_dtype, dtype)
        self.scale_block_bytes = token_heads * kv_scale_token_head_bytes(
            self.kv_dtype)
        num_blocks = apply_byte_budget(int(num_blocks), self.block_bytes)
        if num_blocks < 1:
            raise ValueError(
                "PagedKVCacheManager needs >= 1 block (the paged layout "
                "has no cache-off mode: the pool IS the decode cache)")
        self.num_blocks = num_blocks
        self.block_tokens = bt
        self.tree = RadixTree()
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._lock = threading.Lock()
        self.epoch = 0
        self.stats = {"hits": 0, "misses": 0, "partial_hit_tokens": 0,
                      "stores": 0, "stored_blocks": 0,
                      "evicted_blocks": 0, "promote_h2d_bytes": 0}
        self._flight = get_flight_recorder()
        # capacity tier below the pool (docs/DESIGN.md §21), installed
        # by the pool OWNER (only it can gather page bytes): the hook
        # receives each eviction victim's (full key path, freed ids)
        # BEFORE those ids are handed back out; ``tier`` makes the
        # tier's occupancy ride this manager's snapshot()/stats surface
        self.demote_hook = None
        self.tier = None

    @classmethod
    def for_model(cls, cfg, num_blocks: int, block_tokens: int,
                  dtype=None,
                  kv_dtype: Optional[str] = None) -> "PagedKVCacheManager":
        dtype = dtype if dtype is not None else cfg.dtype
        return cls(cfg.num_layers, cfg.num_kv_heads, cfg.head_dim,
                   num_blocks, block_tokens, dtype, kv_dtype=kv_dtype)

    # ------------------------------------------------------------------
    # lookup (same tree walk as the dense manager)

    def _block_keys(self, prompt, n_blocks: int):
        bt = self.block_tokens
        return [tuple(int(t) for t in prompt[i * bt:(i + 1) * bt])
                for i in range(n_blocks)]

    def match(self, prompt) -> Optional[PagedBlockLease]:
        """Longest cached block-prefix (capped at ``len(prompt) - 1``
        tokens) as a pinned lease of page IDS — zero bytes move; the
        caller writes the ids into its block table and holds the lease
        until the table dies."""
        prompt = np.asarray(prompt).reshape(-1)
        max_blocks = (len(prompt) - 1) // self.block_tokens
        if max_blocks < 1:
            return None
        with self._lock:
            ids, node = self.tree.match(
                self._block_keys(prompt, max_blocks))
            if not ids:
                self.stats["misses"] += 1
                return None
            self.tree.acquire(node)
            tokens = len(ids) * self.block_tokens
            self.stats["hits"] += 1
            self.stats["partial_hit_tokens"] += tokens
        self._flight.record("kvcache_hit", tokens=tokens,
                            blocks=len(ids), prompt_len=len(prompt),
                            layout="paged")
        return PagedBlockLease(self, node, list(ids), tokens)

    def peek(self, prompt) -> int:
        prompt = np.asarray(prompt).reshape(-1)
        max_blocks = (len(prompt) - 1) // self.block_tokens
        if max_blocks < 1:
            return 0
        with self._lock:
            ids, _ = self.tree.match(
                self._block_keys(prompt, max_blocks), touch=False)
            return len(ids) * self.block_tokens

    def _release(self, node) -> None:
        with self._lock:
            self.tree.release(node)

    # ------------------------------------------------------------------
    # allocation

    def _reclaimable_locked(self) -> int:
        """Tree blocks eviction could eventually free: everything except
        nodes that are pinned or have a pinned descendant (a pin keeps
        its whole ancestor chain non-childless, so those nodes can never
        become evictable leaves while the lease lives)."""
        protected = set()
        stack = [self.tree.root]
        pinned = []
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node.refs > 0:
                pinned.append(node)
        for node in pinned:
            while node is not None and id(node) not in protected:
                protected.add(id(node))
                node = node.parent
        out = 0
        stack = [self.tree.root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if id(node) not in protected:
                out += len(node.blocks)
        return out

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` free page ids, evicting LRU unpinned tree leaves under
        pressure; None (nothing allocated, nothing evicted) when the
        request is infeasible — feasibility is checked FIRST, so a
        pending admission that cannot be satisfied does not flush the
        prefix cache on every retry."""
        evicted = 0
        demote = []
        with self._lock:
            if len(self._free) + self._reclaimable_locked() < n:
                return None
            while len(self._free) < n:
                path, freed = self.tree.evict_lru_leaf_entry()
                assert freed, "feasibility check promised evictable blocks"
                self._free.extend(freed)
                evicted += len(freed)
                if self.demote_hook is not None:
                    demote.append((path, freed))
            out = [self._free.pop() for _ in range(n)]
            if evicted:
                self.stats["evicted_blocks"] += evicted
                self.epoch += 1
        # demote OUTSIDE the lock (the hook d2h-gathers page bytes and
        # may block) but BEFORE returning the allocation: the caller
        # has not seen the ids yet, so none of the freed pages — some
        # of which are being handed right back out — can be rewritten
        # before the gather dispatch reads them.  The hook never raises
        # (a failed demotion costs cache capacity, not admission).
        for path, freed in demote:
            self.demote_hook(path, freed)
        if evicted:
            self._flight.record("kvcache_evict", blocks=evicted,
                                layout="paged")
        return out

    def free(self, block_ids) -> None:
        """Return request-owned pages to the pool (never tree-owned ones
        — eviction is the only path that frees those)."""
        with self._lock:
            for bid in block_ids:
                if not 0 <= bid < self.num_blocks:
                    raise ValueError(f"bad block id {bid}")
                self._free.append(bid)
            if len(self._free) > self.num_blocks:
                raise RuntimeError("double free: pool over capacity")

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - len(self._free)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    # ------------------------------------------------------------------
    # store (ownership adoption, no copy)

    def store_shared(self, prompt, block_ids) -> tuple:
        """Insert the prompt's full blocks into the tree by ADOPTING the
        caller's pages: ``block_ids[j]`` must already hold block ``j``'s
        K/V on device.  Blocks the tree already covers are declined (the
        caller keeps owning its redundant copies); adopted ids become
        tree-owned.  ``block_ids[j]`` may be None for blocks the caller
        BELIEVES are already covered (it allocated no page for them —
        the backend's tail-only store): if the tree disagrees (an
        eviction raced the caller's coverage peek), insertion stops
        there — a stored proper prefix is still a valid cache entry,
        and adopting a nonexistent page would corrupt the pool.
        Returns ``(adopted_ids, lease)`` — the lease pins the stored
        path so eviction cannot free adopted (or prefix-matched) pages
        while the caller's table still references them; release it at
        request completion.
        """
        prompt = np.asarray(prompt).reshape(-1)
        bt = self.block_tokens
        n_blocks = len(prompt) // bt
        if n_blocks < 1:
            return [], None
        keys = self._block_keys(prompt, n_blocks)
        block_ids = list(block_ids)
        if len(block_ids) < n_blocks:
            raise ValueError(
                f"store_shared needs one page per full prompt block: "
                f"{len(block_ids)} ids for {n_blocks} blocks")
        adopted: List[int] = []

        with self._lock:
            def adopt(j):
                if block_ids[j] is None:
                    return None          # caller has no page: stop here
                adopted.append(block_ids[j])
                return block_ids[j]

            n_existing, added = self.tree.insert(keys, adopt)
            assert added == len(adopted)
            # pin the deepest node covering the stored prefix: the walk
            # is the same one `match` does, without stats or LRU touch
            ids, node = self.tree.match(keys, touch=False)
            lease = None
            if not node.is_root():
                self.tree.acquire(node)
                lease = PagedBlockLease(self, node, list(ids),
                                        len(ids) * bt)
            self.epoch += 1
            self.stats["stores"] += 1
            self.stats["stored_blocks"] += added
        if added:
            self._flight.record("kvcache_admit", blocks=added,
                                tokens=added * bt,
                                prompt_len=len(prompt), layout="paged")
        return adopted, lease

    # ------------------------------------------------------------------

    def note_promote_h2d(self, nbytes: int) -> None:
        """Count a tier promotion's adopt-scatter bytes: the ONE honest
        exception to the paged layout's h2d_bytes == 0 claim (docs/
        DESIGN.md §21) — the bytes really do cross host -> device."""
        with self._lock:
            self.stats["promote_h2d_bytes"] += int(nbytes)

    def reset_stats(self) -> None:
        with self._lock:
            for k in self.stats:
                self.stats[k] = 0
        if self.tier is not None:
            self.tier.reset_stats()

    def snapshot(self) -> dict:
        """Counters + occupancy for ``/stats`` and the ``dwt_kvcache_*``
        bridge.  ``h2d_bytes`` is 0 by construction on every lookup /
        store path (hits are block-table references, stores ownership
        adoptions); the ONE thing that can move bytes host -> device is
        a §21 tier promotion, counted honestly here.  ``resident_bytes``
        (host) stays 0 — the pool is device HBM, reported as
        ``device_resident_bytes``/``capacity_bytes``; the HOST tier
        reports its own bytes under the ``tier`` sub-dict."""
        with self._lock:
            used = self.num_blocks - len(self._free)
            out = dict(self.stats,
                       layout="paged",
                       h2d_bytes=self.stats["promote_h2d_bytes"],
                       block_tokens=self.block_tokens,
                       blocks_total=self.num_blocks,
                       blocks_used=used,
                       resident_bytes=0,
                       device_resident_bytes=used * self.block_bytes,
                       capacity_bytes=self.num_blocks * self.block_bytes,
                       page_dtype=self.kv_dtype,
                       quant_scale_bytes=used * self.scale_block_bytes,
                       tree_blocks=self.tree.block_count,
                       nodes=self.tree.node_count - 1)
        if self.tier is not None:
            # outside self._lock (lock order: manager -> tier, never
            # nested the other way).  The digest list is the gateway's
            # second-chance routing hint; it rides /stats so the
            # registry prober carries it for free.
            frag = self.tier.snapshot()
            frag["digest"] = self.tier.digest()["digests"]
            out["tier"] = frag
        return out

    def debug_state(self) -> dict:
        snap = self.snapshot()
        with self._lock:
            leaves = sorted(self.tree.evictable_leaves(),
                            key=lambda n: n.last_use)[:8]
            snap["lru_leaves"] = [
                {"blocks": len(n.blocks), "last_use": n.last_use}
                for n in leaves]
            snap["leased_nodes"] = sum(
                1 for n in self._iter_nodes() if n.refs > 0)
        return snap

    def _iter_nodes(self):
        stack = [self.tree.root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            yield node
