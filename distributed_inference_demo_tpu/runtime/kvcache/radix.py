"""Radix tree over token BLOCKS: longest-partial-prefix match for KV reuse.

The tree the manager walks (SGLang's RadixAttention structure, block-
granular like vLLM's prefix hash): every edge is labeled with a run of
block keys — each key the tuple of ``block_tokens`` token ids that block
covers — and carries the pool block ids holding that run's K/V.  A
lookup therefore returns the longest run of WHOLE cached blocks agreeing
with a new prompt's head, which is exactly the set of positions whose KV
can be reused verbatim (causal attention: a prefix's KV depends only on
the prefix).  Unlike the full-prompt LRU this replaces, a hit can land
mid-prompt — shorter than any stored prompt, shorter than the new one.

Concurrency/lifetime rules (the "copy-on-write lease" contract):

- Stored blocks are IMMUTABLE: the store path only ever writes freshly
  allocated blocks, readers copy block data out into their own cache
  rows.  Writers never touch a visible block, so sharing needs no
  versioning — only a guarantee that eviction cannot free a block while
  a reader is copying it.
- That guarantee is the refcount: ``acquire`` pins a node (and,
  transitively, its ancestors — eviction only removes CHILDLESS nodes,
  and a pinned node keeps the chain above it non-childless).  ``release``
  unpins.  Eviction skips any node with ``refs > 0``.
- Eviction is LRU over evictable leaves (childless, unpinned), whole
  nodes at a time; node splits during insert keep block identity, so an
  interior split never copies or frees K/V.

Pure host-side bookkeeping — the tree never touches numpy data; it maps
block keys to pool block ids and owns their lifetime.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

BlockKey = Tuple[int, ...]          # the block's token ids, len block_tokens


class RadixNode:
    __slots__ = ("keys", "blocks", "children", "parent", "refs",
                 "last_use")

    def __init__(self, keys: List[BlockKey], blocks: List[int],
                 parent: Optional["RadixNode"]):
        self.keys = keys            # per-block token tuples along this edge
        self.blocks = blocks        # pool block ids, len == len(keys)
        self.children: Dict[BlockKey, RadixNode] = {}
        self.parent = parent
        self.refs = 0               # live leases pinning this node
        self.last_use = 0           # LRU clock tick

    def is_root(self) -> bool:
        return self.parent is None


class RadixTree:
    """Block-keyed radix tree with refcounted nodes and LRU leaf eviction."""

    def __init__(self):
        self.root = RadixNode([], [], None)
        self._clock = itertools.count(1)
        self.node_count = 1         # incl. root
        self.block_count = 0        # blocks referenced by the tree

    # ------------------------------------------------------------------
    # lookup

    def match(self, keys: List[BlockKey], touch: bool = True):
        """Longest whole-block prefix of ``keys`` present in the tree.

        Returns ``(block_ids, node)``: the matched pool blocks in order
        and the node holding the LAST matched block (the root for a
        0-block match).  Touches the LRU clock along the path unless
        ``touch=False`` (a pure classification peek must not perturb
        eviction order).  A match may end mid-edge — blocks within an
        edge are independent units, so no split is needed to consume
        part of one.  ONE owner of the walk: the manager's ``peek``
        rides this too.
        """
        tick = next(self._clock) if touch else None
        node, ids, i = self.root, [], 0
        if touch:
            node.last_use = tick
        while i < len(keys):
            child = node.children.get(keys[i])
            if child is None:
                break
            n = 0
            while (n < len(child.keys) and i + n < len(keys)
                   and child.keys[n] == keys[i + n]):
                n += 1
            ids.extend(child.blocks[:n])
            if touch:
                child.last_use = tick
            if n < len(child.keys):      # partial edge: stop inside it
                return ids, child
            node, i = child, i + n
        return ids, node

    def acquire(self, node: RadixNode) -> None:
        node.refs += 1

    def release(self, node: RadixNode) -> None:
        if node.refs <= 0:
            raise RuntimeError("release without matching acquire")
        node.refs -= 1

    # ------------------------------------------------------------------
    # insert

    def insert(self, keys: List[BlockKey], alloc) -> Tuple[int, int]:
        """Ensure ``keys`` is present, allocating missing blocks.

        ``alloc(block_index)`` is called once per MISSING block (in
        order) and must return a pool block id — after filling it with
        that block's K/V — or None to stop (pool exhausted and nothing
        evictable); a stored proper prefix is still a valid cache entry.

        Returns ``(n_existing, n_added)`` in blocks.
        """
        tick = next(self._clock)
        node, i = self.root, 0
        node.last_use = tick
        while i < len(keys):
            child = node.children.get(keys[i])
            if child is None:
                break
            n = 0
            while (n < len(child.keys) and i + n < len(keys)
                   and child.keys[n] == keys[i + n]):
                n += 1
            child.last_use = tick
            if n < len(child.keys):
                if i + n == len(keys):
                    # new sequence ends inside the edge: nothing to add
                    # (the edge's tail blocks simply extend past it)
                    return len(keys), 0
                # diverges mid-edge: split so the new tail can branch
                child = self._split(child, n)
                child.last_use = tick
                node, i = child, i + n
                break
            node, i = child, i + n
        n_existing, added = i, []
        # pin the attach node: ``alloc`` may evict under pool pressure,
        # and the LRU victim (or a post-evict chain merge) must never be
        # the node we are about to hang the new edge off
        node.refs += 1
        try:
            for j in range(i, len(keys)):
                bid = alloc(j)
                if bid is None:
                    break
                added.append((keys[j], bid))
        finally:
            node.refs -= 1
        if added:
            new = RadixNode([k for k, _ in added], [b for _, b in added],
                            node)
            new.last_use = tick
            node.children[added[0][0]] = new
            self.node_count += 1
            self.block_count += len(added)
        return n_existing, len(added)

    def _split(self, node: RadixNode, n: int) -> RadixNode:
        """Split ``node``'s edge after its first ``n`` blocks; returns the
        new upper node.  Pure relabeling: block ids move, K/V doesn't."""
        upper = RadixNode(node.keys[:n], node.blocks[:n], node.parent)
        upper.last_use = node.last_use
        node.parent.children[upper.keys[0]] = upper
        node.keys, node.blocks = node.keys[n:], node.blocks[n:]
        node.parent = upper
        upper.children[node.keys[0]] = node
        # a lease pinned to the lower node keeps protecting every block
        # it matched: its ancestors (upper included) now have children
        self.node_count += 1
        return upper

    # ------------------------------------------------------------------
    # eviction

    def evictable_leaves(self) -> List[RadixNode]:
        out, stack = [], [self.root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if (not node.is_root() and not node.children
                    and node.refs == 0):
                out.append(node)
        return out

    def evict_lru_leaf(self) -> List[int]:
        """Remove the least-recently-used evictable leaf; returns its
        pool block ids (for the caller to free), or [] when nothing is
        evictable (every leaf is leased)."""
        return self.evict_lru_leaf_entry()[1]

    def evict_lru_leaf_entry(self) -> Tuple[List[BlockKey], List[int]]:
        """Like :meth:`evict_lru_leaf`, but also returns the victim's
        FULL root-to-leaf key path (ancestor edge keys + its own) — the
        tier-demotion hook keys the freed blocks by their chain digest,
        which covers every preceding block, not just the leaf's edge.
        The path's last ``len(blocks)`` keys label the returned blocks.
        Returns ``([], [])`` when nothing is evictable."""
        leaves = self.evictable_leaves()
        if not leaves:
            return [], []
        victim = min(leaves, key=lambda n: n.last_use)
        parent = victim.parent
        path: List[BlockKey] = list(victim.keys)
        node = parent
        while node is not None:
            path = list(node.keys) + path
            node = node.parent
        del parent.children[victim.keys[0]]
        self.node_count -= 1
        self.block_count -= len(victim.blocks)
        # merge a now-single-child unpinned parent back into one edge so
        # repeated split/evict cycles don't accrete chain nodes
        if (not parent.is_root() and len(parent.children) == 1
                and parent.refs == 0):
            (only,) = parent.children.values()
            only.keys = parent.keys + only.keys
            only.blocks = parent.blocks + only.blocks
            only.parent = parent.parent
            parent.parent.children[only.keys[0]] = only
            only.last_use = max(only.last_use, parent.last_use)
            self.node_count -= 1
        return path, victim.blocks

    # ------------------------------------------------------------------
    # invariants (test hook)

    def check(self) -> None:
        """Structural invariants; raises AssertionError on violation."""
        seen_blocks = set()
        count, blocks = 0, 0
        stack = [self.root]
        while stack:
            node = stack.pop()
            count += 1
            blocks += len(node.blocks)
            assert len(node.keys) == len(node.blocks)
            assert node.is_root() or node.keys, "empty non-root edge"
            for first, child in node.children.items():
                assert child.keys[0] == first
                assert child.parent is node
                stack.append(child)
            for bid in node.blocks:
                assert bid not in seen_blocks, "block in two nodes"
                seen_blocks.add(bid)
            assert node.refs >= 0
        assert count == self.node_count, (count, self.node_count)
        assert blocks == self.block_count, (blocks, self.block_count)
