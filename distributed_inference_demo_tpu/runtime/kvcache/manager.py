"""The KV cache manager: the ONE prefix-reuse path for every engine.

Ties the host block pool (``pool.py``) and the block radix tree
(``radix.py``) into the surface the engines consume:

- ``match(prompt) -> KVLease | None`` — longest-partial-prefix lookup.
  A lease pins the matched nodes against eviction (refcount) until the
  caller has copied the blocks out (``gather`` + ``release``, or the
  ``with`` form).  Matched length is whole blocks, capped at
  ``len(prompt) - 1`` so the caller's suffix forward is never empty.
- ``store(prompt, keys, values, row)`` — slice a freshly prefilled
  device cache row into full blocks and insert them (one D2H copy for
  the missing tail; already-cached blocks are recognized, not
  re-copied).  Stores happen at PREFILL time — the next request sharing
  the prefix hits even while this one is still decoding.
- ``peek(prompt)`` — match length without stats, leases, or LRU touch
  (scheduler classification, e.g. batching's ``_needs_stream``).

Eviction is LRU over unpinned leaves, triggered by allocation pressure:
``store`` evicts just enough to place the new blocks and gives up (still
correct, smaller cache) when every leaf is leased.  The byte budget is
the pool's preallocated capacity — there is nothing to account drift
against.

Reuse is EXACT by construction: blocks are keyed by the exact token ids
they cover, and causal attention makes a prefix's K/V independent of
any suffix — a primed generation is token-identical to a cold one
(pinned by tests/test_kvcache.py and the engine exactness tests).

Config knobs (CLI flags override env, 0 disables):
``DWT_KVCACHE_BLOCKS`` (pool size, blocks), ``DWT_KVCACHE_BLOCK_TOKENS``
(granularity, default 16), ``DWT_KVCACHE_BYTES`` (cap: shrinks BLOCKS
to fit when set).
"""

from __future__ import annotations

import threading
from typing import List, Optional

import numpy as np

from ...telemetry._env import env_int
from ...telemetry.flightrecorder import get_flight_recorder
from .pool import KVBlockPool
from .radix import RadixTree

DEFAULT_BLOCK_TOKENS = 16


def resolve_kvcache_config(num_blocks: Optional[int] = None,
                           block_tokens: Optional[int] = None,
                           default_blocks: int = 0):
    """(num_blocks, block_tokens) from explicit args over env knobs over
    ``default_blocks`` (each engine's own default — the batching
    scheduler defaults ON, the single-request engines default OFF).
    ``None`` means "not specified"; 0 blocks disables the subsystem."""
    if num_blocks is None:
        num_blocks = env_int("DWT_KVCACHE_BLOCKS", default_blocks)
    if block_tokens is None:
        block_tokens = env_int("DWT_KVCACHE_BLOCK_TOKENS",
                               DEFAULT_BLOCK_TOKENS)
    return num_blocks, block_tokens


def apply_byte_budget(num_blocks: int, block_bytes: int) -> int:
    """Shrink ``num_blocks`` to the DWT_KVCACHE_BYTES cap (0 = uncapped).
    Never rounds up — the env cap is a ceiling, not a target."""
    budget = env_int("DWT_KVCACHE_BYTES", 0)
    if budget > 0 and block_bytes > 0:
        num_blocks = min(num_blocks, budget // block_bytes)
    return num_blocks


class KVLease:
    """A pinned prefix match: ``tokens`` positions of reusable KV.

    The pin (a refcount on the deepest matched radix node) guarantees
    eviction cannot free the matched blocks before the caller copies
    them out; stored blocks are never mutated, so the copy the caller
    takes is the copy-on-write snapshot.  Release promptly — an
    unreleased lease shrinks what eviction may reclaim."""

    def __init__(self, mgr: "KVCacheManager", node, block_ids: List[int],
                 tokens: int):
        self._mgr = mgr
        self._node = node
        self.block_ids = block_ids
        self.tokens = tokens
        self._released = False

    def gather(self):
        """Host ``[L, H, tokens, D]`` K/V run for the matched blocks.
        The copy-out is destined for a device cache row, so the bytes
        count toward ``h2d_bytes`` (the dense layout's per-hit H2D cost
        the paged layout exists to delete)."""
        if self._released:
            raise RuntimeError("gather on a released lease")
        k, v = self._mgr.pool.gather(self.block_ids)
        k, v = k[:, :, :self.tokens], v[:, :, :self.tokens]
        with self._mgr._lock:
            self._mgr.stats["h2d_bytes"] += k.nbytes + v.nbytes
        return k, v

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._mgr._release(self._node)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class KVCacheManager:
    """Block-level KV cache with radix-tree prefix sharing."""

    def __init__(self, num_layers: int, num_kv_heads: int, head_dim: int,
                 num_blocks: int, block_tokens: int, dtype):
        num_layers = int(num_layers)
        bt = int(block_tokens)
        block_bytes = (2 * num_layers * int(num_kv_heads) * bt
                       * int(head_dim) * np.dtype(dtype).itemsize)
        num_blocks = apply_byte_budget(int(num_blocks), block_bytes)
        if num_blocks < 1:
            raise ValueError(
                "KVCacheManager needs >= 1 block (0 means: don't build "
                "a manager at all)")
        self.block_tokens = bt
        self.pool = KVBlockPool(num_blocks, num_layers, num_kv_heads,
                                bt, head_dim, dtype)
        self.tree = RadixTree()
        # serializes tree/pool mutation: engines on scheduler threads and
        # /metrics scrapes on HTTP threads share one manager
        self._lock = threading.Lock()
        # content mutation epoch: memoized classifications (e.g.
        # batching's _needs_stream) revalidate against it
        self.epoch = 0
        self.stats = {"hits": 0, "misses": 0, "partial_hit_tokens": 0,
                      "stores": 0, "stored_blocks": 0,
                      "evicted_blocks": 0, "h2d_bytes": 0}
        self._flight = get_flight_recorder()

    @classmethod
    def for_model(cls, cfg, num_blocks: int, block_tokens: int,
                  dtype=None) -> Optional["KVCacheManager"]:
        """Build from a ModelConfig (+ optional reduced cache dtype —
        blocks store whatever the engine's KV cache holds, so a hit
        round-trips the exact on-device bytes).  Returns None when the
        DWT_KVCACHE_BYTES ceiling leaves room for less than one block:
        for the engines that means "cache off", and an env knob
        documented as a ceiling must never crash serve startup."""
        dtype = dtype if dtype is not None else cfg.dtype
        block_bytes = (2 * int(cfg.num_layers) * int(cfg.num_kv_heads)
                       * int(block_tokens) * int(cfg.head_dim)
                       * np.dtype(dtype).itemsize)
        if apply_byte_budget(int(num_blocks), block_bytes) < 1:
            return None
        return cls(cfg.num_layers, cfg.num_kv_heads, cfg.head_dim,
                   num_blocks, block_tokens, dtype)

    # ------------------------------------------------------------------

    def _block_keys(self, prompt, n_blocks: int):
        bt = self.block_tokens
        return [tuple(int(t) for t in prompt[i * bt:(i + 1) * bt])
                for i in range(n_blocks)]

    def match(self, prompt) -> Optional[KVLease]:
        """Longest cached block-prefix of ``prompt`` (capped at
        ``len(prompt) - 1`` tokens), as a pinned lease, or None."""
        prompt = np.asarray(prompt).reshape(-1)
        max_blocks = (len(prompt) - 1) // self.block_tokens
        if max_blocks < 1:
            # too short to ever reuse a whole block: not a lookup at all
            return None
        with self._lock:
            ids, node = self.tree.match(
                self._block_keys(prompt, max_blocks))
            if not ids:
                self.stats["misses"] += 1
                return None
            self.tree.acquire(node)
            tokens = len(ids) * self.block_tokens
            self.stats["hits"] += 1
            self.stats["partial_hit_tokens"] += tokens
        self._flight.record("kvcache_hit", tokens=tokens,
                            blocks=len(ids), prompt_len=len(prompt))
        return KVLease(self, node, ids, tokens)

    def peek(self, prompt) -> int:
        """Matched token count with no stats, lease, or LRU touch (the
        same walk as ``match`` — RadixTree.match is the one owner)."""
        prompt = np.asarray(prompt).reshape(-1)
        max_blocks = (len(prompt) - 1) // self.block_tokens
        if max_blocks < 1:
            return 0
        with self._lock:
            ids, _node = self.tree.match(
                self._block_keys(prompt, max_blocks), touch=False)
            return len(ids) * self.block_tokens

    def _release(self, node) -> None:
        with self._lock:
            self.tree.release(node)

    # ------------------------------------------------------------------

    def store(self, prompt, keys_dev, values_dev, row: int = 0) -> int:
        """Cache every full block of ``prompt`` from a prefilled device
        cache ``[L, B, H, S, D]`` (row ``row``); returns blocks added.

        Only the MISSING tail is copied device→host (one slice per
        store); blocks already in the tree are recognized by key.  Under
        pool pressure, LRU leaves are evicted to make room; if eviction
        cannot free enough (all leased), the tail is simply not cached.
        """
        prompt = np.asarray(prompt).reshape(-1)
        bt = self.block_tokens
        n_blocks = len(prompt) // bt
        if n_blocks < 1:
            return 0
        keys = self._block_keys(prompt, n_blocks)
        with self._lock:
            existing_ids, _ = self.tree.match(keys)
            n_existing = len(existing_ids)
        if n_existing >= n_blocks:
            return 0
        # The D2H copy runs OUTSIDE the lock: it forces a device sync
        # (possibly MBs of K/V), and a /metrics scrape's snapshot() or a
        # sibling engine's match() must not stall behind it.  ONE slice
        # for the whole missing tail, then split into blocks
        # ([L, H, n*bt, D] -> per-block [L, H, bt, D]).
        lo, hi = n_existing * bt, n_blocks * bt
        k_tail = np.asarray(keys_dev[:, row, :, lo:hi, :])
        v_tail = np.asarray(values_dev[:, row, :, lo:hi, :])
        with self._lock:
            evicted = 0

            def alloc(j):
                nonlocal evicted
                if j < n_existing:
                    # a concurrent eviction removed blocks we classified
                    # as existing (and did not copy): skip this store —
                    # caching less is always correct
                    return None
                bid = self.pool.alloc()
                while bid is None:
                    freed = self.tree.evict_lru_leaf()
                    if not freed:
                        return None          # everything left is leased
                    self.pool.free(freed)
                    evicted += len(freed)
                    bid = self.pool.alloc()
                o = (j - n_existing) * bt
                self.pool.write(bid, k_tail[:, :, o:o + bt],
                                v_tail[:, :, o:o + bt])
                return bid

            # insert re-walks under the lock, so blocks another store
            # added meanwhile are recognized (alloc only runs for what
            # is still missing, always at offsets we actually copied)
            _, added = self.tree.insert(keys, alloc)
            self.epoch += 1
            self.stats["stores"] += 1
            self.stats["stored_blocks"] += added
            if evicted:
                self.stats["evicted_blocks"] += evicted
        if evicted:
            self._flight.record("kvcache_evict", blocks=evicted)
        if added:
            self._flight.record("kvcache_admit", blocks=added,
                                tokens=added * bt,
                                prompt_len=len(prompt))
        return added

    # ------------------------------------------------------------------

    def reset_stats(self) -> None:
        with self._lock:
            for k in self.stats:
                self.stats[k] = 0

    def snapshot(self) -> dict:
        """Counters + occupancy for ``/stats`` and the ``dwt_kvcache_*``
        catalog bridge."""
        with self._lock:
            return dict(self.stats,
                        layout="dense",
                        block_tokens=self.block_tokens,
                        blocks_total=self.pool.num_blocks,
                        blocks_used=self.pool.used_blocks,
                        resident_bytes=self.pool.resident_bytes,
                        device_resident_bytes=0,   # host pool: see paged.py
                        capacity_bytes=self.pool.capacity_bytes,
                        nodes=self.tree.node_count - 1)   # excl. root

    def debug_state(self) -> dict:
        """``GET /debugz`` fragment: occupancy + the LRU picture (a few
        coldest evictable leaves), bounded and read-only."""
        snap = self.snapshot()
        with self._lock:
            leaves = sorted(self.tree.evictable_leaves(),
                            key=lambda n: n.last_use)[:8]
            snap["lru_leaves"] = [
                {"blocks": len(n.blocks), "last_use": n.last_use}
                for n in leaves]
            snap["leased_nodes"] = sum(
                1 for n in self._iter_nodes() if n.refs > 0)
        return snap

    def _iter_nodes(self):
        stack = [self.tree.root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            yield node
