"""Block-level KV cache with radix-tree prefix sharing (docs/DESIGN.md
§10 host pool, §11 paged layout, §14 universal-paged contract).

The single prefix-reuse path for the serving stack, behind the
:mod:`~.backend` seam every engine consumes.  The blocks live on
device — the batching scheduler's slot cache IS a page pool addressed
through block tables, the ring stage workers hold per-stage page
pools, and the single-request engines keep a device-resident prefix
pool (:class:`~.backend.PagedKVBackend`).  Hits are device gathers /
block-table references, stores are device scatters / ownership
adoptions — zero bytes cross the host boundary
(``dwt_kvcache_h2d_bytes_total == 0`` structurally).

The dense host-pool *layout* (``--kv-layout dense``, deprecated in the
disaggregation release) is REMOVED: :func:`resolve_kv_layout` fails
loudly on it.  The §10 host pool itself (:class:`KVCacheManager`)
survives as a host-staging building block, but no engine runs behind
it — the dense backend class and the legacy require-dense shim are
deleted, and ``tools/check_kv_layout.py`` lints that neither identifier
regrows anywhere in the package.

Layout selection: the ``kv_layout`` engine kwarg / ``--kv-layout`` flag
over the ``DWT_KV_LAYOUT`` env knob over the default ``paged`` — all
three funnel through :func:`resolve_kv_layout`, the one owner.

Page WIDTH selection mirrors it (docs/DESIGN.md §17): the ``kv_dtype``
kwarg / ``--kv-dtype`` flag over ``DWT_KV_DTYPE`` over ``bf16``,
funneled through :func:`resolve_kv_dtype` (owned by ``ops/quant.py``
next to the quantized-page rails, re-exported here) — called at every
pool-creation site, so the env knob reaches engines without an explicit
kwarg.
"""

import os

from ...ops.quant import KV_DTYPES, resolve_kv_dtype
from .backend import PagedKVBackend, make_kv_backend
from .manager import (DEFAULT_BLOCK_TOKENS, KVCacheManager, KVLease,
                      resolve_kvcache_config)
from .paged import PagedBlockLease, PagedKVCacheManager
from .pool import KVBlockPool
from .radix import RadixTree
from .tiered import (TieredKVStore, make_demote_hook, promote_prefix,
                     resolve_tier_config)

KV_LAYOUTS = ("paged",)

# The message every removed-layout path fails with — one string so the
# CLI flag, the env knob, and the direct engine kwarg all name the same
# removal and the same migration.
_DENSE_REMOVED_MSG = (
    "kv_layout='dense' was REMOVED in the gateway release "
    "(docs/DESIGN.md §14): the host-pool escape hatch was deprecated "
    "for one release and is deleted — drop --kv-layout dense / "
    "DWT_KV_LAYOUT=dense; the paged layout is the only layout and "
    "needs no flag")


def resolve_kv_layout(kv_layout=None) -> str:
    """``kv_layout`` arg over ``DWT_KV_LAYOUT`` env over "paged".

    The one owner of layout resolution: the removed dense layout fails
    here, loudly, naming the removal — whether it arrives via flag, env
    knob, or direct engine kwarg (none can bypass this funnel)."""
    layout = kv_layout or os.environ.get("DWT_KV_LAYOUT", "") or "paged"
    if layout == "dense":
        raise ValueError(_DENSE_REMOVED_MSG)
    if layout not in KV_LAYOUTS:
        raise ValueError(
            f"unknown kv layout {layout!r}; expected one of {KV_LAYOUTS}")
    return layout


__all__ = ["KVBlockPool", "KVCacheManager", "KVLease",
           "PagedKVBackend", "make_kv_backend",
           "PagedBlockLease", "PagedKVCacheManager", "RadixTree",
           "resolve_kvcache_config", "resolve_kv_layout",
           "resolve_kv_dtype", "DEFAULT_BLOCK_TOKENS",
           "KV_LAYOUTS", "KV_DTYPES",
           "TieredKVStore", "make_demote_hook", "promote_prefix",
           "resolve_tier_config"]
