"""Block-level KV cache with radix-tree prefix sharing (docs/DESIGN.md
§10 dense layout, §11 paged layout).

The single prefix-reuse path for the serving stack: the continuous-
batching scheduler, the plain ``InferenceEngine`` generate paths, and
the speculative target engine all match and store through one manager.
Two layouts share the radix tree and the block granularity:

- **dense** (:class:`KVCacheManager`): host numpy block pool; hits pay
  one H2D load into the engine's dense cache rows, stores one D2H
  slice.  Every engine supports it.
- **paged** (:class:`~.paged.PagedKVCacheManager`): the blocks live on
  device in the engine's page pool and the manager keeps ids only —
  hits are block-table references (zero H2D), stores are ownership
  adoptions (zero copy).  Plumbed for the continuous-batching decode
  path; everything else must reject it (``require_dense_kv_layout``),
  never silently fall back.

Layout selection: the ``kv_layout`` engine kwarg / ``--kv-layout`` flag
over the ``DWT_KV_LAYOUT`` env knob over the default ``dense``.
"""

import os

from .manager import (DEFAULT_BLOCK_TOKENS, KVCacheManager, KVLease,
                      resolve_kvcache_config)
from .paged import PagedBlockLease, PagedKVCacheManager
from .pool import KVBlockPool
from .radix import RadixTree

KV_LAYOUTS = ("dense", "paged")


def resolve_kv_layout(kv_layout=None) -> str:
    """``kv_layout`` arg over ``DWT_KV_LAYOUT`` env over "dense"."""
    layout = kv_layout or os.environ.get("DWT_KV_LAYOUT", "") or "dense"
    if layout not in KV_LAYOUTS:
        raise ValueError(
            f"unknown kv layout {layout!r}; expected one of {KV_LAYOUTS}")
    return layout


def require_dense_kv_layout(mode: str, kv_layout=None) -> str:
    """Resolve the layout for a mode with no paged plumbing: honors
    "dense", raises on "paged" — an env knob or flag asking for the
    paged pool must fail loudly, never be silently ignored (the caller
    would believe HBM reservations shrank when they did not)."""
    layout = resolve_kv_layout(kv_layout)
    if layout == "paged":
        raise ValueError(
            f"kv layout 'paged' is not supported by {mode}; the paged "
            "block pool is plumbed for the continuous-batching decode "
            "path only (--batch-slots without a speculative proposer). "
            "Use the dense layout here, or serve via --batch-slots.")
    return layout


__all__ = ["KVBlockPool", "KVCacheManager", "KVLease",
           "PagedBlockLease", "PagedKVCacheManager", "RadixTree",
           "resolve_kvcache_config", "resolve_kv_layout",
           "require_dense_kv_layout", "DEFAULT_BLOCK_TOKENS",
           "KV_LAYOUTS"]
