"""Block-level KV cache with radix-tree prefix sharing (docs/DESIGN.md
§10 dense layout, §11 paged layout, §14 universal-paged contract).

The single prefix-reuse path for the serving stack.  Two layouts share
the radix tree and the block granularity, both behind the
:mod:`~.backend` seam every engine consumes:

- **paged** (the DEFAULT): the blocks live on device — the batching
  scheduler's slot cache IS a page pool addressed through block tables,
  the ring stage workers hold per-stage page pools, and the
  single-request engines keep a device-resident prefix pool
  (:class:`~.backend.PagedKVBackend`).  Hits are device gathers /
  block-table references, stores are device scatters / ownership
  adoptions — zero bytes cross the host boundary
  (``dwt_kvcache_h2d_bytes_total == 0`` structurally).
- **dense** (:class:`KVCacheManager` behind
  :class:`~.backend.DenseKVBackend`): host numpy block pool; hits pay
  one H2D load, stores one D2H slice.  Survives one release as the
  explicit ``--kv-layout dense`` escape hatch on the single-request
  engines; the batching scheduler and the ring stages are paged-native.

Layout selection: the ``kv_layout`` engine kwarg / ``--kv-layout`` flag
over the ``DWT_KV_LAYOUT`` env knob over the default ``paged``.
"""

import logging
import os

from .backend import (DenseKVBackend, PagedKVBackend, make_kv_backend)
from .manager import (DEFAULT_BLOCK_TOKENS, KVCacheManager, KVLease,
                      resolve_kvcache_config)
from .paged import PagedBlockLease, PagedKVCacheManager
from .pool import KVBlockPool
from .radix import RadixTree

KV_LAYOUTS = ("dense", "paged")

# The dense escape hatch is DEPRECATED (ROADMAP item 1 tail): paged has
# been the universal default since PR 7 and dense survives exactly one
# release for single-request-engine users who have not migrated.  This
# names the removal so the warning below can state it, and the delete
# PR can grep for it.
DENSE_REMOVAL_RELEASE = "the next release (the PR after disaggregation)"
_dense_deprecation_warned = False

log = logging.getLogger(__name__)


def resolve_kv_layout(kv_layout=None) -> str:
    """``kv_layout`` arg over ``DWT_KV_LAYOUT`` env over "paged".

    Resolving to "dense" logs a LOUD once-per-process deprecation
    warning naming the removal release — the one owner of layout
    resolution is the one place the deprecation cannot be bypassed
    (flag, env knob, and direct engine kwarg all funnel here)."""
    layout = kv_layout or os.environ.get("DWT_KV_LAYOUT", "") or "paged"
    if layout not in KV_LAYOUTS:
        raise ValueError(
            f"unknown kv layout {layout!r}; expected one of {KV_LAYOUTS}")
    if layout == "dense":
        global _dense_deprecation_warned
        if not _dense_deprecation_warned:
            _dense_deprecation_warned = True
            log.warning(
                "DEPRECATED: kv_layout='dense' (the host-pool escape "
                "hatch) is scheduled for REMOVAL in %s; the paged "
                "layout is the universal default (docs/DESIGN.md §14) "
                "and every serve/generate mode accepts it — drop "
                "--kv-layout dense / DWT_KV_LAYOUT=dense now",
                DENSE_REMOVAL_RELEASE)
    return layout


def require_dense_kv_layout(mode: str, kv_layout=None) -> str:
    """LEGACY guard from the §11 rejection-matrix era: honors "dense",
    raises on "paged".  Every production call site is gone — the matrix
    is dissolved; every engine and CLI mode accepts the paged layout
    (docs/DESIGN.md §14) — and ``tools/check_kv_layout.py`` lints that
    none regrows outside this package.  Kept only so an out-of-tree
    caller that still imports it fails the same loud way it always did
    rather than with an ImportError mid-request."""
    layout = resolve_kv_layout(kv_layout)
    if layout == "paged":
        raise ValueError(
            f"kv layout 'paged' is not supported by {mode}; use the "
            "dense layout here")
    return layout


__all__ = ["KVBlockPool", "KVCacheManager", "KVLease",
           "DenseKVBackend", "PagedKVBackend", "make_kv_backend",
           "PagedBlockLease", "PagedKVCacheManager", "RadixTree",
           "resolve_kvcache_config", "resolve_kv_layout",
           "require_dense_kv_layout", "DEFAULT_BLOCK_TOKENS",
           "KV_LAYOUTS", "DENSE_REMOVAL_RELEASE"]
