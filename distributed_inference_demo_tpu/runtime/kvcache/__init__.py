"""Block-level KV cache with radix-tree prefix sharing (docs/DESIGN.md §10).

The single prefix-reuse path for the serving stack: the continuous-
batching scheduler, the plain ``InferenceEngine`` generate paths, and
the speculative target engine all match and store through one
:class:`KVCacheManager`.  See ``manager.py`` for the contract.
"""

from .manager import (DEFAULT_BLOCK_TOKENS, KVCacheManager, KVLease,
                      resolve_kvcache_config)
from .pool import KVBlockPool
from .radix import RadixTree

__all__ = ["KVBlockPool", "KVCacheManager", "KVLease", "RadixTree",
           "resolve_kvcache_config", "DEFAULT_BLOCK_TOKENS"]
