"""Device-side copy programs for the block KV cache.

The manager (``manager.py``) is pure host bookkeeping; these are the two
ends of the device seam the engines share:

- loads ride :func:`seed_prefix_cache` — one fused dynamic_update_slice
  pair writing a gathered block run into a fresh cache's columns
  ``[0, m)`` (the engine-cache twin of batching's ``load_prefix`` row
  program);
- stores are plain ``np.asarray`` D2H slices taken by
  ``KVCacheManager.store`` (no program needed — the copy is the fence).

Kept separate from ``manager.py`` so the manager (and its tests) never
import jax.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, donate_argnums=(0, 1))
def seed_prefix_cache(ck, cv, pk, pv):
    """Write a ``[L, b, H, m, D]`` block run into a (fresh, donatable)
    cache's columns ``[0, m)``.  The caller sets the cache's valid
    length to ``m`` afterwards; columns past m stay zero and are
    overwritten by the suffix prefill before any query attends them
    (stale-slot invariant)."""
    zero = jnp.zeros((), jnp.int32)
    idx = (zero, zero, zero, zero, zero)
    return (jax.lax.dynamic_update_slice(ck, pk.astype(ck.dtype), idx),
            jax.lax.dynamic_update_slice(cv, pv.astype(cv.dtype), idx))
