"""Device-side copy programs for the block KV cache.

The manager (``manager.py``) is pure host bookkeeping; these are the two
ends of the device seam the engines share:

- loads ride :func:`seed_prefix_cache` — one fused dynamic_update_slice
  pair writing a gathered block run into a fresh cache's columns
  ``[0, m)`` (the engine-cache twin of batching's ``load_prefix`` row
  program);
- stores are plain ``np.asarray`` D2H slices taken by
  ``KVCacheManager.store`` (no program needed — the copy is the fence).

Kept separate from ``manager.py`` so the manager (and its tests) never
import jax.

Every paged program below is dtype-polymorphic (docs/DESIGN.md §17): a
pool tensor is either a plain array or a :class:`QuantizedKVPages` tree
whose leaves share the pool's leading ``[L, N, H, bt]`` axes, so one
tree-mapped gather/scatter serves both.  The quantize/dequantize always
happens HERE, at the row <-> pages seam — dense working rows stay
full-width, pages hold the narrow bytes + scale sidecar.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ...ops.quant import (QuantizedKVPages, quantize_kv_like,
                          quantize_kv_pages)


def _gather_run(pool, idx):
    """``[L, n, H, bt, D]`` FULL-WIDTH block run for index row ``idx``
    into a pool's page axis — narrow leaves gather first (only the
    table's bytes move), then the gathered view dequantizes."""
    g = jax.tree.map(lambda p: jnp.take(p, idx, axis=1), pool)
    if isinstance(g, QuantizedKVPages):
        return g.dequantize(jnp.float32)
    return g


def _scatter_run(pool, run, table):
    """Scatter a full-width ``[L, n, H, bt, D]`` block run into the pool
    at ``table``'s ids (sentinels drop) — quantizing once, here, when
    the pool is narrow."""
    payload = quantize_kv_like(pool, run)
    return jax.tree.map(
        lambda p, b: p.at[:, table].set(b, mode="drop"), pool, payload)


@partial(jax.jit, donate_argnums=(0, 1))
def seed_prefix_cache(ck, cv, pk, pv):
    """Write a ``[L, b, H, m, D]`` block run into a (fresh, donatable)
    cache's columns ``[0, m)``.  The caller sets the cache's valid
    length to ``m`` afterwards; columns past m stay zero and are
    overwritten by the suffix prefill before any query attends them
    (stale-slot invariant)."""
    zero = jnp.zeros((), jnp.int32)
    idx = (zero, zero, zero, zero, zero)
    return (jax.lax.dynamic_update_slice(ck, pk.astype(ck.dtype), idx),
            jax.lax.dynamic_update_slice(cv, pv.astype(cv.dtype), idx))


# ---------------------------------------------------------------------------
# paged layout (docs/DESIGN.md §11): the row <-> pages seam.  Both
# programs are device-to-device — the paged cache's whole point is that
# neither a prefix hit nor a store crosses the host boundary.


@jax.jit
def seed_row_from_pages(pk, pv, table):
    """Gather one slot's block table out of the page pool into a dense
    prefill row: pages ``[L, N, H, bt, D]`` + table ``[W]`` ->
    row ``[L, 1, H, W*bt, D]``.

    The WHOLE table gathers in one compiled shape regardless of how many
    entries are real: sentinel entries (>= N) clamp to some page and the
    gathered garbage sits at columns past the matched prefix, which the
    suffix prefill / decode rewrite before any query attends them
    (stale-slot invariant) — garbage is finite (pool pages always hold
    finite values), so the causal mask zeroes it exactly."""
    L, N, H, bt, D = pk.shape
    W = table.shape[0]
    safe = jnp.clip(table, 0, N - 1)
    rk = _gather_run(pk, safe)               # [L, W, H, bt, D]
    rv = _gather_run(pv, safe)
    rk = rk.transpose(0, 2, 1, 3, 4).reshape(L, 1, H, W * bt, D)
    rv = rv.transpose(0, 2, 1, 3, 4).reshape(L, 1, H, W * bt, D)
    return rk, rv


@partial(jax.jit, donate_argnums=(0, 1))
def seed_cache_from_pages(ck, cv, pk, pv, table):
    """Gather a matched block run out of the page pool into a (fresh,
    donatable) engine cache's columns ``[0, n*bt)`` — the PAGED twin of
    :func:`seed_prefix_cache`: pages ``[L, N, H, bt, D]`` + table ``[n]``
    of real page ids -> cache ``[L, 1, H, S, D]``.  Device-to-device:
    a prefix hit on the paged backend moves zero bytes through the host
    (``dwt_kvcache_h2d_bytes_total`` stays 0 by construction).  Compiled
    per matched length, like the dense seed program it mirrors."""
    L, N, H, bt, D = pk.shape
    n = table.shape[0]
    rk = _gather_run(pk, table)               # [L, n, H, bt, D]
    rv = _gather_run(pv, table)
    rk = rk.transpose(0, 2, 1, 3, 4).reshape(L, 1, H, n * bt, D)
    rv = rv.transpose(0, 2, 1, 3, 4).reshape(L, 1, H, n * bt, D)
    zero = jnp.zeros((), jnp.int32)
    idx = (zero, zero, zero, zero, zero)
    return (jax.lax.dynamic_update_slice(ck, rk.astype(ck.dtype), idx),
            jax.lax.dynamic_update_slice(cv, rv.astype(cv.dtype), idx))


@partial(jax.jit, donate_argnums=(0, 1))
def store_cache_to_pages(pk, pv, ck, cv, table, start):
    """Scatter an engine cache's full blocks ``[start, start + n)`` into
    the page pool at ``table``'s ids — the paged store: cache ``[L, 1,
    H, S, D]`` columns ``[start*bt, (start+n)*bt)`` land in pages
    ``table[0..n)`` in place on device, zero D2H (the dense manager's
    per-store host slice is the copy this program deletes).  ``start``
    (traced block offset) is the tail-only store seam: blocks the radix
    tree already covers are neither re-allocated nor re-written.  The
    cache is read, not donated — the caller keeps decoding against it;
    only the pool buffers rotate."""
    L, N, H, bt, D = pk.shape
    n = table.shape[0]
    run_k = jax.lax.dynamic_slice_in_dim(ck[:, 0], start * bt, n * bt,
                                         axis=2)
    run_v = jax.lax.dynamic_slice_in_dim(cv[:, 0], start * bt, n * bt,
                                         axis=2)
    rk = run_k.reshape(L, H, n, bt, D).transpose(0, 2, 1, 3, 4)
    rv = run_v.reshape(L, H, n, bt, D).transpose(0, 2, 1, 3, 4)
    return _scatter_run(pk, rk, table), _scatter_run(pv, rv, table)


@partial(jax.jit, donate_argnums=(0, 1))
def adopt_blocks_into_pages(pk, pv, k_blocks, v_blocks, table):
    """Scatter migrated block payloads ``[n, L, H, bt, D]`` into the
    page pool at ``table``'s ids — the disaggregation import seam
    (docs/DESIGN.md §15): a decode worker lands a complete migration's
    staged blocks in ONE device scatter, then the radix tree ADOPTS
    the pages (``store_shared``) and the joining request's block table
    references them.  The pool never round-trips through a dense row,
    so ``dwt_kvcache_h2d_bytes_total`` (the dense-seed counter) stays 0
    on the decode side by construction; the migration's own bytes are
    accounted as ``dwt_disagg_migrated_bytes_total``.

    Payloads may arrive quantized (a quantized prefill pool ships its
    narrow bytes + scale sidecar on the wire): matching leaves adopt
    VERBATIM — the decode pool holds bit-identical pages to the prefill
    side.  A full-width payload into a quantized pool quantizes here
    (the premigrated-join escape hatch for full-width exporters)."""
    def _adopt(pool, blocks):
        if (isinstance(pool, QuantizedKVPages)
                and not isinstance(blocks, QuantizedKVPages)):
            blocks = quantize_kv_pages(blocks.astype(jnp.float32),
                                       pool.bits)
        return jax.tree.map(
            lambda p, b: p.at[:, table].set(
                jnp.moveaxis(b, 0, 1).astype(p.dtype), mode="drop"),
            pool, blocks)

    return _adopt(pk, k_blocks), _adopt(pv, v_blocks)


@jax.jit
def export_blocks_from_pages(pk, pv, table):
    """Gather block payloads ``[n, L, H, bt, D]`` out of the page pool at
    ``table``'s (real) ids — the EXACT inverse of
    :func:`adopt_blocks_into_pages` and the live-migration export seam
    (docs/DESIGN.md §18): a decode replica snapshots a mid-flight
    request's pages in one device gather, ships them, and the target's
    adopt scatter lands bit-identical pages.

    Quantized pools gather their narrow leaves VERBATIM (no dequantize /
    re-quantize round trip) — the payload stays a
    :class:`QuantizedKVPages` tree with block-leading leaves, which the
    adopt side recognizes and writes back untouched.  The caller slices
    the table to the request's used blocks; the partial tail block ships
    as-is (its columns past the valid length hold garbage the stale-slot
    invariant already covers — decode rewrites them before any query
    attends)."""
    def _export(pool):
        return jax.tree.map(
            lambda p: jnp.moveaxis(jnp.take(p, table, axis=1), 0, 1),
            pool)

    return _export(pk), _export(pv)


@partial(jax.jit, donate_argnums=(0, 1))
def write_row_to_pages(pk, pv, row_k, row_v, table):
    """Scatter a prefilled dense row ``[L, 1, H, W*bt, D]`` into the page
    pool at ``table``'s ids — the paged store: blocks land in place on
    device, zero D2H.  Sentinel entries (>= N) DROP their block — the
    caller sentinels the matched-prefix slots (those pages are tree-owned
    and immutable) and the unallocated tail; everything written is a page
    this request owns.  Write contract: ops.attention.prepare_kv_chunk
    (blocks past the prompt length hold garbage until decode rewrites
    them — the stale-slot invariant, block-shaped)."""
    L, N, H, bt, D = pk.shape
    W = table.shape[0]
    rk = row_k[:, 0].reshape(L, H, W, bt, D).transpose(0, 2, 1, 3, 4)
    rv = row_v[:, 0].reshape(L, H, W, bt, D).transpose(0, 2, 1, 3, 4)
    return _scatter_run(pk, rk, table), _scatter_run(pv, rv, table)
