"""Tiered KV: a host-RAM/disk capacity tier BELOW the device page pool.

The paged pool (``paged.py``) holds exactly ``num_blocks`` pages of HBM;
under pressure, LRU leaf eviction frees pages — and before this module,
an evicted prefix was simply gone: the next request sharing it paid a
full re-prefill.  CachedAttention (USENIX ATC '24, arXiv:2403.19708)
shows the fix for a fleet serving far more reusable prefix state than
HBM can hold: DEMOTE evicted KV blocks into a host-RAM ring (with an
optional mmap'd disk segment below it) and PROMOTE them back on reuse —
a prefix re-prefill becomes one h2d adopt scatter.

Design (docs/DESIGN.md §21):

- **keying**: entries are keyed per block by the CHAIN DIGEST — an
  incremental sha1 over the raw token ids (8-byte big-endian signed per
  token), read out at each ``block_tokens`` boundary.  This is exactly
  the gateway router's ``_keys`` scheme, so a replica's demoted-prefix
  digest is directly comparable gateway-side (the tier-aware
  second-chance route) with no token data leaving the replica.
- **demotion**: the manager's eviction loop hands each victim leaf's
  full key path + freed page ids to a hook; the owner (engine/backend)
  gathers the pages' bytes with :func:`~.device.export_blocks_from_pages`
  — quantized pools export their narrow int8/int4 leaves + scale
  sidecars VERBATIM, so the host copy is as cheap as §17 made the pages
  — and inserts them here.  Demotion happens before the freed ids are
  handed back out, so the d2h gather can never read recycled pages.
- **promotion**: :func:`promote_prefix` runs at admission, between the
  staged-import and the radix ``match``: peek the device-covered prefix,
  walk the chain from there, adopt the tier's continuation through the
  SAME :func:`~.device.adopt_blocks_into_pages` scatter the §15/§18
  migration paths use (no second h2d path), then ``store_shared`` hands
  the pages to the tree — the admission's own ``match`` finds them as
  an ordinary prefix hit.  Promotion is move-semantics (the entry
  leaves the tier) and best-effort: alloc pressure skips it.
- **tiers**: one LRU ring (ordered dict) spans both tiers.  The host
  ring is byte-budgeted; overflow spills the oldest host entries into
  the disk segment (fixed-size slots over one mmap'd file) when
  configured, else drops them.  Disk overflow drops oldest.  All blocks
  of a config are the same size, so disk slots never fragment.

Accounting is exact and assertable (:meth:`TieredKVStore.check`): every
entry is host-resident XOR disk-resident, byte sums match the ledger,
and the h2d bytes a promotion moves are counted honestly into the
manager's ``h2d_bytes`` (``dwt_kvcache_h2d_bytes_total`` — the paged
layout's "0 by construction" claim becomes "0 except honest tier
promotions") plus the ``dwt_kvcache_tier_*`` family.

Like ``manager.py``, this module never imports jax at module scope —
the promote/demote payload conversion imports lazily, so the tier's
bookkeeping stays testable on a bare host.

Config knobs (CLI flags override env, 0 disables):
``DWT_KV_HOST_TIER_BYTES`` (host ring budget), ``DWT_KV_DISK_TIER_PATH``
/ ``DWT_KV_DISK_TIER_BYTES`` (optional disk segment below the ring).
"""

from __future__ import annotations

import hashlib
import mmap
import os
import threading
import time
from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ...telemetry._env import env_int
from ...telemetry.flightrecorder import get_flight_recorder

#: newest-first cap on the demoted-prefix digest a replica publishes in
#: /stats — the gateway's second-chance index is a HINT, not a mirror;
#: a truncated digest costs one hashed route, never a wrong answer
DIGEST_CAP = 256


def _catalog():
    """The dwt_kvcache_tier_* series, resolved lazily and never fatally
    (a metrics regression must not take down eviction or admission) —
    the disagg transport's pattern."""
    try:
        from ...telemetry import catalog
        return catalog
    except Exception:           # pragma: no cover - defensive
        return None


def resolve_tier_config(host_bytes: Optional[int] = None,
                        disk_path: Optional[str] = None,
                        disk_bytes: Optional[int] = None):
    """(host_bytes, disk_path, disk_bytes) from explicit args over the
    ``DWT_KV_HOST_TIER_BYTES`` / ``DWT_KV_DISK_TIER_PATH`` /
    ``DWT_KV_DISK_TIER_BYTES`` env knobs (None = "not specified"; 0 /
    empty disables).  The env fallback is the §17 pattern: every worker
    behind ``make_kv_backend`` inherits the tier with zero plumbing."""
    if host_bytes is None:
        host_bytes = env_int("DWT_KV_HOST_TIER_BYTES", 0)
    if disk_path is None:
        disk_path = os.environ.get("DWT_KV_DISK_TIER_PATH", "") or None
    if disk_bytes is None:
        disk_bytes = env_int("DWT_KV_DISK_TIER_BYTES", 0)
    host_bytes = max(0, int(host_bytes))
    disk_bytes = max(0, int(disk_bytes))
    if disk_path is not None and disk_bytes < 1:
        disk_path = None        # a path without a budget is no segment
    if disk_path is None:
        disk_bytes = 0
    if host_bytes < 1 and disk_path is not None:
        raise ValueError(
            "the disk tier sits BELOW the host ring (entries spill "
            "host -> disk): --kv-disk-tier-path/bytes need "
            "--kv-host-tier-bytes > 0")
    return host_bytes, disk_path, disk_bytes


def chain_digests(keys: Sequence[Tuple[int, ...]]) -> List[bytes]:
    """One cumulative sha1 digest per block boundary of ``keys`` (each
    key the block's token-id tuple) — byte-compatible with the gateway
    router's ``_keys`` so replica digests and gateway lookups agree."""
    h = hashlib.sha1()
    out: List[bytes] = []
    for key in keys:
        for t in key:
            h.update(int(t).to_bytes(8, "big", signed=True))
        out.append(h.digest())
    return out


def _leaf_lists(blocks):
    """Flatten one side's (possibly quantized) block payload into a flat
    host tensor list + page-width tag — the §15 wire convention
    (bf16: the one tensor; int8: data+scale; int4: data+scale+zero).
    ``np.asarray`` here IS the d2h sync for device payloads."""
    from ...ops.quant import QuantizedKVPages
    if isinstance(blocks, QuantizedKVPages):
        leaves = [np.asarray(blocks.data), np.asarray(blocks.scale)]
        if blocks.zero is not None:
            leaves.append(np.asarray(blocks.zero))
        return leaves, ("int4" if blocks.bits == 4 else "int8")
    return [np.asarray(blocks)], "bf16"


def _from_leaves(leaves, kv_dtype: str):
    """Rebuild one side's block payload from its leaf list (inverse of
    :func:`_leaf_lists`)."""
    if kv_dtype == "bf16":
        return leaves[0]
    from ...ops.quant import QuantizedKVPages
    bits = 4 if kv_dtype == "int4" else 8
    zero = leaves[2] if bits == 4 else None
    return QuantizedKVPages(leaves[0], leaves[1], zero, bits)


class _TierEntry:
    """One demoted block: host leaf arrays, or a disk slot index."""

    __slots__ = ("tier", "k_leaves", "v_leaves", "slot", "nbytes")

    def __init__(self, k_leaves, v_leaves, nbytes: int):
        self.tier = "host"
        self.k_leaves = k_leaves
        self.v_leaves = v_leaves
        self.slot: Optional[int] = None
        self.nbytes = nbytes


class _DiskSegment:
    """Fixed-slot block store over one mmap'd file.

    Every entry of a given pool config is the same byte size (same
    shapes, same dtypes), so the segment is a trivial slot allocator:
    slot size and leaf layout are fixed by the FIRST write, capacity is
    ``budget // slot_bytes``, and a free list recycles slots.  Reads
    copy out (the mmap pages may be evicted by the OS at any time; the
    promoted arrays must own their bytes)."""

    def __init__(self, path: str, budget_bytes: int):
        self.path = path
        self.budget_bytes = int(budget_bytes)
        self._fh = open(path, "w+b")
        self._mm: Optional[mmap.mmap] = None
        self._layout = None      # [(shape, dtype_str, nbytes), ...] k++v
        self._n_k = 0            # how many leaves belong to K
        self.slot_bytes = 0
        self.capacity_slots = 0
        self._free: List[int] = []
        self._next = 0

    def _configure(self, k_leaves, v_leaves) -> None:
        layout = [(lv.shape, str(lv.dtype), lv.nbytes)
                  for lv in list(k_leaves) + list(v_leaves)]
        self._layout = layout
        self._n_k = len(k_leaves)
        self.slot_bytes = sum(n for _, _, n in layout)
        self.capacity_slots = (self.budget_bytes // self.slot_bytes
                               if self.slot_bytes else 0)
        if self.capacity_slots < 1:
            return
        self._fh.truncate(self.slot_bytes * self.capacity_slots)
        self._fh.flush()
        self._mm = mmap.mmap(self._fh.fileno(),
                             self.slot_bytes * self.capacity_slots)

    def write(self, k_leaves, v_leaves) -> Optional[int]:
        """Store one entry; returns its slot index, or None when the
        segment is full (or the budget fits no slot at all)."""
        if self._layout is None:
            self._configure(k_leaves, v_leaves)
        if self.capacity_slots < 1:
            return None
        if self._free:
            slot = self._free.pop()
        elif self._next < self.capacity_slots:
            slot = self._next
            self._next += 1
        else:
            return None
        off = slot * self.slot_bytes
        for lv in list(k_leaves) + list(v_leaves):
            raw = np.ascontiguousarray(lv).tobytes()
            self._mm[off:off + len(raw)] = raw
            off += len(raw)
        return slot

    def read(self, slot: int):
        """(k_leaves, v_leaves) copied OUT of the segment."""
        off = slot * self.slot_bytes
        leaves = []
        for shape, dtype, nbytes in self._layout:
            leaves.append(np.frombuffer(
                self._mm[off:off + nbytes],
                dtype=np.dtype(dtype)).reshape(shape).copy())
            off += nbytes
        return leaves[:self._n_k], leaves[self._n_k:]

    def free(self, slot: int) -> None:
        self._free.append(slot)

    @property
    def used_slots(self) -> int:
        return self._next - len(self._free)

    def close(self) -> None:
        if self._mm is not None:
            self._mm.close()
            self._mm = None
        if not self._fh.closed:
            self._fh.close()


class TieredKVStore:
    """Byte-budgeted host-RAM LRU ring + optional disk segment for
    demoted KV blocks (see module docstring).

    Thread-safety matches the managers: one lock; demote/promote run on
    the owning engine's scheduler thread, ``snapshot``/``digest`` from
    scrape threads."""

    def __init__(self, host_bytes: int, block_tokens: int, *,
                 disk_path: Optional[str] = None, disk_bytes: int = 0,
                 digest_cap: int = DIGEST_CAP):
        if host_bytes < 1:
            raise ValueError("TieredKVStore needs a host byte budget "
                             ">= 1 (0 means: no tier — pass None to "
                             "the engine instead)")
        self.host_budget_bytes = int(host_bytes)
        self.block_tokens = int(block_tokens)
        self.digest_cap = int(digest_cap)
        self.kv_dtype: Optional[str] = None
        self._disk = (_DiskSegment(disk_path, disk_bytes)
                      if disk_path else None)
        self.disk_budget_bytes = int(disk_bytes) if disk_path else 0
        # digest -> entry, LRU order (oldest first); one dict spans both
        # tiers so host->disk spill preserves recency order
        self._entries: "OrderedDict[bytes, _TierEntry]" = OrderedDict()
        self._host_bytes = 0
        self._disk_bytes = 0
        self._lock = threading.Lock()
        self._flight = get_flight_recorder()
        self.stats = self._zero_stats()

    @staticmethod
    def _zero_stats() -> dict:
        return {"demoted_blocks": 0, "demoted_bytes": 0,
                "promoted_blocks": 0, "promoted_bytes": 0,
                "dropped_blocks": 0, "spilled_blocks": 0,
                "host_hits": 0, "disk_hits": 0, "demote_errors": 0}

    # ------------------------------------------------------------------
    # demotion (eviction hook side)

    def demote(self, path_keys: Sequence[Tuple[int, ...]],
               k_blocks, v_blocks) -> int:
        """Insert the evicted leaf's blocks, keyed by the chain digests
        of ``path_keys`` (the victim's FULL root-to-leaf key path; the
        payloads cover its last ``n`` keys).  Device payloads sync d2h
        here, before the freed page ids can be recycled.  Returns the
        number of blocks admitted (duplicates refresh, not re-copy)."""
        t0 = time.perf_counter()
        k_leaves, kv_dtype = _leaf_lists(k_blocks)
        v_leaves, _ = _leaf_lists(v_blocks)
        n = int(k_leaves[0].shape[0])
        if n < 1 or len(path_keys) < n:
            return 0
        digests = chain_digests(path_keys)[len(path_keys) - n:]
        admitted, admitted_bytes = 0, 0
        with self._lock:
            self.kv_dtype = self.kv_dtype or kv_dtype
            for j, dg in enumerate(digests):
                if dg in self._entries:
                    self._entries.move_to_end(dg)
                    continue
                ek = [np.ascontiguousarray(lv[j]) for lv in k_leaves]
                ev = [np.ascontiguousarray(lv[j]) for lv in v_leaves]
                nbytes = sum(a.nbytes for a in ek + ev)
                self._entries[dg] = _TierEntry(ek, ev, nbytes)
                self._host_bytes += nbytes
                admitted += 1
                admitted_bytes += nbytes
            self._evict_over_budget_locked()
            self.stats["demoted_blocks"] += admitted
            self.stats["demoted_bytes"] += admitted_bytes
        dt = time.perf_counter() - t0
        cat = _catalog()
        if cat is not None and admitted:
            cat.KVCACHE_TIER_DEMOTE_SECONDS.observe(dt)
        if admitted:
            self._flight.record("kvcache_tier_demote", blocks=admitted,
                                seconds=round(dt, 6))
        return admitted

    def _evict_over_budget_locked(self) -> None:
        """Spill the oldest host entries to disk past the host budget
        (or drop them when no segment / segment full); drop the oldest
        disk entries past the disk budget."""
        while self._host_bytes > self.host_budget_bytes:
            dg = next((d for d, e in self._entries.items()
                       if e.tier == "host"), None)
            if dg is None:       # pragma: no cover - budget >= 1 entry
                break
            e = self._entries[dg]
            slot = None
            if self._disk is not None:
                # make room first: everything already on disk is OLDER
                # than the entry spilling (spill preserves LRU order),
                # so dropping oldest-disk to admit it is the correct
                # bottom-of-hierarchy eviction — without this, a full
                # segment would drop the NEWER host entry instead
                while self._disk_bytes + e.nbytes > self.disk_budget_bytes:
                    old = next((d for d, x in self._entries.items()
                                if x.tier == "disk"), None)
                    if old is None:
                        break
                    self._drop_locked(old)
                if (self._disk_bytes + e.nbytes
                        <= self.disk_budget_bytes):
                    slot = self._disk.write(e.k_leaves, e.v_leaves)
            if slot is not None:
                e.tier, e.slot = "disk", slot
                e.k_leaves = e.v_leaves = None
                self._disk_bytes += e.nbytes
                self.stats["spilled_blocks"] += 1
                # keep LRU position: a spilled entry is still older
                # than everything demoted after it
                self._host_bytes -= e.nbytes
            else:
                del self._entries[dg]
                self._host_bytes -= e.nbytes
                self.stats["dropped_blocks"] += 1
        while self._disk_bytes > self.disk_budget_bytes:
            dg = next((d for d, e in self._entries.items()
                       if e.tier == "disk"), None)
            if dg is None:       # pragma: no cover - accounting guard
                break
            self._drop_locked(dg)

    def _drop_locked(self, dg: bytes) -> None:
        e = self._entries.pop(dg)
        if e.tier == "disk":
            self._disk_bytes -= e.nbytes
            self._disk.free(e.slot)
        else:
            self._host_bytes -= e.nbytes
        self.stats["dropped_blocks"] += 1

    # ------------------------------------------------------------------
    # promotion (admission side)

    def match(self, prompt, start_blocks: int) -> List[bytes]:
        """The longest run of consecutive demoted blocks continuing the
        prompt from block index ``start_blocks`` (the device-covered
        prefix), capped below the prompt length like the managers'
        ``match``.  Returns the run's chain digests (pass to
        :meth:`take`); pure lookup, refreshes LRU recency."""
        prompt = np.asarray(prompt).reshape(-1)
        bt = self.block_tokens
        max_blocks = (len(prompt) - 1) // bt
        if start_blocks >= max_blocks:
            return []
        keys = [tuple(int(t) for t in prompt[i * bt:(i + 1) * bt])
                for i in range(max_blocks)]
        digests = chain_digests(keys)
        run: List[bytes] = []
        with self._lock:
            for dg in digests[start_blocks:]:
                e = self._entries.get(dg)
                if e is None:
                    break
                self._entries.move_to_end(dg)
                run.append(dg)
        return run

    def take(self, digests: Sequence[bytes]):
        """Remove ``digests``' entries (move semantics: a promoted block
        lives in the device tree afterwards, not here) and assemble
        their payloads, stopping at the first hole.

        Returns ``(k_blocks, v_blocks, nbytes, n)`` with block-leading
        ``[n, ...]`` leaves ready for ``adopt_blocks_into_pages``
        (quantized entries rebuild their QuantizedKVPages tree, adopted
        VERBATIM), or None when nothing could be taken."""
        taken: List[_TierEntry] = []
        with self._lock:
            for dg in digests:
                e = self._entries.get(dg)
                if e is None:
                    break
                if e.tier == "disk":
                    e.k_leaves, e.v_leaves = self._disk.read(e.slot)
                    self._disk.free(e.slot)
                    self._disk_bytes -= e.nbytes
                    self.stats["disk_hits"] += 1
                else:
                    self._host_bytes -= e.nbytes
                    self.stats["host_hits"] += 1
                del self._entries[dg]
                taken.append(e)
        if not taken:
            return None
        k_leaves = [np.stack([e.k_leaves[i] for e in taken])
                    for i in range(len(taken[0].k_leaves))]
        v_leaves = [np.stack([e.v_leaves[i] for e in taken])
                    for i in range(len(taken[0].v_leaves))]
        nbytes = sum(e.nbytes for e in taken)
        kv_dtype = self.kv_dtype or "bf16"
        return (_from_leaves(k_leaves, kv_dtype),
                _from_leaves(v_leaves, kv_dtype), nbytes, len(taken))

    def note_promoted(self, blocks: int, nbytes: int,
                      seconds: float) -> None:
        """Account one completed promotion (called by the owner after
        the adopt scatter dispatched — the h2d actually happened)."""
        with self._lock:
            self.stats["promoted_blocks"] += blocks
            self.stats["promoted_bytes"] += nbytes
        cat = _catalog()
        if cat is not None:
            cat.KVCACHE_TIER_PROMOTE_SECONDS.observe(seconds)
        self._flight.record("kvcache_tier_promote", blocks=blocks,
                            bytes=nbytes, seconds=round(seconds, 6))

    # ------------------------------------------------------------------
    # introspection

    @property
    def host_resident_bytes(self) -> int:
        return self._host_bytes

    def snapshot(self) -> dict:
        with self._lock:
            host_blocks = sum(1 for e in self._entries.values()
                              if e.tier == "host")
            return dict(self.stats,
                        block_tokens=self.block_tokens,
                        host_resident_bytes=self._host_bytes,
                        host_capacity_bytes=self.host_budget_bytes,
                        host_blocks=host_blocks,
                        disk_resident_bytes=self._disk_bytes,
                        disk_capacity_bytes=self.disk_budget_bytes,
                        disk_blocks=len(self._entries) - host_blocks)

    def digest(self) -> dict:
        """The compact demoted-prefix digest a replica publishes in
        ``/stats`` for the gateway's second-chance lookup: the NEWEST
        ``digest_cap`` entries' chain digests (truncated to 64-bit hex —
        a routing hint tolerates collisions; 10x smaller probes don't),
        plus the block granularity the gateway must recompute at."""
        with self._lock:
            newest = list(self._entries.keys())[-self.digest_cap:]
        return {"block_tokens": self.block_tokens,
                "digests": [d.hex()[:16] for d in newest]}

    def reset_stats(self) -> None:
        with self._lock:
            self.stats = self._zero_stats()

    def check(self) -> None:
        """Accounting invariants (test hook): every entry host XOR disk,
        ledger byte sums exact, disk free list consistent."""
        with self._lock:
            host = [e for e in self._entries.values() if e.tier == "host"]
            disk = [e for e in self._entries.values() if e.tier == "disk"]
            assert all(e.k_leaves is not None for e in host)
            assert all(e.slot is not None and e.k_leaves is None
                       for e in disk)
            assert self._host_bytes == sum(e.nbytes for e in host), \
                (self._host_bytes, sum(e.nbytes for e in host))
            assert self._disk_bytes == sum(e.nbytes for e in disk), \
                (self._disk_bytes, sum(e.nbytes for e in disk))
            if self._disk is not None:
                assert self._disk.used_slots == len(disk), \
                    (self._disk.used_slots, len(disk))

    def close(self) -> None:
        with self._lock:
            self._entries.clear()
            self._host_bytes = self._disk_bytes = 0
            if self._disk is not None:
                self._disk.close()


# ---------------------------------------------------------------------------
# the promotion seam, shared by the batching engine and PagedKVBackend


def make_demote_hook(tier: TieredKVStore, get_pools):
    """The eviction-side hook a pool owner installs on its
    :class:`~.paged.PagedKVCacheManager`: gather the victim leaf's
    pages (one device gather, quantized leaves verbatim — the §18
    export seam) and demote them.  ``get_pools()`` returns the CURRENT
    ``(pk, pv)`` — the owner's pool references rotate on every donating
    dispatch, so the hook must not close over one snapshot.  Never
    raises into ``alloc``: a demotion failure costs cache capacity,
    not admission."""
    def hook(path_keys, block_ids) -> None:
        try:
            import jax
            import jax.numpy as jnp

            from .device import export_blocks_from_pages
            pk, pv = get_pools()
            ids = np.asarray(block_ids, np.int32)
            n = int(ids.shape[0])
            # pad the gather table to the next power of two (repeating
            # the last id — the surplus rows are sliced off below) so
            # the jitted export compiles O(log n) variants, not one per
            # leaf size: an unlucky leaf shape would otherwise stall an
            # ADMISSION ~60ms+ on a fresh XLA compile mid-wave
            bucket = 1 << max(0, int(n - 1).bit_length())
            if bucket > n:
                ids = np.concatenate(
                    [ids, np.full(bucket - n, ids[-1], np.int32)])
            kb, vb = export_blocks_from_pages(pk, pv, jnp.asarray(ids))
            if bucket > n:
                kb = jax.tree.map(lambda a: a[:n], kb)
                vb = jax.tree.map(lambda a: a[:n], vb)
            tier.demote(path_keys, kb, vb)
        except Exception:
            with tier._lock:
                tier.stats["demote_errors"] += 1
            tier._flight.record("kvcache_tier_demote_error",
                                blocks=len(block_ids))
    return hook


def promote_prefix(mgr, tier: TieredKVStore, pk, pv, prompt,
                   profiler=None):
    """Promote the tier's continuation of ``prompt``'s device-covered
    prefix back into the page pool — the admission-side seam, run
    BEFORE the manager's ``match`` so the promoted blocks land as an
    ordinary prefix hit.

    Mirrors the §15 staged-import dance exactly (alloc -> adopt scatter
    -> ``store_shared`` with None placeholders for the device-covered
    head -> free declined -> release lease); the adopt h2d bytes are
    counted honestly into the manager's ``h2d_bytes`` and the tier's
    promoted counters.  Best-effort by design: pool pressure (alloc
    infeasible) or a racing eviction just skips — the suffix prefills.

    Returns ``(pk, pv, promoted_tokens)``."""
    run = tier.match(prompt, mgr.peek(prompt) // mgr.block_tokens)
    if not run:
        return pk, pv, 0
    ids = mgr.alloc(len(run))
    if ids is None:
        return pk, pv, 0
    t0 = time.perf_counter()
    # the alloc above may itself have evicted (and demoted) tree leaves
    # — never the run's entries (they are host-side), but the device
    # coverage may have SHRUNK: re-peek so the placeholder head matches
    # the tree's current state; a stale, larger head would make
    # store_shared stop early, which is correct but wastes the adopt
    start = mgr.peek(prompt) // mgr.block_tokens
    payload = tier.take(run)
    if payload is None:
        mgr.free(ids)
        return pk, pv, 0
    k_blocks, v_blocks, nbytes, n = payload
    if n < len(ids):
        mgr.free(ids[n:])
        ids = ids[:n]
    import jax
    import jax.numpy as jnp

    from .device import adopt_blocks_into_pages
    bt = mgr.block_tokens
    # bucket the adopt to the next power of two so the jitted scatter
    # compiles O(log n) variants (mirror of the demote-side export
    # bucketing): the table pads with an out-of-range id — the scatter
    # runs ``mode="drop"`` so the surplus rows land nowhere — and the
    # payload pads by repeating its last block
    bucket = 1 << max(0, int(n - 1).bit_length())
    table = np.asarray(ids, np.int32)
    if bucket > n:
        table = np.concatenate(
            [table, np.full(bucket - n, mgr.num_blocks, np.int32)])
        pad = bucket - n
        k_blocks = jax.tree.map(
            lambda a: np.concatenate(
                [a, np.repeat(a[-1:], pad, axis=0)]), k_blocks)
        v_blocks = jax.tree.map(
            lambda a: np.concatenate(
                [a, np.repeat(a[-1:], pad, axis=0)]), v_blocks)
    sig = None
    if profiler is not None:
        from ...telemetry import profiling as _profiling
        sig = _profiling.dispatch_signature(
            "tier_promote", batch=bucket, chunk=bt, kv_dtype=mgr.kv_dtype)
        _pt0 = profiler.begin(sig)
    pk, pv = adopt_blocks_into_pages(
        pk, pv, jax.tree.map(jnp.asarray, k_blocks),
        jax.tree.map(jnp.asarray, v_blocks),
        jnp.asarray(table))
    if sig is not None:
        profiler.end(sig, _pt0, out=(pk, pv), hbm_bytes=nbytes)
    adopted, lease = mgr.store_shared(
        np.asarray(prompt).reshape(-1)[:(start + n) * bt],
        [None] * start + list(ids))
    adopted_set = set(adopted)
    leftovers = [b for b in ids if b not in adopted_set]
    if leftovers:
        mgr.free(leftovers)
    if lease is not None:
        lease.release()
    mgr.note_promote_h2d(nbytes)
    tier.note_promoted(len(adopted), nbytes, time.perf_counter() - t0)
    return pk, pv, len(adopted) * bt
