"""The KV backend seam: ONE prefix-reuse surface over the paged pool.

Before this seam, every engine special-cased the dense manager inline
(match → host gather → H2D seed; D2H slice → store) and *rejected*
``--kv-layout paged`` outright — the DESIGN.md §11 rejection matrix.
The seam is the two calls an engine actually needs around its prefill:

- ``seed(ids, cache) -> (start, cache)`` — write the longest cached
  prefix of the (batch-1) prompt into a fresh engine cache's leading
  columns; ``start`` is how many positions are now exact, so the engine
  prefills only the suffix.
- ``store(ids, cache) -> None`` — cache the prefilled prompt's full
  blocks for the next shared-prefix request.  Runs before the decode
  program donates the cache buffers.

The dense host-pool backend (a hit paying one H2D gather and a store
one D2H slice) was deleted with the ``--kv-layout dense`` escape
hatch; the §10 :class:`~.manager.KVCacheManager` it wrapped survives
as a host-staging building block only.

:class:`PagedKVBackend` owns a DEVICE-resident page pool
  ``[L, N, H, bt, D]`` plus the §11 page-id
  :class:`~.paged.PagedKVCacheManager`: seeds gather pages into the
  cache on device and stores scatter cache blocks into freshly
  allocated pages on device — zero bytes cross the host boundary in
  either direction, and two prompts sharing a prefix share the very
  same pages in HBM (radix-tree dedup; the speculative engine's target
  prefills ride this, so a draft/verify request never duplicates an
  accepted prefix already paged in).

The single-request engines keep a dense *working* cache for the one
request in flight (its decode loop donates it); the layout choice
governs the standing *pool* — which is where the reserved-HBM story
lives once the batching scheduler and the ring stages page their own
decode caches (docs/DESIGN.md §14).

Ownership (paged): pages are tree-owned or free — a seed copies out of
tree pages under a short-lived pin, a store hands freshly written pages
to the tree (redundant ones are freed immediately), so after every
``seed``/``store`` the leak invariant ``used == tree.block_count``
holds with zero live leases.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .manager import apply_byte_budget, resolve_kvcache_config
from .paged import PagedKVCacheManager


class PagedKVBackend:
    """Device page pool behind the seam (docs/DESIGN.md §11/§14)."""

    layout = "paged"

    def __init__(self, cfg, num_blocks: int, block_tokens: int,
                 dtype=None, kv_dtype=None,
                 kv_host_tier_bytes: Optional[int] = None,
                 kv_disk_tier_path: Optional[str] = None,
                 kv_disk_tier_bytes: Optional[int] = None):
        import jax
        import jax.numpy as jnp

        from ...ops.quant import alloc_kv_pages, resolve_kv_dtype
        self.kv_dtype = resolve_kv_dtype(kv_dtype)
        self.mgr = PagedKVCacheManager.for_model(
            cfg, num_blocks, block_tokens, dtype=dtype,
            kv_dtype=self.kv_dtype)
        self.block_tokens = self.mgr.block_tokens
        page_dtype = dtype if dtype is not None else cfg.dtype
        self._pk = alloc_kv_pages(
            (cfg.num_layers, self.mgr.num_blocks, cfg.num_kv_heads,
             self.mgr.block_tokens, cfg.head_dim), self.kv_dtype,
            page_dtype)
        self._pv = jax.tree.map(jnp.zeros_like, self._pk)
        # tiered KV (docs/DESIGN.md §21): evicted tree leaves demote
        # into the host ring; seed() promotes a demoted continuation
        # back before its match.  Arg over env (resolve_tier_config),
        # so single-request engines inherit DWT_KV_HOST_TIER_BYTES with
        # zero per-engine plumbing — the §17 kv_dtype pattern.
        from .tiered import (TieredKVStore, make_demote_hook,
                             resolve_tier_config)
        tier_host, tier_path, tier_disk = resolve_tier_config(
            kv_host_tier_bytes, kv_disk_tier_path, kv_disk_tier_bytes)
        self.tier = None
        if tier_host > 0:
            self.tier = TieredKVStore(tier_host, self.block_tokens,
                                      disk_path=tier_path,
                                      disk_bytes=tier_disk)
            self.mgr.tier = self.tier
            self.mgr.demote_hook = make_demote_hook(
                self.tier, lambda: (self._pk, self._pv))

    def seed(self, ids, cache):
        """Match + device gather out of the pool into the fresh cache —
        zero H2D on the device-tier path (this class never moves bytes
        through the host; ``dwt_kvcache_h2d_bytes_total`` counts only
        §21 tier promotions, re-staged here before the match so a
        demoted prefix still seeds).  The pin is released right after
        the gather dispatch: device ops execute in dispatch order, so a
        later store/evict can never overwrite the pages before the
        gather reads them."""
        import jax.numpy as jnp

        from ...models.base import KVCache
        from .device import seed_cache_from_pages
        if ids.shape[0] != 1:
            return 0, cache
        if self.tier is not None:
            from .tiered import promote_prefix
            self._pk, self._pv, _ = promote_prefix(
                self.mgr, self.tier, self._pk, self._pv,
                np.asarray(ids[0]))
        lease = self.mgr.match(np.asarray(ids[0]))
        if lease is None:
            return 0, cache
        m = lease.tokens
        ck, cv = seed_cache_from_pages(
            cache.keys, cache.values, self._pk, self._pv,
            jnp.asarray(lease.block_ids, jnp.int32))
        lease.release()
        return m, KVCache(ck, cv, jnp.int32(m))

    def store(self, ids, cache) -> None:
        """Allocate pages for the prompt's MISSING tail blocks, scatter
        the matching cache columns into them on device (zero D2H), and
        hand them to the radix tree.  Blocks the tree already covers
        (``peek``) allocate and write nothing — a warm store must not
        evict hot prefixes to stage pages the tree would immediately
        decline.  ``peek`` is capped below the prompt length and an
        eviction can race the coverage read, so the tree-side contract
        (``store_shared`` with None placeholders) stops insertion at
        any block the caller brought no page for — caching less is
        always correct; genuinely redundant tail pages are declined and
        freed here."""
        import jax.numpy as jnp

        from .device import store_cache_to_pages
        if ids.shape[0] != 1:
            return
        prompt = np.asarray(ids[0])
        n_blocks = len(prompt) // self.mgr.block_tokens
        if n_blocks < 1:
            return
        covered = self.mgr.peek(prompt) // self.mgr.block_tokens
        missing = n_blocks - covered         # >= 1: peek caps at len-1
        block_ids = self.mgr.alloc(missing)
        if block_ids is None:
            return      # every evictable page pinned: caching less is fine
        self._pk, self._pv = store_cache_to_pages(
            self._pk, self._pv, cache.keys, cache.values,
            jnp.asarray(block_ids, jnp.int32), jnp.int32(covered))
        adopted, lease = self.mgr.store_shared(
            prompt, [None] * covered + list(block_ids))
        if lease is not None:
            # nothing outlives this call that references the pages (the
            # engine decodes against its own cache copy) — release now
            lease.release()
        declined = set(block_ids) - set(adopted)
        if declined:
            self.mgr.free(sorted(declined))

    @property
    def stats(self) -> dict:
        return self.mgr.stats

    def snapshot(self) -> dict:
        return self.mgr.snapshot()

    def debug_state(self) -> dict:
        return self.mgr.debug_state()

    def reset_stats(self) -> None:
        self.mgr.reset_stats()

    def close(self) -> None:
        """Drop the host/disk tier with the pool it shadows — demoted
        entries reference a page layout a successor backend may not
        share, so they die here rather than resurrect wrong."""
        if self.tier is not None:
            self.mgr.demote_hook = None
            self.mgr.tier = None
            self.tier.close()
            self.tier = None


def make_kv_backend(cfg, kv_cache_blocks: Optional[int],
                    kv_block_tokens: Optional[int], *, layout: str,
                    dtype=None, kv_dtype=None, default_blocks: int = 0,
                    kv_host_tier_bytes: Optional[int] = None,
                    kv_disk_tier_path: Optional[str] = None,
                    kv_disk_tier_bytes: Optional[int] = None):
    """The one constructor every engine calls: resolve the block-count /
    block-tokens knobs (CLI over env over ``default_blocks``) and build
    the layout's backend — or None when the pool is off (0 blocks, or a
    ``DWT_KVCACHE_BYTES`` ceiling below one block: a knob documented as
    a ceiling must never crash engine construction).

    ``kv_dtype`` (arg over ``DWT_KV_DTYPE`` over bf16) selects the page
    WIDTH; every engine behind this seam inherits it with no per-engine
    plumbing.  Mutually exclusive with a ``dtype`` storage cast: the
    cast rescales the same full-width layout, quantization replaces it."""
    from ...ops.quant import kv_token_head_bytes, resolve_kv_dtype
    if layout != "paged":
        raise ValueError(
            f"unknown kv layout {layout!r}: paged is the only layout "
            "(the dense backend was removed; docs/DESIGN.md §14)")
    kv_dtype = resolve_kv_dtype(kv_dtype)
    if kv_dtype != "bf16" and dtype is not None:
        raise ValueError(
            f"kv_dtype={kv_dtype!r} quantizes the page pool and cannot "
            "compose with a kv_cache_dtype storage cast "
            f"({np.dtype(dtype).name}); drop one of the two knobs")
    n_blocks, block_tokens = resolve_kvcache_config(
        kv_cache_blocks, kv_block_tokens, default_blocks=default_blocks)
    if n_blocks < 1:
        return None
    # the byte budget admits blocks at their ACTUAL page width (narrow
    # data + scale sidecar), not the full-width itemsize — one shared
    # owner with PagedKVCacheManager so admission and accounting agree
    dtype_ = dtype if dtype is not None else cfg.dtype
    block_bytes = (2 * int(cfg.num_layers) * int(cfg.num_kv_heads)
                   * int(block_tokens)
                   * kv_token_head_bytes(int(cfg.head_dim), kv_dtype,
                                         dtype_))
    if apply_byte_budget(n_blocks, block_bytes) < 1:
        return None
    return PagedKVBackend(cfg, n_blocks, block_tokens, dtype=dtype,
                          kv_dtype=kv_dtype,
                          kv_host_tier_bytes=kv_host_tier_bytes,
                          kv_disk_tier_path=kv_disk_tier_path,
                          kv_disk_tier_bytes=kv_disk_tier_bytes)
