"""Fixed-granularity KV block pool: the byte budget, made physical.

Every cached prefix is stored as whole blocks of ``block_tokens`` K/V
positions, host-side, in two preallocated numpy arrays of shape
``[num_blocks, num_layers, num_kv_heads, block_tokens, head_dim]``.
Fixed granularity is what makes sharing work (vLLM's PagedAttention
insight): two prompts that agree on their first N*block_tokens tokens
share the SAME N blocks, refcounted by the radix tree above this pool —
no per-prompt copies, no fragmentation, and the byte budget is exactly
``num_blocks * block_bytes``, enforced by construction rather than by
accounting.

Host-side on purpose: cached prefixes are cold capacity (HBM is the
scarce resource the decode batch and the weights already fight over),
and the device copies in and out ride the engines' existing
``load_prefix``-style programs (one H2D per hit, one D2H per store —
amortized over the prefill dispatches they replace).

The pool knows nothing about tokens or trees: it allocates, frees, and
moves bytes.  ``alloc`` returns ``None`` when empty — the caller (the
manager) decides whether to evict or to skip caching.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class KVBlockPool:
    """Preallocated host store of fixed-size KV blocks."""

    def __init__(self, num_blocks: int, num_layers: int, num_kv_heads: int,
                 block_tokens: int, head_dim: int, dtype):
        if num_blocks < 1:
            raise ValueError("num_blocks must be >= 1")
        if block_tokens < 1:
            raise ValueError("block_tokens must be >= 1")
        self.num_blocks = num_blocks
        self.block_tokens = block_tokens
        shape = (num_blocks, num_layers, num_kv_heads, block_tokens,
                 head_dim)
        self.dtype = np.dtype(dtype)
        self.keys = np.zeros(shape, self.dtype)
        self.values = np.zeros(shape, self.dtype)
        # K + V for one block — the unit the byte budget counts in
        self.block_bytes = 2 * int(
            np.prod(shape[1:])) * self.dtype.itemsize
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))

    # ------------------------------------------------------------------

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - len(self._free)

    @property
    def resident_bytes(self) -> int:
        return self.used_blocks * self.block_bytes

    @property
    def capacity_bytes(self) -> int:
        return self.num_blocks * self.block_bytes

    def alloc(self) -> Optional[int]:
        """One free block id, or None when the pool is exhausted."""
        return self._free.pop() if self._free else None

    def free(self, block_ids) -> None:
        for bid in block_ids:
            if not 0 <= bid < self.num_blocks:
                raise ValueError(f"bad block id {bid}")
            self._free.append(bid)
        if len(self._free) > self.num_blocks:
            raise RuntimeError("double free: pool over capacity")

    # ------------------------------------------------------------------

    def write(self, block_id: int, k: np.ndarray, v: np.ndarray) -> None:
        """Fill one block with ``[L, H, block_tokens, D]`` K/V data."""
        self.keys[block_id] = k
        self.values[block_id] = v

    def gather(self, block_ids):
        """Contiguous ``[L, H, n*block_tokens, D]`` K/V run over blocks
        (the shape engines reshape into their cache rows)."""
        k = self.keys[block_ids]            # [n, L, H, bt, D]
        v = self.values[block_ids]
        n, L, H, bt, D = k.shape
        # [n, L, H, bt, D] -> [L, H, n*bt, D]
        k = np.ascontiguousarray(np.transpose(k, (1, 2, 0, 3, 4))
                                 ).reshape(L, H, n * bt, D)
        v = np.ascontiguousarray(np.transpose(v, (1, 2, 0, 3, 4))
                                 ).reshape(L, H, n * bt, D)
        return k, v
