"""Disaggregated prefill/decode serving with KV page migration.

Colocating compute-bound prefill and bandwidth-bound decode on one
engine makes TTFT and TPOT fight for the same chip (docs/DESIGN.md
§3/§6): a long prefill's chunks interleave with — and stall — every
in-flight decode step, and decode steals the HBM bandwidth the chunked
prefill needs.  This module splits the two roles (docs/DESIGN.md §15):

- :class:`PrefillWorker` runs chunked prefill into its local paged pool
  and **migrates the request's KV pages** to a decode worker over the
  §12 transport — a new tagged frame kind (``pg:{rid}:{attempt}:{seq}``)
  carrying page payloads + block metadata, so CRC integrity, bounded
  send retry, and receiver dedup come for free.  Pages stream
  **per prefill chunk**: migration overlaps the remaining prefill
  instead of waiting for it.
- :class:`DecodeWorker` stages arriving page frames on the HOST (a
  partial migration therefore holds ZERO pool pages — crash cleanup is
  structural), and on a complete, CRC-verified migration ADOPTS the
  pages into its scheduler's pool + radix tree
  (``ContinuousBatchingEngine.submit_premigrated`` → §11
  ``store_shared`` ownership adoption) and joins the request into the
  paged-native continuous-batching drain.  The join is a block-table
  reference plus one short suffix prefill (≤ one block) — decode
  batches never stall behind a long prefill again, and
  ``dwt_kvcache_h2d_bytes_total`` stays 0 on the decode side (the
  adopt is a device scatter + table reference, never a dense-row
  host gather).
- :class:`DisaggCoordinator` owns request handoff and migration
  scheduling: round-robin dispatch over prefill workers, and
  crash-rescheduling — a prefill worker that dies mid-migration gets
  its unfinished requests resent to a surviving worker under a bumped
  ``attempt`` (the decode worker discards stale-attempt frames, so a
  half-migrated attempt can never corrupt the decode-side tree).

Reliability protocol (rides the §12 substrate):

- every frame is a `wire.serialize_tensors` payload → CRC-checked; a
  corrupt page frame is counted + dropped, never adopted;
- the receiver tracks the expected next ``seq`` per (rid, attempt):
  duplicated / reordered / retried frames are dropped idempotently
  (the (rid, step) dedup rule, migration-shaped);
- the end frame (``pge``) carries the frame count; the receiver acks
  with its expected seq, and the sender retransmits the missing tail
  (go-back-n) under a bounded retry budget — drops and CRC rejections
  recover without resharding;
- a completed (joined) rid re-acks "complete" for any late attempt's
  frames, so retransmits and reschedule races stay idempotent.

Exactness: migrated pages hold the model's K/V for whole prompt
blocks, which depend only on the prompt prefix (causality) — the same
bytes the decode engine's own cold prefill would write.  Chunked
prefill is bit-identical to whole-prompt prefill (§10), so greedy
output through the disaggregated path is bit-identical to the
colocated engine (pinned by tests/test_disagg.py + the chaos soak).
Under a quantized ``kv_dtype`` (docs/DESIGN.md §17) the prefill worker
quantizes ONCE at export; the frames carry the narrow bytes plus the
scale sidecars (a ``kv_dtype`` tag in the page-frame metadata), and
the decode pool adopts them verbatim — the migrated pages are
bit-identical to the prefill side's, so there is exactly one
quantization rounding on the whole path, the same one a colocated
quantized engine pays at its own page-write.

Frame tags (rids must not contain ``:``):

    dreq:{rid}:{attempt}    coordinator → prefill   request handoff
    pg:{rid}:{attempt}:{n}  prefill → decode        page payload frame
    pge:{rid}:{attempt}     prefill → decode        migration end/manifest
    pga:{rid}:{attempt}     decode → prefill        ack (status, expected)
    pgx:{rid}               coordinator → decode    abort a staged attempt
    tok:{rid}:{i}           decode → coordinator    one streamed token
    fin:{rid}               decode → coordinator    final tokens / error
    perr:{rid}:{attempt}    prefill → coordinator   handoff failed
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..comm import wire
from ..comm.transport import (TransportError, TransportTimeout,
                              record_corrupt_frame)
from ..telemetry._env import env_float, env_int
from ..telemetry.flightrecorder import get_flight_recorder
from ..telemetry.tracing import SpanClock, TraceRecorder, new_trace_id

log = logging.getLogger(__name__)

# migration reliability knobs (docs/DESIGN.md §15 table)
DEFAULT_ACK_TIMEOUT_S = env_float("DWT_DISAGG_ACK_TIMEOUT_S", 2.0)
DEFAULT_MIGRATION_RETRIES = env_int("DWT_DISAGG_MIGRATION_RETRIES", 5)


def _disagg_metrics():
    """The dwt_disagg_* series, resolved lazily and never fatally (a
    metrics regression must not take down the data plane) — the
    transport's pattern."""
    try:
        from ..telemetry import catalog
        return catalog
    except Exception:           # pragma: no cover - defensive
        return None


def _meta_frame(meta: dict, tensors=(), trace=None) -> bytes:
    """One migration-control payload: a JSON metadata blob as a u8
    tensor, followed by any data tensors — CRC + optional trace-context
    trailer via the standard wire codec."""
    blob = np.frombuffer(json.dumps(meta).encode(), np.uint8)
    arrays = [blob] + list(tensors)
    if trace is None:
        return wire.serialize_tensors(arrays)
    return wire.serialize_tensors_traced(arrays, trace[0], trace[1])


def _parse_meta_frame(payload: bytes):
    """(meta, tensors, trace_ctx) — raises WireError/WireIntegrityError
    on a corrupt or malformed frame (the caller drops it)."""
    tensors, ctx = wire.split_trace_context(
        wire.deserialize_tensors(payload))
    if not tensors:
        raise wire.WireError("migration frame without metadata tensor")
    meta = json.loads(bytes(tensors[0].tobytes()).decode())
    return meta, tensors[1:], ctx


#: leaves per side on the wire for each page width: bf16 ships the one
#: full-width tensor (byte-identical to the pre-quantization format),
#: int8 ships (data, scale), packed int4 (data, scale, zero).
_WIRE_LEAVES = {"bf16": 1, "int8": 2, "int4": 3}


def _kv_leaf_lists(blocks):
    """Flatten one side's (possibly quantized) host block payload into
    the wire's flat tensor list + its page-width tag."""
    from ..ops.quant import QuantizedKVPages
    if isinstance(blocks, QuantizedKVPages):
        leaves = [np.asarray(blocks.data), np.asarray(blocks.scale)]
        if blocks.zero is not None:
            leaves.append(np.asarray(blocks.zero))
        return leaves, ("int4" if blocks.bits == 4 else "int8")
    return [np.asarray(blocks)], "bf16"


def _kv_from_leaves(leaves, kv_dtype: str):
    """Rebuild one side's block payload from its wire leaf list."""
    if kv_dtype == "bf16":
        return leaves[0]
    from ..ops.quant import QuantizedKVPages
    bits = 4 if kv_dtype == "int4" else 8
    zero = leaves[2] if bits == 4 else None
    return QuantizedKVPages(leaves[0], leaves[1], zero, bits)


def _page_frame(k_blocks, v_blocks, first_block: int, trace=None) -> bytes:
    """One page-payload frame: ``[n, L, H, bt, D]`` K and V block runs
    starting at block index ``first_block`` of the migration.  Quantized
    runs (``ops.quant.QuantizedKVPages``) ship their narrow data plus
    the scale (and int4 zero-point) sidecars as extra tensors with a
    ``kv_dtype`` tag in the metadata; full-width frames carry no tag and
    stay byte-identical to the pre-quantization wire format."""
    k_leaves, kv_dtype = _kv_leaf_lists(k_blocks)
    v_leaves, _ = _kv_leaf_lists(v_blocks)
    meta = {"first_block": int(first_block),
            "n_blocks": int(k_blocks.shape[0])}
    if kv_dtype != "bf16":
        meta["kv_dtype"] = kv_dtype
    return _meta_frame(meta, k_leaves + v_leaves, trace=trace)


class MigrationError(RuntimeError):
    """A migration could not complete within its retry budget."""


class PageStager:
    """Host-side ``(rid, attempt)`` page-frame staging shared by the §15
    admission join (:class:`DecodeWorker`) and the §18 live-migration
    handoff (``runtime.migration.MigrationWorker``).

    One store serves both protocols because their bulk-transfer leg is
    the same ``pg:`` frame stream — when a decode replica runs both
    workers on one transport, they SHARE one stager (the worker whose
    completion frame arrives — ``pge:`` or ``rsd:`` — claims the staged
    record), so an inbound page frame never needs to announce which
    protocol it belongs to.

    Invariants the stager owns:

    - staging is HOST memory only — zero pool pages held until a
      complete, CRC-verified frame set is adopted (crash cleanup is
      structural);
    - ``staged_bytes`` tracks every staged tensor byte and every removal
      path (abort, adopt, supersede, eviction) funnels through
      :meth:`clear`, so an aborted handoff provably leaves
      ``staged_bytes == 0`` and no record behind;
    - an ABORTED ``(rid, attempt)`` is remembered (bounded markers): a
      late frame of that attempt drops instead of silently restaging a
      leak the abort already cleaned up;
    - bounded: past ``STAGED_CAP`` records the OLDEST evicts — the
      backstop for migrations orphaned by a sender that died without an
      abort reaching us.  Evicting a still-live migration is safe: its
      next frame restages from seq 0, the end frame nacks, and the
      sender's go-back-n retransmits the lot.

    Record schema (the keys tests may pin): ``attempt``, ``expected``
    (next seq), ``frames`` ({seq: (first_block, k_leaves, v_leaves)}),
    ``kv_dtype``, ``bytes``, ``t0``, plus ``state_meta`` /
    ``state_tensors`` / ``ctx`` slots the live-migration manifest
    fills."""

    STAGED_CAP = 256
    MARK_CAP = 4096

    def __init__(self, device_id: str, on_evict=None):
        self.device_id = device_id
        self._staged: Dict[str, dict] = {}
        from collections import OrderedDict
        self._aborted: "OrderedDict[str, int]" = OrderedDict()
        self.staged_bytes = 0
        self._on_evict = on_evict
        self._flight = get_flight_recorder()

    def clear(self, rid: str) -> Optional[dict]:
        """Pop a staging record AND its byte accounting."""
        st = self._staged.pop(rid, None)
        if st is not None:
            self.staged_bytes -= st["bytes"]
        return st

    def mark_aborted(self, rid: str, attempt: int) -> None:
        self._aborted[rid] = attempt
        self._aborted.move_to_end(rid)
        while len(self._aborted) > self.MARK_CAP:
            self._aborted.popitem(last=False)

    def staging(self, rid: str, attempt: int) -> Optional[dict]:
        """The record for ``(rid, attempt)``: fresh on the first frame
        of a NEWER attempt (superseding the stale one), None for a stale
        or aborted attempt (the caller drops the frame)."""
        if self._aborted.get(rid, -1) >= attempt:
            return None
        st = self._staged.get(rid)
        if st is None or st["attempt"] < attempt:
            if st is not None:
                self.clear(rid)
                self._flight.record("disagg_attempt_superseded", rid=rid,
                                    old=st["attempt"], new=attempt)
            st = {"attempt": attempt, "expected": 0, "frames": {},
                  "kv_dtype": "bf16", "bytes": 0,
                  "state_meta": None, "state_tensors": None, "ctx": None,
                  "t0": time.perf_counter()}
            self._staged[rid] = st
            while len(self._staged) > self.STAGED_CAP:
                victim = min(self._staged,
                             key=lambda r: self._staged[r]["t0"])
                self.clear(victim)
                self._flight.record("disagg_staging_evicted", rid=victim)
                if self._on_evict is not None:
                    self._on_evict(victim)
            return st
        if st["attempt"] > attempt:
            return None
        return st

    def stage_page(self, rid: str, attempt: int, seq: int,
                   payload: bytes, tag: str) -> str:
        """Stage one ``pg:`` frame; returns ``"staged"`` or the drop
        reason (``"corrupt"`` frames are counted via
        :func:`record_corrupt_frame` here — the sender's ack round
        retransmits them)."""
        try:
            meta, tensors, _ = _parse_meta_frame(payload)
        except wire.WireError as e:
            record_corrupt_frame(self.device_id, tag, len(payload), e)
            return "corrupt"
        st = self.staging(rid, attempt)
        if st is None:
            return "stale_attempt"
        if seq != st["expected"]:
            # duplicate (seq < expected) or a reorder hole (seq >
            # expected): drop — the (rid, attempt, seq) dedup that makes
            # retried page frames idempotent; go-back-n refills holes
            return "dedup"
        kv_dtype = meta.get("kv_dtype", "bf16")
        nk = _WIRE_LEAVES.get(kv_dtype)
        if nk is None or len(tensors) != 2 * nk:
            # a malformed leaf list is a corrupt frame, not a protocol
            # state: drop it and let the sender's ack round retransmit
            record_corrupt_frame(
                self.device_id, tag, len(payload),
                wire.WireError(f"page frame kv_dtype={kv_dtype!r} with "
                               f"{len(tensors)} tensors"))
            return "corrupt"
        # frames of one migration share one width (one exporter); the
        # leaf lists stage per frame and concatenate leaf-wise on adopt
        st["kv_dtype"] = kv_dtype
        nb = int(sum(t.nbytes for t in tensors))
        st["frames"][seq] = (int(meta["first_block"]),
                             [np.asarray(t) for t in tensors[:nk]],
                             [np.asarray(t) for t in tensors[nk:]])
        st["bytes"] += nb
        st["expected"] += 1
        self.staged_bytes += nb
        return "staged"

    def concat_blocks(self, st: dict, n_blocks: int):
        """``(k_blocks, v_blocks)`` assembled from a complete frame set:
        frames apply in seq order at their ``first_block`` offsets, so a
        later frame's version of a block (the live handoff's re-shipped
        partial tail) OVERWRITES an earlier one's.  Raises
        :class:`MigrationError` on block holes (a manifest/frames
        mismatch — the caller fails the migration rather than adopting
        the wrong pages)."""
        if not st["frames"]:
            return None, None
        slots: List[Optional[tuple]] = [None] * n_blocks
        overrun = 0
        for seq in sorted(st["frames"]):
            first, k_leaves, v_leaves = st["frames"][seq]
            n = k_leaves[0].shape[0]
            for j in range(n):
                if 0 <= first + j < n_blocks:
                    slots[first + j] = (
                        [lv[j:j + 1] for lv in k_leaves],
                        [lv[j:j + 1] for lv in v_leaves])
                else:
                    overrun += 1
        holes = sum(s is None for s in slots)
        if holes or overrun:
            raise MigrationError(
                f"staged frames cover {n_blocks - holes}/{n_blocks} "
                f"blocks ({overrun} out of range)")
        k_leaves = [np.concatenate(parts, axis=0)
                    for parts in zip(*(s[0] for s in slots))]
        v_leaves = [np.concatenate(parts, axis=0)
                    for parts in zip(*(s[1] for s in slots))]
        return (_kv_from_leaves(k_leaves, st["kv_dtype"]),
                _kv_from_leaves(v_leaves, st["kv_dtype"]))

    def debug_state(self) -> dict:
        return {rid: {"attempt": st["attempt"],
                      "frames_staged": st["expected"],
                      "bytes": st["bytes"]}
                for rid, st in list(self._staged.items())}


# ---------------------------------------------------------------------------
# prefill worker
# ---------------------------------------------------------------------------


class PrefillWorker:
    """Prefill-only serving role: chunked prefill into a local paged
    pool, per-chunk KV page migration to a decode worker.

    The worker never samples a token — the LM head is dead code on this
    role (chunks run the logits-free ``chunk_mid`` program, so XLA
    drops the head matmul entirely), and the first sampled token comes
    from the decode worker's suffix prefill.  Its paged pool + radix
    tree give repeat prompts prefix reuse: a matched prefix migrates
    straight out of the pool with zero recompute.
    """

    def __init__(self, cfg, params, transport, max_seq: int = 1024,
                 prefill_chunk: int = 32,
                 kv_cache_blocks: Optional[int] = None,
                 kv_block_tokens: Optional[int] = None,
                 ack_timeout: Optional[float] = None,
                 migration_retries: Optional[int] = None,
                 kv_dtype: Optional[str] = None):
        import jax
        import jax.numpy as jnp

        from ..models.base import KVCache, StageSpec
        from ..ops.quant import alloc_kv_pages, resolve_kv_dtype
        from ..parallel.tensor import make_forward_seam
        from .engine import make_chunk_programs, validate_prefill_chunk
        from .kvcache import PagedKVCacheManager, resolve_kvcache_config

        self.cfg = cfg
        self.params = params
        self.transport = transport
        self.device_id = transport.device_id
        self.max_seq = max_seq
        self.prefill_chunk = validate_prefill_chunk(
            prefill_chunk or 32, max_seq) or 32
        self.ack_timeout = (DEFAULT_ACK_TIMEOUT_S if ack_timeout is None
                            else float(ack_timeout))
        self.migration_retries = (DEFAULT_MIGRATION_RETRIES
                                  if migration_retries is None
                                  else int(migration_retries))
        spec = StageSpec(0, 1, 0, cfg.num_layers)
        fwd, _ = make_forward_seam(cfg, spec, None, params)
        self._chunk_mid, _ = make_chunk_programs(fwd)
        self._KVCache = KVCache

        n_blocks, bt = resolve_kvcache_config(
            kv_cache_blocks, kv_block_tokens, default_blocks=0)
        if n_blocks < 1:
            # default pool: enough pages for a handful of max_seq prompts
            n_blocks = 4 * max(1, -(-max_seq // bt))
        # page width: the local reuse pool, the exported block payloads
        # and the decode-side engine pool all share ONE kv_dtype so a
        # migrated page adopts verbatim (docs/DESIGN.md §17)
        self.kv_dtype = resolve_kv_dtype(kv_dtype)
        self.kv_cache = PagedKVCacheManager.for_model(
            cfg, n_blocks, bt, kv_dtype=self.kv_dtype)
        N = self.kv_cache.num_blocks
        self._pk = alloc_kv_pages((cfg.num_layers, N, cfg.num_kv_heads,
                                   bt, cfg.head_dim), self.kv_dtype,
                                  cfg.dtype)
        self._pv = jax.tree.map(jnp.zeros_like, self._pk)

        self.tracer = TraceRecorder(f"prefill:{self.device_id}")
        self.stats = {"handoffs": 0, "migrated_pages": 0,
                      "migrated_bytes": 0, "retransmitted_frames": 0,
                      "failed_handoffs": 0, "last_migration_ms": None}
        self._backlog: List[tuple] = []
        self._inflight_rid: Optional[str] = None
        self._stop = threading.Event()
        self._flight = get_flight_recorder()

    # -- serve loop --------------------------------------------------------

    def stop(self) -> None:
        self._stop.set()

    def serve_forever(self, idle_timeout: Optional[float] = None) -> None:
        """Process handoff requests until :meth:`stop` (or
        ``idle_timeout`` seconds without work)."""
        idle_since = time.monotonic()
        while not self._stop.is_set():
            try:
                tag, payload = self.transport.recv_any(timeout=0.1)
            except TransportTimeout:
                if not self._backlog:
                    if (idle_timeout is not None
                            and time.monotonic() - idle_since
                            > idle_timeout):
                        return
                    continue
                tag = None
            if tag is not None:
                idle_since = time.monotonic()
                if tag.startswith("dreq:"):
                    self._backlog.append((tag, payload))
                # anything else (stray late acks) is dropped: the
                # handoff that wanted it already resolved
            if self._backlog:
                t, p = self._backlog.pop(0)
                self._handle_request(t, p)
                idle_since = time.monotonic()

    def _handle_request(self, tag: str, payload: bytes) -> None:
        try:
            meta, tensors, ctx = _parse_meta_frame(payload)
        except wire.WireError as e:
            record_corrupt_frame(self.device_id, tag, len(payload), e)
            return
        prompt = np.asarray(tensors[0], np.int32).reshape(-1)
        rid, attempt = meta["rid"], int(meta.get("attempt", 0))
        self._inflight_rid = rid
        try:
            self.handoff(rid, attempt, prompt, int(meta["max_new"]),
                         meta["decode_id"], meta["reply_to"], ctx)
        except (MigrationError, TransportError) as e:
            # a dead/blocked decode peer surfaces as TransportError out
            # of ship()/end sends: a FAILED HANDOFF, never a dead
            # worker — report perr so the coordinator reschedules.
            # (InjectedCrash is a RuntimeError, not TransportError: a
            # chaos crash still kills the serve loop like a real one.)
            self.stats["failed_handoffs"] += 1
            self._flight.record("disagg_handoff_failed", rid=rid,
                                attempt=attempt, error=str(e))
            try:
                self.transport.send(
                    meta["reply_to"], f"perr:{rid}:{attempt}",
                    _meta_frame({"rid": rid, "attempt": attempt,
                                 "error": str(e)}))
            except TransportError:
                pass      # the coordinator's supervision will notice
        finally:
            self._inflight_rid = None

    # -- the handoff itself ------------------------------------------------

    def _export_blocks(self, row_k, row_v, lo: int, hi: int):
        """Blocks ``[lo, hi)`` of a dense prefill row as numpy
        ``[n, L, H, bt, D]`` pairs (one D2H slice each — this IS the
        wire export; the decode-side adopt stays device-resident).
        Under a quantized ``kv_dtype`` each side quantizes here, ONCE,
        before hitting the wire: the frames carry the narrow bytes plus
        scale sidecars, and the decode pool adopts them verbatim —
        bit-identical to this worker's own reuse pool."""
        import jax

        bt = self.kv_cache.block_tokens
        L, _, H, _, D = row_k.shape
        n = hi - lo
        k = np.asarray(row_k[:, 0, :, lo * bt:hi * bt, :])
        v = np.asarray(row_v[:, 0, :, lo * bt:hi * bt, :])
        k = np.ascontiguousarray(k.reshape(L, H, n, bt, D)
                                 .transpose(2, 0, 1, 3, 4))
        v = np.ascontiguousarray(v.reshape(L, H, n, bt, D)
                                 .transpose(2, 0, 1, 3, 4))
        if self.kv_dtype == "bf16":
            return k, v
        from ..ops.quant import quantize_kv_pages
        bits = 4 if self.kv_dtype == "int4" else 8
        to_host = lambda q: jax.tree.map(np.asarray, q)
        return (to_host(quantize_kv_pages(k, bits)),
                to_host(quantize_kv_pages(v, bits)))

    def handoff(self, rid: str, attempt: int, prompt: np.ndarray,
                max_new: int, decode_id: str, reply_to: str,
                ctx=None) -> None:
        """Run chunked prefill for ``prompt`` and migrate its KV pages
        to ``decode_id``, streaming page frames per chunk; the decode
        worker samples and streams tokens straight to ``reply_to``."""
        import jax.numpy as jnp

        from .kvcache.device import seed_cache_from_pages

        mgr = self.kv_cache
        bt = mgr.block_tokens
        plen = len(prompt)
        n_mig = (plen - 1) // bt     # blocks the decode-side join can use
        clock = SpanClock()
        trace = ctx
        span = 0
        if trace is not None:
            span = self.tracer.next_span_id()
        self.stats["handoffs"] += 1
        self._flight.record("disagg_handoff", rid=rid, attempt=attempt,
                            prompt_len=plen, blocks=n_mig)

        frames: List[bytes] = []    # kept until acked, for retransmit

        def ship(k_blocks, v_blocks, first_block):
            body = _page_frame(k_blocks, v_blocks, first_block,
                               trace=(trace[0], span) if trace else None)
            frames.append(body)
            self.transport.send(decode_id,
                                f"pg:{rid}:{attempt}:{len(frames) - 1}",
                                body)

        # 1. prefix reuse: matched blocks migrate straight out of the
        #    pool (zero recompute); the row is seeded from the same
        #    pages so the remaining chunks continue from position m.
        #    The lease is released in the finally — a handoff that dies
        #    mid-send (dead decode peer, injected crash) must not pin
        #    prefix pages in the pool forever.
        lease = mgr.match(prompt) if n_mig >= 1 else None
        try:
            m = lease.tokens if lease is not None else 0
            row = self._KVCache.create(self.cfg, self.cfg.num_layers, 1,
                                       self.max_seq)
            row_k, row_v = row.keys, row.values
            if lease is not None:
                ids = jnp.asarray(np.asarray(lease.block_ids, np.int32))
                row_k, row_v = seed_cache_from_pages(
                    row_k, row_v, self._pk, self._pv, ids)
                pk, pv = self._export_blocks(row_k, row_v, 0, m // bt)
                ship(pk, pv, 0)

            # 2. chunked prefill over [m, n_mig*bt), exporting each
            #    chunk's completed blocks the moment the chunk lands —
            #    migration overlaps the remaining prefill.  Logits-free
            #    chunk_mid only: this role never samples.
            C = self.prefill_chunk
            cache = self._KVCache(row_k, row_v, jnp.int32(m))
            pos, exported = m, m // bt
            prefill_clock = SpanClock()
            while pos < n_mig * bt:
                step = min(C, n_mig * bt - pos)
                chunk = np.zeros((1, C), np.int32)
                chunk[0, :step] = prompt[pos:pos + step]
                cache = self._chunk_mid(self.params, jnp.asarray(chunk),
                                        cache, jnp.int32(pos))
                pos += step
                done_blocks = min(pos // bt, n_mig)
                if done_blocks > exported:
                    pk, pv = self._export_blocks(
                        cache.keys, cache.values, exported, done_blocks)
                    ship(pk, pv, exported)
                    exported = done_blocks
            if trace is not None:
                self.tracer.record("disagg_prefill", trace[0], span,
                                   clock=prefill_clock, rid=rid,
                                   blocks=n_mig)

            # 3. adopt the freshly computed blocks into the local
            #    pool/tree (prefix reuse for the NEXT request with this
            #    prompt) — before the ack wait, so a slow decode worker
            #    cannot delay the store.  Best-effort: pool pressure
            #    skips it.
            self._store_local(prompt, cache, m, n_mig)
        finally:
            if lease is not None:
                lease.release()

        # 4. end-of-migration manifest + bounded ack/retransmit loop.
        end_meta = {"rid": rid, "attempt": attempt,
                    "n_frames": len(frames), "n_blocks": n_mig,
                    "block_tokens": bt, "max_new": int(max_new),
                    "reply_to": reply_to, "prefill_id": self.device_id}
        end = _meta_frame(end_meta, (prompt,),
                          trace=(trace[0], span) if trace else None)
        acked = False
        for round_i in range(self.migration_retries + 1):
            self.transport.send(decode_id, f"pge:{rid}:{attempt}", end)
            try:
                body = self.transport.recv(f"pga:{rid}:{attempt}",
                                           timeout=self.ack_timeout)
            except TransportTimeout:
                continue
            try:
                status = np.asarray(
                    wire.deserialize_tensors(body).tensors[0]
                ).reshape(-1)
            except wire.WireError as e:
                # a corrupted ack burns one retry round, nothing more
                record_corrupt_frame(self.device_id,
                                     f"pga:{rid}:{attempt}",
                                     len(body), e)
                continue
            if int(status[0]) == 0:
                acked = True
                break
            expected = int(status[1])    # go-back-n from the receiver
            for seq in range(expected, len(frames)):
                self.stats["retransmitted_frames"] += 1
                cat = _disagg_metrics()
                if cat is not None:
                    try:
                        cat.DISAGG_RETRANSMITTED.inc()
                    except Exception:    # pragma: no cover - defensive
                        pass
                self.transport.send(decode_id,
                                    f"pg:{rid}:{attempt}:{seq}",
                                    frames[seq])
        if not acked:
            raise MigrationError(
                f"migration {rid} attempt {attempt} not acknowledged "
                f"after {self.migration_retries + 1} rounds "
                f"({len(frames)} frames, {n_mig} blocks)")

        nbytes = sum(len(f) for f in frames)
        dt = clock.seconds
        self.stats["migrated_pages"] += n_mig
        self.stats["migrated_bytes"] += nbytes
        self.stats["last_migration_ms"] = round(dt * 1e3, 3)
        cat = _disagg_metrics()
        if cat is not None:
            try:
                cat.DISAGG_MIGRATED_PAGES.inc(n_mig)
                cat.DISAGG_MIGRATED_BYTES.inc(nbytes)
                cat.DISAGG_MIGRATION_SECONDS.observe(dt)
            except Exception:            # pragma: no cover - defensive
                pass
        if trace is not None:
            self.tracer.record("disagg_migrate", trace[0], span,
                               clock=clock, rid=rid, blocks=n_mig,
                               bytes=nbytes)
        self._flight.record("disagg_migrated", rid=rid, attempt=attempt,
                            blocks=n_mig, nbytes=nbytes,
                            ms=round(dt * 1e3, 3))

    def _store_local(self, prompt, cache, m: int, n_mig: int) -> None:
        """Adopt blocks ``[m//bt, n_mig)`` of the prefill row into the
        local pool + tree (store_cache_to_pages scatter + store_shared
        ownership adoption) so a repeat prompt migrates from cache.
        Ownership: adopted pages become tree-owned; non-adopted ones go
        straight back to the free list — idle ``used_blocks`` always
        equals ``tree.block_count`` (the prefill half of the leak
        invariant)."""
        import jax.numpy as jnp

        from .kvcache.device import store_cache_to_pages

        mgr = self.kv_cache
        bt = mgr.block_tokens
        start = m // bt
        if n_mig <= start:
            return
        new_ids = mgr.alloc(n_mig - start)
        if new_ids is None:
            return              # pool pressure: reuse is best-effort
        self._pk, self._pv = store_cache_to_pages(
            self._pk, self._pv, cache.keys, cache.values,
            jnp.asarray(np.asarray(new_ids, np.int32)), jnp.int32(start))
        # table for store_shared: matched ids are already tree-owned
        # (declined by insert); None would also work but the real ids
        # keep the assertion inside store_shared meaningful
        table: List[Optional[int]] = [None] * start + list(new_ids)
        adopted, store_lease = mgr.store_shared(prompt[:n_mig * bt],
                                                table)
        adopted_set = set(adopted)
        leftovers = [b for b in new_ids if b not in adopted_set]
        if leftovers:
            mgr.free(leftovers)
        if store_lease is not None:
            store_lease.release()

    # -- observability -----------------------------------------------------

    def debug_state(self) -> dict:
        """``GET /debugz`` fragment for the prefill role: in-flight
        handoff, backlog depth, migration counters, pool picture."""
        return {"role": "prefill",
                "inflight_handoff": self._inflight_rid,
                "handoff_backlog": len(self._backlog),
                "migration": dict(self.stats),
                "kvcache": self.kv_cache.snapshot()}

    def scrape_stats(self) -> dict:
        return {"kvcache": self.kv_cache.snapshot()}


# ---------------------------------------------------------------------------
# decode worker
# ---------------------------------------------------------------------------


class DecodeWorker:
    """Decode-only serving role: stages inbound page frames, adopts
    complete migrations into the batching engine's pool, and streams
    the joined request's tokens back to the requester.

    Partial migrations are HOST staging only — no pool pages are
    allocated until the migration is complete and CRC-verified, so a
    crashed or aborted migration holds zero pages and the §11 ownership
    invariant (``used == tree.block_count + in-flight requests'
    pages``) holds unconditionally on this side.
    """

    def __init__(self, engine, transport, stager: "PageStager" = None):
        self.engine = engine
        self.transport = transport
        self.device_id = transport.device_id
        self.tracer = TraceRecorder(f"decode:{self.device_id}")
        # (rid, attempt) page-frame staging — shared with a co-serving
        # live-migration worker when one is chained (docs/DESIGN.md §18)
        self.stager = stager or PageStager(
            self.device_id, on_evict=self._evicted)
        self._staged = self.stager._staged       # test seam (schema pin)
        # rid -> attempt that joined (re-ack + duplicate suppression).
        # BOUNDED: oldest markers evict past _JOINED_CAP — a marker
        # only matters while late retransmits/reschedules of its rid
        # can still arrive, not for the process lifetime
        from collections import OrderedDict
        self._joined: "OrderedDict[str, int]" = OrderedDict()
        self.stats = {"joined_requests": 0, "adopted_pages": 0,
                      "dropped_frames": 0, "aborted_migrations": 0,
                      "last_migration_ms": None}
        self._stop = threading.Event()
        self._flight = get_flight_recorder()

    def _evicted(self, rid: str) -> None:
        self.stats["aborted_migrations"] += 1

    _JOINED_CAP = 4096

    def _mark_joined(self, rid: str, attempt: int) -> None:
        self._joined[rid] = attempt
        self._joined.move_to_end(rid)
        while len(self._joined) > self._JOINED_CAP:
            self._joined.popitem(last=False)

    def stop(self) -> None:
        self._stop.set()

    def serve_forever(self, idle_timeout: Optional[float] = None) -> None:
        idle_since = time.monotonic()
        while not self._stop.is_set():
            try:
                tag, payload = self.transport.recv_any(timeout=0.1)
            except TransportTimeout:
                if (idle_timeout is not None
                        and time.monotonic() - idle_since > idle_timeout):
                    return
                continue
            idle_since = time.monotonic()
            try:
                self.handle_message(tag, payload)
            except Exception:
                # one malformed frame must not take the decode worker
                # (and every future migration) down with it
                log.exception("%s: migration frame %r failed",
                              self.device_id, tag)

    # -- message handling --------------------------------------------------

    def handle_message(self, tag: str, payload: bytes) -> bool:
        """Dispatch one inbound frame; returns True when the tag was a
        migration frame this worker owns (test seam)."""
        parts = tag.split(":")
        kind = parts[0]
        if kind == "pg":
            self._on_page(parts[1], int(parts[2]), int(parts[3]),
                          payload, tag)
        elif kind == "pge":
            self._on_end(parts[1], int(parts[2]), payload, tag)
        elif kind == "pgx":
            self._on_abort(parts[1])
        else:
            return False
        return True

    def _drop(self, tag: str, why: str) -> None:
        self.stats["dropped_frames"] += 1
        cat = _disagg_metrics()
        if cat is not None:
            try:
                cat.DISAGG_DROPPED_FRAMES.inc()
            except Exception:            # pragma: no cover - defensive
                pass
        self._flight.record("disagg_frame_dropped", tag=tag, why=why)

    def _ack(self, rid: str, attempt: int, prefill_id: str,
             complete: bool, expected: int) -> None:
        body = wire.serialize_tensors(
            [np.asarray([0 if complete else 1, expected], np.int32)])
        try:
            self.transport.send(prefill_id, f"pga:{rid}:{attempt}", body)
        except TransportError:
            pass                 # sender timeout/retry path recovers

    def _on_page(self, rid: str, attempt: int, seq: int, payload: bytes,
                 tag: str) -> None:
        if rid in self._joined:
            # late retransmit / stale reschedule for a request already
            # decoding: dropped; the end frame's complete-ack keeps the
            # sender happy without a second join
            self._drop(tag, "already_joined")
            return
        status = self.stager.stage_page(rid, attempt, seq, payload, tag)
        if status in ("stale_attempt", "dedup"):
            self._drop(tag, status)

    def _on_end(self, rid: str, attempt: int, payload: bytes,
                tag: str) -> None:
        try:
            meta, tensors, ctx = _parse_meta_frame(payload)
        except wire.WireError as e:
            record_corrupt_frame(self.device_id, tag, len(payload), e)
            return
        prefill_id = meta.get("prefill_id", "")
        if rid in self._joined:
            self._ack(rid, attempt, prefill_id, True, 0)
            return
        st = self.stager.staging(rid, attempt)
        if st is None:
            self._drop(tag, "stale_attempt")
            return
        n_frames = int(meta["n_frames"])
        if st["expected"] < n_frames:
            # dropped/corrupt frames upstream: nack with the expected
            # seq so the sender retransmits exactly the missing tail
            self._ack(rid, attempt, prefill_id, False, st["expected"])
            return
        prompt = np.asarray(tensors[0], np.int32).reshape(-1)
        n_blocks = int(meta["n_blocks"])
        try:
            k_blocks, v_blocks = self.stager.concat_blocks(st, n_blocks)
        except MigrationError:
            # manifest/frames disagree — treat as a failed migration
            # rather than adopting the wrong pages
            self._drop(tag, "manifest_mismatch")
            self._ack(rid, attempt, prefill_id, False, 0)
            self.stager.clear(rid)
            return
        try:
            req = self.engine.submit_premigrated(
                prompt, int(meta["max_new"]), k_blocks, v_blocks)
        except Exception as e:
            # an admission rejection (overload shed, capacity bound) is
            # a per-REQUEST failure, never a dead decode worker: ack
            # complete (the migration itself arrived — retransmitting
            # cannot fix admission) and surface the error to the
            # requester through the ordinary fin path
            self.stager.clear(rid)
            self._mark_joined(rid, attempt)
            self._flight.record("disagg_join_rejected", rid=rid,
                                error=type(e).__name__, detail=str(e))
            self._ack(rid, attempt, prefill_id, True, st["expected"])
            try:
                self.transport.send(
                    meta["reply_to"], f"fin:{rid}",
                    _meta_frame({"rid": rid, "ok": False,
                                 "error": f"{type(e).__name__}: {e}"},
                                (np.zeros(0, np.int32),)))
            except TransportError:
                pass
            return
        self._mark_joined(rid, attempt)
        self.stager.clear(rid)
        self.stats["joined_requests"] += 1
        self.stats["adopted_pages"] += n_blocks
        dt = time.perf_counter() - st["t0"]
        self.stats["last_migration_ms"] = round(dt * 1e3, 3)
        cat = _disagg_metrics()
        if cat is not None:
            try:
                cat.DISAGG_ADOPTED_PAGES.inc(n_blocks)
                cat.DISAGG_JOINED.inc()
            except Exception:            # pragma: no cover - defensive
                pass
        if ctx is not None:
            self.tracer.record("disagg_adopt", ctx[0], ctx[1],
                               ts=time.time() - dt, dur=dt,
                               rid=rid, blocks=n_blocks)
        self._flight.record("disagg_join", rid=rid, attempt=attempt,
                            blocks=n_blocks, prompt_len=len(prompt))
        self._ack(rid, attempt, prefill_id, True, st["expected"])
        reply_to = meta["reply_to"]
        t = threading.Thread(target=self._drain, args=(req, rid, reply_to),
                             daemon=True,
                             name=f"disagg-drain-{rid}")
        t.start()

    def _on_abort(self, rid: str) -> None:
        """Abort a staged migration: the host buffers AND their byte
        accounting clear (``staged_bytes`` back to what it was before
        frame 1), and the attempt is marked aborted so a late frame of
        the same handoff drops instead of restaging a leak."""
        if rid in self._joined:
            return               # too late: the request is decoding
        st = self.stager.clear(rid)
        if st is not None:
            self.stager.mark_aborted(rid, st["attempt"])
            self.stats["aborted_migrations"] += 1
            self._flight.record("disagg_abort", rid=rid,
                                attempt=st["attempt"])

    def _drain(self, req, rid: str, reply_to: str) -> None:
        """Forward one joined request's token stream to the requester
        (its own thread: the serve loop must keep staging other
        migrations while this request decodes)."""
        idx = 0
        while True:
            item = req.stream.get()
            if item is None:
                break
            try:
                self.transport.send(reply_to, f"tok:{rid}:{idx}",
                                    wire.serialize_token(int(item)))
            except TransportError:
                pass             # fin carries the authoritative tokens
            idx += 1
        err = req.error
        meta = {"rid": rid, "ok": err is None,
                "error": None if err is None else
                f"{type(err).__name__}: {err}"}
        body = _meta_frame(meta, (np.asarray(req.tokens, np.int32),))
        try:
            self.transport.send(reply_to, f"fin:{rid}", body)
        except TransportError:
            pass

    # -- observability -----------------------------------------------------

    def debug_state(self) -> dict:
        """``GET /debugz`` fragment for the decode role: staged
        (in-flight) migrations, joined/adopted counters, the engine's
        KV picture."""
        out = {"role": "decode",
               "staged_migrations": self.stager.debug_state(),
               "staged_bytes": self.stager.staged_bytes,
               "migration": dict(self.stats)}
        try:
            out["engine"] = self.engine.debug_state()
        except Exception:                # pragma: no cover - defensive
            pass
        return out

    def scrape_stats(self) -> dict:
        return self.engine.stats()


# ---------------------------------------------------------------------------
# coordinator
# ---------------------------------------------------------------------------


class DisaggRequest:
    """One disaggregated request as the coordinator sees it."""

    def __init__(self, rid: str, prompt: np.ndarray, max_new: int,
                 worker: str):
        self.rid = rid
        self.prompt = prompt
        self.max_new = max_new
        self.worker = worker          # current prefill worker
        self.attempt = 0
        self.tokens: List[int] = []
        self.error: Optional[BaseException] = None
        self.done = threading.Event()
        self.t_submit = time.perf_counter()
        self.t_first = 0.0
        self.trace_id = new_trace_id()

    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self.done.wait(timeout):
            raise TimeoutError(f"request {self.rid} did not complete")
        if self.error is not None:
            raise self.error
        return np.asarray(self.tokens, np.int32)

    @property
    def ttft_s(self) -> Optional[float]:
        return (self.t_first - self.t_submit) if self.t_first else None


class DisaggCoordinator:
    """Request handoff + migration scheduling + crash rescheduling.

    Fronts a fleet of prefill workers and one decode worker: submits
    route round-robin over the prefill workers; a worker failure
    (signalled by supervision, a ``perr`` frame, or an undeliverable
    handoff) resends every unfinished request it held to the next
    surviving worker under a bumped attempt, and aborts the stale
    staged attempt on the decode side.  Rides the elastic machinery's
    supervision pattern: the caller watches worker liveness (thread or
    process) and calls :meth:`signal_failure`.
    """

    def __init__(self, transport, prefill_ids: List[str],
                 decode_id: str, max_attempts: int = 4):
        if not prefill_ids:
            raise ValueError("need at least one prefill worker")
        self.max_attempts = max(1, int(max_attempts))
        self.transport = transport
        self.device_id = transport.device_id
        self.prefill_ids = list(prefill_ids)
        self.decode_id = decode_id
        self.tracer = TraceRecorder(f"coord:{self.device_id}")
        import uuid
        self._session = uuid.uuid4().hex[:8]
        self._alive = set(prefill_ids)
        # LIVE requests only: finished ones are pruned in _finish so a
        # long-running coordinator's memory (and the per-token depth
        # gauge scan) stays bounded by in-flight work, not history
        self._reqs: Dict[str, DisaggRequest] = {}
        self._rr = 0
        self._n = 0
        self._lock = threading.Lock()
        self.stats = {"submitted": 0, "completed": 0, "rescheduled": 0}
        self._stop = threading.Event()
        self._flight = get_flight_recorder()
        self._pump = threading.Thread(target=self._pump_loop, daemon=True,
                                      name=f"disagg-coord-{self.device_id}")
        self._pump.start()

    # -- submission --------------------------------------------------------

    def _pick_worker(self) -> str:
        alive = [w for w in self.prefill_ids if w in self._alive]
        if not alive:
            raise RuntimeError("no live prefill workers")
        w = alive[self._rr % len(alive)]
        self._rr += 1
        return w

    def _live_reqs(self) -> list:
        """Locked snapshot: the pump thread prunes `_reqs` concurrently
        with submitters and scrape threads — bare iteration would race
        ('dictionary changed size during iteration')."""
        with self._lock:
            return list(self._reqs.values())

    def _queue_depth(self) -> int:
        return sum(1 for r in self._live_reqs()
                   if not r.done.is_set() and not r.t_first)

    def _set_depth_gauge(self) -> None:
        cat = _disagg_metrics()
        if cat is not None:
            reqs = self._live_reqs()
            try:
                cat.DISAGG_HANDOFF_QUEUE.set(
                    sum(1 for r in reqs
                        if not r.done.is_set() and not r.t_first))
                cat.DISAGG_INFLIGHT.set(
                    sum(1 for r in reqs if not r.done.is_set()))
            except Exception:            # pragma: no cover - defensive
                pass

    def _send_handoff(self, req: DisaggRequest) -> None:
        span = self.tracer.next_span_id()
        self.tracer.record("disagg_submit", req.trace_id, 0,
                           span_id=span, rid=req.rid,
                           attempt=req.attempt, worker=req.worker)
        meta = {"rid": req.rid, "attempt": req.attempt,
                "max_new": req.max_new, "decode_id": self.decode_id,
                "reply_to": self.device_id}
        body = _meta_frame(meta, (req.prompt,),
                           trace=(req.trace_id, span))
        self.transport.send(req.worker,
                            f"dreq:{req.rid}:{req.attempt}", body)

    def submit(self, prompt_ids, max_new: int) -> DisaggRequest:
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        with self._lock:
            # salted per coordinator INSTANCE: a restarted client's
            # 'r0' must not collide with the previous session's in the
            # decode worker's per-rid joined/staged dedup state (a
            # collision would drop the new migration as already_joined)
            rid = f"r{self._session}-{self._n}"
            self._n += 1
            req = DisaggRequest(rid, prompt, max_new, self._pick_worker())
            self._reqs[rid] = req
            self.stats["submitted"] += 1
        self._flight.record("disagg_submit", rid=rid, worker=req.worker,
                            prompt_len=len(prompt))
        try:
            self._send_handoff(req)
        except TransportError:
            self._reschedule_locked_safe(req)
        self._set_depth_gauge()
        return req

    def generate(self, prompts, max_new: int,
                 timeout: float = 120.0) -> List[np.ndarray]:
        """Submit every row and wait for all (bench/test convenience)."""
        reqs = [self.submit(p, max_new) for p in prompts]
        return [r.wait(timeout=timeout) for r in reqs]

    # -- failure handling --------------------------------------------------

    def signal_failure(self, prefill_id: str) -> None:
        """A prefill worker died: reschedule its unfinished handoffs
        (requests already streaming tokens stay with the decode worker
        — their prefill is done)."""
        with self._lock:
            self._alive.discard(prefill_id)
            victims = [r for r in self._reqs.values()
                       if r.worker == prefill_id and not r.done.is_set()
                       and not r.t_first]
        for req in victims:
            self._reschedule_locked_safe(req)

    def _reschedule_locked_safe(self, req: DisaggRequest) -> None:
        with self._lock:
            req.attempt += 1
            fail: Optional[BaseException] = None
            if req.attempt >= self.max_attempts:
                # bounded: a persistently failing handoff (e.g. a DEAD
                # decode side — every prefill worker would fail the
                # same way) must terminally fail the request, not churn
                # full prefills forever
                fail = MigrationError(
                    f"request {req.rid} failed {req.attempt} handoff "
                    f"attempts (max_attempts={self.max_attempts})")
            else:
                try:
                    req.worker = self._pick_worker()
                except RuntimeError as e:
                    fail = e
            if fail is None:
                self.stats["rescheduled"] += 1
        if fail is not None:
            self._finish(req, error=fail)
            return
        cat = _disagg_metrics()
        if cat is not None:
            try:
                cat.DISAGG_RESCHEDULED.inc()
            except Exception:            # pragma: no cover - defensive
                pass
        self._flight.record("disagg_reschedule", rid=req.rid,
                            attempt=req.attempt, worker=req.worker)
        # stale staged frames on the decode side are superseded by the
        # new attempt anyway; the abort just frees the staging promptly
        self._abort_decode(req.rid)
        try:
            self._send_handoff(req)
        except TransportError as e:
            self._finish(req, error=e)

    def _abort_decode(self, rid: str) -> None:
        try:
            self.transport.send(self.decode_id, f"pgx:{rid}", b"")
        except TransportError:
            pass

    def _finish(self, req: DisaggRequest,
                error: Optional[BaseException] = None) -> None:
        """Complete a request and PRUNE it from the live table: late
        tok/fin/perr frames for a finished rid fall through the dict
        lookup and are ignored, and the table only ever holds in-flight
        work.  A terminal FAILURE also aborts the decode side so a
        half-staged migration's host buffers are freed promptly (the
        decode worker's staging cap is the backstop).  Never called
        with self._lock held."""
        if error is not None:
            req.error = error
            self._abort_decode(req.rid)
        req.done.set()
        with self._lock:
            self._reqs.pop(req.rid, None)
        self._set_depth_gauge()

    # -- inbound pump ------------------------------------------------------

    def _pump_loop(self) -> None:
        while not self._stop.is_set():
            try:
                tag, payload = self.transport.recv_any(timeout=0.1)
            except TransportTimeout:
                continue
            try:
                self._dispatch(tag, payload)
            except Exception:            # pragma: no cover - defensive
                log.exception("coordinator dispatch failed for %r", tag)

    def _dispatch(self, tag: str, payload: bytes) -> None:
        parts = tag.split(":")
        kind = parts[0]
        if kind == "tok":
            rid, idx = parts[1], int(parts[2])
            req = self._reqs.get(rid)
            if req is None or req.done.is_set():
                return
            if idx == len(req.tokens):   # (rid, step) dedup
                req.tokens.append(wire.deserialize_token(payload))
                if idx == 0:
                    req.t_first = time.perf_counter()
                    self._set_depth_gauge()
        elif kind == "fin":
            try:
                meta, tensors, _ = _parse_meta_frame(payload)
            except wire.WireError as e:
                record_corrupt_frame(self.device_id, tag, len(payload), e)
                return
            req = self._reqs.get(parts[1])
            if req is None or req.done.is_set():
                return
            err = None
            if meta.get("ok"):
                req.tokens = [int(t) for t in
                              np.asarray(tensors[0]).reshape(-1)]
                if not req.t_first:
                    req.t_first = time.perf_counter()
            else:
                err = RuntimeError(
                    meta.get("error") or "decode-side failure")
            self.stats["completed"] += 1
            self._finish(req, error=err)
        elif kind == "perr":
            req = self._reqs.get(parts[1])
            if req is not None and not req.done.is_set():
                self._reschedule_locked_safe(req)

    # -- observability / lifecycle -----------------------------------------

    def debug_state(self) -> dict:
        with self._lock:
            inflight = {r.rid: {"worker": r.worker, "attempt": r.attempt,
                                "tokens": len(r.tokens)}
                        for r in self._reqs.values()
                        if not r.done.is_set()}
        return {"role": "coordinator", "inflight": inflight,
                "handoff_queue_depth": self._queue_depth(),
                "alive_prefill_workers": sorted(self._alive),
                "stats": dict(self.stats)}

    def close(self) -> None:
        self._stop.set()
        self._pump.join(timeout=2.0)
