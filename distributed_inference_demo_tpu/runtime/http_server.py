"""HTTP inference endpoint: the working replacement for the reference's stub.

The reference parses HTTP by hand off a raw socket and answers every
inference request with ``"Inference not implemented yet"``
(``server.py:539-678``).  Here: a stdlib ``ThreadingHTTPServer`` exposing

- ``GET  /health``    — model, device, capacity
- ``GET  /stats``     — hot-loop metrics (per-stage comm/compute split,
  byte counts, ring-RTT percentiles — the reference's
  ``commutimeArraySum``/``infertimeArraySum`` dump as an API,
  ``Communication.java:650-661``)
- ``POST /generate``  — ``{"prompt_ids": [[...]], "max_new_tokens": N,
  "stream": false}`` → ``{"tokens": [[...]]}``; with ``"prompt": "text"``
  when a tokenizer is attached; ``"stream": true`` switches to chunked
  JSONL, one ``{"step": i, "tokens": [...]}`` line per decoded step (the
  reference streams partial decodes to its UI via DataRepository,
  ``Communication.java:629-638`` — this is that capability as an API).

The backend is anything with the engine surface (``generate`` /
``generate_stream``): the single-chip ``InferenceEngine``, or an
``ElasticHeader`` via :class:`HeaderBackend`.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np


def _round_lps(row) -> list:
    """JSON-friendly logprob row (6 decimals ≈ float32 noise floor)."""
    return [round(float(x), 6) for x in row]


def _accepts_kwarg(fn, name: str) -> bool:
    """Duck-typed capability check: does ``fn`` accept ``name=``?  True
    for an explicit parameter OR a **kwargs catch-all (wrapper backends
    that forward to an engine)."""
    import inspect
    params = inspect.signature(fn).parameters
    return (name in params
            or any(p.kind is inspect.Parameter.VAR_KEYWORD
                   for p in params.values()))


class HeaderBackend:
    """Adapts a PipelineHeader/ElasticHeader to the engine surface used by
    the HTTP handler (generate + generate_stream)."""

    def __init__(self, header, max_seq: int, num_stages: int = 2):
        self.header = header
        self.max_seq = max_seq
        self.num_stages = num_stages
        self._lock = threading.Lock()   # one pipeline run at a time

    def stats(self) -> dict:
        """Header snapshot + polled downstream stage snapshots."""
        with self._lock:
            stages = self.header.collect_stats(self.num_stages)
        return {"stages": stages}

    def generate(self, prompt_ids: np.ndarray, max_new_tokens: int,
                 seed: int = 0):
        import time

        from .engine import GenerationResult
        ids = np.asarray(prompt_ids)
        t0 = time.perf_counter()
        with self._lock:
            toks = self.header.generate(ids, max_new_tokens)
        return GenerationResult(tokens=toks, prompt_len=ids.shape[1],
                                num_new=toks.shape[1],
                                seconds=time.perf_counter() - t0)

    def generate_stream(self, prompt_ids: np.ndarray, max_new_tokens: int,
                        seed: int = 0):
        """TRUE streaming over the pipeline: the header's run loop fires
        ``on_token`` per ring step on a worker thread; tokens are yielded
        the moment each one returns from the tail (the reference streams
        partial decodes to its UI the same way, DataRepository)."""
        import queue as queue_mod

        q: "queue_mod.Queue" = queue_mod.Queue()
        SENTINEL = object()

        def run():
            try:
                with self._lock:
                    self.header.generate_many(
                        [np.asarray(prompt_ids)], max_new_tokens,
                        on_token=lambda i, step, toks: q.put(toks))
            except BaseException as e:     # surface in the consumer
                q.put(e)
            finally:
                q.put(SENTINEL)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is SENTINEL:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
        t.join(timeout=10)

    def classify(self, prompt_ids: np.ndarray, label_token_ids):
        with self._lock:
            [pred] = self.header.classify_many(
                [np.asarray(prompt_ids)], label_token_ids)
        return pred

    def reset_stats(self):
        with self._lock:
            self.header.reset_stats()


class InferenceHTTPServer:
    """Threaded HTTP server over an engine-like backend."""

    def __init__(self, backend, host: str = "127.0.0.1", port: int = 0,
                 tokenizer=None, model_name: str = "",
                 default_max_new: int = 128):
        self.backend = backend
        self.tokenizer = tokenizer
        self.model_name = model_name
        self.default_max_new = default_max_new
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):   # quiet by default
                pass

            def _json(self, code: int, obj: dict) -> None:
                body = json.dumps(obj).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/health":
                    import jax
                    self._json(200, {
                        "status": "ok",
                        "model": outer.model_name,
                        "backend": type(outer.backend).__name__,
                        "device": str(jax.devices()[0]),
                        "max_seq": getattr(outer.backend, "max_seq", None),
                    })
                elif self.path == "/stats":
                    if hasattr(outer.backend, "stats"):
                        self._json(200, outer.backend.stats())
                    else:
                        self._json(200, {"stages": []})
                else:
                    self._json(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path == "/stats/reset":
                    # zero hot-loop counters on every stage (benchmarks
                    # call this after compile warmup for steady-state
                    # numbers — the statsreset control message as HTTP)
                    if hasattr(outer.backend, "reset_stats"):
                        outer.backend.reset_stats()
                        self._json(200, {"reset": True})
                    else:
                        self._json(501, {"error": "backend has no "
                                                  "reset_stats"})
                    return
                if self.path == "/classify":
                    self._classify()
                    return
                if self.path != "/generate":
                    self._json(404, {"error": f"no route {self.path}"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    ids = outer._prompt_ids(req)
                    max_new = int(req.get("max_new_tokens",
                                          outer.default_max_new))
                    seed = int(req.get("seed", 0))
                    image = req.get("image")
                except (ValueError, KeyError) as e:
                    self._json(400, {"error": str(e)})
                    return
                if image is not None:
                    # honor-or-reject: only a multimodal backend takes
                    # an image, and images don't stream (the fused
                    # multimodal program emits all tokens at once)
                    if req.get("stream"):
                        self._json(501, {"error": "image input does not "
                                                  "support stream"})
                        return
                    if not _accepts_kwarg(outer.backend.generate, "image"):
                        self._json(501, {"error": "backend does not "
                                                  "support image input"})
                        return
                stop = req.get("stop")
                if stop is not None:
                    if isinstance(stop, str):
                        stop = [stop]
                    if (not isinstance(stop, list) or not stop
                            or not all(isinstance(s, str) and s
                                       for s in stop)):
                        self._json(400, {
                            "error": "stop must be a non-empty string "
                                     "or list of non-empty strings"})
                        return
                    # honor-or-reject: stop strings need server-side
                    # text, and compose with the plain blocking path
                    unsupported = [w for w, on in [
                        ("a server-side tokenizer (none attached)",
                         outer.tokenizer is None),
                        ("stream", bool(req.get("stream"))),
                        ("logprobs", bool(req.get("logprobs"))),
                        ("image", image is not None)] if on]
                    if unsupported:
                        self._json(501, {
                            "error": "stop does not support "
                                     + ", ".join(unsupported)})
                        return
                    try:
                        self._generate_stop(ids, max_new, seed, stop)
                    except ValueError as e:
                        self._json(400, {"error": str(e)})
                    except Exception as e:
                        self._json(500, {"error": str(e)})
                    return
                try:
                    if req.get("stream"):
                        want_lp = bool(req.get("logprobs"))
                        if want_lp and not _accepts_kwarg(
                                outer.backend.generate_stream, "logprobs"):
                            # honor-or-reject, never silently drop
                            self._json(501, {
                                "error": "backend does not support "
                                         "logprobs with stream"})
                            return
                        self._stream(ids, max_new, seed, logprobs=want_lp)
                    else:
                        kwargs = {}
                        if image is not None:
                            kwargs["image"] = image
                        if req.get("logprobs"):
                            if not _accepts_kwarg(outer.backend.generate,
                                                  "logprobs"):
                                self._json(501, {
                                    "error": "backend does not support "
                                             "logprobs"})
                                return
                            kwargs["logprobs"] = True
                        res = outer.backend.generate(ids, max_new,
                                                     seed=seed, **kwargs)
                        out = {"tokens": res.tokens.tolist()}
                        if getattr(res, "logprobs", None) is not None:
                            out["logprobs"] = [_round_lps(row)
                                               for row in res.logprobs]
                        if outer.tokenizer is not None:
                            out["text"] = [outer.tokenizer.decode(row)
                                           for row in res.tokens.tolist()]
                        self._json(200, out)
                except ValueError as e:     # capacity etc.
                    self._json(400, {"error": str(e)})
                except Exception as e:      # e.g. a stalled pipeline's
                    self._json(500, {"error": str(e)})  # TransportTimeout

            def _classify(self):
                """``{"prompt_ids"|"prompt", "label_token_ids": [...]}`` →
                ``{"labels": [...]}`` — the classification task endpoint
                (reference ``task_type`` classification,
                ``inference.cpp:220-270``)."""
                if not hasattr(outer.backend, "classify"):
                    self._json(501, {"error": "backend has no classify"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    ids = outer._prompt_ids(req)
                    label_ids = req["label_token_ids"]
                except (ValueError, KeyError) as e:
                    self._json(400, {"error": f"bad request: {e}"})
                    return
                try:
                    pred = outer.backend.classify(ids, label_ids)
                    self._json(200, {"labels": np.asarray(pred).tolist()})
                except ValueError as e:
                    self._json(400, {"error": str(e)})
                except Exception as e:      # stalled pipeline etc. -> 500
                    self._json(500, {"error": str(e)})

            def _generate_stop(self, ids, max_new, seed, stop):
                """Blocking generation with STOP SEQUENCES: rows end at
                the earliest occurrence of any stop string (which is
                excluded from the output — the OpenAI convention), and
                the batch stops consuming once every row finished
                (stream backends with resumable dispatches skip the
                remaining decode; fused/pipeline backends finish their
                in-flight program in the background).  Rows are matched
                on their incrementally detokenized text
                (StreamDetokenizer — a stop split across tokens matches
                when it completes).  Tokens truncate to the set that
                PRODUCED the reported text (they may decode slightly
                past it when the detokenizer held back a split UTF-8
                sequence at the cut — never short of it); rows are
                RAGGED.  ``stop_reason`` per row: "stop", "eos" (the
                backend's eos ended the row first; the eos token is
                included, engine convention), or "length"."""
                import bisect

                from ..tokenizer import StreamDetokenizer

                gen = outer.backend.generate_stream(ids, max_new,
                                                    seed=seed)
                b = len(ids)
                eos = getattr(outer.backend, "eos_id", None)
                detoks = [StreamDetokenizer(outer.tokenizer)
                          for _ in range(b)]
                texts = [""] * b
                toks = [[] for _ in range(b)]
                lens = [[] for _ in range(b)]   # cum text len per token
                done = [False] * b
                reason = ["length"] * b

                def match(r):
                    hits = [texts[r].find(s) for s in stop
                            if s in texts[r]]
                    if not hits:
                        return False
                    m = min(hits)
                    # keep every token needed to produce text[:m]: up to
                    # the first whose cumulative visible text reaches m
                    keep = bisect.bisect_left(lens[r], m) + 1
                    toks[r] = toks[r][:min(keep, len(toks[r]))]
                    texts[r] = texts[r][:m]
                    done[r], reason[r] = True, "stop"
                    return True

                for item in gen:
                    arr = np.asarray(item).reshape(-1).tolist()
                    for r in range(b):
                        if done[r]:
                            continue
                        toks[r].append(int(arr[r]))
                        texts[r] += detoks[r].push(arr[r])
                        lens[r].append(len(texts[r]))
                        if not match(r) and eos is not None \
                                and int(arr[r]) == eos:
                            # natural termination beats budget: a row
                            # past its eos only pads (engine _mask_eos)
                            done[r], reason[r] = True, "eos"
                    if all(done):
                        gen.close()
                        break
                for r in range(b):
                    if not done[r]:
                        texts[r] += detoks[r].flush()
                        if lens[r]:
                            lens[r][-1] = len(texts[r])
                        match(r)
                self._json(200, {"tokens": toks, "text": texts,
                                 "stop_reason": reason})

            def _stream(self, ids, max_new, seed, logprobs=False):
                # pull the FIRST step before committing to 200 + chunked:
                # validation errors (capacity etc.) surface on first next()
                # and must become a clean 400, not a status line spliced
                # into an already-open chunked body.
                kwargs = {"logprobs": True} if logprobs else {}
                gen = outer.backend.generate_stream(ids, max_new, seed=seed,
                                                    **kwargs)
                first = None
                try:
                    first = next(gen)
                except StopIteration:
                    pass
                except ValueError as e:
                    self._json(400, {"error": str(e)})
                    return
                except Exception as e:
                    # e.g. a TransportTimeout from a stalled pipeline —
                    # still before headers, so a clean 500 is possible
                    self._json(500, {"error": str(e)})
                    return

                self.send_response(200)
                self.send_header("Content-Type", "application/jsonl")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def chunk(data: bytes) -> None:
                    self.wfile.write(f"{len(data):x}\r\n".encode())
                    self.wfile.write(data + b"\r\n")

                # incremental detokenization, per row: the "text" field
                # carries printable deltas (tokenizer.StreamDetokenizer —
                # one owner of the boundary/holdback rules, shared with
                # the chat REPL)
                from ..tokenizer import StreamDetokenizer
                detoks: dict = {}

                def row_text(r, tok):
                    if r not in detoks:
                        detoks[r] = StreamDetokenizer(outer.tokenizer)
                    return detoks[r].push(tok)

                def emit(i, item):
                    toks, lps = item if logprobs else (item, None)
                    line = {"step": i, "tokens": np.asarray(toks).tolist()}
                    if lps is not None:
                        line["logprobs"] = _round_lps(np.asarray(lps))
                    if outer.tokenizer is not None:
                        line["text"] = [row_text(r, t) for r, t in
                                        enumerate(np.asarray(toks).tolist())]
                    chunk((json.dumps(line) + "\n").encode("utf-8"))

                n_steps = 0
                try:
                    if first is not None:
                        emit(0, first)
                        n_steps = 1
                        for i, item in enumerate(gen, start=1):
                            emit(i, item)
                            n_steps = i + 1
                    if outer.tokenizer is not None and detoks:
                        # flush text held back by the U+FFFD guard: a
                        # stream ending on a split (or genuinely
                        # replacement-decoding) token must not silently
                        # drop its final characters
                        rem = [detoks[r].flush() if r in detoks else ""
                               for r in range(max(detoks) + 1)]
                        if any(rem):
                            chunk((json.dumps(
                                {"step": n_steps, "tokens": [],
                                 "text": rem}) + "\n").encode("utf-8"))
                except OSError:
                    return      # client went away; the socket is dead
                except Exception as e:
                    # generator failure mid-stream: an error JSONL line
                    # keeps the chunked framing intact for the client
                    try:
                        chunk((json.dumps({"error": str(e)}) + "\n")
                              .encode("utf-8"))
                    except OSError:
                        return
                try:
                    chunk(b"")      # terminating chunk
                    self.wfile.flush()
                except OSError:
                    pass

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self.httpd.server_address
        self._thread: Optional[threading.Thread] = None

    def _prompt_ids(self, req: dict) -> np.ndarray:
        if "prompt_ids" in req:
            ids = np.asarray(req["prompt_ids"], dtype=np.int32)
            if ids.ndim == 1:
                ids = ids[None, :]
            if ids.ndim != 2 or ids.size == 0:
                raise ValueError("prompt_ids must be a non-empty 1D/2D list")
            return ids
        if "prompt" in req:
            if self.tokenizer is None:
                raise ValueError(
                    "text prompt given but no tokenizer is attached; "
                    "send prompt_ids or start the server with --tokenizer")
            ids = self.tokenizer.encode(str(req["prompt"]))
            return np.asarray([ids], dtype=np.int32)
        raise ValueError("request needs 'prompt_ids' or 'prompt'")

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def serve_forever(self) -> None:
        self.httpd.serve_forever()

    def shutdown(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=10)
