"""HTTP inference endpoint: the working replacement for the reference's stub.

The reference parses HTTP by hand off a raw socket and answers every
inference request with ``"Inference not implemented yet"``
(``server.py:539-678``).  Here: a stdlib ``ThreadingHTTPServer`` exposing

- ``GET  /health``    — model, device, capacity
- ``GET  /stats``     — hot-loop metrics (per-stage comm/compute split,
  byte counts, ring-RTT percentiles — the reference's
  ``commutimeArraySum``/``infertimeArraySum`` dump as an API,
  ``Communication.java:650-661``)
- ``GET  /metrics``   — Prometheus text exposition (telemetry/catalog):
  the same stage counters as /stats plus batching/speculative and
  monitor series, scrapeable by a stock Prometheus
- ``GET  /trace``     — Chrome trace-event JSON of the spans recorded
  since the last call (pipeline + batching backends; load in Perfetto)
- ``GET  /timeline``  — recent per-request timeline records + the
  per-tenant SLO/goodput summary (telemetry/slo; ``?n=`` bounds the
  tail)
- ``POST /generate``  — ``{"prompt_ids": [[...]], "max_new_tokens": N,
  "stream": false}`` → ``{"tokens": [[...]]}``; with ``"prompt": "text"``
  when a tokenizer is attached; ``"stream": true`` switches to chunked
  JSONL, one ``{"step": i, "tokens": [...]}`` line per decoded step (the
  reference streams partial decodes to its UI via DataRepository,
  ``Communication.java:629-638`` — this is that capability as an API).

The backend is anything with the engine surface (``generate`` /
``generate_stream``): the single-chip ``InferenceEngine``, or an
``ElasticHeader`` via :class:`HeaderBackend`.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from ..telemetry import catalog as _metrics
from .overload import SchedulerOverloaded


def _round_lps(row) -> list:
    """JSON-friendly logprob row (6 decimals ≈ float32 noise floor)."""
    return [round(float(x), 6) for x in row]


class StopMatcher:
    """Incremental stop-sequence matching over streamed text — one owner
    for the blocking and streaming ``stop`` paths.

    ``feed(piece) -> (emittable, matched)``: ``emittable`` is the text
    that can be released to the client NOW — everything before the
    longest trailing run that is still a prefix of some stop string
    (streaming must never emit characters it would have to retract when
    the stop completes a step later).  When a stop completes,
    ``matched`` is True, ``pos`` is the cut position (start of the
    earliest match across all stop strings), and ``emittable`` carries
    exactly the remaining pre-stop text.

    The cut is CHUNKING-INDEPENDENT: feeding per-token pieces and
    feeding the whole text yield the same ``pos`` (the position the
    whole-string ``min(text.find(s))`` reference produces).  The subtle
    case is a short stop completing while an EARLIER-starting longer
    stop is still a live prefix of the buffer tail (stop=["abc", "b"],
    fed "a" then "b": "b" completes at 1, but "ab" may still become
    "abc" cutting at 0) — the verdict is DEFERRED, bounded by the
    longest stop length, until the earlier candidate completes (it wins)
    or dies (the completed match stands).  ``finish()`` resolves a
    still-pending verdict at stream end: no more text can arrive, so the
    completed match stands."""

    def __init__(self, stop):
        self.stop = list(stop)
        # empty stop set = valid pass-through matcher (never matches)
        self._maxlen = max((len(s) for s in self.stop), default=0)
        # only the UNEMITTED tail is buffered: emitted text was released
        # precisely because the holdback proved no future stop can start
        # inside it, so matching stays O(piece + longest_stop) per feed
        # and memory stays bounded regardless of generation length.
        self._buf = ""
        self._base = 0                  # absolute offset of _buf[0]
        self.pos: Optional[int] = None  # absolute cut position

    def feed(self, piece: str):
        if self.pos is not None:
            return "", True
        self._buf += piece
        return self._scan(final=False)

    def _live_start_before(self, comp: int) -> Optional[int]:
        """Earliest start j < comp of a LONGER stop still a live prefix
        running through the buffer end — the position a later-completing
        match could still cut at, making the verdict at ``comp``
        undecidable this feed.  Only starts within ``maxlen`` of the
        buffer end can qualify (a live prefix must outrun the buffer)."""
        buf = self._buf
        for j in range(max(0, len(buf) - self._maxlen + 1), comp):
            tail = buf[j:]
            if any(len(s) > len(tail) and s.startswith(tail)
                   for s in self.stop):
                return j
        return None

    def _scan(self, final: bool):
        buf = self._buf
        hits = [buf.find(s) for s in self.stop if s in buf]
        if hits:
            comp = min(hits)
            live = None if final else self._live_start_before(comp)
            if live is None:
                self.pos = self._base + comp
                out = buf[:comp]
                self._base += comp
                self._buf = ""
                return out, True
            # verdict deferred: emit only up to the live earlier
            # candidate's start; the pending completed match stays in
            # the buffer and is re-found (or beaten) next feed
            out = buf[:live]
            self._base += live
            self._buf = buf[live:]
            return out, False
        hold = max((k for s in self.stop for k in range(1, len(s))
                    if buf.endswith(s[:k])), default=0)
        safe_end = len(buf) - hold
        if safe_end > 0:
            out = buf[:safe_end]
            self._base += safe_end
            self._buf = buf[safe_end:]
            return out, False
        return "", False

    def finish(self):
        """End of stream: resolve any deferred verdict (a pending
        completed match now stands — no more text can complete the
        earlier candidate) and release held-back text otherwise.
        Returns ``(emittable, matched)`` like ``feed``."""
        if self.pos is not None:
            return "", True
        out, matched = self._scan(final=True)
        if not matched:
            out += self._buf
            self._base += len(self._buf)
            self._buf = ""
        return out, matched

    def flush(self) -> str:
        """Back-compat wrapper: ``finish()``'s text alone.  Callers that
        can still act on a late match should use ``finish`` and check
        ``matched`` (a deferred verdict may resolve to a cut here)."""
        return self.finish()[0]


class _StopSession:
    """The per-row decode/match/cut core shared by the BLOCKING and
    STREAMING stop paths (one owner — the eos-flush and token-truncation
    rules must not fork).  ``consume(item)`` processes one step's [b]
    tokens and returns per-row emittable text; ``finish()`` flushes rows
    that ran to length.  Results: ``toks`` (truncated, ragged),
    ``texts``, ``reason`` ("stop" | "eos" | "length"), ``done``.

    With ``logprobs=True``, ``consume`` takes the backend's
    ``(tokens, logprobs)`` pairs and ``lps`` carries per-row logprob
    rows truncated EXACTLY where ``toks`` truncates — one cut position,
    two parallel lists, so a stop can never leave a logprob for a token
    the client never saw (or vice versa)."""

    def __init__(self, tokenizer, stop, b: int, eos,
                 logprobs: bool = False):
        from ..tokenizer import StreamDetokenizer
        self.eos = eos
        self.detoks = [StreamDetokenizer(tokenizer) for _ in range(b)]
        self.matchers = [StopMatcher(stop) for _ in range(b)]
        self.texts = [""] * b
        self.toks = [[] for _ in range(b)]
        self.lens = [[] for _ in range(b)]   # cum text len per token
        self.lps = [[] for _ in range(b)] if logprobs else None
        self.done = [False] * b
        self.reason = ["length"] * b
        self.b = b

    def _cut(self, r: int) -> None:
        """Apply a completed match: truncate text at the cut and keep
        every token needed to produce it (up to the first whose
        cumulative visible text reaches the cut) — logprob rows cut at
        the same token index."""
        import bisect
        m = self.matchers[r].pos
        keep = bisect.bisect_left(self.lens[r], m) + 1
        self.toks[r] = self.toks[r][:min(keep, len(self.toks[r]))]
        if self.lps is not None:
            self.lps[r] = self.lps[r][:len(self.toks[r])]
        self.texts[r] = self.texts[r][:m]
        self.done[r], self.reason[r] = True, "stop"

    def _push(self, r: int, raw: str) -> None:
        self.texts[r] += raw
        if self.lens[r]:
            self.lens[r][-1] = len(self.texts[r])

    def consume(self, item) -> list:
        if self.lps is not None:
            toks_item, lps_item = item
            lp_arr = np.asarray(lps_item).reshape(-1).tolist()
        else:
            toks_item, lp_arr = item, None
        arr = np.asarray(toks_item).reshape(-1).tolist()
        pieces = [""] * self.b
        for r in range(self.b):
            if self.done[r]:
                continue
            self.toks[r].append(int(arr[r]))
            if lp_arr is not None:
                self.lps[r].append(float(lp_arr[r]))
            raw = self.detoks[r].push(arr[r])
            self.texts[r] += raw
            self.lens[r].append(len(self.texts[r]))
            pieces[r], matched = self.matchers[r].feed(raw)
            if matched:
                self._cut(r)
            elif self.eos is not None and int(arr[r]) == self.eos:
                # natural termination beats budget (a row past its eos
                # only pads — engine _mask_eos); the detokenizer may
                # still hold back chars from EARLIER tokens: flush them
                # through the matcher so they are neither lost nor
                # allowed to complete a stop unnoticed
                tail = self.detoks[r].flush()
                self._push(r, tail)
                extra, matched = self.matchers[r].feed(tail)
                pieces[r] += extra
                if not matched:
                    # the row is over: resolve any deferred verdict (a
                    # pending completed stop now stands) before calling
                    # it an eos finish
                    extra2, matched = self.matchers[r].finish()
                    pieces[r] += extra2
                if matched:
                    self._cut(r)
                else:
                    self.done[r], self.reason[r] = True, "eos"
        return pieces

    def finish(self) -> list:
        """Flush detok + matcher holdback for rows that ran to length
        (a stop may still complete inside the flushed tail)."""
        pieces = [""] * self.b
        for r in range(self.b):
            if self.done[r]:
                continue
            tail = self.detoks[r].flush()
            self._push(r, tail)
            piece, matched = self.matchers[r].feed(tail)
            if not matched:
                extra, matched = self.matchers[r].finish()
                piece += extra
            pieces[r] = piece
            if matched:
                self._cut(r)
        return pieces


def _accepts_kwarg(fn, name: str) -> bool:
    """Duck-typed capability check: does ``fn`` accept ``name=``?  True
    for an explicit parameter OR a **kwargs catch-all (wrapper backends
    that forward to an engine)."""
    import inspect
    params = inspect.signature(fn).parameters
    return (name in params
            or any(p.kind is inspect.Parameter.VAR_KEYWORD
                   for p in params.values()))


class HeaderBackend:
    """Adapts a PipelineHeader/ElasticHeader to the engine surface used by
    the HTTP handler (generate + generate_stream)."""

    def __init__(self, header, max_seq: int, num_stages: int = 2):
        from ..telemetry.anomaly import AnomalyMonitor
        self.header = header
        self.max_seq = max_seq
        self.num_stages = num_stages
        self._lock = threading.Lock()   # one pipeline run at a time
        # straggler watch over the polled stage snapshots: every /stats
        # or /metrics collection feeds the detector, so a scheduled
        # Prometheus scrape is what drives straggler-hop detection in
        # production (no extra polling thread)
        self.anomaly = AnomalyMonitor(config={
            "backend": type(self).__name__, "num_stages": num_stages,
            "max_seq": max_seq})

    def stats(self) -> dict:
        """Header snapshot + polled downstream stage snapshots."""
        with self._lock:
            stages = self.header.collect_stats(self.num_stages)
        self.anomaly.observe({"stages": stages})
        return {"stages": stages}

    def export_trace(self) -> dict:
        """Chrome trace JSON of all spans recorded since the last export
        (header + every downstream stage, via the statsreq path)."""
        with self._lock:
            return self.header.collect_trace(self.num_stages)

    def scrape_stats(self) -> dict:
        """Like :meth:`stats` but BOUNDED end to end — a Prometheus
        scrape runs on a schedule and must not stall behind an in-flight
        generation (the request lock is held for a whole run) or a dead
        stage (the stats poll waits ~10s per missing reply).  When the
        pipeline is busy, return no stages: the scrape renders the
        last-bridged series instead of going DOWN exactly while the
        system is under the load telemetry exists to observe."""
        if not self._lock.acquire(timeout=2.0):
            return {"stages": []}
        try:
            stages = self.header.collect_stats(self.num_stages,
                                               timeout=2.0)
        finally:
            self._lock.release()
        self.anomaly.observe({"stages": stages})
        return {"stages": stages}

    def generate(self, prompt_ids: np.ndarray, max_new_tokens: int,
                 seed: int = 0):
        import time

        from .engine import GenerationResult
        ids = np.asarray(prompt_ids)
        t0 = time.perf_counter()
        with self._lock:
            toks = self.header.generate(ids, max_new_tokens)
        return GenerationResult(tokens=toks, prompt_len=ids.shape[1],
                                num_new=toks.shape[1],
                                seconds=time.perf_counter() - t0)

    def generate_stream(self, prompt_ids: np.ndarray, max_new_tokens: int,
                        seed: int = 0):
        """TRUE streaming over the pipeline: the header's run loop fires
        ``on_token`` per ring step on a worker thread; tokens are yielded
        the moment each one returns from the tail (the reference streams
        partial decodes to its UI the same way, DataRepository)."""
        import queue as queue_mod

        q: "queue_mod.Queue" = queue_mod.Queue()
        SENTINEL = object()

        def run():
            try:
                with self._lock:
                    self.header.generate_many(
                        [np.asarray(prompt_ids)], max_new_tokens,
                        on_token=lambda i, step, toks: q.put(toks))
            except BaseException as e:     # surface in the consumer
                q.put(e)
            finally:
                q.put(SENTINEL)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is SENTINEL:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
        t.join(timeout=10)

    def classify(self, prompt_ids: np.ndarray, label_token_ids):
        with self._lock:
            [pred] = self.header.classify_many(
                [np.asarray(prompt_ids)], label_token_ids)
        return pred

    def reset_stats(self):
        with self._lock:
            self.header.reset_stats()

    def debug_state(self) -> dict:
        """Backend fragment of ``GET /debugz``: the ring steps still
        awaiting their reply (racy read of header-owned state — a
        diagnostic peek, not an invariant) + straggler-detector state."""
        sent = getattr(self.header, "_sent_at", {})
        return {"num_stages": self.num_stages,
                "in_flight": [[r, s] for r, s in sorted(sent.keys())],
                "anomaly": self.anomaly.state()}


class InferenceHTTPServer:
    """Threaded HTTP server over an engine-like backend."""

    def __init__(self, backend, host: str = "127.0.0.1", port: int = 0,
                 tokenizer=None, model_name: str = "",
                 default_max_new: int = 128,
                 request_timeout: Optional[float] = None):
        """``request_timeout``: per-request deadline for blocking
        ``/generate`` — passed as ``timeout=`` to backends that accept
        it (the continuous-batching engine cancels the request through
        ``Request.cancel()``, freeing its slot) and mapped to a 504
        instead of a hang.  None/0 = no deadline."""
        self.backend = backend
        self.tokenizer = tokenizer
        self.model_name = model_name
        self.default_max_new = default_max_new
        self.request_timeout = request_timeout or None
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):   # quiet by default
                pass

            # known routes only: the route label must stay bounded — a
            # client probing arbitrary paths must not mint one counter
            # child (and one /metrics line) per junk URL forever
            _ROUTES = frozenset((
                "/health", "/stats", "/stats/reset", "/metrics", "/trace",
                "/timeline", "/debugz", "/sketch", "/generate",
                "/classify"))

            def _json(self, code: int, obj: dict,
                      headers: Optional[dict] = None) -> None:
                # counted BEFORE the body goes out: a client that reacts
                # to this response with a /metrics scrape must see its
                # own request (the scrape itself bypasses _json)
                route = self.path.split("?")[0]
                if route not in self._ROUTES:
                    route = "other"
                _metrics.HTTP_REQUESTS.inc(route=route, code=str(code))
                body = json.dumps(obj).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                tid = getattr(self, "_trace_id", None)
                if tid:
                    self.send_header("X-DWT-Trace-Id", tid)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _obs_kwargs(self, fn) -> dict:
                """tenant/trace_id kwargs for backends that take them
                (the continuous-batching engine) — duck-typed like
                image/timeout, so pipeline backends stay untouched."""
                out = {}
                tenant = getattr(self, "_tenant", None)
                if tenant and _accepts_kwarg(fn, "tenant"):
                    out["tenant"] = str(tenant)
                tid = getattr(self, "_trace_id", None)
                if tid and _accepts_kwarg(fn, "trace_id"):
                    try:
                        out["trace_id"] = int(str(tid), 16)
                    except ValueError:
                        pass
                return out

            def _shed(self, e: SchedulerOverloaded) -> None:
                """503/429 + Retry-After: the admission queue is past
                its configured depth — honest fast rejection, not an
                unbounded queue (clients with backoff recover; clients
                without get a clear signal instead of a timeout).  The
                exception carries the code: 503 = service saturated
                (batching scheduler), 429 = back off, the sp queue is
                full behind a long-context request."""
                self._json(getattr(e, "http_code", 503), {"error": str(e)},
                           headers={"Retry-After":
                                    str(max(1, int(e.retry_after_s)))})

            def _metrics_scrape(self) -> None:
                """Prometheus text exposition over the shared registry +
                this backend's bridged series (telemetry/catalog)."""
                try:
                    text = _metrics.scrape(outer.backend)
                    code = 200
                except Exception as e:   # the scrape must never crash
                    text = f"# scrape error: {e}\n"
                    code = 500
                body = text.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; "
                                 "charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.split("?")[0] == "/metrics":
                    self._metrics_scrape()
                elif self.path == "/trace":
                    # spans recorded since the last /trace call, as
                    # Chrome trace JSON (Perfetto-loadable)
                    if hasattr(outer.backend, "export_trace"):
                        try:
                            self._json(200, outer.backend.export_trace())
                        except Exception as e:
                            self._json(500, {"error": str(e)})
                    else:
                        self._json(501, {"error": "backend has no trace "
                                                  "export"})
                elif self.path == "/health":
                    import jax
                    self._json(200, {
                        "status": "ok",
                        "model": outer.model_name,
                        "backend": type(outer.backend).__name__,
                        "device": str(jax.devices()[0]),
                        "max_seq": getattr(outer.backend, "max_seq", None),
                    })
                elif self.path == "/stats":
                    if hasattr(outer.backend, "stats"):
                        self._json(200, outer.backend.stats())
                    else:
                        self._json(200, {"stages": []})
                elif self.path.split("?")[0] == "/timeline":
                    # recent closed request timelines + per-tenant SLO
                    # summary (telemetry/slo) — the fleet plane's
                    # where-did-the-milliseconds-go surface
                    from urllib.parse import parse_qs, urlparse
                    from ..telemetry import slo as _slo
                    try:
                        qs = parse_qs(urlparse(self.path).query)
                        n = max(1, min(1024, int(qs.get("n", ["64"])[0])))
                    except ValueError:
                        n = 64
                    try:
                        self._json(200, _slo.debug_state(tail=n))
                    except Exception as e:
                        self._json(500, {"error": str(e)})
                elif self.path.split("?")[0] == "/debugz":
                    try:
                        self._json(200, outer._debugz())
                    except Exception as e:
                        self._json(500, {"error": str(e)})
                elif self.path.split("?")[0] == "/sketch":
                    # §20 workload-sketch artifact: serve the recorder's
                    # CANONICAL bytes verbatim (re-dumping would break
                    # the byte-identity determinism contract)
                    from ..telemetry import profiling as _profiling
                    try:
                        body = _profiling.get_sketch().to_json() \
                            .encode("utf-8")
                        _metrics.HTTP_REQUESTS.inc(route="/sketch",
                                                   code="200")
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/json")
                        self.send_header("Content-Length",
                                         str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    except Exception as e:
                        self._json(500, {"error": str(e)})
                else:
                    self._json(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path == "/stats/reset":
                    # zero hot-loop counters on every stage (benchmarks
                    # call this after compile warmup for steady-state
                    # numbers — the statsreset control message as HTTP)
                    if hasattr(outer.backend, "reset_stats"):
                        outer.backend.reset_stats()
                        self._json(200, {"reset": True})
                    else:
                        self._json(501, {"error": "backend has no "
                                                  "reset_stats"})
                    return
                if self.path == "/classify":
                    self._classify()
                    return
                if self.path != "/generate":
                    self._json(404, {"error": f"no route {self.path}"})
                    return
                # gateway trace propagation (docs/DESIGN.md §16): a
                # proxied request carries the gateway's trace id — echo
                # it on every response and land it in the flight
                # recorder, so one id joins gateway spans, replica
                # flight events, and the client's copy of the response
                tid = self.headers.get("X-DWT-Trace-Id")
                if tid:
                    self._trace_id = tid[:64]
                    from ..telemetry.flightrecorder import \
                        get_flight_recorder
                    get_flight_recorder().record(
                        "http_generate_proxied", trace_id=self._trace_id)
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    ids = outer._prompt_ids(req)
                    max_new = int(req.get("max_new_tokens",
                                          outer.default_max_new))
                    seed = int(req.get("seed", 0))
                    image = req.get("image")
                except (ValueError, KeyError) as e:
                    self._json(400, {"error": str(e)})
                    return
                # tenant identity (docs/DESIGN.md §7): body field wins
                # over the gateway-forwarded header; either way it rides
                # the batching rows into the per-tenant SLO ledger
                self._tenant = (req.get("tenant")
                                or self.headers.get("X-DWT-Tenant"))
                if image is not None:
                    # honor-or-reject: only a multimodal backend takes
                    # an image, and images don't stream (the fused
                    # multimodal program emits all tokens at once)
                    if req.get("stream"):
                        self._json(501, {"error": "image input does not "
                                                  "support stream"})
                        return
                    if not _accepts_kwarg(outer.backend.generate, "image"):
                        self._json(501, {"error": "backend does not "
                                                  "support image input"})
                        return
                resume = req.get("resume")
                if resume is not None:
                    # mid-stream failover resumption (docs/DESIGN.md
                    # §23): the gateway re-POSTs the journaled request
                    # with the delivered prefix; the engine replays it
                    # silently and streams the suffix bit-identically.
                    # Honor-or-reject: only the batching engine carries
                    # the submit_resumed path
                    err_code, err = None, None
                    if not req.get("stream"):
                        err_code, err = 400, "resume requires stream"
                    elif (image is not None or req.get("stop") is not None
                          or req.get("logprobs")):
                        err_code, err = 501, ("resume does not support "
                                              "image, stop, or logprobs")
                    elif not _accepts_kwarg(outer.backend.generate_stream,
                                            "resume"):
                        err_code, err = 501, ("backend does not support "
                                              "resume")
                    elif not isinstance(resume, dict):
                        err_code, err = 400, "resume must be an object"
                    if err is None:
                        delivered = resume.get("delivered_tokens")
                        if (not isinstance(delivered, (list, tuple))
                                or not delivered
                                or not all(isinstance(t, int)
                                           for t in delivered)):
                            err_code, err = 400, (
                                "resume.delivered_tokens must be a "
                                "non-empty list of token ids")
                        elif int(resume.get("rng_step_offset",
                                            len(delivered))) \
                                != len(delivered):
                            # the rng fast-forward replays one sampler
                            # split per delivered token — an offset
                            # that disagrees with the prefix length
                            # cannot be bit-identical
                            err_code, err = 400, (
                                "resume.rng_step_offset must equal "
                                "len(delivered_tokens)")
                    if err is not None:
                        self._json(err_code, {"error": err})
                        return
                stop = req.get("stop")
                if stop is not None:
                    if isinstance(stop, str):
                        stop = [stop]
                    if (not isinstance(stop, list) or not stop
                            or not all(isinstance(s, str) and s
                                       for s in stop)):
                        self._json(400, {
                            "error": "stop must be a non-empty string "
                                     "or list of non-empty strings"})
                        return
                    # honor-or-reject: stop strings need server-side
                    # text; they compose with blocking AND streaming
                    unsupported = [w for w, on in [
                        ("a server-side tokenizer (none attached)",
                         outer.tokenizer is None),
                        ("image", image is not None)] if on]
                    if unsupported:
                        self._json(501, {
                            "error": "stop does not support "
                                     + ", ".join(unsupported)})
                        return
                    want_lp = bool(req.get("logprobs"))
                    if want_lp and not _accepts_kwarg(
                            outer.backend.generate_stream, "logprobs"):
                        # both stop paths consume the STREAM surface, so
                        # streaming logprob support is the one capability
                        # they need (honor-or-reject, never drop)
                        self._json(501, {
                            "error": "backend does not support "
                                     "logprobs with stop"})
                        return
                    if req.get("stream"):
                        self._stream_stop(ids, max_new, seed, stop,
                                          logprobs=want_lp)
                        return
                    try:
                        self._generate_stop(ids, max_new, seed, stop,
                                            logprobs=want_lp)
                    except SchedulerOverloaded as e:
                        self._shed(e)
                    except TimeoutError as e:   # --request-timeout: the
                        self._json(504, {"error": str(e) or  # stop path
                                         "request deadline exceeded"})
                    except ValueError as e:
                        self._json(400, {"error": str(e)})
                    except Exception as e:
                        self._json(500, {"error": str(e)})
                    return
                try:
                    if req.get("stream"):
                        want_lp = bool(req.get("logprobs"))
                        if want_lp and not _accepts_kwarg(
                                outer.backend.generate_stream, "logprobs"):
                            # honor-or-reject, never silently drop
                            self._json(501, {
                                "error": "backend does not support "
                                         "logprobs with stream"})
                            return
                        self._stream(ids, max_new, seed, logprobs=want_lp,
                                     resume=resume)
                    else:
                        kwargs = {}
                        if image is not None:
                            kwargs["image"] = image
                        if req.get("logprobs"):
                            if not _accepts_kwarg(outer.backend.generate,
                                                  "logprobs"):
                                self._json(501, {
                                    "error": "backend does not support "
                                             "logprobs"})
                                return
                            kwargs["logprobs"] = True
                        if (outer.request_timeout
                                and _accepts_kwarg(outer.backend.generate,
                                                   "timeout")):
                            # per-request deadline: the batching engine
                            # cancels through Request.cancel() on expiry
                            # (slot freed), surfacing as TimeoutError
                            kwargs["timeout"] = outer.request_timeout
                        kwargs.update(
                            self._obs_kwargs(outer.backend.generate))
                        t_req = time.perf_counter()
                        res = outer.backend.generate(ids, max_new,
                                                     seed=seed, **kwargs)
                        _metrics.HTTP_REQUEST_SECONDS.observe(
                            time.perf_counter() - t_req, route="/generate")
                        _metrics.HTTP_GENERATED_TOKENS.inc(
                            int(res.tokens.size))
                        out = {"tokens": res.tokens.tolist()}
                        if getattr(res, "logprobs", None) is not None:
                            out["logprobs"] = [_round_lps(row)
                                               for row in res.logprobs]
                        if outer.tokenizer is not None:
                            out["text"] = [outer.tokenizer.decode(row)
                                           for row in res.tokens.tolist()]
                        self._json(200, out)
                except SchedulerOverloaded as e:
                    self._shed(e)
                except TimeoutError as e:   # --request-timeout expired;
                    self._json(504, {"error": str(e) or  # request was
                                     "request deadline exceeded"})  # shed
                except ValueError as e:     # capacity etc.
                    self._json(400, {"error": str(e)})
                except Exception as e:      # e.g. a stalled pipeline's
                    self._json(500, {"error": str(e)})  # TransportTimeout

            def _classify(self):
                """``{"prompt_ids"|"prompt", "label_token_ids": [...]}`` →
                ``{"labels": [...]}`` — the classification task endpoint
                (reference ``task_type`` classification,
                ``inference.cpp:220-270``)."""
                if not hasattr(outer.backend, "classify"):
                    self._json(501, {"error": "backend has no classify"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    ids = outer._prompt_ids(req)
                    label_ids = req["label_token_ids"]
                except (ValueError, KeyError) as e:
                    self._json(400, {"error": f"bad request: {e}"})
                    return
                try:
                    pred = outer.backend.classify(ids, label_ids)
                    self._json(200, {"labels": np.asarray(pred).tolist()})
                except ValueError as e:
                    self._json(400, {"error": str(e)})
                except Exception as e:      # stalled pipeline etc. -> 500
                    self._json(500, {"error": str(e)})

            def _generate_stop(self, ids, max_new, seed, stop,
                               logprobs=False):
                """Blocking generation with STOP SEQUENCES: rows end at
                the earliest occurrence of any stop string (which is
                excluded from the output — the OpenAI convention), and
                the batch stops consuming once every row finished
                (stream backends with resumable dispatches skip the
                remaining decode; fused/pipeline backends finish their
                in-flight program in the background).  With
                ``logprobs=True`` each row additionally carries its
                per-token logprobs, truncated at EXACTLY the same token
                index as the tokens (_StopSession owns the one cut).
                Matching, token
                truncation, and eos handling live in ONE owner shared
                with the streaming path (_StopSession); rows are
                RAGGED.  ``stop_reason`` per row: "stop", "eos" (the
                backend's eos ended the row first; the eos token is
                included, engine convention), or "length"."""
                kwargs = {"logprobs": True} if logprobs else {}
                if (outer.request_timeout
                        and _accepts_kwarg(outer.backend.generate_stream,
                                           "timeout")):
                    # the same per-request deadline as the plain branch:
                    # a wedged scheduler surfaces as 504, never a hang
                    kwargs["timeout"] = outer.request_timeout
                kwargs.update(
                    self._obs_kwargs(outer.backend.generate_stream))
                gen = outer.backend.generate_stream(ids, max_new,
                                                    seed=seed, **kwargs)
                ses = _StopSession(outer.tokenizer, stop, len(ids),
                                   getattr(outer.backend, "eos_id", None),
                                   logprobs=logprobs)
                for item in gen:
                    ses.consume(item)
                    if all(ses.done):
                        gen.close()
                        break
                ses.finish()
                out = {"tokens": ses.toks, "text": ses.texts,
                       "stop_reason": ses.reason}
                if logprobs:
                    out["logprobs"] = [_round_lps(row) for row in ses.lps]
                self._json(200, out)

            def _stream_stop(self, ids, max_new, seed, stop,
                             logprobs=False):
                """STREAMING generation with stop sequences: chunked
                JSONL where each line carries per-row TEXT deltas only
                (tokens would mislead — text is authoritative under
                stop, and characters that might begin a stop string are
                held back until they provably aren't part of one, so
                nothing ever has to be retracted).  A final line carries
                the truncated token rows + per-row ``stop_reason`` (+
                per-row logprob rows cut at the same token index, with
                ``logprobs=True`` — deltas can't carry them: a logprob
                belongs to a token, and tokens aren't streamed here)."""
                kwargs = {"logprobs": True} if logprobs else {}
                kwargs.update(
                    self._obs_kwargs(outer.backend.generate_stream))
                gen = outer.backend.generate_stream(ids, max_new,
                                                    seed=seed, **kwargs)

                def lines(items, gen):
                    ses = _StopSession(
                        outer.tokenizer, stop, len(ids),
                        getattr(outer.backend, "eos_id", None),
                        logprobs=logprobs)
                    step = 0
                    for item in items:
                        pieces = ses.consume(item)
                        if any(pieces):
                            yield {"step": step, "text": pieces}
                        step += 1
                        if all(ses.done):
                            gen.close()
                            break
                    tail = ses.finish()
                    if any(tail):
                        yield {"step": step, "text": tail}
                    final = {"done": True, "tokens": ses.toks,
                             "stop_reason": ses.reason}
                    if logprobs:
                        final["logprobs"] = [_round_lps(row)
                                             for row in ses.lps]
                    yield final

                self._stream_lines(gen, lines)

            def _stream_lines(self, gen, lines_fn):
                """ONE owner of the chunked-JSONL framing shared by the
                plain and stop streaming paths: pull the FIRST backend
                item before committing to 200 + chunked (validation
                errors surface on first next() and must become a clean
                400/500, not a status line spliced into an open chunked
                body), then emit ``lines_fn(first, gen)``'s dict lines;
                a mid-stream failure becomes an {"error": ...} line so
                the framing stays intact, and the terminating chunk
                always goes out.  ``lines_fn(items, gen)`` receives the
                first item already spliced back into ``items`` (one
                owner of that dance too); ``gen`` rides along only for
                early ``gen.close()``."""
                import itertools
                first = None
                try:
                    first = next(gen)
                except StopIteration:
                    pass
                except SchedulerOverloaded as e:
                    self._shed(e)       # still before headers: clean 503
                    return
                except ValueError as e:
                    self._json(400, {"error": str(e)})
                    return
                except Exception as e:
                    # e.g. a TransportTimeout from a stalled pipeline —
                    # still before headers, so a clean 500 is possible
                    self._json(500, {"error": str(e)})
                    return
                items = itertools.chain(
                    [] if first is None else [first], gen)

                self.send_response(200)
                self.send_header("Content-Type", "application/jsonl")
                self.send_header("Transfer-Encoding", "chunked")
                tid = getattr(self, "_trace_id", None)
                if tid:
                    self.send_header("X-DWT-Trace-Id", tid)
                self.end_headers()

                def chunk(data: bytes) -> None:
                    self.wfile.write(f"{len(data):x}\r\n".encode())
                    self.wfile.write(data + b"\r\n")

                try:
                    for line in lines_fn(items, gen):
                        chunk((json.dumps(line) + "\n").encode("utf-8"))
                except OSError:
                    return      # client went away; the socket is dead
                except Exception as e:
                    # generator failure mid-stream: an error JSONL line
                    # keeps the chunked framing intact for the client
                    try:
                        chunk((json.dumps({"error": str(e)}) + "\n")
                              .encode("utf-8"))
                    except OSError:
                        return
                try:
                    chunk(b"")      # terminating chunk
                    self.wfile.flush()
                except OSError:
                    pass

            def _stream(self, ids, max_new, seed, logprobs=False,
                        resume=None):
                kwargs = {"logprobs": True} if logprobs else {}
                if resume is not None:
                    kwargs["resume"] = resume
                kwargs.update(
                    self._obs_kwargs(outer.backend.generate_stream))
                gen = outer.backend.generate_stream(ids, max_new, seed=seed,
                                                    **kwargs)
                # a resumed stream continues the dead replica's step
                # numbering so the client's concatenated stream reads
                # seamlessly (delivered prefix ends at step k-1)
                step0 = len(resume["delivered_tokens"]) if resume else 0

                def lines(items, gen):
                    # incremental detokenization, per row: the "text"
                    # field carries printable deltas
                    # (tokenizer.StreamDetokenizer — one owner of the
                    # boundary/holdback rules, shared with the chat REPL)
                    from ..tokenizer import StreamDetokenizer
                    detoks: dict = {}

                    def row_text(r, tok):
                        if r not in detoks:
                            detoks[r] = StreamDetokenizer(outer.tokenizer)
                        return detoks[r].push(tok)

                    n_steps = 0
                    for i, item in enumerate(items):
                        toks, lps = item if logprobs else (item, None)
                        line = {"step": step0 + i,
                                "tokens": np.asarray(toks).tolist()}
                        if lps is not None:
                            line["logprobs"] = _round_lps(np.asarray(lps))
                        if outer.tokenizer is not None:
                            line["text"] = [
                                row_text(r, t) for r, t in
                                enumerate(np.asarray(toks).tolist())]
                        yield line
                        n_steps = i + 1
                    if outer.tokenizer is not None and detoks:
                        # flush text held back by the U+FFFD guard: a
                        # stream ending on a split (or genuinely
                        # replacement-decoding) token must not silently
                        # drop its final characters
                        rem = [detoks[r].flush() if r in detoks else ""
                               for r in range(max(detoks) + 1)]
                        if any(rem):
                            yield {"step": step0 + n_steps, "tokens": [],
                                   "text": rem}

                self._stream_lines(gen, lines)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self.httpd.server_address
        self._thread: Optional[threading.Thread] = None

    def _debugz(self) -> dict:
        """``GET /debugz``: live black-box state — flight-recorder tail,
        backend anomaly-detector state (when the backend has one), and
        the postmortem bundles written so far.  Read-only and bounded:
        an operator can hit it during an incident without touching the
        pipeline (unlike /stats, it never polls remote stages)."""
        from ..telemetry import flightrecorder, postmortem
        out = {"flight": flightrecorder.debug_state()}
        debug_state = getattr(self.backend, "debug_state", None)
        if callable(debug_state):
            out["backend"] = debug_state()
        out["postmortem"] = postmortem.debug_state()
        return out

    def _prompt_ids(self, req: dict) -> np.ndarray:
        if "prompt_ids" in req:
            ids = np.asarray(req["prompt_ids"], dtype=np.int32)
            if ids.ndim == 1:
                ids = ids[None, :]
            if ids.ndim != 2 or ids.size == 0:
                raise ValueError("prompt_ids must be a non-empty 1D/2D list")
            return ids
        if "prompt" in req:
            if self.tokenizer is None:
                raise ValueError(
                    "text prompt given but no tokenizer is attached; "
                    "send prompt_ids or start the server with --tokenizer")
            ids = self.tokenizer.encode(str(req["prompt"]))
            return np.asarray([ids], dtype=np.int32)
        raise ValueError("request needs 'prompt_ids' or 'prompt'")

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def serve_forever(self) -> None:
        self.httpd.serve_forever()

    def shutdown(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=10)
