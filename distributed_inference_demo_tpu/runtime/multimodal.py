"""LLaVA-style multimodal inference: vision prefix + text decode.

BASELINE.json config #5: "vision encoder on an edge client, LLM decoder
shard on TPU".  The reference has no vision path; its closest concept is
heterogeneous per-device module placement (``server.py:831-832``).  Three
pieces:

- :class:`MultimodalEngine` — single-process reference: ViT+projector
  (``models/vision.py``) encodes the image, the projected patches are
  concatenated with token embeddings, the decoder prefils the combined
  prefix and decodes with the ordinary fused scan.
- :class:`VisionWorker` — the "edge client": a transport node that
  receives images (``img:{rid}``) and answers with projected patch hidden
  states (``imgh:{rid}``).  It holds no decoder weights at all.
- :class:`MultimodalHeader` — a :class:`PipelineHeader` whose prefill
  chunk is the pre-embedded multimodal prefix (vision worker round-trip +
  local token embedding); every downstream decoder stage is unchanged —
  stages only ever see ``[b, s, H]`` hidden states, so the multimodal
  prefix needs nothing new after stage 0.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..comm import wire
from ..comm.transport import BaseTransport
from ..models.base import ModelConfig, StageParams
from ..models.decoder import embed_tokens, stage_forward
from ..models.vision import VisionConfig, vision_forward
from ..ops.sampling import SamplingParams
from .distributed import (DEFAULT_STEP_TIMEOUT, PipelineHeader, StageRuntime,
                          _Request)
from .engine import GenerationResult, InferenceEngine

log = logging.getLogger(__name__)


def make_multimodal_encode(cfg: ModelConfig, vcfg: VisionConfig):
    """Jitted (vparams, dec_params, images, text_ids) -> [b, n_img+s, H]:
    the LLaVA input recipe — projected patches prepended to the text."""

    @jax.jit
    def encode(vparams, dec_params, images, text_ids):
        img_h = vision_forward(vparams, vcfg, images).astype(cfg.dtype)
        tok = embed_tokens(dec_params, cfg, text_ids)
        return jnp.concatenate([img_h, tok], axis=1)

    return encode


class MultimodalEngine:
    """Single-process image+text generation (the parity reference for the
    distributed composition below)."""

    def __init__(self, cfg: ModelConfig, params: StageParams,
                 vcfg: VisionConfig, vparams: dict,
                 max_seq: Optional[int] = None,
                 sampling: SamplingParams = SamplingParams(),
                 eos_id: Optional[int] = None,
                 attn_backend: str = "auto",
                 kv_layout: Optional[str] = None,
                 kv_dtype: Optional[str] = None):
        self.engine = InferenceEngine(cfg, params, max_seq, sampling,
                                      eos_id, attn_backend,
                                      kv_layout=kv_layout,
                                      kv_dtype=kv_dtype)
        self.cfg = cfg
        self.vcfg = vcfg
        self.vparams = vparams
        self._encode = make_multimodal_encode(cfg, vcfg)
        attn_impl = self.engine._attn_impl
        spec = self.engine.spec

        @jax.jit
        def prefill_embeds(dec_params, embeds, cache):
            b, s = embeds.shape[0], embeds.shape[1]
            pos = jnp.broadcast_to(jnp.arange(s), (b, s))
            logits, cache = stage_forward(dec_params, cfg, spec, embeds,
                                          cache, pos, attn_impl=attn_impl,
                                          last_logits_only=True)
            return logits[:, -1], cache

        self._prefill_embeds = prefill_embeds

    def generate(self, images: np.ndarray, text_ids: np.ndarray,
                 max_new_tokens: int, seed: int = 0) -> GenerationResult:
        """``images``: [b, H, W, C]; ``text_ids``: [b, s] int32."""
        eng = self.engine
        ids = jnp.asarray(text_ids, jnp.int32)
        embeds = self._encode(self.vparams, eng.params,
                              jnp.asarray(images), ids)
        b, seq = embeds.shape[0], embeds.shape[1]
        eng._check_capacity(seq, max_new_tokens)
        t0 = time.perf_counter()
        cache = eng.new_cache(b)
        logits, cache = self._prefill_embeds(eng.params, embeds, cache)
        toks, _, _ = eng._decode(eng.params, logits, cache,
                                 jax.random.PRNGKey(seed),
                                 eng._eos_scalar(), max_new_tokens)
        toks = np.asarray(toks)
        return GenerationResult(tokens=toks, prompt_len=seq,
                                num_new=max_new_tokens,
                                seconds=time.perf_counter() - t0)


class MultimodalBackend:
    """``serve --vision``: MultimodalEngine behind InferenceHTTPServer.

    POST /generate gains an optional ``image`` field — nested JSON
    floats shaped [H][W][C] (one image broadcast to the prompt batch of
    1) or [b][H][W][C] — prepended to the prompt as projected patches,
    LLaVA-style.  Requests WITHOUT an image run the wrapped text engine
    unchanged, so one server serves both modalities.  Shape and batch
    mismatches are ValueErrors (HTTP 400 with the expected tower
    geometry spelled out).  The reference has no multimodal path at all
    (BASELINE config #5 is this framework's addition)."""

    def __init__(self, engine: MultimodalEngine):
        self.mm = engine
        self._counts_lock = threading.Lock()
        self._served = {"text": 0, "image": 0}

    @property
    def max_seq(self) -> int:
        return self.mm.engine.max_seq

    def generate(self, prompt_ids: np.ndarray, max_new_tokens: int,
                 seed: int = 0, image=None,
                 logprobs: bool = False) -> GenerationResult:
        ids = np.asarray(prompt_ids, np.int32)
        if image is None:
            # text-only requests run the wrapped engine's FULL surface
            # (incl. logprobs) unchanged
            with self._counts_lock:
                self._served["text"] += 1
            return self.mm.engine.generate(ids, max_new_tokens, seed=seed,
                                           logprobs=logprobs)
        if logprobs:
            raise ValueError(
                "logprobs is not supported with image input")
        images = np.asarray(image, np.float32)
        if images.ndim == 3:
            images = images[None]
        vcfg = self.mm.vcfg
        want = (vcfg.image_size, vcfg.image_size, vcfg.channels)
        if images.ndim != 4 or images.shape[1:] != want:
            raise ValueError(
                f"image must be [H][W][C] or [b][H][W][C] with shape "
                f"{want} for this tower, got {images.shape}")
        if images.shape[0] != ids.shape[0]:
            raise ValueError(
                f"image batch {images.shape[0]} != prompt batch "
                f"{ids.shape[0]}")
        with self._counts_lock:
            self._served["image"] += 1
        return self.mm.generate(images, ids, max_new_tokens, seed=seed)

    def generate_stream(self, prompt_ids: np.ndarray, max_new_tokens: int,
                        seed: int = 0, logprobs: bool = False):
        """Text-only streaming delegates to the wrapped engine (image +
        stream is rejected at the HTTP layer — the fused multimodal
        program emits all tokens at once)."""
        with self._counts_lock:
            self._served["text"] += 1
        return self.mm.engine.generate_stream(
            np.asarray(prompt_ids, np.int32), max_new_tokens, seed=seed,
            logprobs=logprobs)

    def classify(self, prompt_ids: np.ndarray, label_token_ids):
        return self.mm.engine.classify(np.asarray(prompt_ids, np.int32),
                                       label_token_ids)

    def stats(self) -> dict:
        vcfg = self.mm.vcfg
        with self._counts_lock:
            served = dict(self._served)
        return {
            "mode": "multimodal",
            "image_size": vcfg.image_size,
            "patches_per_image": vcfg.num_patches,
            "vit_layers": vcfg.num_layers,
            "requests_text": served["text"],
            "requests_image": served["image"],
        }

    def reset_stats(self) -> None:
        with self._counts_lock:
            self._served = {"text": 0, "image": 0}


class VisionWorker:
    """The edge-client vision stage: owns ONLY the ViT+projector weights;
    serves ``img:{rid}`` -> ``imgh:{rid}`` over the transport."""

    def __init__(self, vparams: dict, vcfg: VisionConfig,
                 transport: BaseTransport, header_id: str,
                 step_timeout: float = DEFAULT_STEP_TIMEOUT):
        self.vparams = vparams
        self.transport = transport
        self.header_id = header_id
        self.step_timeout = step_timeout
        self._fwd = jax.jit(
            lambda p, img: vision_forward(p, vcfg, img))

    def serve_forever(self, idle_timeout: Optional[float] = None) -> None:
        from ..comm.transport import TransportTimeout
        while True:
            try:
                tag, payload = self.transport.recv_any(
                    timeout=idle_timeout or self.step_timeout)
            except TransportTimeout:
                log.info("vision worker %s: idle timeout, exiting",
                         self.transport.device_id)
                return
            kind, _, rest = tag.partition(":")
            if kind == "stop":
                return
            if kind != "img":
                log.warning("vision worker: unexpected tag %r", tag)
                continue
            [images] = wire.deserialize_tensors(payload).tensors
            hidden = np.asarray(self._fwd(self.vparams, jnp.asarray(images)))
            self.transport.send(self.header_id, f"imgh:{rest}",
                                wire.serialize_tensors([hidden]))


class MultimodalHeader(PipelineHeader):
    """PipelineHeader whose requests may carry an image: the prefill chunk
    becomes (vision-worker patches ++ local token embeddings), everything
    after stage 0 — ring hops, tail sampling, KV caches — is untouched."""

    def __init__(self, runtime: StageRuntime, transport: BaseTransport,
                 next_id: str, vision_id: str,
                 eos_id: Optional[int] = None,
                 step_timeout: float = DEFAULT_STEP_TIMEOUT):
        super().__init__(runtime, transport, next_id, eos_id, step_timeout)
        self.vision_id = vision_id
        self._mm_prefix: Dict[int, np.ndarray] = {}

    def _prefill_array(self, req: _Request) -> np.ndarray:
        prefix = self._mm_prefix.pop(req.rid, None)
        if prefix is None:
            return req.prompt.astype(np.int32)
        return prefix

    def _encode_image(self, images: np.ndarray) -> np.ndarray:
        """Round-trip to the vision node (the edge client)."""
        from ..comm.transport import TransportTimeout
        self.transport.send(self.vision_id, "img:0",
                            wire.serialize_tensors([np.asarray(images)]))
        deadline = time.monotonic() + self.step_timeout
        while True:
            left = deadline - time.monotonic()
            if left <= 0:
                raise TransportTimeout("vision worker did not answer")
            tag, payload = self.transport.recv_any(timeout=left)
            if tag.startswith("imgh:"):
                [hidden] = wire.deserialize_tensors(payload).tensors
                return hidden
            log.warning("header: unexpected tag %r awaiting vision", tag)

    def generate_mm(self, images: np.ndarray, text_ids: np.ndarray,
                    max_new_tokens: int, on_token=None) -> np.ndarray:
        """Image+text generation over the pipeline; returns [b, new].
        ``on_token`` streams steps exactly like ``generate_many``'s."""
        img_h = self._encode_image(images)
        tok = np.asarray(embed_tokens(self.rt.params, self.rt.cfg,
                                      jnp.asarray(text_ids, jnp.int32)))
        prefix = np.concatenate(
            [np.asarray(img_h).astype(tok.dtype), tok], axis=1)
        # capacity bookkeeping sees the combined length via a placeholder
        # id array; the real prefill input is the stashed float prefix.
        placeholder = np.zeros(prefix.shape[:2], np.int32)
        rid = self._next_rid
        self._mm_prefix[rid] = prefix
        try:
            return self.generate_many([placeholder], max_new_tokens,
                                      on_token=on_token)[0]
        finally:
            # if validation raised before _launch consumed the stash, a
            # later unrelated request would inherit this rid and prefill
            # with the wrong content — always clean up.
            self._mm_prefix.pop(rid, None)
