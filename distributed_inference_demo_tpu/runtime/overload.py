"""Overload shedding: the exception contract between scheduler and HTTP.

Deliberately a tiny dependency-free module: ``runtime/batching.py``
(which raises) pulls in jax, and ``runtime/http_server.py`` (which
catches and maps to ``503 + Retry-After``) must stay importable without
it — as must the replicated serving gateway (``runtime/gateway/``),
whose whole process holds no engine at all.  Graceful degradation is
the point — a saturated admission queue answers *quickly and honestly*
instead of queueing unboundedly until every client has timed out anyway
(docs/DESIGN.md §12, §16).
"""

from __future__ import annotations


class SchedulerOverloaded(RuntimeError):
    """The admission queue is past its configured depth: the request was
    REJECTED, not queued.  ``retry_after_s`` is the server's hint for the
    HTTP ``Retry-After`` header; ``http_code`` picks the status the HTTP
    layer renders — 503 (service saturated; the batching scheduler's
    convention) or 429 (this client should back off; the sp backend's
    one-request-at-a-time queue)."""

    def __init__(self, msg: str, retry_after_s: float = 1.0,
                 http_code: int = 503):
        super().__init__(msg)
        self.retry_after_s = retry_after_s
        self.http_code = http_code


class GatewayOverloaded(SchedulerOverloaded):
    """The gateway's federated-admission rejection (docs/DESIGN.md §16):
    no admitted replica can take this request — every replica is evicted
    from routing, or every candidate answered its own 503/429.  A
    subclass so the HTTP layer's one ``_shed`` path renders it; the
    distinct type lets tests (and operators reading tracebacks) tell a
    gateway-level shed from a replica's own admission rejection."""
