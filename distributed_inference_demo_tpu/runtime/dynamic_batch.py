"""Dynamic batching over the socket pipeline (``serve --chain --pool-size``).

The plain ``HeaderBackend`` serializes concurrent HTTP requests with a
lock — each waits for the whole previous generation.  The ring protocol
itself interleaves requests fine: messages are rid-tagged and every stage
keeps per-rid KV cache slots (the reference's ``core_pool_size`` socket
sets, ``Communication.java:425-437``, rebuilt as tags).  This backend
exploits that for the HTTP surface: requests that arrive while a window is
in flight queue up and launch TOGETHER in the next ``generate_many``
window, ``pool_size`` rids interleaving through the stages.

This is *dynamic* batching (grouped windows), not the slot-continuous
admission of ``runtime/batching.py`` — a request never joins a window
mid-flight.  The trade is deliberate: continuous admission needs the
device-side step program to absorb new rows between steps (one chip, one
compiled step — batching.py), while a pipeline stage's unit of work is a
whole rid-tagged forward; grouping at window boundaries gets the
concurrency without touching the ring protocol.

Control operations (stats / reset / classify) run as commands on the same
scheduler thread, BETWEEN windows — the transport has exactly one
consumer, so a stats reply can never be eaten by a generate window's
``recv_any`` loop.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from .engine import GenerationResult, check_capacity


@dataclass
class _HttpRequest:
    """One queued HTTP generation (a whole [b, s] prompt batch = one rid)."""
    prompt: np.ndarray
    max_new: int
    stream: "queue.Queue" = field(default_factory=queue.Queue)
    done: threading.Event = field(default_factory=threading.Event)
    tokens: Optional[np.ndarray] = None
    error: Optional[BaseException] = None


@dataclass
class _Command:
    """A control op executed between windows on the scheduler thread."""
    fn: object                      # callable(header) -> result
    done: threading.Event = field(default_factory=threading.Event)
    result: object = None
    error: Optional[BaseException] = None


class DynamicBatchingHeaderBackend:
    """Adapts a PipelineHeader/ElasticHeader to the HTTP surface with
    windowed request grouping (``pool_size`` rids in flight)."""

    def __init__(self, header, max_seq: int, num_stages: int = 2,
                 pool_size: int = 2, max_group: int = 8):
        self.header = header
        self.max_seq = max_seq
        self.num_stages = num_stages
        self.pool_size = max(1, pool_size)
        self.max_group = max(1, max_group)
        from ..telemetry.anomaly import AnomalyMonitor
        self._queue: "queue.Queue" = queue.Queue()
        # straggler watch over the polled stage snapshots, same wiring
        # as HeaderBackend: the /stats // metrics poll drives detection
        self.anomaly = AnomalyMonitor(config={
            "backend": type(self).__name__, "num_stages": num_stages,
            "pool_size": pool_size})
        self._running = True
        # serializes submissions against close(): nothing can land in the
        # queue after the drain ran, so no waiter can hang forever
        self._submit_lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------
    # HTTP surface

    def submit(self, prompt_ids, max_new_tokens: int) -> _HttpRequest:
        prompt = np.asarray(prompt_ids, np.int32)
        check_capacity(self.max_seq, prompt.shape[1], max_new_tokens)
        req = _HttpRequest(prompt=prompt, max_new=max_new_tokens)
        with self._submit_lock:
            if not self._running:
                raise RuntimeError("backend is closed")
            self._queue.put(req)
        return req

    def generate(self, prompt_ids, max_new_tokens: int, seed: int = 0):
        import time
        t0 = time.perf_counter()
        req = self.submit(prompt_ids, max_new_tokens)
        req.done.wait()
        if req.error is not None:
            raise req.error
        return GenerationResult(tokens=req.tokens,
                                prompt_len=req.prompt.shape[1],
                                num_new=req.tokens.shape[1],
                                seconds=time.perf_counter() - t0)

    def generate_stream(self, prompt_ids, max_new_tokens: int,
                        seed: int = 0):
        req = self.submit(prompt_ids, max_new_tokens)
        while True:
            item = req.stream.get()
            if item is None:
                break
            yield item
        if req.error is not None:
            raise req.error

    def classify(self, prompt_ids, label_token_ids):
        [pred] = self._command(
            lambda h: h.classify_many([np.asarray(prompt_ids)],
                                      label_token_ids))
        return pred

    def stats(self) -> dict:
        stages = self._command(
            lambda h: h.collect_stats(self.num_stages))
        self.anomaly.observe({"stages": stages})
        return {"stages": stages}

    def reset_stats(self) -> None:
        self._command(lambda h: h.reset_stats())

    def close(self) -> None:
        with self._submit_lock:
            self._running = False
            self._queue.put(None)
        self._thread.join(timeout=30)

    # ------------------------------------------------------------------
    # scheduler

    def _command(self, fn):
        cmd = _Command(fn=fn)
        with self._submit_lock:
            if not self._running:
                raise RuntimeError("backend is closed")
            self._queue.put(cmd)
        cmd.done.wait()
        if cmd.error is not None:
            raise cmd.error
        return cmd.result

    def _run_window(self, reqs: List[_HttpRequest]) -> None:
        try:
            results = self.header.generate_many(
                [r.prompt for r in reqs], [r.max_new for r in reqs],
                pool_size=self.pool_size,
                on_token=lambda i, step, toks: reqs[i].stream.put(toks))
            for r, toks in zip(reqs, results):
                r.tokens = toks
        except BaseException as e:      # surface to every waiter
            for r in reqs:
                r.error = e
        finally:
            for r in reqs:
                r.stream.put(None)
                r.done.set()

    def _loop(self) -> None:
        while self._running:
            item = self._queue.get()
            if item is None:
                break
            group = [item]
            while len(group) < self.max_group:
                try:
                    group.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            for cmd in (g for g in group if isinstance(g, _Command)):
                try:
                    cmd.result = cmd.fn(self.header)
                except BaseException as e:
                    cmd.error = e
                finally:
                    cmd.done.set()
            reqs = [g for g in group if isinstance(g, _HttpRequest)]
            if reqs:
                self._run_window(reqs)
        # drain: fail anything still queued
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                return
            if isinstance(item, _HttpRequest):
                item.error = RuntimeError("backend closed")
                item.stream.put(None)
                item.done.set()
            elif isinstance(item, _Command):
                item.error = RuntimeError("backend closed")
                item.done.set()
