"""Elastic pipeline: heartbeat-triggered re-planning, live shard migration,
and token-preserving drain/resume.

The reference *intended* all of this and shipped none of it (SURVEY.md §5.3):
failed devices are only removed from the pool (``server.py:73-100``) while
in-flight pipelines hang on blocking recv; the client-side re-balance
scaffold is commented out (``Client.java:124-153``); ``ModifySession``'s live
ONNX-session swap exists but has no server trigger (``LoadBalance.java:
125-149``); ``reload_sampleId`` is always None (``server.py:1011``).  This
module finishes the design, TPU-style:

- **Live migration** (= ``ModifySession``): every elastic node holds the
  full host-side parameter tree; ``reassign`` re-slices its active layer
  range (a zero-copy array slice, ``models.base.slice_stage``) and re-jits
  the stage function.  No module files, no downloads — the "session swap"
  is a new XLA executable.
- **Re-planning**: on a device failure the header re-splits the layer
  ranges over the surviving chain (``split_layer_ranges`` — the planner's
  bottleneck DP) and pushes ``reshard`` control messages over the same
  transport as the data plane.  Scale-up works identically: hand the
  header a longer chain.
- **Drain/resume** (= ``reload_sampleId`` done properly): the header owns
  every request's prompt + tokens-so-far, so after a reshard it re-prefills
  ``prompt ++ generated`` on the new pipeline and decoding continues at the
  same step counter.  With KV-cache-consistent prefill/decode (tested in
  test_models.py) the continuation is bit-identical for greedy sampling.
- **Failure detection** plugs into the control plane: wire
  ``DevicePoolManager.on_failure`` to :meth:`ElasticHeader.signal_failure`;
  the header's receive loop polls, reshards, and resumes — no hang.

Control tags (data tags are inherited from runtime/distributed.py, with a
**reshard epoch** appended — ``h:{rid}:{step}:{epoch}`` — so traffic from a
slow-but-not-dead pre-reshard worker is identifiable and dropped instead of
being run against a fresh cache and producing a wrong token):

- ``reshard:{header_id}``       header → worker, JSON plan {spec, next_id,
  epoch}
- ``rack:{device_id}:{epoch}``  worker → header, reshard applied — the ack
  carries the epoch it acknowledges, so a delayed ack from reshard N can
  never satisfy reshard N+1's ack-wait
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..comm import wire
from ..comm.transport import (BaseTransport, TransportError,
                              TransportTimeout, record_corrupt_frame)
from ..models.base import (ModelConfig, StageParams, StageSpec, slice_stage,
                           split_layer_ranges)
from ..ops.sampling import SamplingParams
from .distributed import (DEFAULT_STEP_TIMEOUT, PipelineHeader,
                          PipelineWorker, StageRuntime, _h_tag, _Request)

log = logging.getLogger(__name__)


class ElasticStageRuntime(StageRuntime):
    """A StageRuntime that can migrate to a different layer range live.

    Holds the full parameter tree host-side; the active stage's params are
    a slice view.  ``reassign`` is the reference's ``ModifySession``
    equivalent: drop old sessions (jitted fns + caches), create the new
    stage function for the new layer range.
    """

    def __init__(self, cfg: ModelConfig, spec: StageSpec,
                 full_params: StageParams, max_seq: int,
                 sampling: SamplingParams = SamplingParams(),
                 seed: int = 0, mesh=None, kv_cache_dtype=None,
                 kv_layout=None):
        self.full_params = full_params
        super().__init__(cfg, spec, slice_stage(full_params, cfg, spec),
                         max_seq, sampling, seed, mesh=mesh,
                         kv_cache_dtype=kv_cache_dtype,
                         kv_layout=kv_layout)
        self._seed = seed

    def reassign(self, spec: StageSpec) -> None:
        if (spec.layer_start, spec.layer_end, spec.stage_id,
                spec.num_stages) == (self.spec.layer_start,
                                     self.spec.layer_end, self.spec.stage_id,
                                     self.spec.num_stages):
            # topology unchanged but run restarts: paged tables hand
            # their pages back; dense rows garbage-collect
            self.reset_caches()
            return
        # Re-init via StageRuntime.__init__ to rebuild the jitted closures
        # for the new spec (old executables are dropped with the old refs).
        StageRuntime.__init__(self, self.cfg, spec,
                              slice_stage(self.full_params, self.cfg, spec),
                              self.max_seq, self.sampling, self._seed,
                              mesh=self.mesh,
                              kv_cache_dtype=self.kv_cache_dtype,
                              kv_layout=self.kv_layout)


def _spec_payload(spec: StageSpec) -> dict:
    return {"stage_id": spec.stage_id, "num_stages": spec.num_stages,
            "layer_start": spec.layer_start, "layer_end": spec.layer_end}


def _spec_from(p: dict) -> StageSpec:
    return StageSpec(p["stage_id"], p["num_stages"], p["layer_start"],
                     p["layer_end"])


class ElasticWorker(PipelineWorker):
    """PipelineWorker that applies ``reshard`` control messages in-loop and
    speaks epoch-tagged data tags (stale pre-reshard traffic is dropped)."""

    epoch: int = 0

    def _make_h_tag(self, rid: int, step: int) -> str:
        return f"{_h_tag(rid, step)}:{self.epoch}"

    def _make_tok_tag(self, rid: int, step: int) -> str:
        return f"tok:{rid}:{step}:{self.epoch}"

    def handle_message(self, tag: str, payload: bytes) -> bool:
        kind, _, rest = tag.partition(":")
        if kind == "reshard":
            plan = json.loads(payload.decode("utf-8"))
            if plan.get("park"):
                # dropped from the chain but alive: free every cache and
                # stand by as a spare for a future scale-up.
                self.rt.reset_caches()
                self._next_step.clear()
                self.epoch = plan["epoch"]
                self.next_id = None
                self.transport.send(
                    rest, f"rack:{self.transport.device_id}:{self.epoch}",
                    b"")
                log.info("worker %s: parked (epoch %d)",
                         self.transport.device_id, self.epoch)
                return True
            self.rt.reassign(_spec_from(plan["spec"]))
            self._next_step.clear()   # fresh caches: relaunched requests
            self.next_id = plan["next_id"]   # restart at any step
            self.epoch = plan["epoch"]
            self.transport.send(
                rest, f"rack:{self.transport.device_id}:{self.epoch}", b"")
            log.info("worker %s: resharded (epoch %d) to layers [%d,%d) "
                     "of %d stages", self.transport.device_id, self.epoch,
                     self.rt.spec.layer_start, self.rt.spec.layer_end,
                     self.rt.spec.num_stages)
            return True
        if kind == "h":
            fields = rest.split(":")
            if len(fields) > 2 and int(fields[2]) != self.epoch:
                log.info("worker %s: dropping stale epoch-%s chunk %s",
                         self.transport.device_id, fields[2], tag)
                return True
        return super().handle_message(tag, payload)

    def _run_and_forward(self, rid: int, step: int, payload: bytes) -> None:
        try:
            super()._run_and_forward(rid, step, payload)
        except TransportError:
            # next hop died mid-flight; the header's reshard will fix the
            # routing and restart the request — just keep serving.
            log.warning("worker %s: send to %r failed (peer down?)",
                        self.transport.device_id, self.next_id)


class ElasticHeader(PipelineHeader):
    """PipelineHeader that re-plans, migrates, and resumes on failure.

    ``chain`` is the pipeline order of device ids, header first.  Call
    :meth:`signal_failure` (thread-safe — wire it to
    ``DevicePoolManager.on_failure``) or :meth:`reshard` directly for
    planned scale-up/down.
    """

    def __init__(self, runtime: ElasticStageRuntime, transport: BaseTransport,
                 chain: Sequence[str], eos_id: Optional[int] = None,
                 step_timeout: float = DEFAULT_STEP_TIMEOUT,
                 poll_interval: float = 0.5,
                 layer_costs: Optional[Sequence[float]] = None,
                 stall_reshard_timeout: Optional[float] = None):
        """``stall_reshard_timeout``: in-place recovery — when no token
        has arrived for this long but no failure was signaled (a frame
        lost to corruption/drop, not a dead worker), the header reshards
        over the SAME chain: epoch bump, caches cleared, every in-flight
        request re-prefilled from its collected tokens.  The lost frame
        is effectively retransmitted and greedy output is unchanged
        (drain/resume exactness).  Default ``step_timeout / 4``; 0/None
        disables (then a lost frame rides the full step_timeout to the
        stall postmortem, pre-PR-5 behavior)."""
        if list(chain)[0] != transport.device_id:
            raise ValueError("chain must start with the header's device id")
        if len(chain) < 2:
            raise ValueError("elastic pipeline needs at least 2 devices")
        super().__init__(runtime, transport, next_id=list(chain)[1],
                         eos_id=eos_id, step_timeout=step_timeout)
        self.chain: List[str] = list(chain)
        self.poll_interval = poll_interval
        self.layer_costs = list(layer_costs) if layer_costs else None
        self.stall_reshard_timeout = (
            step_timeout / 4 if stall_reshard_timeout is None
            else (stall_reshard_timeout or None))
        self.epoch = 0
        self._failed: List[str] = []
        self._failed_lock = threading.Lock()

    def _make_h_tag(self, rid: int, step: int) -> str:
        return f"{_h_tag(rid, step)}:{self.epoch}"

    # -- failure intake ----------------------------------------------------

    def signal_failure(self, device_id: str) -> None:
        """Thread-safe: mark a device dead; the run loop reshards at its
        next poll.  Hook for ``DevicePoolManager.on_failure``."""
        self.flight.record("device_failure", device=device_id,
                           stage=self.transport.device_id)
        with self._failed_lock:
            if device_id not in self._failed:
                self._failed.append(device_id)

    def _take_failures(self) -> List[str]:
        with self._failed_lock:
            failed, self._failed = self._failed, []
            return [d for d in failed if d in self.chain]

    # -- re-planning + migration ------------------------------------------

    def reshard(self, chain: Sequence[str],
                in_flight: Optional[Dict[int, "_Request"]] = None,
                dead: Sequence[str] = ()) -> None:
        """Re-split layers over ``chain``, push the plan, resume requests.

        ``chain`` must start with the header and contain only live workers
        (longer than before for scale-up, shorter after failures or planned
        scale-down).  ``dead`` lists devices known unreachable — live
        workers dropped from the chain but not in ``dead`` are **parked**:
        told to free their caches and stand by as spares.
        """
        chain = list(chain)
        if chain[0] != self.transport.device_id:
            raise ValueError("chain must start with the header")
        if len(chain) < 2:
            raise RuntimeError(
                "pipeline no longer has enough devices (need >= 2)")
        costs = self.layer_costs
        specs = split_layer_ranges(self.rt.cfg.num_layers, len(chain), costs)
        self.epoch += 1
        log.info("reshard (epoch %d): %s -> ranges %s", self.epoch, chain,
                 [(s.layer_start, s.layer_end) for s in specs])
        self.flight.record("reshard", epoch=self.epoch, chain=list(chain),
                           dead=list(dead))

        # push plans to workers (everyone but us), then collect acks;
        # stray data messages racing the reshard are dropped (their caches
        # are invalid anyway — requests restart below).
        parked = [d for d in self.chain[1:]
                  if d not in chain and d not in dead]
        expected_acks = set(chain[1:]) | set(parked)
        for i, dev in enumerate(chain[1:], start=1):
            nxt = chain[i + 1] if i + 1 < len(chain) else None
            plan = {"spec": _spec_payload(specs[i]), "next_id": nxt,
                    "epoch": self.epoch}
            self.transport.send(
                dev, f"reshard:{self.transport.device_id}",
                json.dumps(plan).encode("utf-8"))
        for dev in parked:      # live but out of the chain: free + stand by
            plan = {"park": True, "epoch": self.epoch}
            self.transport.send(
                dev, f"reshard:{self.transport.device_id}",
                json.dumps(plan).encode("utf-8"))
        deadline = time.monotonic() + self.step_timeout
        while expected_acks:
            # a worker that dies MID-RESHARD must not cost the full ack
            # deadline: a failure signal for a pending acker aborts this
            # reshard now (the signal stays queued — the run loop's next
            # poll reshards again without the dead device)
            with self._failed_lock:
                dead_waiters = sorted(d for d in self._failed
                                      if d in expected_acks)
            if dead_waiters:
                raise TransportTimeout(
                    f"reshard (epoch {self.epoch}) aborted: "
                    f"{dead_waiters} failed mid-reshard")
            left = deadline - time.monotonic()
            if left <= 0:
                raise TransportTimeout(
                    f"reshard acks missing from {sorted(expected_acks)}")
            try:
                # sliced waits so the dead-waiter check above runs even
                # while nothing arrives
                tag, _ = self.transport.recv_any(timeout=min(left, 0.5))
            except TransportTimeout:
                continue  # deadline check above raises the informative error
            kind, _, rest = tag.partition(":")
            if kind == "rack":
                # rpartition: device ids may themselves contain ':'
                dev, _, ep = rest.rpartition(":")
                # epoch-checked: a delayed ack from a previous reshard must
                # not satisfy this one's ack-wait (ADVICE r1 #3).
                if dev and ep.isdigit() and int(ep) == self.epoch:
                    expected_acks.discard(dev)
            # anything else is pre-reshard traffic: drop.

        self.rt.reassign(specs[0])
        self.chain = chain
        self.next_id = chain[1]

        # drain/resume: restart every in-flight request from its collected
        # tokens (reload_sampleId semantics, done per-token not per-sample).
        if in_flight:
            for req in in_flight.values():
                self._relaunch(req)

    def _relaunch(self, req: _Request) -> None:
        """Re-prefill prompt ++ generated-so-far; decoding continues at the
        same step index (tail rng is fold_in(rid, step) — unchanged)."""
        ids = req.prompt.astype(np.int32)
        if req.tokens:
            gen = np.stack(req.tokens, axis=1).astype(np.int32)
            ids = np.concatenate([ids, gen], axis=1)
        hidden = self.rt.run_chunk(req.rid, ids)
        self.transport.send(
            self.next_id, self._make_h_tag(req.rid, req.step),
            wire.serialize_tensors_traced([np.asarray(hidden)],
                                          req.trace_id or None))

    # -- the elastic run loop ----------------------------------------------

    def generate_many(self, prompts: Sequence[np.ndarray],
                      max_new_tokens: int,
                      pool_size: int = 1,
                      on_token=None) -> List[np.ndarray]:
        pending = self._make_requests(prompts, max_new_tokens)
        rid_to_index = {req.rid: i for i, req in enumerate(pending)}
        queue = list(pending)
        in_flight: Dict[int, _Request] = {}
        # last_progress: real token progress only (bounds the final
        # give-up); last_recovery additionally resets on every recovery
        # attempt (paces the in-place stall reshards)
        last_progress = last_recovery = time.monotonic()
        # cumulative: _take_failures consumes each signal, but a reshard
        # aborted by a cascading failure leaves the earlier dead device
        # in self.chain — the retry must still exclude it
        dead_seen: set = set()

        while queue or in_flight:
            failed = self._take_failures()
            if failed:
                dead_seen.update(failed)
                alive = [d for d in self.chain if d not in dead_seen]
                try:
                    self.reshard(alive, in_flight,
                                 dead=[d for d in self.chain
                                       if d in dead_seen])
                    last_progress = last_recovery = time.monotonic()
                except TransportTimeout:
                    # a SECOND device died mid-reshard (the ack-wait
                    # aborted early on its failure signal, or its acks
                    # never came): its signal is queued, so the next
                    # poll reshards again without it — a cascading
                    # failure must not kill a run that survivors could
                    # finish.  The no-progress watchdog stays the
                    # backstop if reshards keep failing.
                    log.warning("header: reshard after %s failed "
                                "(another device down mid-reshard?); "
                                "retrying on the next failure signal",
                                failed)
                    last_recovery = time.monotonic()

            while queue and len(in_flight) < pool_size:
                req = queue.pop(0)
                in_flight[req.rid] = req
                try:
                    self._launch(req)
                except TransportError:
                    # first hop unreachable: hold the request in flight;
                    # the failure signal will reshard and relaunch it.
                    log.warning("header: launch of rid=%d failed "
                                "(next hop down?)", req.rid)

            try:
                tag, payload = self.transport.recv_any(
                    timeout=self.poll_interval)
            except TransportTimeout:
                now = time.monotonic()
                if now - last_progress > self.step_timeout:
                    # reshard couldn't save this run: black-box it like
                    # the static header's step timeout
                    self._stall_postmortem("generate")
                    raise TransportTimeout(
                        f"no progress for {self.step_timeout}s and no "
                        "failure signal; pipeline stalled")
                if (self.stall_reshard_timeout and in_flight
                        and now - last_recovery
                        > self.stall_reshard_timeout):
                    # a frame was lost (dropped/corrupt) but nobody died:
                    # reshard IN PLACE — epoch bump + drain/resume acts
                    # as the retransmit (docs/DESIGN.md §12)
                    self.flight.record(
                        "stall_reshard", stage=self.transport.device_id,
                        idle_s=round(now - last_progress, 3),
                        epoch=self.epoch)
                    log.warning(
                        "header: no progress for %.1fs with no failure "
                        "signal; resharding in place (epoch %d -> %d)",
                        now - last_progress, self.epoch, self.epoch + 1)
                    try:
                        # over the live chain: a device from an earlier
                        # ABORTED failure-reshard must stay excluded
                        self.reshard([d for d in self.chain
                                      if d not in dead_seen], in_flight,
                                     dead=[d for d in self.chain
                                           if d in dead_seen])
                    except TransportTimeout:
                        # a worker IS dead (acks missing / aborted by a
                        # failure signal): the signal-driven reshard at
                        # the top of the loop finishes the job
                        log.warning("header: in-place reshard failed; "
                                    "awaiting failure signal")
                    last_recovery = time.monotonic()
                continue

            kind, _, rest = tag.partition(":")
            if kind != "tok":
                continue       # stray acks / stale traffic
            fields = rest.split(":")
            rid, step = int(fields[0]), int(fields[1])
            if len(fields) > 2 and int(fields[2]) != self.epoch:
                continue       # pre-reshard token from a stale worker
            req = in_flight.get(rid)
            if req is None or step != req.step:
                continue       # duplicate or out-of-order token
            self.flight.record("tok_recv",
                               stage=self.transport.device_id,
                               rid=rid, step=step)
            try:
                [toks] = wire.split_trace_context(
                    wire.deserialize_tensors(payload))[0]
            except wire.WireIntegrityError as e:
                # dropped, counted, flight-recorded; the request's step
                # stays pending and the no-progress watchdog (or a
                # failure signal) reshards — never a garbage token
                record_corrupt_frame(self.transport.device_id, tag,
                                     len(payload), e)
                continue
            if on_token is not None:
                on_token(rid_to_index[rid], step, toks)
            try:
                self._advance(req, toks)
            except TransportError:
                # token is recorded; the follow-up send failed — the
                # failure signal will reshard and relaunch from tokens.
                log.warning("header: advance send for rid=%d failed "
                            "(next hop down?)", rid)
            last_progress = last_recovery = time.monotonic()
            if req.done:
                del in_flight[rid]

        return [np.stack(r.tokens, axis=1) for r in pending]
