"""Single-stage inference engine: prefill + fused decode loop.

The reference's token loop does, *per token, per device*: socket recv ->
deserialize -> ORT session metadata reflection -> run -> serialize -> socket
send -> host-side sampling in C++ (``Communication.java:682-928``,
``inference.cpp:145-218``, ``decoding.cpp:24-66``).  The TPU-native engine
collapses all of it into two compiled programs:

- ``prefill``: one jit over the whole prompt chunk.
- ``decode_loop``: ONE ``lax.while_loop`` over the new tokens — sampling
  fused in, KV cache donated, on-device eos/stop-token matching with
  ALL-ROWS-DONE EARLY EXIT, zero host round-trips until the token block
  comes back.  Per-token host work is literally nothing, and an early
  eos no longer burns the remainder of a fixed block.

``generate_stream`` runs the same loop in K-token chunks
(``stream_block``): one host dispatch per K tokens instead of per token
(the BENCH_SELF_r05 15.31 ms dispatch floor amortizes K-fold), flushing
early when the device reports all rows done; K=1 keeps the per-token
jitted step the loop is bit-identical to (the reference streams partial
strings to the UI via DataRepository, ``Communication.java:629-638``).

Also enforces the KV capacity bound host-side (prompt + new tokens <=
max_seq) — the traced path cannot (dynamic_update_slice clamps silently).
"""

from dataclasses import dataclass
from functools import partial
from typing import Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.base import KVCache, ModelConfig, StageParams, StageSpec
from ..models.decoder import stage_forward
from ..ops.flash_attention import make_flash_attn_impl
from ..ops.sampling import (SamplingParams, match_stop_ids, pad_stop_ids,
                            sample_logits)
from ..telemetry import profiling as _profiling
from ..telemetry.flightrecorder import get_flight_recorder
from ..telemetry.runlog import get_run_log


def resolve_stream_block(stream_block) -> int:
    """The streaming decode-block size K, ONE owner for every engine
    that fuses K device-loop steps per host dispatch: ``None`` defers to
    the ``DWT_STREAM_BLOCK`` env knob, default 1 (the per-token path —
    the parity reference the device loop is pinned against)."""
    if stream_block is None:
        from ..telemetry._env import env_int
        stream_block = env_int("DWT_STREAM_BLOCK", 1)
    stream_block = int(stream_block)
    if stream_block < 1:
        raise ValueError(f"stream_block must be >= 1, got {stream_block}")
    return stream_block


def count_device_loop(engine_name: str, steps: int,
                      dispatches: int = 1) -> None:
    """Feed the device-loop telemetry pair: one host DISPATCH issued,
    ``steps`` decode steps executed inside it.  dispatches/token ≈ 1/K
    is the headline invariant the decode_fused bench leg measures."""
    from ..telemetry.catalog import (ENGINE_DEVICE_LOOP_STEPS,
                                     ENGINE_HOST_DISPATCHES)
    ENGINE_HOST_DISPATCHES.inc(dispatches, engine=engine_name)
    ENGINE_DEVICE_LOOP_STEPS.inc(steps, engine=engine_name)


def shard_engine_params(params: "StageParams", cfg: "ModelConfig", mesh):
    """Place a full parameter tree onto a tp mesh in the engine's layout
    (Megatron-sliced weights, replicated embed — the same specs the
    forward's shard_map consumes) — the companion to
    ``InferenceEngine(mesh=...)``.  Without this the engine is still
    correct (GSPMD reshards per call) but the weights waste HBM on every
    chip."""
    from ..parallel.sharding import shard_params

    return shard_params(params, cfg, mesh, vocab_parallel_embed=False)


def check_capacity(max_seq: int, prompt_len: int, max_new_tokens: int):
    """Host-side KV capacity bound shared by all engines (the traced path
    cannot enforce it — ``dynamic_update_slice`` clamps silently)."""
    need = prompt_len + max_new_tokens
    if need > max_seq:
        raise ValueError(
            f"prompt ({prompt_len}) + new tokens ({max_new_tokens}) = "
            f"{need} exceeds KV-cache capacity {max_seq}")


@dataclass
class GenerationResult:
    tokens: np.ndarray          # [batch, max_new_tokens] int32
    prompt_len: int
    num_new: int
    seconds: float = 0.0
    # model log-probabilities of the emitted tokens (raw log-softmax, NOT
    # the temperature/top-k-filtered sampling distribution — the
    # OpenAI-style convention), [batch, max_new_tokens] f32, or None
    logprobs: Optional[np.ndarray] = None
    # decode steps the device loop actually RAN (docs/DESIGN.md §13):
    # early exit on eos/stop can make this < num_new, in which case
    # token columns >= steps_computed are deterministic padding the
    # device never computed.  None = engines without the loop (every
    # step ran).
    steps_computed: Optional[int] = None

    @property
    def tokens_per_second(self) -> float:
        """Throughput over steps the device actually ran — an
        early-exited run must not claim rate for padding it skipped."""
        steps = (self.steps_computed if self.steps_computed is not None
                 else self.num_new)
        total = self.tokens.shape[0] * steps
        return total / self.seconds if self.seconds > 0 else float("nan")


def validate_prefill_chunk(prefill_chunk, max_seq: int):
    """The chunk-size rule, ONE owner for every engine that accepts
    ``prefill_chunk`` (plain / speculative / prompt-lookup)."""
    if prefill_chunk is not None and not (1 <= prefill_chunk <= max_seq):
        raise ValueError(
            f"prefill_chunk must be in [1, max_seq={max_seq}]")
    return prefill_chunk


def make_chunk_programs(fwd):
    """``(chunk_mid, chunk_last)`` jitted programs over a forward seam —
    ONE factory shared by InferenceEngine and SpeculativeEngine (which
    builds a pair per model), so the two engines' chunk programs cannot
    drift and :func:`run_chunked_prefill` has one set of semantics."""

    @partial(jax.jit, donate_argnums=(2,))
    def chunk_mid(params, ids, cache, start):
        """One non-final prompt chunk: extend the cache, drop logits."""
        b, s = ids.shape
        pos = start + jnp.broadcast_to(jnp.arange(s), (b, s))
        _, cache = fwd(params, ids, cache, pos, True)
        return cache

    @partial(jax.jit, donate_argnums=(2,))
    def chunk_last(params, ids, cache, start, gather_idx):
        """Final (possibly pad-tailed) chunk: logits at the prompt's
        true last position."""
        b, s = ids.shape
        pos = start + jnp.broadcast_to(jnp.arange(s), (b, s))
        logits, cache = fwd(params, ids, cache, pos, False)
        last = jax.lax.dynamic_index_in_dim(logits, gather_idx, axis=1,
                                            keepdims=False)
        return last, cache

    return chunk_mid, chunk_last


def make_paged_chunk_programs(fwd_p, bind_tables):
    """``(chunk_mid, slab_body)`` prefill programs over a PAGED forward
    seam (``make_paged_forward_seam``): chunks write K/V straight to the
    page pool through the block tables — no dense temp row, no
    gather/scatter round trip, ``dwt_kvcache_h2d_bytes_total`` stays 0.

    ``chunk_mid`` is the jitted non-final-chunk program (pool donated,
    logits dropped) used by serialized chunked admission; ``slab_body``
    is the UNJITTED traced body for a [n_seg, C] slab of segments at
    per-row start offsets — the mixed token-budget dispatch composes it
    with the fused decode loop inside ONE jit (batching._mixed_step),
    so it must stay a plain function.  Both rely on the paged attention
    path's prefill contract: in-chunk keys are written before the
    gather/kernel inside each layer, and causal masking keeps a
    segment's queries on its own prior pages plus in-chunk keys
    (ops/paged_attention.paged_prefill_attention)."""

    @partial(jax.jit, donate_argnums=(1, 2))
    def chunk_mid(params, pk, pv, ids, tables, start):
        """One non-final prompt chunk at global offset ``start``,
        written through ``tables`` [b, W]: extend the pool, drop
        logits."""
        bind_tables(tables)
        b, s = ids.shape
        pos = start + jnp.broadcast_to(jnp.arange(s), (b, s))
        cache = KVCache(pk, pv, jnp.zeros((), jnp.int32))
        _, cache = fwd_p(params, ids, cache, pos, True)
        return cache.keys, cache.values

    def slab_body(params, cache, ids, tables, starts):
        """Traced slab forward: row r of ``ids`` [n, s] runs at
        positions ``starts[r] + arange(s)`` through ``tables[r]``;
        returns all-position logits (callers slice their own final
        positions) and the extended cache."""
        bind_tables(tables)
        b, s = ids.shape
        pos = starts[:, None] + jnp.arange(s)[None, :]
        logits, cache = fwd_p(params, ids, cache, pos, False)
        return logits, cache

    return chunk_mid, slab_body


def run_chunked_prefill(params, ids, cache, C: int, max_seq: int,
                        chunk_mid, chunk_last=None, start: int = 0):
    """The chunked-prefill driver, shared by InferenceEngine and
    SpeculativeEngine (which runs it once per model).

    The prompt is zero-padded to a chunk multiple and every chunk runs
    through the same compiled programs (mid + last) — one chunk shape
    for ALL prompt lengths, short ones included.  The final chunk is
    left-shifted when the padded length would spill past ``max_seq``
    ("aligned last window"): the overlapped real tokens are recomputed
    and rewritten at their own positions (same values — K/V depend only
    on the prefix), so no pad slot is ever written beyond max_seq and
    ``dynamic_update_slice`` can never clamp into valid entries.  The
    cache's valid length is rewound to the true prompt length afterwards
    so decode's first insert overwrites the first pad slot (stale-slot
    invariant).

    ``chunk_last=None`` runs the final chunk through ``chunk_mid`` too
    and returns ``(None, cache)`` — the draft-model case, where only the
    filled cache matters and no logits are needed.

    ``start``: prefill SUFFIX mode — ``ids`` are the tokens from
    position ``start`` on, and the cache already holds exact K/V for
    columns ``[0, start)`` (a KV-cache block run, runtime/kvcache).
    Chunks run at global offsets and the aligned last window never
    left-shifts below ``start`` (the overlapped-recompute trick needs
    the overlapped ids, and the caller only has the suffix); when the
    room past ``start`` is smaller than one chunk, the suffix runs as a
    single unpadded dispatch instead."""
    b, plen = ids.shape
    cap = max_seq - start          # columns available at/after start
    cache = KVCache(cache.keys, cache.values, jnp.int32(start))
    if cap < C:
        # near-capacity seeded suffix: no room to pad to a whole chunk
        # without spilling past max_seq, and no room to left-shift
        # without the prefix ids — one unpadded dispatch (a per-length
        # compile, only reachable on prefix hits within C of max_seq)
        if chunk_last is None:
            cache = chunk_mid(params, ids, cache, jnp.int32(start))
            last = None
        else:
            last, cache = chunk_last(params, ids, cache, jnp.int32(start),
                                     jnp.int32(plen - 1))
        return last, KVCache(cache.keys, cache.values,
                             jnp.int32(start + plen))
    n_chunks = -(-plen // C)
    padded = jnp.zeros((b, n_chunks * C), jnp.int32)
    padded = jax.lax.dynamic_update_slice(padded, ids, (0, 0))
    for i in range(n_chunks - 1):
        cache = chunk_mid(params, jax.lax.dynamic_slice_in_dim(
            padded, i * C, C, axis=1), cache, jnp.int32(start + i * C))
    tail_start = min((n_chunks - 1) * C, cap - C)
    # the left shift must apply to the cache WRITE offset too (the
    # insert position is cache.length inside stage_forward), so the
    # column==position invariant holds; with the buffer padded past
    # max_seq (pad_cache_capacity) the old implicit
    # dynamic_update_slice start-clamp no longer realizes it
    cache = KVCache(cache.keys, cache.values, jnp.int32(start + tail_start))
    tail = jax.lax.dynamic_slice_in_dim(padded, tail_start, C, axis=1)
    if chunk_last is None:
        cache = chunk_mid(params, tail, cache, jnp.int32(start + tail_start))
        last = None
    else:
        last, cache = chunk_last(params, tail, cache,
                                 jnp.int32(start + tail_start),
                                 jnp.int32(plen - 1 - tail_start))
    cache = KVCache(cache.keys, cache.values, jnp.int32(start + plen))
    return last, cache


def run_seeded_prefill(params, ids, cache, C, max_seq, prefill,
                       chunk_mid, chunk_last, start: int = 0):
    """Whole-prompt or chunked prefill with an optional KV-cache-seeded
    prefix: the ONE dispatch rule shared by InferenceEngine and
    PromptLookupEngine (SpeculativeEngine drives the same pieces per
    model).  ``start`` > 0: the cache already holds exact K/V for
    columns ``[0, start)`` and only ``ids[:, start:]`` runs — one
    chunk-last dispatch (compiled per suffix length, no worse than the
    whole-prompt prefill's per-length compile), or the chunked driver's
    suffix mode."""
    if start:
        suffix = ids[:, start:]
        if C is not None:
            return run_chunked_prefill(params, suffix, cache, C, max_seq,
                                       chunk_mid, chunk_last, start=start)
        cache = KVCache(cache.keys, cache.values, jnp.int32(start))
        last, cache = chunk_last(params, suffix, cache, jnp.int32(start),
                                 jnp.int32(suffix.shape[1] - 1))
        return last, KVCache(cache.keys, cache.values,
                             jnp.int32(ids.shape[1]))
    if C is None:
        return prefill(params, ids, cache)
    return run_chunked_prefill(params, ids, cache, C, max_seq,
                               chunk_mid, chunk_last)


def resolve_cache_dtype_backend(kv_cache_dtype, attn_backend: str):
    """The reduced-precision-cache rule, ONE owner for every engine
    (plain / speculative / prompt-lookup / batching): a reduced-dtype KV
    cache forces the jnp attention path (the Pallas kernel is not
    exercised on f8 loads), and an explicit non-jnp kernel request
    errors rather than silently downgrading.  Returns
    ``(jnp.dtype | None, attn_backend)``."""
    dt = jnp.dtype(kv_cache_dtype) if kv_cache_dtype else None
    if dt is not None:
        if attn_backend not in ("auto", "jnp"):
            raise ValueError(
                f"attn_backend={attn_backend!r} is incompatible with "
                "kv_cache_dtype (the Pallas kernel is not exercised "
                "on reduced-precision cache loads); use 'auto' or "
                "'jnp'")
        attn_backend = "jnp"
    return dt, attn_backend


class InferenceEngine:
    """KV-cached generation over a full model — single chip, or
    tensor-parallel over a tp mesh (``mesh=`` + :func:`shard_engine_params`)."""

    def __init__(self, cfg: ModelConfig, params: StageParams,
                 max_seq: Optional[int] = None,
                 sampling: SamplingParams = SamplingParams(),
                 eos_id: Optional[int] = None,
                 attn_backend: str = "auto",
                 kv_cache_dtype: Optional[str] = None,
                 prefill_chunk: Optional[int] = None,
                 mesh=None,
                 kv_cache_blocks: Optional[int] = None,
                 kv_block_tokens: Optional[int] = None,
                 kv_layout: Optional[str] = None,
                 kv_dtype: Optional[str] = None,
                 stop_token_ids=None,
                 stream_block: Optional[int] = None):
        """``attn_backend``: "auto" (Pallas flash kernel on TPU, jnp
        elsewhere), "flash", "flash-interpret" (testing), or "jnp".

        ``kv_layout``: layout of the prefix-reuse pool behind the
        ``runtime/kvcache`` backend seam (docs/DESIGN.md §14).  "paged"
        (the default) keeps the pool device-resident: hits gather pages
        into the fresh cache on device and stores scatter blocks back —
        zero bytes cross the host boundary either way; it is the ONLY
        layout ("dense", the §10 host-pool escape hatch, was removed
        after its one-release deprecation).  The ONE request in flight
        decodes against a dense working cache its decode loop donates —
        the layout governs the standing pool, which is where reserved
        HBM lives.

        ``mesh``: a ``jax.sharding.Mesh`` with a ``tp`` axis — every
        forward then runs inside a shard_map with Megatron-sliced weights
        and a kv-head-sharded cache (BASELINE config #3: attention-head
        shards across chips via ICI all-gather); activations/logits come
        back replicated so sampling and the decode scan are unchanged.
        Pass params through :func:`shard_engine_params` first so the
        weight shards live on their chips.  Forces the jnp attention path
        (the Pallas kernel is not exercised per-shard).

        ``prefill_chunk``: process prompts in fixed chunks of this many
        tokens instead of one whole-prompt program.  Bounds prefill
        activation memory (a 32k-token prompt's [b, s, I] MLP
        intermediates dwarf the weights) and keeps ONE compiled chunk
        shape regardless of prompt length — the prompt is padded up to a
        chunk multiple and the pad positions are overwritten by decode
        before anything can attend them (same stale-slot invariant as
        speculative rollback / batching admission).

        ``kv_cache_dtype``: store the KV cache at a reduced precision,
        e.g. "float8_e4m3fn" — HALF the cache bytes (and cache-read
        traffic, which rivals the weight stream at large batch x long
        context) with no scale bookkeeping, at a small accuracy cost.
        Attention math stays f32 (``ops.attention`` upcasts whatever the
        cache holds); inserts round via ``update_kv_cache``'s cast.
        Forces the jnp attention path (the Pallas kernel is not exercised
        on f8 loads).

        ``kv_dtype``: page WIDTH of the prefix-reuse pool behind the
        kvcache seam — "bf16" (full width, the default), "int8", or
        packed "int4" with a per-token scale sidecar riding the same
        block table (docs/DESIGN.md §17).  Resolved arg over
        ``DWT_KV_DTYPE`` over bf16 inside ``make_kv_backend``; mutually
        exclusive with the ``kv_cache_dtype`` storage cast.  The dense
        working cache for the one request in flight stays full width —
        quantization happens at the page boundary (store scatter), and
        seeds dequantize back to full rows.

        ``kv_cache_blocks`` / ``kv_block_tokens``: block-level KV prefix
        cache (``runtime/kvcache``, docs/DESIGN.md §10) for the
        single-request ``generate``/``generate_stream`` paths (batch 1):
        a prompt sharing whole leading blocks with any previously
        prefilled prompt seeds its cache from the stored blocks and
        prefills only the suffix; every prefill stores its full blocks
        back.  ``None`` defers to ``DWT_KVCACHE_*`` env knobs; default
        off (0) — the continuous-batching engine is the default-on
        consumer.

        ``stop_token_ids``: token ids that end a row ON DEVICE, inside
        the fused decode loop (docs/DESIGN.md §13) — single-token stop
        matching at zero host round-trips (text-level stop STRINGS stay
        a server-side concern, runtime/http_server.StopMatcher).  The
        stop token itself is emitted (the eos-include convention); the
        row then pads with eos like an eos finish.  With ``eos_id``
        UNSET there is no pad token: ``generate``'s fixed-width output
        pads with token 0 past the cut — read
        ``GenerationResult.steps_computed`` for where real output ends,
        or use ``generate_stream``, which simply stops.

        ``stream_block``: fuse this many decode steps per
        ``generate_stream`` host dispatch (K).  The device loop checks
        eos/stop and all-rows-done ON DEVICE, so an early finish exits
        after j <= K steps instead of burning the block; the host sees
        tokens in K-sized chunks (dispatches/token ≈ 1/K — the
        BENCH_SELF_r05 15.31 ms host dispatch floor amortizes K-fold).
        1 (default; ``DWT_STREAM_BLOCK`` env between) keeps the
        per-token path, which the fused loop is bit-identical to
        (greedy) by construction."""
        from .kvcache import resolve_kv_layout
        self.kv_layout = resolve_kv_layout(kv_layout)
        self.cfg = cfg
        self.params = params
        self.max_seq = max_seq or cfg.max_seq_len
        self.sampling = sampling
        self.eos_id = eos_id
        self.spec = StageSpec(0, 1, 0, cfg.num_layers)
        self.prefill_chunk = validate_prefill_chunk(prefill_chunk,
                                                    self.max_seq)
        self.stream_block = resolve_stream_block(stream_block)
        self._stop_ids = pad_stop_ids(stop_token_ids)
        self._has_stop_ids = bool(stop_token_ids)
        # host-dispatch / device-step counters for THIS engine instance
        # (the dwt_engine_* series aggregate across instances); the
        # decode_fused bench leg and the 1/K invariant test read these
        self.loop_stats = {"host_dispatches": 0, "device_loop_steps": 0}
        self.mesh = mesh
        tp = mesh.shape.get("tp", 1) if mesh is not None else 1
        from ..parallel.tensor import resolve_tp_attn_backend
        # kv_cache_dtype composes with a tp mesh: the insert cast
        # (update_kv_cache) and the read upcast (ops.attention) both run
        # INSIDE the shard on its local kv-head planes, and the cache
        # sharding specs are dtype-agnostic — tp just forces the jnp
        # attention path, which is what reduced-precision caches use
        # anyway (parity pinned by tests/test_engine.py)
        attn_backend = resolve_tp_attn_backend(tp, attn_backend)
        self.kv_cache_dtype, attn_backend = resolve_cache_dtype_backend(
            kv_cache_dtype, attn_backend)
        if attn_backend == "auto":
            attn_backend = ("flash" if jax.default_backend() == "tpu"
                            else "jnp")
        self.attn_backend = attn_backend
        if attn_backend == "flash":
            attn_impl = make_flash_attn_impl()
        elif attn_backend == "flash-interpret":
            attn_impl = make_flash_attn_impl(interpret=True)
        elif attn_backend == "jnp":
            attn_impl = None
        else:
            raise ValueError(
                f"unknown attn_backend {attn_backend!r}; expected "
                "'auto', 'flash', 'flash-interpret', or 'jnp'")

        self._attn_impl = attn_impl   # shared with MultimodalEngine

        from .kvcache import make_kv_backend
        self.kv_cache = make_kv_backend(
            cfg, kv_cache_blocks, kv_block_tokens, layout=self.kv_layout,
            dtype=self.kv_cache_dtype, kv_dtype=kv_dtype,
            default_blocks=0)

        cfg_ = cfg
        spec_ = self.spec
        samp_ = sampling

        # forwards run through the seam from parallel/tensor.py (the one
        # owner of the manual-TP layout): a tp shard_map under a mesh,
        # plain stage_forward otherwise — the code above the seam
        # (sampling, scans, chunking) is mesh-oblivious either way
        from ..parallel.tensor import make_forward_seam
        fwd, self._cache_sharding = make_forward_seam(
            cfg, self.spec, mesh, params, attn_impl=attn_impl)

        @jax.jit
        def prefill(params, ids, cache):
            b, s = ids.shape
            pos = jnp.broadcast_to(jnp.arange(s), (b, s))
            # last_logits_only: the LM head runs on the final position only
            # ([b, 1, V]) — a full [b, s, V] logits tensor at long prompts
            # would burn GBs of HBM and head-matmul FLOPs for nothing.
            logits, cache = fwd(params, ids, cache, pos, True)
            return logits[:, -1], cache

        self._prefill_chunk_mid, self._prefill_chunk_last = \
            make_chunk_programs(fwd)

        def _mask_eos(tok, done, eos):
            """Shared eos row-padding rule (eos < 0 = disabled); the eos id
            is a TRACED scalar so ``engine.eos_id`` can change between
            calls without recompiling or re-baking closures."""
            live = eos >= 0
            tok = jnp.where(done, jnp.where(live, eos, tok), tok)
            done = done | (live & (tok == eos))
            return tok, done

        def _emitted_lp(logits, tok):
            return jnp.take_along_axis(
                jax.nn.log_softmax(logits.astype(jnp.float32), -1),
                tok[:, None].astype(jnp.int32), axis=-1)[:, 0]

        @partial(jax.jit, donate_argnums=(2,), static_argnums=(8, 9))
        def decode_loop(params, last_logits, cache, rng, eos, stop_ids,
                        done, limit, num_steps, with_logprobs=False):
            """The device-resident decode loop (docs/DESIGN.md §13): up
            to ``limit`` fused sample+forward steps in ONE dispatch,
            with on-device eos masking, stop-token-ID matching, and
            ALL-ROWS-DONE EARLY EXIT — an eos at step j < limit ends
            the loop after j+1 steps instead of burning the remainder
            of a fixed block; the host is touched once per block.

            ``num_steps`` (static) sizes the token/logprob buffers;
            ``limit`` (traced) bounds the trip count, so one compiled
            program serves both full blocks and the stream's tail
            block.  Rows that finished keep emitting deterministic eos
            padding while others run (``_mask_eos`` row-wise — the
            per-token path's semantics, which this loop is greedy
            bit-identical to: same rng split order, same mask-then-
            score step order).  Returns ``(toks [b, num_steps],
            lps [b, num_steps], next_logits, cache, rng, done,
            steps_ran)``; buffer columns >= steps_ran are eos padding
            the host must not read past."""
            b = last_logits.shape[0]
            pad = jnp.where(eos >= 0, eos, 0).astype(jnp.int32)
            toks0 = jnp.broadcast_to(pad, (b, num_steps)).astype(jnp.int32)
            lps0 = jnp.zeros((b, num_steps), jnp.float32)

            def cond(carry):
                j, logits, cache, rng, done, toks, lps = carry
                return (j < limit) & ~jnp.all(done)

            def body(carry):
                j, logits, cache, rng, done, toks, lps = carry
                rng, sub = jax.random.split(rng)
                tok = sample_logits(logits, sub, samp_)
                tok, done = _mask_eos(tok, done, eos)
                done = done | match_stop_ids(tok, stop_ids)
                if with_logprobs:
                    lp = _emitted_lp(logits, tok)
                else:
                    lp = jnp.zeros((b,), jnp.float32)
                toks = jax.lax.dynamic_update_slice(
                    toks, tok[:, None], (jnp.int32(0), j))
                lps = jax.lax.dynamic_update_slice(
                    lps, lp[:, None], (jnp.int32(0), j))
                pos = jnp.broadcast_to(cache.length, (b, 1))
                out, cache = fwd(params, tok[:, None], cache, pos, False)
                return (j + 1, out[:, 0], cache, rng, done, toks, lps)

            (steps, logits, cache, rng, done, toks, lps) = \
                jax.lax.while_loop(
                    cond, body,
                    (jnp.int32(0), last_logits, cache, rng, done,
                     toks0, lps0))
            return toks, lps, logits, cache, rng, done, steps

        @partial(jax.jit, donate_argnums=(2,))
        def decode_one(params, last_logits, cache, rng, eos, stop_ids,
                       done):
            """One streamed step — the PER-TOKEN path the device loop is
            pinned against; eos masking, stop-id matching, and the
            logprob all happen HERE in the same order as the loop's body
            (mask first, then score the emitted token), so the two paths
            agree on (token, logprob, done) triples row-wise."""
            rng, sub = jax.random.split(rng)
            tok = sample_logits(last_logits, sub, samp_)
            tok, done = _mask_eos(tok, done, eos)
            done = done | match_stop_ids(tok, stop_ids)
            b = tok.shape[0]
            # per-token logprob rides along (one [b, V] reduction; the
            # streaming path is dispatch-bound, so it's in the noise)
            lp = _emitted_lp(last_logits, tok)
            pos = jnp.broadcast_to(cache.length, (b, 1))
            out, cache = fwd(params, tok[:, None], cache, pos, False)
            return tok, lp, out[:, 0], cache, rng, done

        # observatory seams (docs/DESIGN.md §20): compile accounting on
        # the jitted programs + the sampled dispatch profiler.
        # decode_loop legitimately forks per static (num_steps,
        # with_logprobs) pair, so it carries NO variant budget — only
        # programs with a documented invariant feed recompile_storm.
        _ct = _profiling.get_compile_tracker()
        self._prefill = _ct.wrap("prefill", prefill)
        self._decode_loop = _ct.wrap("decode_loop", decode_loop)
        self._decode_one = _ct.wrap("decode_one", decode_one)
        self._prof = _profiling.get_profiler()
        # dense-cache attribution: KV bytes one (row, token) touches
        self._kv_token_bytes = _profiling.kv_dispatch_bytes(
            1, cfg.num_layers, cfg.num_kv_heads, cfg.head_dim,
            None, self.kv_cache_dtype)

    # ------------------------------------------------------------------

    def _check_capacity(self, prompt_len: int, max_new_tokens: int):
        check_capacity(self.max_seq, prompt_len, max_new_tokens)

    def _eos_scalar(self):
        """eos_id as the traced sentinel scalar (-1 = disabled), read at
        call time so eos_id assignment between calls takes effect."""
        return jnp.int32(self.eos_id if self.eos_id is not None else -1)

    def _count_loop(self, steps: int, dispatches: int = 1) -> None:
        """One decode dispatch left the host and ran ``steps`` device
        steps: feed the instance counters + the dwt_engine_* series."""
        self.loop_stats["host_dispatches"] += dispatches
        self.loop_stats["device_loop_steps"] += steps
        count_device_loop(type(self).__name__, steps, dispatches)

    def new_cache(self, batch: int) -> KVCache:
        # KVCache.create pads the buffer to the sublane granule; max_seq
        # stays the enforced capacity bound (check_capacity)
        cache = KVCache.create(self.cfg, self.cfg.num_layers, batch,
                               self.max_seq, dtype=self.kv_cache_dtype)
        if self._cache_sharding is not None:
            # commit the fresh (donatable) buffers to their kv-head shards
            # up front so the first forward doesn't pay a reshard
            cache = jax.device_put(cache, self._cache_sharding)
        return cache

    def _run_prefill(self, ids: jnp.ndarray, cache: KVCache,
                     start: int = 0):
        """Whole-prompt or chunked prefill → (last_logits [b, V], cache).
        Chunked semantics (padding, aligned last window, length rewind)
        live in :func:`run_chunked_prefill`; the seeded-suffix dispatch
        rule in :func:`run_seeded_prefill` — both shared with the
        speculative and prompt-lookup engines.  ``start`` > 0 is the
        KV-cache-seeded SUFFIX path: ``ids`` still carries the whole
        prompt, columns ``[0, start)`` of the cache already hold its
        prefix K/V, and only ``ids[:, start:]`` runs."""
        return run_seeded_prefill(
            self.params, ids, cache, self.prefill_chunk, self.max_seq,
            self._prefill, self._prefill_chunk_mid,
            self._prefill_chunk_last, start=start)

    # -- block KV cache (runtime/kvcache) seams ------------------------

    def _kv_seed(self, ids: jnp.ndarray, cache: KVCache):
        """(start, cache): seed a fresh batch-1 cache from the longest
        cached block-prefix of the prompt, or (0, cache) on a miss —
        the backend seam (kvcache/backend.py) owns the layout-specific
        copy path (dense: host gather + H2D; paged: device gather,
        zero H2D)."""
        if self.kv_cache is None:
            return 0, cache
        return self.kv_cache.seed(ids, cache)

    def _kv_store(self, ids: jnp.ndarray, cache: KVCache) -> None:
        """Store the prefilled prompt's full blocks (batch 1 only).
        Must run before the decode scan donates the cache buffers."""
        if self.kv_cache is not None:
            self.kv_cache.store(ids, cache)

    def _decode(self, params, last_logits, cache, rng, eos, num_steps,
                with_logprobs=False):
        """Back-compat fused-decode surface (multimodal engine, bench
        long_context leg): the device loop with ``limit == num_steps``
        — same output contract as the old fixed-trip scan, now with
        all-rows-done early exit.  Returns ``(toks, lps, cache)``."""
        b = last_logits.shape[0]
        toks, lps, _, cache, _, _, steps = self._decode_loop(
            params, last_logits, cache, rng, eos, self._stop_ids,
            jnp.zeros((b,), bool), jnp.int32(num_steps), num_steps,
            with_logprobs)
        self._count_loop(int(steps))
        return toks, lps, cache

    def scrape_stats(self) -> dict:
        """Metrics-scrape fragment (telemetry/catalog.scrape): the KV
        cache counters, when the cache is on.  Deliberately NOT
        ``stats()`` — the /stats route keeps its engine-less shape."""
        return ({"kvcache": self.kv_cache.snapshot()}
                if self.kv_cache is not None else {})

    def debug_state(self) -> dict:
        """``GET /debugz`` fragment: KV cache occupancy/LRU picture +
        the device-loop dispatch accounting (§13 runbook)."""
        out = {"device_loop": dict(self.loop_stats,
                                   stream_block=self.stream_block),
               "observatory": _profiling.observatory_state()}
        if self.kv_cache is not None:
            out["kvcache"] = self.kv_cache.debug_state()
        return out

    def generate(self, prompt_ids: np.ndarray, max_new_tokens: int,
                 seed: int = 0, logprobs: bool = False) -> GenerationResult:
        """Batch generation, fused decode scan (the throughput path).

        Runs exactly once; ``seconds`` includes compile on the first call
        for a given shape signature (jit-cached afterwards).  Benchmarks
        wanting steady-state timing call this twice and keep the second
        result (see bench.py).  ``logprobs=True`` also returns each
        emitted token's raw log-softmax probability.
        """
        import time
        ids = jnp.asarray(prompt_ids, jnp.int32)
        b, plen = ids.shape
        self._check_capacity(plen, max_new_tokens)
        rng = jax.random.PRNGKey(seed)

        t0 = time.perf_counter()
        cache = self.new_cache(b)
        start, cache = self._kv_seed(ids, cache)
        last_logits, cache = self._run_prefill(ids, cache, start=start)
        self._kv_store(ids, cache)
        _sig = _profiling.dispatch_signature(
            "decode_loop", batch=b, chunk=max_new_tokens,
            kv_dtype=np.dtype(self.kv_cache_dtype).name)
        _t0 = self._prof.begin(_sig)
        toks, lps, _, _, _, _, steps = self._decode_loop(
            self.params, last_logits, cache, rng, self._eos_scalar(),
            self._stop_ids, jnp.zeros((b,), bool),
            jnp.int32(max_new_tokens), max_new_tokens, logprobs)
        toks = np.asarray(toks)
        steps = int(steps)
        if _t0 is not None:
            # the asarray above already synced; every device step reads
            # each row's prompt history and writes one token
            self._prof.end(_sig, _t0, hbm_bytes=(
                b * (plen + 1) * steps * self._kv_token_bytes))
        self._count_loop(steps)
        lps_np = np.asarray(lps) if logprobs else None
        dt = time.perf_counter() - t0
        result = GenerationResult(tokens=toks, prompt_len=plen,
                                  num_new=max_new_tokens, seconds=dt,
                                  logprobs=lps_np, steps_computed=steps)
        rl = get_run_log()
        if rl.enabled:   # per-request summary in the structured run log
            rl.event("generate", engine=type(self).__name__,
                     batch=b, prompt_len=plen,
                     new_tokens=max_new_tokens,
                     seconds=round(dt, 6),
                     tokens_per_sec=round(result.tokens_per_second, 2))
        get_flight_recorder().record(
            "engine_generate", engine=type(self).__name__, batch=b,
            prompt_len=plen, new_tokens=max_new_tokens,
            seconds=round(dt, 6))
        return result

    def classify(self, prompt_ids: np.ndarray,
                 label_token_ids) -> np.ndarray:
        """Classify each row: argmax of the last-position logits restricted
        to ``label_token_ids`` (verbalizer tokens, one per class).  Returns
        [batch] int32 label indices.  The reference's classification
        variant (``inference.cpp:220-270``) as a single prefill."""
        ids = jnp.asarray(prompt_ids, jnp.int32)
        label_ids = np.asarray(label_token_ids, np.int64)
        if label_ids.ndim != 1 or label_ids.size < 2:
            raise ValueError("label_token_ids must be >= 2 token ids")
        if (label_ids < 0).any() or (label_ids >= self.cfg.vocab_size).any():
            raise ValueError(
                f"label_token_ids out of range [0, {self.cfg.vocab_size})")
        self._check_capacity(ids.shape[1], 0)
        cache = self.new_cache(ids.shape[0])
        logits, _ = self._run_prefill(ids, cache)
        sub = np.asarray(logits)[:, label_ids]
        pred = np.argmax(sub, axis=-1).astype(np.int32)
        rl = get_run_log()
        if rl.enabled:
            rl.event("classify", engine=type(self).__name__,
                     batch=int(ids.shape[0]),
                     prompt_len=int(ids.shape[1]),
                     num_labels=int(label_ids.size))
        get_flight_recorder().record(
            "engine_classify", engine=type(self).__name__,
            batch=int(ids.shape[0]), prompt_len=int(ids.shape[1]))
        return pred

    def generate_stream(self, prompt_ids: np.ndarray, max_new_tokens: int,
                        seed: int = 0,
                        logprobs: bool = False) -> Iterator[np.ndarray]:
        """Yield one [batch] token array per step (UI streaming path);
        with ``logprobs=True`` yields ([batch] tokens, [batch] logprobs)
        pairs instead.

        With ``stream_block`` K > 1 the per-token dispatch is replaced
        by the device loop: ONE dispatch produces up to K tokens
        (buffered host-side and yielded one step at a time, so the
        consumer surface is unchanged), the stream flushes early the
        moment the device reports all rows done, and the host never
        pays a dispatch for steps the loop skipped.  Greedy output is
        bit-identical to K=1 (pinned by tests)."""
        ids = jnp.asarray(prompt_ids, jnp.int32)
        b, plen = ids.shape
        self._check_capacity(plen, max_new_tokens)
        cache = self.new_cache(b)
        rng = jax.random.PRNGKey(seed)
        start, cache = self._kv_seed(ids, cache)
        logits, cache = self._run_prefill(ids, cache, start=start)
        self._kv_store(ids, cache)
        done = jnp.zeros((b,), bool)
        K = self.stream_block
        if K > 1:
            remaining = max_new_tokens
            _sig = _profiling.dispatch_signature(
                "decode_loop", batch=b, chunk=K,
                kv_dtype=np.dtype(self.kv_cache_dtype).name)
            while remaining > 0:
                _t0 = self._prof.begin(_sig)
                toks, lps, logits, cache, rng, done, steps = \
                    self._decode_loop(
                        self.params, logits, cache, rng,
                        self._eos_scalar(), self._stop_ids, done,
                        jnp.int32(min(K, remaining)), K, logprobs)
                steps = int(steps)
                if _t0 is not None:
                    # int(steps) above already synced the dispatch; rows
                    # entered at length plen + tokens already streamed
                    self._prof.end(_sig, _t0, hbm_bytes=(
                        b * (plen + max_new_tokens - remaining + 1)
                        * steps * self._kv_token_bytes))
                self._count_loop(steps)
                if steps == 0:      # all rows were already done on entry
                    return
                tok_np = np.asarray(toks)
                lp_np = np.asarray(lps) if logprobs else None
                for j in range(steps):
                    yield ((tok_np[:, j], lp_np[:, j]) if logprobs
                           else tok_np[:, j])
                remaining -= steps
                if bool(np.asarray(done).all()):
                    return
            return
        for _ in range(max_new_tokens):
            tok, lp, logits, cache, rng, done = self._decode_one(
                self.params, logits, cache, rng, self._eos_scalar(),
                self._stop_ids, done)
            self._count_loop(1)
            tok_np = np.asarray(tok)
            yield (tok_np, np.asarray(lp)) if logprobs else tok_np
            if ((self.eos_id is not None or self._has_stop_ids)
                    and np.asarray(done).all()):
                return
