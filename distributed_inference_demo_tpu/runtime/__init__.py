from .engine import InferenceEngine, GenerationResult

__all__ = ["InferenceEngine", "GenerationResult"]
