from .engine import InferenceEngine, GenerationResult
from .elastic import ElasticHeader, ElasticStageRuntime, ElasticWorker
from .speculative import SpeculativeEngine, SpecStats
from .batching import ContinuousBatchingEngine
from .kvcache import KVCacheManager

__all__ = ["InferenceEngine", "GenerationResult", "ElasticHeader",
           "ElasticStageRuntime", "ElasticWorker", "SpeculativeEngine",
           "SpecStats", "ContinuousBatchingEngine", "KVCacheManager"]
