from .engine import InferenceEngine, GenerationResult
from .elastic import ElasticHeader, ElasticStageRuntime, ElasticWorker

__all__ = ["InferenceEngine", "GenerationResult", "ElasticHeader",
           "ElasticStageRuntime", "ElasticWorker"]
