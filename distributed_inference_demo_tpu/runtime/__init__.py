from .engine import InferenceEngine, GenerationResult
from .elastic import ElasticHeader, ElasticStageRuntime, ElasticWorker
from .speculative import SpeculativeEngine, SpecStats
from .batching import ContinuousBatchingEngine

__all__ = ["InferenceEngine", "GenerationResult", "ElasticHeader",
           "ElasticStageRuntime", "ElasticWorker", "SpeculativeEngine",
           "SpecStats", "ContinuousBatchingEngine"]
