"""Speculative decoding: a draft model proposes, the target verifies.

Decode is HBM-bandwidth-bound — every step streams all target weights for
one token's worth of MXU work (see bench.py roofline legs).  Speculative
decoding converts that stream into several tokens: a small DRAFT model
autoregressively proposes ``num_draft`` tokens (cheap — its weights are a
fraction of the target's), then the TARGET verifies all of them in ONE
prefill-shaped forward ([batch, K+1] positions — the MXU-friendly shape),
and the standard rejection rule keeps a prefix that is distributed exactly
as target-only sampling (Leviathan et al., 2023; PAPERS.md).

Everything per round is ONE compiled program (`_rounds`): draft scan →
target verify → accept/resample → cache rollback, with ``R`` rounds fused
in a ``lax.scan`` so one dispatch yields up to ``R*(K+1)`` tokens — on the
tunneled bench device a dispatch costs ~10 ms, so fusing rounds matters as
much as the algorithm.

TPU-first design points (vs the CUDA/torch implementations of this idea):

- **Static shapes throughout**: every round emits a fixed ``[b, K+1]``
  token block plus a count; the host trims.  No dynamic-length tensors,
  no recompiles.
- **Cache rollback is a length reset.**  ``KVCache.length`` is a traced
  scalar; rejected tokens' KV simply stays as stale slots ABOVE the valid
  length.  The causal mask (kv_pos <= q_pos) guarantees a stale slot is
  never attended before the next round overwrites it — no scatter, no
  copy.
- **Batch rows advance in lockstep** by ``m = min_b(accepted_b + 1)``
  (the per-round emit count must be one scalar for static shapes).  Each
  row's kept prefix is its own exactly-distributed sample; rows that
  accepted more than ``m`` tokens just re-propose them next round, so
  batch skew costs throughput, never correctness.  The reference has no
  analog (one token per ring trip, ``Communication.java:682-928``); this
  is a pure capability add on top of engine.py's fused decode.

The draft and target must share a vocabulary (checked).  Greedy mode
(``SamplingParams(greedy=True)``) verifies by argmax equality and is
bit-exact vs target-only greedy decode — the property the tests pin.
"""

import threading
import time
from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.base import KVCache, ModelConfig, StageParams, StageSpec
from ..models.decoder import stage_forward
from ..ops.flash_attention import make_flash_attn_impl
from ..ops.sampling import SamplingParams, filtered_logits, sample_logits
from .engine import GenerationResult, check_capacity


@dataclass
class SpecStats:
    """Acceptance diagnostics for one generate() call."""
    rounds: int = 0
    drafted: int = 0            # num_draft * rounds
    accepted: int = 0           # draft tokens accepted (excl. bonus/resample)
    emitted: int = 0            # tokens actually kept (after min + trim)

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.drafted if self.drafted else float("nan")

    @property
    def tokens_per_round(self) -> float:
        return self.emitted / self.rounds if self.rounds else float("nan")


def accept_and_extra(t_logits, drafts, q_logits, samp: SamplingParams,
                     sub_u, sub_x, k_cap=None):
    """The speculative accept/resample rule, shared by every proposer
    (draft model, prompt lookup) and every advance policy (lockstep,
    per-row).

    t_logits: [b, K+1, V] target logits over [last_tok, d_1..d_K];
    drafts:   [b, K] proposals;
    q_logits: [b, K, V] proposer's filtered logits, or None for a
              DETERMINISTIC proposer (one-hot q: accept d with prob p(d),
              resample from p with d masked out).
    k_cap:    [b] int32 per-row draft-length cap in [1, K], or None for
              the full width.  Proposals at positions >= k_cap[i] are
              never accepted — the adaptive-draft-length seam
              (docs/DESIGN.md §22): a capped row behaves exactly as if
              only its first k_cap proposals existed.  A TRUNCATION at
              k_cap (< K, with the capped proposal otherwise live) is
              not a rejection: the follow-up token samples from the
              target's own distribution at that position, not the
              residual.  Rng spend is identical either way, so capped
              and uncapped schedules stay split-for-split comparable.

    Returns (a [b] accepted-draft counts in [0, K], extra [b]: the
    rejection-point resample, or the bonus token after K accepts).
    """
    b, K = drafts.shape
    if samp.greedy:
        t_arg = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
        accept = drafts == t_arg[:, :K]                # [b, K] bool
        if k_cap is not None:
            accept = accept & (jnp.arange(K)[None, :] < k_cap[:, None])
        a = jnp.sum(jnp.cumprod(accept, axis=1), axis=1)   # [b] in [0, K]
        # rejected at a -> the target's own argmax; all accepted -> bonus
        # argmax after d_K.  Both are t_arg[:, a].  A k_cap truncation is
        # also t_arg[:, a] — the token greedy decode would emit there.
        extra = jnp.take_along_axis(t_arg, a[:, None], axis=1)[:, 0]
    else:
        p_logits = filtered_logits(t_logits, samp)     # [b, K+1, V]
        p = jax.nn.softmax(p_logits[:, :K], axis=-1)
        p_d = jnp.take_along_axis(
            p, drafts[..., None], axis=-1)[..., 0]     # [b, K]
        u = jax.random.uniform(sub_u, p_d.shape)
        if q_logits is None:
            accept = u < p_d
        else:
            q = jax.nn.softmax(q_logits, axis=-1)
            q_d = jnp.take_along_axis(
                q, drafts[..., None], axis=-1)[..., 0]
            accept = u * jnp.maximum(q_d, 1e-20) < p_d
        if k_cap is not None:
            accept = accept & (jnp.arange(K)[None, :] < k_cap[:, None])
        a = jnp.sum(jnp.cumprod(accept, axis=1), axis=1)
        # resample dist at the rejection point: norm(max(p - q, 0)); for a
        # one-hot q that is p with the draft token masked out
        a_idx = jnp.minimum(a, K - 1)[:, None, None]
        p_a = jnp.take_along_axis(p, a_idx, axis=1)[:, 0]  # [b, V]
        if q_logits is None:
            d_a = jnp.take_along_axis(
                drafts, jnp.minimum(a, K - 1)[:, None], axis=1)
            resid_a = p_a.at[jnp.arange(b)[:, None], d_a].set(0.0)
        else:
            resid = jnp.maximum(p - jax.nn.softmax(q_logits, -1), 0.0)
            resid_a = jnp.take_along_axis(resid, a_idx, axis=1)[:, 0]
        # all-zero residual (p == q exactly / point mass on d): fall back
        # to p_a — accept/resample then reduces to plain sampling from p
        resid_sum = jnp.sum(resid_a, axis=-1, keepdims=True)
        resid_a = jnp.where(resid_sum > 0, resid_a, p_a)
        if k_cap is not None:
            # truncated at k_cap < K with every eligible proposal
            # accepted: the position-a proposal was never offered, so
            # the correct follow-up is a plain sample from p there
            trunc = (a == k_cap) & (k_cap < K)
            resid_a = jnp.where(trunc[:, None], p_a, resid_a)
        bonus = jax.nn.softmax(p_logits[:, K], axis=-1)
        extra_probs = jnp.where((a == K)[:, None], bonus, resid_a)
        extra = jax.random.categorical(
            sub_x, jnp.log(extra_probs + 1e-30), axis=-1).astype(jnp.int32)
    return a, extra


def assemble_emitted(drafts, a, extra):
    """[b, K+1] emitted block from per-row accept counts: row i is
    [d_1..d_{a_i}, extra_i, 0...] — each row its own exactly-distributed
    sample."""
    K = drafts.shape[1]
    idx = jnp.arange(K + 1)[None, :]
    drafts_pad = jnp.pad(drafts, ((0, 0), (0, 1)))
    return jnp.where(idx < a[:, None], drafts_pad,
                     jnp.where(idx == a[:, None], extra[:, None], 0))


def verify_emit(t_logits, drafts, q_logits, samp: SamplingParams,
                sub_u, sub_x):
    """Accept/resample + emitted-block assembly with LOCKSTEP advance:
    all rows move by ``m = min_b(a_b) + 1`` (one scalar keeps the
    single-cache engines' shapes static; rows that accepted more
    re-propose next round).

    Returns (emitted [b, K+1], m scalar in [1, K+1], new_last [b]).
    """
    b = drafts.shape[0]
    a, extra = accept_and_extra(t_logits, drafts, q_logits, samp,
                                sub_u, sub_x)
    emitted = assemble_emitted(drafts, a, extra)
    m = jnp.min(a) + 1                                 # scalar, [1, K+1]
    new_last = jnp.take_along_axis(
        emitted, (m - 1)[None, None].astype(jnp.int32).repeat(b, axis=0),
        axis=1)[:, 0]
    return emitted, m, new_last


def verify_emit_per_row(t_logits, drafts, q_logits, samp: SamplingParams,
                        sub_u, sub_x, k_cap=None):
    """Accept/resample + assembly with PER-ROW advance: row i moves by
    ``n_i = a_i + 1`` — no lockstep minimum, no wasted acceptances.  The
    policy for engines whose cache positions are already per-row (the
    continuous-batching slot cache); the follow-up token is always the
    row's ``extra``.  ``k_cap`` ([b] or None): per-row draft-length cap
    (see :func:`accept_and_extra`).

    Returns (emitted [b, K+1], n [b] in [1, K+1], new_last [b]).
    """
    a, extra = accept_and_extra(t_logits, drafts, q_logits, samp,
                                sub_u, sub_x, k_cap=k_cap)
    return assemble_emitted(drafts, a, extra), a + 1, extra


def mask_after_eos(toks: np.ndarray, eos_id: Optional[int]) -> np.ndarray:
    """Rows keep emitting ``eos_id`` after their first eos — the fused
    decode scan's row-padding semantics (engine.py ``_mask_eos``), applied
    host-side to a speculative run's assembled [b, T] block."""
    if eos_id is None:
        return toks
    hit = toks == eos_id
    after = (np.cumsum(hit, axis=1) - hit) > 0
    toks = toks.copy()
    toks[after] = eos_id
    return toks


def init_done(first: np.ndarray, eos_id: Optional[int]) -> np.ndarray:
    """[b] done mask seeded from the prefill-sampled first token — the
    one definition shared by every speculative generate/stream path."""
    return (first == eos_id if eos_id is not None
            else np.zeros(first.shape, bool))


def pad_to_width(toks: np.ndarray, max_new: int,
                 eos_id: Optional[int]) -> np.ndarray:
    """Pad an early-eos-stopped [b, T] block out to the fused scan's
    full [b, max_new] shape.  Only reachable when every row already
    emitted eos (the generate loops can't stop early otherwise), so the
    pad is all-eos."""
    b, t = toks.shape
    if t < max_new:
        toks = np.concatenate(
            [toks, np.full((b, max_new - t), eos_id, toks.dtype)], axis=1)
    return toks


def emit_stream_block(block, m, done, total, max_new, eos_id,
                      stats: SpecStats):
    """Mask and hand out one verify round's [b, m] token block for the
    streaming surface: finished rows keep emitting eos (the streamed twin
    of the fused scan's _mask_eos padding, like
    InferenceEngine.generate_stream), ``done`` ([b] bool) updates in
    place, ``stats.emitted`` advances per token.  Yields
    ``(tok, all_done)`` pairs; the caller yields ``tok`` outward and
    returns on ``all_done``.  Shared by every speculative engine."""
    for j in range(min(m, max_new - total)):
        tok = block[:, j].copy()
        if eos_id is not None:
            tok[done] = eos_id
        stats.emitted = total + j + 1
        all_done = False
        if eos_id is not None:
            np.logical_or(done, tok == eos_id, out=done)
            all_done = bool(done.all())
        yield tok, all_done


def drain_round_blocks(em, ms, out, stats: SpecStats, num_draft: int,
                       total: int, max_new: int, eos_id: Optional[int] = None,
                       done: Optional[np.ndarray] = None) -> int:
    """Host-side collection of a fused dispatch's round blocks into
    ``out``/``stats``; returns the updated emitted-token total.  With
    ``eos_id``/``done`` given, ORs each block's eos hits into ``done``
    row-wise (the generate loops' incremental early-stop mask).  Shared
    by every speculative engine's generate loop."""
    for r in range(em.shape[0]):
        m = int(ms[r])
        block = em[r][:, :m]
        out.append(block)
        if eos_id is not None and done is not None:
            np.logical_or(done, (block == eos_id).any(axis=1), out=done)
        stats.rounds += 1
        stats.drafted += num_draft
        stats.accepted += m - 1   # lockstep: min_b(accepted) used
        total += m
        if total >= max_new:
            break
    return total


class SpeculativeEngine:
    """Draft/verify generation over two full single-stage models."""

    def __init__(self, cfg: ModelConfig, params: StageParams,
                 draft_cfg: ModelConfig, draft_params: StageParams,
                 max_seq: Optional[int] = None,
                 sampling: SamplingParams = SamplingParams(),
                 num_draft: int = 4,
                 attn_backend: str = "auto",
                 mesh=None,
                 eos_id: Optional[int] = None,
                 kv_cache_dtype=None,
                 prefill_chunk: Optional[int] = None,
                 kv_cache_blocks: Optional[int] = None,
                 kv_block_tokens: Optional[int] = None,
                 kv_layout: Optional[str] = None,
                 kv_dtype: Optional[str] = None):
        """``kv_cache_dtype``: reduced-precision storage for BOTH the
        target and draft caches (same contract as InferenceEngine /
        ContinuousBatchingEngine: insert rounds via update_kv_cache's
        cast, attention upcasts to f32, the jnp attention path is
        forced) — greedy output matches a plain engine with the same
        cache dtype bit-exactly.

        ``prefill_chunk``: bound prefill activation memory on long
        prompts by running BOTH models' prefill in fixed C-token chunks
        (engine.run_chunked_prefill, once per model; the draft's final
        chunk needs no logits).  Same semantics as InferenceEngine's.

        ``kv_cache_blocks`` / ``kv_block_tokens``: block-level KV prefix
        cache (``runtime/kvcache``) on the TARGET side, batch 1: a hit
        seeds the target cache from stored blocks and prefills only the
        suffix; the draft always prefills its full prompt (it is cheap
        by construction, and only the target's logits gate emission, so
        reuse exactness is a target-side property).  Default off; env
        ``DWT_KVCACHE_*`` knobs apply as in InferenceEngine.

        ``kv_layout``: layout of the target-side prefix pool behind the
        backend seam (docs/DESIGN.md §14) — "paged" (default) keeps it
        device-resident, so two speculative requests sharing a prompt
        prefix reference the SAME pages in HBM (the accepted prefix is
        never duplicated; pinned by the ownership tests) and hits move
        zero bytes through the host; it is the ONLY layout ("dense"
        was removed — docs/DESIGN.md §14)."""
        if cfg.vocab_size != draft_cfg.vocab_size:
            raise ValueError(
                f"draft vocab ({draft_cfg.vocab_size}) != target vocab "
                f"({cfg.vocab_size}); speculative decoding needs a shared "
                "token space")
        if num_draft < 1:
            raise ValueError("num_draft must be >= 1")
        from .kvcache import resolve_kv_layout
        self.kv_layout = resolve_kv_layout(kv_layout)
        self.cfg, self.params = cfg, params
        self.draft_cfg, self.draft_params = draft_cfg, draft_params
        self.max_seq = max_seq or cfg.max_seq_len
        self.sampling = sampling
        self.num_draft = num_draft
        self.eos_id = eos_id
        from .engine import validate_prefill_chunk
        self.prefill_chunk = validate_prefill_chunk(prefill_chunk,
                                                    self.max_seq)
        self.spec = StageSpec(0, 1, 0, cfg.num_layers)
        self.draft_spec = StageSpec(0, 1, 0, draft_cfg.num_layers)
        self.mesh = mesh

        from ..parallel.tensor import resolve_tp_attn_backend
        from .engine import resolve_cache_dtype_backend
        tp = mesh.shape.get("tp", 1) if mesh is not None else 1
        attn_backend = resolve_tp_attn_backend(tp, attn_backend)
        self.kv_cache_dtype, attn_backend = resolve_cache_dtype_backend(
            kv_cache_dtype, attn_backend)
        if attn_backend == "auto":
            attn_backend = ("flash" if jax.default_backend() == "tpu"
                            else "jnp")
        attn_impl = (make_flash_attn_impl() if attn_backend == "flash"
                     else None)

        cfg_, spec_ = cfg, self.spec
        dcfg_, dspec_ = draft_cfg, self.draft_spec
        samp_, K = sampling, num_draft

        # BOTH models build on the shared seam over the same tp axis
        # (the draft must also satisfy the kv-head divisibility check)
        from ..parallel.tensor import make_forward_seam
        fwd_t, self._cache_sharding = make_forward_seam(
            cfg, self.spec, mesh, params, attn_impl=attn_impl)
        fwd_d, _ = make_forward_seam(
            draft_cfg, self.draft_spec, mesh, draft_params,
            attn_impl=attn_impl)

        @jax.jit
        def prefill_both(tparams, dparams, ids, tcache, dcache):
            b, s = ids.shape
            pos = jnp.broadcast_to(jnp.arange(s), (b, s))
            t_logits, tcache = fwd_t(tparams, ids, tcache, pos, True)
            _, dcache = fwd_d(dparams, ids, dcache, pos, True)
            return t_logits[:, -1], tcache, dcache

        # chunked-prefill programs (engine.run_chunked_prefill drives
        # them; one mid+last pair for the target, mid-only for the
        # draft — its final chunk needs no logits).  Shared factory with
        # InferenceEngine so the programs cannot drift.
        from .engine import make_chunk_programs
        self._t_chunk_mid, self._t_chunk_last = make_chunk_programs(fwd_t)
        self._d_chunk_mid, _ = make_chunk_programs(fwd_d)

        from .kvcache import make_kv_backend
        self.kv_cache = make_kv_backend(
            cfg, kv_cache_blocks, kv_block_tokens, layout=self.kv_layout,
            dtype=self.kv_cache_dtype, kv_dtype=kv_dtype,
            default_blocks=0)

        def one_round(tparams, dparams, last_tok, tcache, dcache, rng):
            """Draft K, verify K+1 in one target forward, accept/resample.

            Returns (emitted [b, K+1], m scalar, accepted [b], new state).
            ``last_tok`` sits at position tcache.length and is not yet in
            either cache.
            """
            b = last_tok.shape[0]
            n = tcache.length

            # --- draft phase: K+1 autoregressive steps --------------------
            # K proposals, plus ONE extra step whose proposal is discarded:
            # the extra step exists to insert d_K's KV into the draft cache
            # (the scan inserts each step's INPUT token), so that after an
            # all-accept round (m = K+1) the rolled-forward draft cache is
            # fully populated — without it, position n+K would be a stale
            # zero slot that silently derails the next round's first draft.
            def dstep(carry, _):
                tok, dc, rng = carry
                pos = jnp.broadcast_to(dc.length, (b, 1))
                logits, dc = fwd_d(dparams, tok[:, None], dc, pos, True)
                logits = logits[:, 0]
                rng, sub = jax.random.split(rng)
                if samp_.greedy:
                    d = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    q = logits  # unused in greedy verify
                else:
                    q = filtered_logits(logits, samp_)
                    d = jax.random.categorical(sub, q, axis=-1)
                    d = d.astype(jnp.int32)
                return (d, dc, rng), (d, q)

            (_, dcache, rng), (drafts, q_logits) = jax.lax.scan(
                dstep, (last_tok, dcache, rng), None, length=K + 1)
            drafts = drafts[:K].T                  # [b, K]
            q_logits = jnp.swapaxes(q_logits[:K], 0, 1)  # [b, K, V]

            # --- target verify: ONE forward over K+1 tokens ---------------
            verify_in = jnp.concatenate([last_tok[:, None], drafts], axis=1)
            pos = n + jnp.broadcast_to(jnp.arange(K + 1), (b, K + 1))
            t_logits, tcache = fwd_t(tparams, verify_in, tcache, pos,
                                     False)        # [b, K+1, V]

            # --- accept / resample / lockstep advance (shared rule) -------
            rng, sub_u, sub_x = jax.random.split(rng, 3)
            emitted, m, new_last = verify_emit(
                t_logits, drafts, None if samp_.greedy else q_logits,
                samp_, sub_u, sub_x)

            # --- cache rollback -------------------------------------------
            tcache = KVCache(tcache.keys, tcache.values, n + m)
            dcache = KVCache(dcache.keys, dcache.values, n + m)
            return emitted, m, new_last, tcache, dcache, rng

        @partial(jax.jit, donate_argnums=(3, 4), static_argnums=(6,))
        def rounds(tparams, dparams, last_tok, tcache, dcache, rng,
                   num_rounds):
            def body(carry, _):
                last_tok, tc, dc, rng = carry
                emitted, m, last_tok, tc, dc, rng = one_round(
                    tparams, dparams, last_tok, tc, dc, rng)
                return (last_tok, tc, dc, rng), (emitted, m)

            (last_tok, tcache, dcache, rng), (em, ms) = jax.lax.scan(
                body, (last_tok, tcache, dcache, rng), None,
                length=num_rounds)
            return em, ms, last_tok, tcache, dcache, rng

        self._prefill_both = prefill_both
        self._rounds = rounds

    # ------------------------------------------------------------------

    def new_caches(self, batch: int):
        # +num_draft+1 slack: a round may write K+1 positions past the
        # valid length before the rollback trims it (KVCache.create pads
        # the buffer to the sublane granule on top)
        cap = self.max_seq + self.num_draft + 1
        tc = KVCache.create(self.cfg, self.cfg.num_layers, batch, cap,
                            dtype=self.kv_cache_dtype)
        dc = KVCache.create(self.draft_cfg, self.draft_cfg.num_layers,
                            batch, cap, dtype=self.kv_cache_dtype)
        if self._cache_sharding is not None:
            tc = jax.device_put(tc, self._cache_sharding)
            dc = jax.device_put(dc, self._cache_sharding)
        return tc, dc

    def _run_prefill_both(self, ids, tcache, dcache):
        """(last_target_logits, tcache, dcache) — whole-prompt in one
        fused program, or chunked per model (engine.run_chunked_prefill
        semantics: zero-pad, aligned last window, length rewind)."""
        C = self.prefill_chunk
        if C is None:
            return self._prefill_both(self.params, self.draft_params,
                                      ids, tcache, dcache)
        from .engine import run_chunked_prefill
        last, tcache = run_chunked_prefill(
            self.params, ids, tcache, C, self.max_seq,
            self._t_chunk_mid, self._t_chunk_last)
        _, dcache = run_chunked_prefill(
            self.draft_params, ids, dcache, C, self.max_seq,
            self._d_chunk_mid)
        return last, tcache, dcache

    def _run_prefills(self, ids, tcache, dcache):
        """The KV-cache-aware prefill front end: on a target-side block
        hit (batch 1), seed the target cache and prefill only its
        suffix while the draft prefills the full prompt; otherwise the
        fused/chunked both-model path.  Stores the target's full blocks
        afterwards — before the rounds program donates the cache."""
        from .engine import run_chunked_prefill
        b, plen = ids.shape
        start = 0
        if self.kv_cache is not None:
            start, tcache = self.kv_cache.seed(ids, tcache)
        if start:
            C = self.prefill_chunk
            suffix = ids[:, start:]
            if C is not None:
                last, tcache = run_chunked_prefill(
                    self.params, suffix, tcache, C, self.max_seq,
                    self._t_chunk_mid, self._t_chunk_last, start=start)
            else:
                last, tcache = self._t_chunk_last(
                    self.params, suffix, tcache, jnp.int32(start),
                    jnp.int32(suffix.shape[1] - 1))
                tcache = KVCache(tcache.keys, tcache.values,
                                 jnp.int32(plen))
            # draft side: always the full prompt (one logits-free
            # dispatch, or its own chunked drive)
            _, dcache = run_chunked_prefill(
                self.draft_params, ids, dcache, C if C else plen,
                self.max_seq, self._d_chunk_mid)
        else:
            last, tcache, dcache = self._run_prefill_both(ids, tcache,
                                                          dcache)
        if self.kv_cache is not None:
            self.kv_cache.store(ids, tcache)
        return last, tcache, dcache

    def generate(self, prompt_ids: np.ndarray, max_new_tokens: int,
                 seed: int = 0,
                 rounds_per_dispatch: Optional[int] = None
                 ) -> "tuple[GenerationResult, SpecStats]":
        """Generate with draft/verify rounds; returns (result, stats).

        ``rounds_per_dispatch``: how many rounds to fuse per device call
        (default 8, capped by the rounds max_new_tokens could possibly
        need — overshoot is trimmed; each extra round costs one wasted
        draft block, each missing round costs a full dispatch, and on the
        tunneled bench device a dispatch is ~10 ms).
        """
        ids = jnp.asarray(prompt_ids, jnp.int32)
        b, plen = ids.shape
        check_capacity(self.max_seq, plen, max_new_tokens)
        R = rounds_per_dispatch or min(8, max(1, max_new_tokens))
        rng = jax.random.PRNGKey(seed)

        t0 = time.perf_counter()
        tcache, dcache = self.new_caches(b)
        last_logits, tcache, dcache = self._run_prefills(
            ids, tcache, dcache)
        # first token comes from the target's prefill logits (the draft
        # never gets to choose a token unchecked)
        rng, sub = jax.random.split(rng)
        last_tok = sample_logits(last_logits, sub, self.sampling)

        stats = SpecStats()
        first = np.asarray(last_tok)
        out = [first[:, None]]
        done = init_done(first, self.eos_id)
        total = 1
        while total < max_new_tokens and not done.all():
            em, ms, last_tok, tcache, dcache, rng = self._rounds(
                self.params, self.draft_params, last_tok, tcache, dcache,
                rng, R)
            total = drain_round_blocks(np.asarray(em), np.asarray(ms), out,
                                       stats, self.num_draft, total,
                                       max_new_tokens, self.eos_id, done)

        toks = np.concatenate(out, axis=1)[:, :max_new_tokens]
        toks = mask_after_eos(pad_to_width(toks, max_new_tokens,
                                           self.eos_id), self.eos_id)
        dt = time.perf_counter() - t0
        # actual emitted count, not the eos-padded width (keeps
        # tokens_per_round honest and matches the stream path)
        stats.emitted = min(total, max_new_tokens)
        return (GenerationResult(tokens=toks.astype(np.int32),
                                 prompt_len=plen,
                                 num_new=toks.shape[1], seconds=dt),
                stats)

    def generate_stream(self, prompt_ids: np.ndarray, max_new_tokens: int,
                        seed: int = 0,
                        stats_out: Optional[SpecStats] = None):
        """Yield [batch] token arrays per emitted token (UI streaming
        surface).  Tokens arrive in bursts — one verify round emits up to
        num_draft+1 at once — which is exactly speculative decoding's
        latency win showing through the stream.  ``stats_out``, if given,
        is updated in place per round (a generator can't return stats)."""
        if max_new_tokens <= 0:
            return
        ids = jnp.asarray(prompt_ids, jnp.int32)
        b, plen = ids.shape
        check_capacity(self.max_seq, plen, max_new_tokens)
        rng = jax.random.PRNGKey(seed)
        stats = stats_out if stats_out is not None else SpecStats()

        tcache, dcache = self.new_caches(b)
        last_logits, tcache, dcache = self._run_prefills(
            ids, tcache, dcache)
        rng, sub = jax.random.split(rng)
        last_tok = sample_logits(last_logits, sub, self.sampling)
        first = np.asarray(last_tok)
        yield first
        done = init_done(first, self.eos_id)
        total = stats.emitted = 1
        while total < max_new_tokens and not done.all():
            em, ms, last_tok, tcache, dcache, rng = self._rounds(
                self.params, self.draft_params, last_tok, tcache, dcache,
                rng, 1)
            m = int(np.asarray(ms)[0])
            block = np.asarray(em)[0]
            stats.rounds += 1
            stats.drafted += self.num_draft
            stats.accepted += m - 1
            for tok, all_done in emit_stream_block(
                    block, m, done, total, max_new_tokens, self.eos_id,
                    stats):
                yield tok
                if all_done:
                    return
            total += m
            stats.emitted = min(total, max_new_tokens)


def stats_json(stats: Optional[SpecStats], num_draft: int) -> Optional[dict]:
    """SpecStats → JSON-safe dict (0 rounds yields NaN rates; JSON has no
    NaN).  The one shaping shared by the CLI and the HTTP backend."""
    if stats is None:
        return None

    def finite(x, nd):
        return round(x, nd) if x == x else None

    return {"num_draft": num_draft,
            "rounds": stats.rounds,
            "acceptance_rate": finite(stats.acceptance_rate, 4),
            "tokens_per_round": finite(stats.tokens_per_round, 3)}


class SpeculativeBackend:
    """Adapts SpeculativeEngine to the HTTP backend surface (engine-style
    ``generate`` returning a result object, plus acceptance stats on
    ``/stats``).  Follows HeaderBackend's streaming discipline
    (http_server.py): the device runs on a worker thread that holds the
    lock only at device pace, tokens cross to the client-paced generator
    over a queue — a stalled client can't wedge the server."""

    def __init__(self, engine: SpeculativeEngine):
        self.engine = engine
        self.max_seq = engine.max_seq
        self.last_stats: Optional[SpecStats] = None
        self._lock = threading.Lock()   # one generation at a time

    def generate(self, prompt_ids, max_new_tokens: int, seed: int = 0):
        with self._lock:
            res, stats = self.engine.generate(prompt_ids, max_new_tokens,
                                              seed=seed)
            self.last_stats = stats
        return res

    def generate_stream(self, prompt_ids, max_new_tokens: int,
                        seed: int = 0):
        import queue as queue_mod

        q: "queue_mod.Queue" = queue_mod.Queue()
        SENTINEL = object()
        stats = SpecStats()

        def run():
            try:
                with self._lock:
                    for toks in self.engine.generate_stream(
                            prompt_ids, max_new_tokens, seed=seed,
                            stats_out=stats):
                        q.put(toks)
                    self.last_stats = stats
            except BaseException as e:     # surface in the consumer
                q.put(e)
            finally:
                q.put(SENTINEL)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is SENTINEL:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
        t.join(timeout=10)

    def stats(self) -> dict:
        with self._lock:
            return {"speculative": stats_json(self.last_stats,
                                              self.engine.num_draft)}
