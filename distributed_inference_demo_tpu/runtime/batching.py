"""Continuous batching: requests join and leave a running decode batch.

The plain :class:`InferenceEngine` serves one ``generate()`` at a time; under
concurrent load each request waits for the whole previous batch.  Serving
systems want *continuous* (in-flight) batching: a fixed pool of batch SLOTS
decodes in lockstep, and a new request is admitted into a free slot between
two decode steps — it never waits for the others to finish, and the chip
always steps the full batch.  The reference's closest concept is
``core_pool_size`` samples in flight over socket sets
(``Communication.java:425-437``); this is that idea rebuilt for a single
accelerator where batching, not sockets, is the concurrency mechanism.

TPU-first design:

- **One compiled step, static shapes.**  Every decode step runs the full
  ``[max_batch]`` slot array through one donated-pool jit; an ``active``
  mask keeps finished/empty slots harmless (their writes land on their own
  stale positions or drop through sentinel tables — see below).
  Admission never recompiles the step.
- **PAGED-NATIVE slot cache** (docs/DESIGN.md §11/§14): K/V live in one
  device-resident page pool ``[L, num_blocks, H, block_tokens, D]``
  addressed through per-slot block tables — HBM is reserved per page a
  request actually holds, never ``B x max_seq`` worst-case rows, and
  radix prefix hits are shared block-table entries (zero copies of any
  kind).  Every slot mode rides the pool: plain decode, the speculative
  proposers (the draft model pages its own scratch pool), tp meshes
  (the pool shards by kv head).  The dense batch cache is deleted.
- **Per-slot cache positions, no per-slot programs.**  Each slot fills
  its pages from position 0 independently.  The attention mask is
  per-row (``kv_pos <= q_position`` — ops/attention.py), so ragged slot
  lengths need no extra masking; writes scatter at
  ``(table[p // bt], p % bt)`` (ops/paged_attention.py).
- **Admission = PAGED prefill straight into the pool.**  The prompt is
  padded to a small set of bucket lengths (one compile per bucket,
  reused) and forwarded through the same block-table seam decode uses
  (ops/paged_attention.paged_prefill_attention): each chunk's K/V
  scatters directly into the request's reserved pages and its queries
  attend causally over the prior pages plus the in-chunk keys.  Matched
  prefix pages are shared table entries — no temp row, no
  gather/scatter round trip, zero H2D.  Under a token budget
  (``mixed_token_budget``) admission chunks ride INSIDE the decode
  dispatch: one jitted program packs every active row's fused decode
  tokens plus prefill chunk segments from one or more admitting
  prompts, so batch-mates never lose their decode fusion while a
  prompt streams in (Orca/Sarathi-style stall-free mixed batching).
- **Stale-slot safety** is the same invariant speculative decoding relies
  on: garbage KV only ever sits at positions >= a row's valid length, a
  query at position p attends only kv_pos <= p, and position p is always
  rewritten before any query reaches it.  Freed slots additionally route
  writes through sentinel table entries, which drop them.

Per-request ``seed`` is not honored (slots share one RNG stream — the
batch's sampling order depends on who else is in flight); the engine-level
seed makes single-request runs reproducible, and greedy decoding is
bit-exact vs InferenceEngine (pinned by tests).
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.base import (KVCache, ModelConfig, StageParams,
                           StageSpec, pad_cache_capacity)
from ..ops.sampling import SamplingParams, filtered_logits, sample_logits
from ..telemetry import postmortem
from ..telemetry import profiling as _profiling
from ..telemetry.anomaly import AnomalyMonitor
from ..telemetry.flightrecorder import get_flight_recorder
from ..telemetry.slo import get_slo_ledger, sanitize_tenant
from ..telemetry.tracing import TraceRecorder, to_chrome_trace
from .engine import (GenerationResult, check_capacity,
                     make_paged_chunk_programs, validate_prefill_chunk)
from .speculative import verify_emit_per_row


def slot_attention_impl(q, k, v, k_cache, v_cache, positions, cache_start,
                        slopes):
    """Attention hook for ragged per-slot cache offsets.

    Ignores the scalar ``cache_start``; ``positions`` [b, s] carries each
    row's true insert offsets.  K/V land via advanced-index scatter (the
    two index arrays broadcast to [b, s] and the indexed result layout
    [b, s, nkv, hd] is exactly the projection layout ``k``/``v`` arrive
    in).  The mask side needs nothing: ``attention`` already bounds each
    row by its own q positions.
    """
    from ..ops.attention import attention
    b, s = positions.shape
    rows = jnp.arange(b)[:, None]
    k_cache = k_cache.at[rows, :, positions].set(k.astype(k_cache.dtype))
    v_cache = v_cache.at[rows, :, positions].set(v.astype(v_cache.dtype))
    max_seq = k_cache.shape[2]
    out = attention(q, k_cache, v_cache, positions,
                    jnp.asarray(max_seq, jnp.int32), slopes)
    return out, k_cache, v_cache


class _BlocksExhausted(Exception):
    """Paged admission could not allocate its pages (pool pressure with
    every evictable block pinned by in-flight tables): the request goes
    back to pending and retries when a completion frees pages — the
    paged twin of 'no free slot', never a request failure."""


# queue sentinel that wakes an idle scheduler without enqueueing work
# (export_request posts it so a checkpoint never waits on the blocking
# get of a truly idle loop)
_WAKE = object()


@dataclass
class Request:
    """One in-flight generation request (row-level)."""
    prompt: np.ndarray                 # [s] int32
    max_new: int
    tokens: List[int] = field(default_factory=list)
    lps: List[float] = field(default_factory=list)   # logprobs (plain mode)
    # latency markers (perf_counter seconds), set by submit()/scheduler
    t_submit: float = 0.0
    t_first: float = 0.0
    t_done: float = 0.0
    stream: "queue.Queue" = field(default_factory=queue.Queue)
    done: threading.Event = field(default_factory=threading.Event)
    error: Optional[BaseException] = None
    cancelled: bool = False
    # engine-unique request id (auto-assigned by submit when the caller
    # passes none) — the address live migration exports/aborts by
    rid: Optional[str] = None
    # fleet observability (docs/DESIGN.md §7): tenant identity and the
    # gateway-propagated trace id ride the request through batching rows
    # AND the migration export/import seam; the wall-clock submit plus
    # the scheduler-pickup marker decompose TTFT into queue wait vs
    # prefill, and migration_pause accumulates freeze→first-relayed-
    # token gaps so a migrated request's timeline still sums to e2e
    tenant: str = "default"
    trace_id: int = 0
    t_submit_wall: float = 0.0     # epoch seconds at admission
    t_sched: float = 0.0           # perf_counter at scheduler pickup
    migration_pause: float = 0.0   # accumulated seconds frozen
    migrated: bool = False         # was live-migrated out at least once
    # adopted (migrated-IN) requests never close a timeline here: the
    # source replica keeps the client connection, so its view is the
    # user-visible one — the adopting engine closing too would double-
    # count the tenant's tokens across the fleet
    adopted: bool = False
    # gateway-failover resume (docs/DESIGN.md §23): admitted via
    # submit_resumed on a survivor replica; resume_pause accumulates the
    # replay window (first recorded token to first VISIBLE token) so the
    # SLO timeline decomposes like a migration pause
    resumed: bool = False
    resume_pause: float = 0.0      # seconds spent re-deriving delivered

    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self.done.wait(timeout):
            raise TimeoutError("request did not complete in time")
        if self.error is not None:
            raise self.error
        return np.asarray(self.tokens, np.int32)

    def cancel(self) -> None:
        """Ask the scheduler to drop this request: a queued request is
        skipped at admission; an in-flight one frees its slot after the
        current step.  Tokens already produced stay in ``tokens``."""
        self.cancelled = True


class ContinuousBatchingEngine:
    """Slot-based continuous batching over a single-stage model."""

    def __init__(self, cfg: ModelConfig, params: StageParams,
                 max_seq: Optional[int] = None, max_batch: int = 8,
                 sampling: SamplingParams = SamplingParams(),
                 eos_id: Optional[int] = None, seed: int = 0,
                 prompt_buckets: tuple = (32, 128, 512, 2048),
                 kv_cache_blocks: Optional[int] = None,
                 kv_block_tokens: Optional[int] = None,
                 mesh=None, kv_cache_dtype=None, kv_dtype=None,
                 draft_cfg: Optional[ModelConfig] = None,
                 draft_params: Optional[StageParams] = None,
                 num_draft: int = 4,
                 prompt_lookup: bool = False,
                 decode_block: int = 1,
                 prefill_chunk: Optional[int] = None,
                 kv_layout: Optional[str] = None,
                 max_queue_depth: Optional[int] = None,
                 mixed_token_budget: Optional[int] = None,
                 spec_adaptive: bool = True,
                 kv_host_tier_bytes: Optional[int] = None,
                 kv_disk_tier_path: Optional[str] = None,
                 kv_disk_tier_bytes: Optional[int] = None):
        """``kv_cache_blocks`` / ``kv_block_tokens``: the block-level KV
        cache (``runtime/kvcache``, docs/DESIGN.md §10) — automatic
        prefix reuse at ``kv_block_tokens`` granularity.  A new prompt
        sharing at least one whole block of leading tokens with ANY
        previously prefilled prompt (hits land mid-prompt, not just on
        full-prompt repeats) skips prefill for the shared run: the
        cached blocks load into the slot row and only the suffix runs
        (causality makes a prefix's KV independent of what follows, so
        the reuse is exact).  ``None`` defers to the ``DWT_KVCACHE_*``
        env knobs, then to the default (64 blocks x 16 tokens);
        ``kv_cache_blocks=0`` disables reuse entirely.

        ``mesh``: tp mesh — slot forwards run sharded (Megatron weights,
        kv-head-sharded cache); the per-slot scatter attn impl runs
        inside each shard on its local head planes, so ragged slots and
        tensor parallelism compose without extra machinery.

        ``kv_cache_dtype``: reduced-precision cache storage (e.g.
        "float8_e4m3fn") — the slot scatter casts on insert and attention
        upcasts on read, same contract as InferenceEngine's.

        ``draft_cfg``/``draft_params``: enable SPECULATIVE decoding inside
        the slot loop — the production serving shape (continuous batching
        x draft/verify).  Each lockstep iteration becomes one speculative
        round: the draft proposes ``num_draft`` tokens per slot, the
        target verifies all slots' proposals in ONE [B, K+1] forward, and
        each row advances by its OWN accepted count (no lockstep minimum —
        the slot cache's per-row positions make ragged advance free,
        unlike SpeculativeEngine's single-offset cache).  Greedy output
        stays bit-identical to the non-draft engine (pinned by tests);
        admission additionally prefills the prompt into a draft-side slot
        row (full prompt — the KV cache accelerates only the target
        side).

        ``prompt_lookup``: draft-FREE speculation in the slot loop — the
        proposer is an n-gram match over each slot's own token history
        (prompt_lookup.ngram_propose), verified the same per-row way.
        No second model, no second cache; exclusive with
        ``draft_cfg``.

        ``decode_block``: fuse N lockstep steps (or, in the speculative
        modes, N draft/verify ROUNDS — SpeculativeEngine's
        rounds_per_dispatch, slot-shaped) into one dispatch when no
        admission could land anyway (one host sync per block — the
        throughput mode for high-dispatch-latency devices).
        Admission/cancel latency grows to <= N steps/rounds; greedy
        output is unchanged (sampled streams differ from N=1 —
        per-request seeds are not honored either way, see above).

        ``prefill_chunk``: chunked ADMISSION — a prompt longer than C
        tokens prefills in C-token dispatches instead of one
        bucket-wide forward, and between chunks the scheduler runs one
        decode step (or speculative round) for the slots already in
        flight.  This bounds the decode stall a long prompt imposes on
        its batch-mates to one chunk's latency (the vLLM-style
        "chunked prefill" scheduling property), on top of the
        activation-memory bound the engines' chunked prefill gives.
        Admission is resumable scheduler state, not an inline loop:
        while one prompt streams its chunks, other queued requests keep
        admitting into free slots past it (no head-of-line blocking);
        further chunk-needing prompts wait their turn in arrival order.
        Streaming starts even while every slot is busy — only the final
        sampling prefill waits for a slot, so a long prompt's chunks
        overlap the busy batch's decode.
        Greedy output is unchanged: chunk boundaries only split where
        K/V is written, and the admitted row samples its first token
        from the same full-context logits (same invariant as
        InferenceEngine's chunked path, runtime/engine.py).  The
        draft-side admission prefill (speculative mode) stays one
        dispatch — the draft is small by construction.

        ``kv_layout``: "paged" only — the scheduler is PAGED-NATIVE
        (docs/DESIGN.md §14): its slot cache IS a device-resident page
        pool ``[L, num_blocks, H, block_tokens, D]`` addressed through
        per-slot block tables.  HBM is reserved per page actually
        allocated instead of ``B x max_seq`` worst-case rows, radix
        prefix hits are shared block-table entries (zero H2D, zero
        copies of any kind), stores are in-place ownership adoptions
        (zero D2H), and EVERY slot mode rides the pool: plain decode,
        the draft-model and prompt-lookup speculative proposers (the
        draft gets its own scratch page pool, reserved and freed with
        the request), and tp meshes (the pool shards by kv head exactly
        like the dense cache did).  ``kv_cache_blocks`` sizes the pool
        (0/None = the dense-equivalent ``B x table_width`` — there is
        no cache-off mode: the pool is the decode cache).  The dense
        batch cache is deleted, and since the gateway release the
        dense layout itself is gone everywhere (docs/DESIGN.md §14).

        ``max_queue_depth``: overload shedding — when the admission
        queue (submitted-but-unslotted requests) already holds this
        many, :meth:`submit` raises
        :class:`~.overload.SchedulerOverloaded` instead of queueing
        unboundedly (the HTTP layer maps it to ``503 + Retry-After``).
        ``None`` defers to ``DWT_MAX_QUEUE_DEPTH``; 0 (the default)
        keeps the queue unbounded.

        ``mixed_token_budget``: MIXED prefill+decode dispatch (docs/
        DESIGN.md §19) — each scheduler iteration becomes ONE jitted
        program packing every active decode row's ``decode_block``
        fused-loop tokens plus prefill chunk segments from one or more
        admitting prompts, up to this many tokens per dispatch.  Decode
        fusion survives admission (the serialized mode's fuse
        suppression is gone) and several prompts stream chunks
        concurrently.  Requires ``prefill_chunk``.  With a speculative
        proposer armed (draft model or prompt lookup) the dispatch's
        decode half runs ``decode_block`` draft/verify ROUNDS instead
        of plain steps (docs/DESIGN.md §22): a spec row is priced at
        ``(K_row + 1) * decode_block`` budget tokens and the remainder
        still packs prefill segments.  ``None`` defers to
        ``DWT_MIXED_TOKEN_BUDGET``; 0 (the default) keeps the
        serialized interleave, which is the bit-identity reference the
        mixed path is pinned against.

        ``spec_adaptive``: adaptive per-row draft length in the mixed
        dispatch (docs/DESIGN.md §22) — an EWMA of each row's
        acceptance rate shrinks/widens its ``K_row`` between iterations
        within a small static bucket set ({1, K/2, K}), so a collapsing
        acceptor degrades to near-plain decode instead of burning
        budget on rejected drafts.  False pins ``K_row = num_draft``
        (the serialized schedule's width — required for SAMPLED
        bit-identity against the serialized spec reference; greedy
        streams are K-invariant and stay bit-identical either way).

        ``kv_host_tier_bytes`` / ``kv_disk_tier_path`` /
        ``kv_disk_tier_bytes``: the TIERED KV capacity layer below the
        page pool (docs/DESIGN.md §21) — LRU-evicted radix leaves
        demote into a byte-budgeted host-RAM ring (plus an optional
        mmap'd disk segment below it) instead of vanishing, and a
        later prompt sharing the demoted prefix promotes the blocks
        back through the §15 adopt seam instead of re-prefilling.
        ``None`` defers to ``DWT_KV_HOST_TIER_BYTES`` /
        ``DWT_KV_DISK_TIER_PATH`` / ``DWT_KV_DISK_TIER_BYTES``; 0
        (the default) disables the tier — eviction discards, exactly
        as before."""
        if max_queue_depth is None:
            from ..telemetry._env import env_int
            max_queue_depth = env_int("DWT_MAX_QUEUE_DEPTH", 0)
        self.max_queue_depth = max(0, int(max_queue_depth))
        if mixed_token_budget is None:
            from ..telemetry._env import env_int
            mixed_token_budget = env_int("DWT_MIXED_TOKEN_BUDGET", 0)
        self.mixed_token_budget = max(0, int(mixed_token_budget))
        self.cfg, self.params = cfg, params
        self.max_seq = max_seq or cfg.max_seq_len
        self.max_batch = max_batch
        self.sampling = sampling
        self.eos_id = eos_id
        self.spec = StageSpec(0, 1, 0, cfg.num_layers)
        self.mesh = mesh
        self.draft_cfg, self.draft_params = draft_cfg, draft_params
        self.num_draft = num_draft
        self.prompt_lookup = prompt_lookup
        self.decode_block = decode_block
        self.prefill_chunk = validate_prefill_chunk(prefill_chunk,
                                                    self.max_seq)
        if decode_block < 1:
            raise ValueError("decode_block must be >= 1")
        if self.mixed_token_budget > 0:
            if self.prefill_chunk is None:
                raise ValueError(
                    "mixed_token_budget needs prefill_chunk: the budget "
                    "is packed with C-token prefill segments")
            if self.mixed_token_budget < self.prefill_chunk:
                raise ValueError(
                    f"mixed_token_budget ({self.mixed_token_budget}) must "
                    f"fit at least one prefill chunk "
                    f"({self.prefill_chunk} tokens)")
        if prompt_lookup and draft_cfg is not None:
            raise ValueError(
                "prompt_lookup and draft_cfg are exclusive proposers")
        if prompt_lookup and num_draft < 1:
            raise ValueError("num_draft must be >= 1")
        if (draft_cfg is None) != (draft_params is None):
            raise ValueError("draft_cfg and draft_params go together")
        if draft_cfg is not None:
            if draft_cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab ({draft_cfg.vocab_size}) != target vocab "
                    f"({cfg.vocab_size}); speculative decoding needs a "
                    "shared token space")
            if num_draft < 1:
                raise ValueError("num_draft must be >= 1")
        self.kv_cache_dtype = (jnp.dtype(kv_cache_dtype)
                               if kv_cache_dtype else None)
        # kv_dtype (docs/DESIGN.md §17): the page pool's QUANTIZED width
        # — int8/int4 pages with a per-token scale sidecar.  Exclusive
        # with the kv_cache_dtype storage CAST (same full-width layout,
        # different grid): one knob or the other.
        from ..ops.quant import resolve_kv_dtype
        self.kv_dtype = resolve_kv_dtype(kv_dtype)
        if self.kv_dtype != "bf16" and self.kv_cache_dtype is not None:
            raise ValueError(
                f"kv_dtype={self.kv_dtype!r} quantizes the page pool and "
                "cannot compose with a kv_cache_dtype storage cast; drop "
                "one of the two knobs")
        self.prompt_buckets = tuple(
            b for b in sorted(prompt_buckets) if b <= self.max_seq
        ) or (self.max_seq,)

        from .kvcache import resolve_kvcache_config, resolve_kv_layout
        self.kv_layout = resolve_kv_layout(kv_layout)
        if self.kv_layout != "paged":
            raise ValueError(
                f"kv_layout={self.kv_layout!r} is not supported by the "
                "paged-native continuous-batching scheduler: its slot "
                "cache IS the device page pool (docs/DESIGN.md §14); "
                "paged is the only layout (dense was removed).")
        n_blocks_arg, block_tokens = resolve_kvcache_config(
            kv_cache_blocks, kv_block_tokens, default_blocks=0)
        if block_tokens < 1:
            raise ValueError("kv_block_tokens must be >= 1")

        cfg_, spec_, samp_ = cfg, self.spec, sampling
        # S is a BUFFER capacity (temp prefill rows, block tables,
        # history), sublane-aligned for the flash kernel AND padded to
        # the page granule (lcm keeps both alignments); admission limits
        # still check the caller's max_seq.  The speculative slot modes
        # additionally fold in SLACK columns: a fused dispatch may write
        # up to decode_block*(K+1) positions past a row's last drained
        # length before the host learns the accepted counts, and every
        # such write must land in a page the request actually reserved
        # (an unreserved write would sentinel-drop K/V a later round
        # attends).
        import math
        B = max_batch
        spec_mode = prompt_lookup or draft_cfg is not None
        self._slack_tokens = (decode_block * (num_draft + 1)
                              if spec_mode else 0)
        # adaptive per-row draft length (docs/DESIGN.md §22): the mixed
        # dispatch prices a spec row at (K_row + 1) tokens per round and
        # an EWMA of its acceptance rate moves K_row between iterations
        # within this SMALL STATIC bucket set — the dispatch-wide draft
        # width is the max active bucket, so compiled variants stay
        # O(buckets) (re-pinned in the §20 CompileTracker budget below).
        # spec_adaptive=False pins K_row = num_draft (the serialized
        # schedule's width).
        K0 = max(1, int(num_draft))
        self._spec_buckets = tuple(sorted({1, max(1, K0 // 2), K0}))
        self.spec_adaptive = bool(spec_adaptive) and spec_mode
        self._spec_krow = np.full((B,), K0, np.int32)
        self._spec_ewma = np.ones((B,), np.float64)
        self._spec_ewma_alpha = 0.5
        g = math.lcm(8, block_tokens)
        S = -(-(pad_cache_capacity(self.max_seq)
                + self._slack_tokens) // g) * g

        from ..parallel.tensor import (make_forward_seam,
                                       make_paged_forward_seam)

        # ------------------------------------------------------------------
        # the DEVICE-resident page pool (docs/DESIGN.md §11/§14): HBM
        # holds num_blocks pages regardless of max_batch x max_seq, and
        # per-slot block tables (host numpy, the scheduler's source of
        # truth, shipped as a few hundred metadata bytes per dispatch)
        # address them.  Entry >= num_blocks = "no page": writes drop
        # (freed slots, fused-block overshoot), reads clamp into
        # causally-masked garbage.  Under a tp mesh the pool shards by
        # kv head (axis 2), exactly like the dense cache did.
        from .kvcache import PagedKVCacheManager
        from .kvcache.device import write_row_to_pages
        bt = block_tokens
        self._table_width = S // bt
        n_blocks = (n_blocks_arg if n_blocks_arg >= 1
                    else B * self._table_width)
        self.kv_cache = PagedKVCacheManager.for_model(
            cfg, n_blocks, bt, dtype=self.kv_cache_dtype,
            kv_dtype=self.kv_dtype)
        N = self.kv_cache.num_blocks
        self._page_sentinel = N
        page_dtype = self.kv_cache_dtype or cfg.dtype
        fwd_p, bind_tables, pool_sharding = make_paged_forward_seam(
            cfg, self.spec, mesh, params, bt)
        from ..ops.quant import alloc_kv_pages
        self._pk = alloc_kv_pages(
            (cfg.num_layers, N, cfg.num_kv_heads, bt, cfg.head_dim),
            self.kv_dtype, page_dtype)
        self._pv = jax.tree.map(jnp.zeros_like, self._pk)
        if pool_sharding is not None:
            # a single NamedSharding broadcasts over the pool's leaves:
            # the quantized layouts' data/scale/zero all keep the
            # [L, N, H(tp), bt, ·] axis order, so the kv-head spec
            # shards scales WITH their pages
            self._pk = jax.device_put(self._pk, pool_sharding.keys)
            self._pv = jax.device_put(self._pv, pool_sharding.values)
        self._tables = np.full((B, self._table_width), N, np.int32)
        # write_row_to_pages survives for the DRAFT side only: the draft
        # prefill still runs a dense temp row (the draft is small by
        # construction) and scatters it into the scratch pool; the
        # TARGET's temp-row path is deleted — prefill pages directly
        self._write_row = write_row_to_pages

        # tiered KV (docs/DESIGN.md §21): the host-RAM/disk capacity
        # layer below the pool.  The demote hook closes over the LIVE
        # pool references (they rotate on every donating dispatch);
        # promotion runs in _reserve_pages, before the match.
        from .kvcache import (TieredKVStore, make_demote_hook,
                              resolve_tier_config)
        tier_host, tier_path, tier_disk = resolve_tier_config(
            kv_host_tier_bytes, kv_disk_tier_path, kv_disk_tier_bytes)
        self._kv_tier = None
        if tier_host > 0:
            self._kv_tier = TieredKVStore(
                tier_host, bt, disk_path=tier_path,
                disk_bytes=tier_disk)
            self.kv_cache.tier = self._kv_tier
            self.kv_cache.demote_hook = make_demote_hook(
                self._kv_tier, lambda: (self._pk, self._pv))

        def _emitted_logprob(logits, tok):
            """Raw log-softmax of the emitted token (the engines'
            OpenAI-style convention, engine.py decode) — one [B, V]
            reduction per step, a rounding error next to the forward."""
            return jnp.take_along_axis(
                jax.nn.log_softmax(logits.astype(jnp.float32), -1),
                tok[:, None].astype(jnp.int32), axis=-1)[:, 0]

        def paged_one_step(params, cache, lengths, last_tok, active,
                           rng):
            """One paged lockstep decode step over all slots — the
            shared core of the per-step jit and the fused multi-step
            loop; mirrors the deleted dense ``one_step`` token for token
            (same rng spends, same masking) so paged-vs-plain-engine
            greedy parity is structural."""
            pos = lengths[:, None]
            logits, cache = fwd_p(params, last_tok[:, None], cache, pos,
                                  True)
            tok = sample_logits(logits[:, 0], rng, samp_)
            tok = jnp.where(active, tok, last_tok)
            lp = _emitted_logprob(logits[:, 0], tok)
            lengths = lengths + active.astype(jnp.int32)
            return cache, lengths, tok, lp

        @partial(jax.jit, donate_argnums=(1, 2))
        def paged_step(params, pk, pv, tables, lengths, last_tok,
                       active, rng):
            bind_tables(tables)
            cache = KVCache(pk, pv, jnp.zeros((), jnp.int32))
            cache, lengths, tok, lp = paged_one_step(
                params, cache, lengths, last_tok, active, rng)
            return cache.keys, cache.values, lengths, tok, lp

        def _fused_loop(step_fn, params, cache, lengths, last_tok,
                        active, rng, eos, budget, num_steps,
                        done0=None):
            """The device-resident fused-block loop shared by the dense
            and paged multi-step jits (docs/DESIGN.md §13): up to
            ``num_steps`` lockstep steps in one dispatch (one host sync
            per BLOCK, not per token — on a device with ~15 ms dispatch
            latency this is the difference between ~100 tok/s and the
            HBM roofline), with EARLY EXIT the moment every active row
            is done — eos'd on device, or out of its remaining token
            ``budget`` — so a block whose rows all finish at step
            j < num_steps stops after j steps instead of decoding into
            stale positions for the rest.  The active mask stays frozen
            (admission still waits out the block); rows that finish
            while OTHERS run keep decoding into their own stale
            positions exactly as before, so the recorded tokens are
            bit-identical to the fixed-trip scan's.  Returns
            ``(cache, lengths, tok, toks [B, num_steps], lps,
            steps_ran)``; the host drain reads ``steps_ran`` columns —
            the on-device active count that tells it how many steps
            actually ran.  rng is pre-split per step (the fixed-trip
            scan's consumption order), so sampled fused blocks keep
            their exact historical streams.  ``done0``: rows already
            done at entry — a mixed dispatch's freshly installed row
            whose first sampled token hit eos."""
            B = last_tok.shape[0]
            keys = jax.random.split(rng, num_steps)
            toks0 = jnp.zeros((B, num_steps), jnp.int32)
            lps0 = jnp.zeros((B, num_steps), jnp.float32)
            if done0 is None:
                done0 = jnp.zeros((B,), bool)

            def cond(carry):
                j, cache, lengths, tok, row_done, toks, lps = carry
                return (j < num_steps) & jnp.any(active & ~row_done)

            def body(carry):
                j, cache, lengths, tok, row_done, toks, lps = carry
                cache, lengths, tok, lp = step_fn(
                    params, cache, lengths, tok, active, keys[j])
                row_done = (row_done
                            | ((eos >= 0) & (tok == eos) & active)
                            | (j + 1 >= budget))
                toks = jax.lax.dynamic_update_slice(
                    toks, tok[:, None], (jnp.int32(0), j))
                lps = jax.lax.dynamic_update_slice(
                    lps, lp[:, None], (jnp.int32(0), j))
                return (j + 1, cache, lengths, tok, row_done, toks, lps)

            (steps, cache, lengths, tok, _, toks, lps) = \
                jax.lax.while_loop(
                    cond, body, (jnp.int32(0), cache, lengths, last_tok,
                                 done0, toks0, lps0))
            return cache, lengths, tok, toks, lps, steps

        @partial(jax.jit, donate_argnums=(1, 2), static_argnums=(10,))
        def paged_multi_step(params, pk, pv, tables, lengths,
                             last_tok, active, rng, eos, budget,
                             num_steps):
            """decode_block fusion: ``_fused_loop`` over
            ``paged_one_step``.  The tables are frozen for the block (no
            admission can land mid-block) and rows that finish while
            others run keep writing — through their own still-reserved
            pages, or through sentinel entries that drop the write (the
            paged stale-slot route)."""
            bind_tables(tables)
            cache = KVCache(pk, pv, jnp.zeros((), jnp.int32))
            cache, lengths, tok, toks, lps, steps = _fused_loop(
                paged_one_step, params, cache, lengths, last_tok,
                active, rng, eos, budget, num_steps)
            return (cache.keys, cache.values, lengths, tok, toks, lps,
                    steps)

        @jax.jit
        def set_slot_state(lengths, last_tok, slot, new_len, new_tok):
            return (lengths.at[slot].set(new_len),
                    last_tok.at[slot].set(new_tok))

        kv_dtype = self.kv_cache_dtype

        # paged chunk programs: the SHARED factory
        # (engine.make_paged_chunk_programs — one owner of paged chunk
        # semantics).  Chunks write K/V straight into the request's
        # reserved pages through its block table — no dense temp row,
        # no gather/scatter round trip, zero H2D across cold admission.
        self._paged_chunk_mid, slab_body = make_paged_chunk_programs(
            fwd_p, bind_tables)

        @partial(jax.jit, donate_argnums=(1, 2))
        def paged_prefill(params, pk, pv, ids, table, start, real_len,
                          rng):
            """Batch-1 (suffix) PAGED prefill over a padded bucket at
            offset ``start``, straight through the request's block
            table [1, W]; samples token #1 at the prompt's true last
            position.

            Cold path: start=0.  Prefix-reuse path: start=m with the
            matched tree pages already in the table (reads only —
            writes begin at ``start``, which is at/past the shared
            pages' frontier).  Padded tail tokens write garbage K/V
            past ``start + real_len`` into the request's OWN reserved
            pages (or sentinel-drop past the reservation), and decode
            overwrites each such position before any query can attend
            it (stale-slot invariant above)."""
            bind_tables(table)
            b, s = ids.shape
            pos = start + jnp.broadcast_to(jnp.arange(s), (b, s))
            cache = KVCache(pk, pv, jnp.zeros((), jnp.int32))
            logits, cache = fwd_p(params, ids, cache, pos, False)
            last = jax.lax.dynamic_index_in_dim(
                logits, real_len - 1, axis=1, keepdims=False)  # [1, V]
            tok = sample_logits(last, rng, samp_)
            lp = _emitted_logprob(last, tok)
            return cache.keys, cache.values, tok[0], lp[0]

        # cost observatory (docs/DESIGN.md §20): every jitted program
        # class is wrapped for compile accounting at its assignment
        # site — cache growth across a call books one compile event.
        # Variant budgets document the compiled-variant invariants
        # (multi_step: the two round-count variants the warmup loop
        # pre-compiles); unbudgeted programs legitimately fork per
        # bucket/chunk shape and never feed recompile_storm.
        _ct = _profiling.get_compile_tracker()
        self._paged_chunk_mid = _ct.wrap("paged_chunk_mid",
                                         self._paged_chunk_mid)
        self._paged_prefill = _ct.wrap("paged_prefill", paged_prefill)
        self._paged_step = _ct.wrap("paged_step", paged_step)
        self._paged_multi_step = _ct.wrap("paged_multi_step",
                                          paged_multi_step,
                                          variant_budget=2)
        self._set_slot_state = set_slot_state

        # ------------------------------------------------------------------
        # the MIXED token-budget dispatch (docs/DESIGN.md §19): one jit
        # packing a [n_seg, C] prefill slab (chunk segments from one or
        # more admitting prompts, final segments sampling token #1 and
        # installing their slot in-program) with the fused decode loop
        # over all active rows.  Segment count is FIXED at
        # budget // C (unused rows ride all-sentinel tables and slot=B,
        # so every install drops), giving exactly two compiled variants
        # (with_finals x num_steps is static per decode_block).
        self._mixed_step = None
        self._mixed_pld_step = None
        self._mixed_spec_step = None
        self._mixed_seg_cap = 0
        if self.mixed_token_budget > 0:
            C_mixed = self.prefill_chunk
            n_seg = max(1, self.mixed_token_budget // C_mixed)
            self._mixed_seg_cap = n_seg

        def slab_finals(logits, seg_lens, seg_keys):
            """Per-row batch-1 sampling of the packed finals' token #1 —
            shared by the plain and speculative mixed programs (each row
            its own key: the serialized final prefill's exact spend)."""
            f_toks, f_lps = [], []
            for r in range(self._mixed_seg_cap):
                last = jax.lax.dynamic_index_in_dim(
                    logits[r], seg_lens[r] - 1, axis=0,
                    keepdims=True)                         # [1, V]
                tok_r = sample_logits(last, seg_keys[r], samp_)
                f_toks.append(tok_r[0])
                f_lps.append(_emitted_logprob(last, tok_r)[0])
            return (jnp.stack(f_toks).astype(jnp.int32),
                    jnp.stack(f_lps))

        if self.mixed_token_budget > 0 and not spec_mode:

            @partial(jax.jit, donate_argnums=(1, 2),
                     static_argnums=(17, 18))
            def mixed_step(params, pk, pv, seg_ids, seg_tables,
                           seg_starts, seg_lens, seg_slot, seg_plen,
                           seg_keys, dec_tables, lengths, last_tok,
                           active, dec_rng, eos, budget, num_steps,
                           with_finals):
                """One mixed dispatch.  Prefill slab first: row r of
                ``seg_ids`` [n_seg, C] runs at positions
                ``seg_starts[r] + arange(C)`` through ``seg_tables[r]``
                (sentinel rows compute into dropped writes).  If
                ``with_finals``, each row samples token #1 at
                ``seg_lens[r] - 1`` from its OWN batch-1 rng key
                (``seg_keys[r]`` — the serialized prefill's exact
                spend) and installs itself at ``seg_slot[r]``
                (slot = B = not-a-final, the install drops).  Then the
                fused decode loop runs over ``dec_tables`` with the
                updated row state — freshly installed rows decode in
                the SAME dispatch, rows whose token #1 was eos enter
                the loop already done."""
                B_ = last_tok.shape[0]
                cache = KVCache(pk, pv, jnp.zeros((), jnp.int32))
                logits, cache = slab_body(params, cache, seg_ids,
                                          seg_tables, seg_starts)
                if with_finals:
                    final_toks, final_lps = slab_finals(
                        logits, seg_lens, seg_keys)
                    lengths = lengths.at[seg_slot].set(
                        seg_plen, mode="drop")
                    last_tok = last_tok.at[seg_slot].set(
                        final_toks, mode="drop")
                    active = active.at[seg_slot].set(True, mode="drop")
                    done0 = jnp.zeros((B_,), bool).at[seg_slot].set(
                        (eos >= 0) & (final_toks == eos), mode="drop")
                    # a max_new=1 install has nothing left to decode:
                    # it enters the loop already done (pre-existing
                    # rows always have budget >= 1 — completed rows
                    # free their slot at drain time)
                    done0 = done0 | (budget <= 0)
                else:
                    final_toks = jnp.zeros((n_seg,), jnp.int32)
                    final_lps = jnp.zeros((n_seg,), jnp.float32)
                    done0 = None
                bind_tables(dec_tables)
                cache, lengths, tok, toks, lps, steps = _fused_loop(
                    paged_one_step, params, cache, lengths, last_tok,
                    active, dec_rng, eos, budget, num_steps,
                    done0=done0)
                return (cache.keys, cache.values, lengths, tok,
                        final_toks, final_lps, toks, lps, steps)

            # the §19 invariant the recompile_storm detector enforces:
            # with_finals x one static num_steps = exactly two variants
            self._mixed_step = _ct.wrap("mixed_step", mixed_step,
                                        variant_budget=2)

        def verify_slots(params, cache, drafts, q_logits, lengths,
                         last_tok, active, rng, k_cap=None):
            """Target-verify all slots' proposals in ONE [B, K+1]
            forward over the PAGE POOL (the [B, K+1] chunk rides the
            paged impl's XLA-gather path; writes scatter through the
            frozen tables) + per-row accept + inactive-row masking — the
            verify half shared by the draft-model and prompt-lookup step
            jits (their host-side twin is _drain_spec_blocks).  Inactive
            rows' chunk writes route through their slots' sentineled
            tables and drop.  ``k_cap`` ([B] or None): per-row
            draft-length cap, the mixed dispatch's adaptive-K seam
            (speculative.accept_and_extra)."""
            K = drafts.shape[1]
            verify_in = jnp.concatenate([last_tok[:, None], drafts],
                                        axis=1)
            pos = lengths[:, None] + jnp.arange(K + 1)[None, :]
            t_logits, cache = fwd_p(params, verify_in, cache, pos, False)
            rng, sub_u, sub_x = jax.random.split(rng, 3)
            emitted, n, new_last = verify_emit_per_row(
                t_logits, drafts, q_logits, samp_, sub_u, sub_x,
                k_cap=k_cap)
            n = jnp.where(active, n, 0)
            new_last = jnp.where(active, new_last, last_tok)
            return cache, emitted, n, new_last, lengths + n

        # ------------------------------------------------------------------
        # draft-free speculative slot decoding (n-gram prompt lookup)
        self._pld_step = None
        if prompt_lookup:
            from .prompt_lookup import ngram_propose
            K = num_draft
            # emitted blocks write up to decode_block*(K+1) past a row's
            # history length before the host drains — S already folds
            # that slack in; +1 is the OOB routing column for inactive
            # rows
            hcap = S + 1

            @partial(jax.jit, donate_argnums=(1, 2, 3),
                     static_argnums=(9,))
            def pld_step(params, pk, pv, history, tables, lengths,
                         last_tok, active, rng, num_rounds):
                """``num_rounds`` prompt-lookup rounds over all slots,
                fused in one dispatch: n-gram propose per row, verify
                [B, K+1] in one paged forward, per-row accept, append
                the emitted block to each active row's history.  The
                K/V lands in each row's own reserved pages (the slack
                columns folded into S cover the fused overshoot)."""
                b = last_tok.shape[0]
                bind_tables(tables)
                cache = KVCache(pk, pv, jnp.zeros((), jnp.int32))

                def one_round(carry, sub):
                    cache, history, lengths, last_tok = carry
                    hist_len = lengths + 1   # history = prompt + emitted
                    drafts = ngram_propose(history, hist_len, K)
                    # one-hot proposer (q_logits=None), like
                    # PromptLookupEngine
                    cache, emitted, n, new_last, new_lengths = \
                        verify_slots(params, cache, drafts, None, lengths,
                                     last_tok, active, sub)
                    # append emitted at cols hist_len..hist_len+K per
                    # row; inactive rows are routed out of bounds
                    # (scatter drops OOB updates) so a freed slot's stale
                    # lengths can't corrupt its row before re-admission
                    # rewrites it
                    rows = jnp.arange(b)[:, None]
                    cols = jnp.where(active[:, None],
                                     hist_len[:, None] + jnp.arange(K + 1),
                                     hcap)
                    history = history.at[rows, cols].set(emitted)
                    return (cache, history, new_lengths, new_last), \
                        (emitted, n)

                (cache, history, lengths, last_tok), (em, ns) = \
                    jax.lax.scan(one_round,
                                 (cache, history, lengths, last_tok),
                                 jax.random.split(rng, num_rounds))
                return (cache.keys, cache.values, history, lengths,
                        last_tok, em, ns)

            @partial(jax.jit, donate_argnums=(0,))
            def admit_h(history, row_ids, slot, plen, tok):
                """Seed a slot's history row: prompt + the first sampled
                token (pad-tail beyond it is masked by hist_len until
                overwritten)."""
                history = jax.lax.dynamic_update_slice(
                    history, row_ids, (slot, jnp.zeros((), jnp.int32)))
                return history.at[slot, plen].set(tok)

            self._pld_step, self._admit_h = pld_step, admit_h
            self._history = jnp.zeros((B, hcap), jnp.int32)

            if self.mixed_token_budget > 0:

                @partial(jax.jit, donate_argnums=(1, 2, 3),
                         static_argnums=(17, 18, 19))
                def mixed_pld_step(params, pk, pv, history, seg_ids,
                                   seg_tables, seg_starts, seg_lens,
                                   seg_slot, seg_plen, seg_keys,
                                   dec_tables, lengths, last_tok, active,
                                   dec_rng, k_row, k_disp, num_rounds,
                                   with_finals):
                    """One mixed SPECULATIVE dispatch, prompt-lookup
                    proposer (docs/DESIGN.md §22): the §19 prefill slab
                    (finals sample token #1 from their own per-row keys,
                    in pack order) followed by ``num_rounds``
                    draft/verify rounds over the PRE-EXISTING active
                    rows.  Freshly installed finals set only
                    lengths/last_tok in-program and stay OUT of the
                    rounds' active mask — their history row seeds
                    host-side after the dispatch (the serialized
                    admission's exact timing), and their sentinel decode
                    table drops any garbage verify write.  ``k_disp``
                    (static, a bucket) is the dispatch-wide draft width;
                    ``k_row`` [B] caps each row's acceptance below it
                    (adaptive K via verify_slots' k_cap) without
                    changing the rng spend."""
                    b = last_tok.shape[0]
                    cache = KVCache(pk, pv, jnp.zeros((), jnp.int32))
                    logits, cache = slab_body(params, cache, seg_ids,
                                              seg_tables, seg_starts)
                    if with_finals:
                        final_toks, final_lps = slab_finals(
                            logits, seg_lens, seg_keys)
                        lengths = lengths.at[seg_slot].set(
                            seg_plen, mode="drop")
                        last_tok = last_tok.at[seg_slot].set(
                            final_toks, mode="drop")
                    else:
                        final_toks = jnp.zeros((n_seg,), jnp.int32)
                        final_lps = jnp.zeros((n_seg,), jnp.float32)
                    bind_tables(dec_tables)

                    def one_round(carry, sub):
                        cache, history, lengths, last_tok = carry
                        hist_len = lengths + 1
                        drafts = ngram_propose(history, hist_len, k_disp)
                        cache, emitted, n, new_last, new_lengths = \
                            verify_slots(params, cache, drafts, None,
                                         lengths, last_tok, active, sub,
                                         k_cap=k_row)
                        rows = jnp.arange(b)[:, None]
                        cols = jnp.where(
                            active[:, None],
                            hist_len[:, None] + jnp.arange(k_disp + 1),
                            hcap)
                        history = history.at[rows, cols].set(emitted)
                        return (cache, history, new_lengths, new_last), \
                            (emitted, n)

                    if num_rounds > 0:
                        (cache, history, lengths, last_tok), (em, ns) = \
                            jax.lax.scan(
                                one_round,
                                (cache, history, lengths, last_tok),
                                jax.random.split(dec_rng, num_rounds))
                    else:
                        em = jnp.zeros((0, b, k_disp + 1), jnp.int32)
                        ns = jnp.zeros((0, b), jnp.int32)
                    return (cache.keys, cache.values, history, lengths,
                            last_tok, final_toks, final_lps, em, ns)

                # §20/§22 variant invariant: with_finals x (each bucket's
                # k_disp with num_rounds=decode_block, plus the
                # rounds-free shape at k_disp=max bucket)
                self._mixed_pld_step = _ct.wrap(
                    "mixed_pld_step", mixed_pld_step,
                    variant_budget=2 * (len(self._spec_buckets) + 1))

        # ------------------------------------------------------------------
        # speculative slot decoding (draft model inside the slot loop)
        self._spec_step = None
        self._dmgr = None
        if draft_cfg is not None:
            # each fused round writes K+1 positions past a row's length
            # before the host learns how many were kept; rows advance
            # contiguously (n <= K+1 per round), so a query only ever
            # reaches a column in the round that writes it — the slack
            # columns folded into S (and into every request's page
            # reservation) are never attended stale, even across slot
            # reuse.  With decode_block rounds fused the overshoot
            # compounds — hence slack = decode_block*(K+1).
            K = num_draft
            dcfg_ = draft_cfg
            dspec = StageSpec(0, 1, 0, draft_cfg.num_layers)
            # dense temp-row prefill (slot impl) + paged decode seam —
            # the draft keeps the temp-row admission path the target
            # dropped (it is small by construction, and its pool is
            # pure scratch)
            fwd_d, dcache_sharding = make_forward_seam(
                draft_cfg, dspec, mesh, draft_params,
                attn_impl=slot_attention_impl)
            # draft rows are born on their kv-head shards under a mesh
            # (out_shardings None = unconstrained) so admission never
            # pays a reshard into the prefill shard_map
            drow_shardings = (None if dcache_sharding is None else
                              (dcache_sharding.keys,
                               dcache_sharding.values))
            fwd_dp, bind_dtables, dpool_sharding = \
                make_paged_forward_seam(draft_cfg, dspec, mesh,
                                        draft_params, bt)
            # the draft page pool: pure per-request SCRATCH — no radix
            # tree ever adopts draft pages (only the target's logits
            # gate emission, so reuse is a target-side property); the
            # manager is used for its free-list/accounting only, and
            # used_blocks == 0 whenever no request is in flight (the
            # draft half of the leak invariant)
            self._dmgr = PagedKVCacheManager.for_model(
                draft_cfg, n_blocks, bt, dtype=self.kv_cache_dtype,
                kv_dtype=self.kv_dtype)
            ND = self._dmgr.num_blocks
            self._dpage_sentinel = ND
            self._dpk = alloc_kv_pages(
                (draft_cfg.num_layers, ND, draft_cfg.num_kv_heads, bt,
                 draft_cfg.head_dim), self.kv_dtype, page_dtype)
            self._dpv = jax.tree.map(jnp.zeros_like, self._dpk)
            if dpool_sharding is not None:
                self._dpk = jax.device_put(self._dpk,
                                           dpool_sharding.keys)
                self._dpv = jax.device_put(self._dpv,
                                           dpool_sharding.values)
            self._dtables = np.full((B, self._table_width), ND, np.int32)

            @partial(jax.jit, donate_argnums=(2, 3, 4, 5),
                     static_argnums=(12,))
            def spec_step(params, dparams, pk, pv, dpk, dpv, tables,
                          dtables, lengths, last_tok, active, rng,
                          num_rounds):
                """``num_rounds`` speculative rounds over all slots,
                fused in one dispatch: draft K per row (through the
                draft page pool), verify [B, K+1] in one paged target
                forward, per-row accept (verify_emit_per_row).  Returns
                [R, B, K+1] emitted blocks + [R, B] counts for the host
                to drain; inactive rows advance by 0 and keep
                last_tok."""
                b = last_tok.shape[0]
                bind_tables(tables)
                bind_dtables(dtables)
                cache = KVCache(pk, pv, jnp.zeros((), jnp.int32))
                dcache = KVCache(dpk, dpv, jnp.zeros((), jnp.int32))

                def one_round(carry, sub):
                    cache, dcache, lengths, last_tok = carry

                    # K proposals + one extra step inserting d_K's KV so
                    # an all-accept round leaves the draft cache fully
                    # populated (speculative.py's dstep, per-row
                    # positions)
                    def dstep(c, j):
                        tok, dc, r = c
                        pos = (lengths + j)[:, None]
                        logits, dc = fwd_dp(dparams, tok[:, None], dc,
                                            pos, True)
                        logits = logits[:, 0]
                        r, s = jax.random.split(r)
                        if samp_.greedy:
                            d = jnp.argmax(logits, axis=-1).astype(
                                jnp.int32)
                            q = logits  # unused in greedy verify
                        else:
                            q = filtered_logits(logits, samp_)
                            d = jax.random.categorical(s, q, axis=-1)
                            d = d.astype(jnp.int32)
                        return (d, dc, r), (d, q)

                    sub, sub_d = jax.random.split(sub)
                    (_, dcache, _), (drafts, q_logits) = jax.lax.scan(
                        dstep, (last_tok, dcache, sub_d),
                        jnp.arange(K + 1))
                    drafts = drafts[:K].T                        # [b, K]
                    q_logits = jnp.swapaxes(q_logits[:K], 0, 1)

                    cache, emitted, n, new_last, lengths = verify_slots(
                        params, cache, drafts,
                        None if samp_.greedy else q_logits, lengths,
                        last_tok, active, sub)
                    return (cache, dcache, lengths, new_last), \
                        (emitted, n)

                (cache, dcache, lengths, last_tok), (em, ns) = \
                    jax.lax.scan(one_round,
                                 (cache, dcache, lengths, last_tok),
                                 jax.random.split(rng, num_rounds))
                return (cache.keys, cache.values, dcache.keys,
                        dcache.values, lengths, last_tok, em, ns)

            @partial(jax.jit, donate_argnums=(2, 3))
            def dprefill(dparams, ids, row_k, row_v):
                """Full-prompt draft-side prefill of a slot row (no
                sampling — the first token always comes from the TARGET's
                prefill logits).  Pad-tail garbage K/V is overwritten by
                the draft scan before any query can attend it (the same
                stale-slot invariant as the target prefill's)."""
                b, s = ids.shape
                pos = jnp.broadcast_to(jnp.arange(s), (b, s))
                dcache = KVCache(row_k, row_v, jnp.zeros((), jnp.int32))
                _, dcache = fwd_d(dparams, ids, dcache, pos, True)
                return dcache.keys, dcache.values

            @partial(jax.jit, out_shardings=drow_shardings)
            def zero_row_d():
                row = KVCache.create(dcfg_, dcfg_.num_layers, 1, S,
                                     dtype=kv_dtype)
                return row.keys, row.values

            self._spec_step = spec_step
            self._dprefill, self._zero_row_d = dprefill, zero_row_d

            if self.mixed_token_budget > 0:

                @partial(jax.jit, donate_argnums=(2, 3, 4, 5),
                         static_argnums=(20, 21, 22))
                def mixed_spec_step(params, dparams, pk, pv, dpk, dpv,
                                    seg_ids, seg_tables, seg_starts,
                                    seg_lens, seg_slot, seg_plen,
                                    seg_keys, dec_tables, dec_dtables,
                                    lengths, last_tok, active, dec_rng,
                                    k_row, k_disp, num_rounds,
                                    with_finals):
                    """One mixed SPECULATIVE dispatch, draft-model
                    proposer (docs/DESIGN.md §22): the §19 prefill slab
                    + finals, then ``num_rounds`` draft/verify rounds
                    over the PRE-EXISTING active rows through the draft
                    scratch pool.  Fresh finals set only
                    lengths/last_tok — their draft cache prefills
                    host-side after the dispatch (their dtable row is
                    still all-sentinel here, so draft-side writes drop).
                    ``k_disp`` is the static dispatch-wide draft width
                    (drafting always runs the full sub-scan so the rng
                    spend matches the serialized spec_step); ``k_row``
                    caps per-row acceptance (adaptive K)."""
                    b = last_tok.shape[0]
                    cache = KVCache(pk, pv, jnp.zeros((), jnp.int32))
                    logits, cache = slab_body(params, cache, seg_ids,
                                              seg_tables, seg_starts)
                    if with_finals:
                        final_toks, final_lps = slab_finals(
                            logits, seg_lens, seg_keys)
                        lengths = lengths.at[seg_slot].set(
                            seg_plen, mode="drop")
                        last_tok = last_tok.at[seg_slot].set(
                            final_toks, mode="drop")
                    else:
                        final_toks = jnp.zeros((n_seg,), jnp.int32)
                        final_lps = jnp.zeros((n_seg,), jnp.float32)
                    bind_tables(dec_tables)
                    bind_dtables(dec_dtables)
                    dcache = KVCache(dpk, dpv, jnp.zeros((), jnp.int32))

                    def one_round(carry, sub):
                        cache, dcache, lengths, last_tok = carry

                        def dstep(c, j):
                            tok, dc, r = c
                            pos = (lengths + j)[:, None]
                            dlogits, dc = fwd_dp(dparams, tok[:, None],
                                                 dc, pos, True)
                            dlogits = dlogits[:, 0]
                            r, s = jax.random.split(r)
                            if samp_.greedy:
                                d = jnp.argmax(dlogits, axis=-1).astype(
                                    jnp.int32)
                                q = dlogits
                            else:
                                q = filtered_logits(dlogits, samp_)
                                d = jax.random.categorical(s, q, axis=-1)
                                d = d.astype(jnp.int32)
                            return (d, dc, r), (d, q)

                        sub, sub_d = jax.random.split(sub)
                        (_, dcache, _), (drafts, q_logits) = jax.lax.scan(
                            dstep, (last_tok, dcache, sub_d),
                            jnp.arange(k_disp + 1))
                        drafts = drafts[:k_disp].T
                        q_logits = jnp.swapaxes(q_logits[:k_disp], 0, 1)

                        cache, emitted, n, new_last, lengths = \
                            verify_slots(
                                params, cache, drafts,
                                None if samp_.greedy else q_logits,
                                lengths, last_tok, active, sub,
                                k_cap=k_row)
                        return (cache, dcache, lengths, new_last), \
                            (emitted, n)

                    if num_rounds > 0:
                        (cache, dcache, lengths, last_tok), (em, ns) = \
                            jax.lax.scan(
                                one_round,
                                (cache, dcache, lengths, last_tok),
                                jax.random.split(dec_rng, num_rounds))
                    else:
                        em = jnp.zeros((0, b, k_disp + 1), jnp.int32)
                        ns = jnp.zeros((0, b), jnp.int32)
                    return (cache.keys, cache.values, dcache.keys,
                            dcache.values, lengths, last_tok,
                            final_toks, final_lps, em, ns)

                self._mixed_spec_step = _ct.wrap(
                    "mixed_spec_step", mixed_spec_step,
                    variant_budget=2 * (len(self._spec_buckets) + 1))
        self.spec_stats = {"rounds": 0, "drafted": 0, "accepted": 0}
        # disaggregated-join counters (docs/DESIGN.md §15): requests
        # admitted with premigrated KV + pages adopted on their behalf
        self.disagg_stats = {"premigrated_requests": 0,
                             "adopted_pages": 0}
        # gateway-failover resume counters (docs/DESIGN.md §23):
        # surfaced under stats()["resumed"], bridged onto
        # dwt_batching_resumed_requests_total by the catalog
        self.resume_stats = {"requests": 0, "replayed_tokens": 0,
                             "diverged": 0}

        self._lengths = jnp.zeros((B,), jnp.int32)
        self._last_tok = jnp.zeros((B,), jnp.int32)
        self._rng = jax.random.PRNGKey(seed)
        # the resume replay (§23) rewinds the engine stream to this key
        # so a survivor re-derives a sampled prefix bit-exactly
        self._seed = int(seed)
        self._step_count = 0
        # device-loop dispatch accounting (docs/DESIGN.md §13): one
        # host dispatch per fused block, device_loop_steps counts the
        # steps (or speculative rounds) that actually ran inside it —
        # early exit makes steps < decode_block visible here
        self.loop_stats = {"host_dispatches": 0, "device_loop_steps": 0}
        self._reset_chunk_stats()
        # resumable chunked admission.  Serialized mode (_adm): at most
        # ONE prompt streams its chunks at a time (scheduler state,
        # advanced one dispatch per loop iteration).  Mixed mode
        # (_adms): several admissions stream concurrently, their chunks
        # packed into each iteration's token-budget dispatch.  _pending
        # holds popped-but-unserved requests: chunk-needing prompts
        # waiting their streaming turn, and short prompts waiting for a
        # free slot — served FIFO each iteration, with serviceable
        # requests passing blocked ones
        self._adm: Optional[dict] = None
        self._adms: List[dict] = []
        self._pending: "deque[Request]" = deque()
        # completed-request latency reservoirs (seconds), bounded FIFO —
        # the /stats percentile source (reference analog: the per-stage
        # timer story, runtime/stats.py)
        self._lat = {"ttft": deque(maxlen=512), "e2e": deque(maxlen=512),
                     "per_token": deque(maxlen=512)}
        self._completed = 0

        # (mixed mode never dispatches the serialized step programs —
        # its two mixed_step variants compile on first use instead)
        if self.decode_block > 1 and self.mixed_token_budget == 0:
            # compile BOTH round-count variants now: the non-fused
            # variant's first use otherwise lands as a multi-second
            # XLA compile in the middle of serving (all-inactive mask:
            # state is unchanged where it matters, rows are unadmitted).
            # Real executions on purpose — jit's AOT path
            # (.lower().compile()) returns a separate executable and
            # does NOT seed the call cache the serving loop hits.
            # all-sentinel tables: writes drop, state holds
            idle = jnp.zeros((B,), bool)
            warm_rng = jax.random.PRNGKey(0)
            tbl = jnp.asarray(self._tables)
            for n_r in (1, self.decode_block):
                if self._pld_step is not None:
                    (self._pk, self._pv, self._history, self._lengths,
                     self._last_tok, _, _) = self._pld_step(
                        self.params, self._pk, self._pv, self._history,
                        tbl, self._lengths, self._last_tok, idle,
                        warm_rng, n_r)
                elif self._spec_step is not None:
                    (self._pk, self._pv, self._dpk, self._dpv,
                     self._lengths, self._last_tok, _, _) = \
                        self._spec_step(
                            self.params, self.draft_params, self._pk,
                            self._pv, self._dpk, self._dpv, tbl,
                            jnp.asarray(self._dtables), self._lengths,
                            self._last_tok, idle, warm_rng, n_r)
                elif n_r > 1:
                    (self._pk, self._pv, self._lengths,
                     self._last_tok, _, _, _) = self._paged_multi_step(
                        self.params, self._pk, self._pv, tbl,
                        self._lengths, self._last_tok, idle,
                        warm_rng, self._eos_scalar(),
                        jnp.zeros((B,), jnp.int32), n_r)
                else:
                    (self._pk, self._pv, self._lengths,
                     self._last_tok, _) = self._paged_step(
                        self.params, self._pk, self._pv, tbl,
                        self._lengths, self._last_tok, idle,
                        warm_rng)

        self._slots: List[Optional[Request]] = [None] * B
        self._queue: "queue.Queue" = queue.Queue()
        # live-migration seam (docs/DESIGN.md §18): rid -> Request for
        # export_request/active_requests addressing (entries die with
        # their request), plus the export mailbox the scheduler thread
        # services between steps (a foreign thread must never touch the
        # donated pool buffers)
        self._by_rid: dict = {}
        self._rid_salt = uuid.uuid4().hex[:8]
        self._rid_counter = itertools.count()
        self._export_q: "deque" = deque()
        self.migration_stats = {"exported_requests": 0,
                                "imported_requests": 0,
                                "detached_requests": 0}
        self._flight = get_flight_recorder()
        # per-engine span sink for the fleet trace stitch (docs/DESIGN.md
        # §7): prefill/decode spans tagged with the gateway-propagated
        # trace id, exported by GET /trace and merged by /trace/fleet.
        # The rid salt keeps proc rows distinct when tests co-locate
        # several engines in one process.
        self.tracer = TraceRecorder(f"engine:{self._rid_salt}")
        # co-located span sources (the migration worker registers its
        # recorder here) drain through export_trace alongside our own,
        # so one replica /trace carries engine AND migration spans
        self._aux_tracers: list = []
        # online anomaly watch over the same stats() surface /stats
        # serves; throttled to ~1 Hz inside the scheduler loop, and
        # bundles only materialize when postmortem capture is configured
        # (DWT_POSTMORTEM_DIR) — detection itself always feeds the
        # dwt_anomaly_* series and the flight ring
        self.anomaly = AnomalyMonitor(config={
            "engine": type(self).__name__, "max_batch": max_batch,
            "max_seq": self.max_seq, "decode_block": decode_block,
            "prefill_chunk": prefill_chunk,
            "mixed_token_budget": self.mixed_token_budget})
        # cost observatory handles (docs/DESIGN.md §20): the sampled
        # dispatch profiler (off-path free: an unsampled dispatch is
        # one dict increment, zero added syncs), the HBM watermark
        # ledger (this engine's owners reset on close()), and the
        # workload sketch recorder feeding GET /sketch
        self._prof = _profiling.get_profiler()
        self._sketch = _profiling.get_sketch()
        self._hbm = _profiling.get_hbm_watermarks()
        self._hbm_owners: set = set()
        # per-token KV byte attribution for achieved-GB/s: K+V over all
        # layers incl. the quantized sidecar, via the pool's block
        # accounting (the one-owner ops/quant.py math)
        self._kv_bytes_per_token = max(
            1, self.kv_cache.block_bytes // self.kv_cache.block_tokens)
        self._running = True
        # serializes submit() against close(): no request can be enqueued
        # after close() returns, so none can slip past the shutdown drain
        self._submit_lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------
    # public API

    def submit(self, prompt_ids, max_new_tokens: int,
               _staged: Optional[dict] = None,
               _replay: Optional[dict] = None,
               request_id: Optional[str] = None,
               tenant: Optional[str] = None,
               trace_id: int = 0) -> Request:
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        check_capacity(self.max_seq, len(prompt), max_new_tokens)
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            # admission records the first sampled token unconditionally,
            # so a 0-token request would still produce one
            raise ValueError("max_new_tokens must be >= 1")
        # the page-pool twin of check_capacity: a request whose full
        # table (incl. the speculative modes' fused-overshoot slack)
        # can never be allocated would wait in pending forever
        bt = self.kv_cache.block_tokens
        need = -(-(len(prompt) + max_new_tokens
                   + self._slack_tokens) // bt)
        pool_bound = self.kv_cache.num_blocks
        if self._dmgr is not None:
            # the draft pool cannot evict (no tree), so it binds too
            pool_bound = min(pool_bound, self._dmgr.num_blocks)
        if need > pool_bound:
            raise ValueError(
                f"request needs {need} KV blocks (prompt "
                f"{len(prompt)} + new {max_new_tokens} + slack "
                f"{self._slack_tokens} at {bt} tokens/block) but the "
                f"paged pool holds only {pool_bound}; raise "
                "kv_cache_blocks")
        if self.max_queue_depth:
            depth = self._queue.qsize() + len(self._pending)
            if depth >= self.max_queue_depth:
                from .overload import SchedulerOverloaded
                self._flight.record("admission_shed", depth=depth,
                                    limit=self.max_queue_depth)
                raise SchedulerOverloaded(
                    f"admission queue full ({depth} waiting >= "
                    f"--admission-queue-depth {self.max_queue_depth}); "
                    "shedding instead of queueing unboundedly",
                    retry_after_s=1.0)
        req = Request(prompt=prompt, max_new=max_new_tokens,
                      t_submit=time.perf_counter(),
                      t_submit_wall=time.time(),
                      tenant=sanitize_tenant(tenant),
                      trace_id=int(trace_id or 0))
        # every request gets a migration-addressable id: caller-supplied,
        # or engine-salted auto id (the salt keeps auto rids distinct
        # across replicas sharing a transport namespace).  Wire frame
        # tags are colon-delimited, so rids must not contain ':'.
        if request_id is not None and ":" in request_id:
            raise ValueError(f"request_id {request_id!r} contains ':'")
        req.rid = (request_id if request_id is not None
                   else f"r{self._rid_salt}-{next(self._rid_counter)}")
        # staged premigrated blocks (submit_premigrated) attach BEFORE
        # the queue put: the scheduler thread may pop the request the
        # instant it lands, and a late-attached staging would silently
        # cold-prefill the full prompt instead of importing
        if _staged is not None:
            req._staged = _staged
        if _replay is not None:
            # resume replay state (submit_resumed) attaches before the
            # queue put for the same reason as _staged: the scheduler
            # may pop the request instantly, and a late attach would
            # stream the replayed prefix to the client a second time
            req.resumed = True
            req._suppress = _replay["suppress"]
            req._rng_rewind = _replay["rewind"]
        with self._submit_lock:
            if not self._running:
                raise RuntimeError("engine is closed")
            self._by_rid[req.rid] = req
            self._queue.put(req)
        # workload sketch: admitted arrivals only (shed requests above
        # never became workload); t_submit doubles as the interarrival
        # clock so the sketch is a pure fold over the request trace
        self._sketch.record_request(len(prompt), tenant=req.tenant,
                                    now=req.t_submit)
        return req

    def submit_premigrated(self, prompt_ids, max_new_tokens: int,
                           k_blocks, v_blocks) -> Request:
        """Decode-side JOIN of a disaggregated request (docs/DESIGN.md
        §15): the prompt's whole-block K/V was computed by a prefill
        worker and migrated here as host block payloads
        ``[n, L, H, bt, D]``.  Admission first lands the blocks in the
        page pool (one device scatter, ``adopt_blocks_into_pages``) and
        ADOPTS them into the radix tree (``store_shared`` — the §11
        ownership-transfer seam, so the invariant `every page owned by
        tree xor one request` is preserved verbatim); the request then
        admits through the ordinary paged path, whose ``match`` finds
        the adopted prefix as block-table references — zero dense-row
        H2D — and only the ≤ one-block suffix prefills here.  The
        import runs ON the scheduler thread between steps (the pool
        buffers are donated every dispatch; a foreign-thread write
        would race them).

        ``k_blocks=None`` (a short prompt with no migratable whole
        block) degrades to a plain :meth:`submit`.

        Quantized migrations (docs/DESIGN.md §17) arrive as
        :class:`~..ops.quant.QuantizedKVPages` payloads — narrow bytes +
        scale sidecar, adopted VERBATIM into a matching quantized pool
        (the decode side holds bit-identical pages to the prefill
        side); a full-width payload into a quantized pool quantizes at
        the adopt scatter."""
        if k_blocks is None:
            return self.submit(prompt_ids, max_new_tokens)
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        from ..ops.quant import QuantizedKVPages
        if isinstance(k_blocks, QuantizedKVPages):
            if (not isinstance(self._pk, QuantizedKVPages)
                    or self._pk.bits != k_blocks.bits):
                raise ValueError(
                    f"premigrated int{k_blocks.bits} blocks need a "
                    f"matching quantized pool; this engine's pages are "
                    f"kv_dtype={self.kv_dtype!r}")
        else:
            k_blocks = np.asarray(k_blocks)
            v_blocks = np.asarray(v_blocks)
        bt = self.kv_cache.block_tokens
        want = (self.cfg.num_layers, self.cfg.num_kv_heads, bt,
                self.cfg.head_dim)
        if (k_blocks.shape != v_blocks.shape or k_blocks.ndim != 5
                or k_blocks.shape[1:] != want):
            raise ValueError(
                f"premigrated blocks must be [n, L, H, bt, D] = "
                f"[n, {want[0]}, {want[1]}, {want[2]}, {want[3]}]; got "
                f"K {k_blocks.shape} / V {v_blocks.shape}")
        if k_blocks.shape[0] > len(prompt) // bt:
            raise ValueError(
                f"{k_blocks.shape[0]} migrated blocks exceed the "
                f"prompt's {len(prompt) // bt} whole blocks")
        return self.submit(prompt, max_new_tokens,
                           _staged={"k": k_blocks, "v": v_blocks,
                                    "imported": False})

    def submit_resumed(self, prompt_ids, max_new_tokens: int,
                       delivered_tokens, *,
                       request_id: Optional[str] = None,
                       tenant: Optional[str] = None,
                       trace_id: int = 0) -> Request:
        """Admit a stream that already delivered tokens on a dead
        replica (docs/DESIGN.md §23): re-derive the delivered prefix
        through the NORMAL paged admission — mixed dispatch, prefix
        reuse, speculation all included — verify it token-by-token
        against the journal, and stream only the suffix.  The caller
        passes the ORIGINAL ``prompt_ids`` / ``max_new_tokens`` plus
        the delivered token ids, so the resumed stream is bit-identical
        to the unfailed run:

        - **greedy** engines extend the prompt with ``delivered[:-1]``
          and prefill it like any other prompt (a delivered token's KV
          is exact regardless of whether prefill or decode produced
          it); admission's argmax re-derives ``delivered[-1]`` and the
          suppress queue verifies it.  Exact on ANY survivor, warm or
          busy.
        - **sampled** engines re-submit the original prompt and rewind
          the engine rng to the constructor seed immediately before
          this request's token-#1 split, replaying the exact per-step
          split schedule (admission split, then one decode split per
          dispatch) that produced the delivered tokens.  Exact when the
          survivor replays the original run's dispatch pattern — same
          engine config and seed, request decoding alone from slot 0
          (the §18/§19 single-stream pinning scope); any deviation is
          caught by the verify queue and fails the request instead of
          streaming a silently-wrong suffix.

        Replayed tokens append to ``tokens`` (budget/page math stays
        exact) but never re-enter the stream queue; the replay window
        is recorded as ``resume_pause`` (the migration-pause analog) so
        the SLO decomposition still sums."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        delivered = [int(t) for t in
                     np.asarray(delivered_tokens, np.int64).reshape(-1)]
        k = len(delivered)
        if k == 0:
            raise ValueError("resume needs at least one delivered token")
        if k >= max_new_tokens:
            raise ValueError(
                f"{k} delivered tokens leave nothing to resume "
                f"(max_new_tokens={max_new_tokens})")
        if self.eos_id is not None and self.eos_id in delivered:
            raise ValueError(
                "delivered tokens contain eos — the stream already "
                "completed and has nothing to resume")
        if self.sampling.greedy:
            ext = np.concatenate(
                [prompt, np.asarray(delivered[:-1], np.int32)])
            replay = {"suppress": deque([delivered[-1]]),
                      "rewind": False}
            req = self.submit(ext, max_new_tokens - (k - 1),
                              _replay=replay, request_id=request_id,
                              tenant=tenant, trace_id=trace_id)
        else:
            replay = {"suppress": deque(delivered), "rewind": True}
            req = self.submit(prompt, max_new_tokens, _replay=replay,
                              request_id=request_id, tenant=tenant,
                              trace_id=trace_id)
        self.resume_stats["requests"] += 1
        self._flight.record("resume_admit", rid=req.rid, delivered=k,
                            greedy=bool(self.sampling.greedy))
        return req

    def _import_staged(self, req: Request) -> None:
        """Land a premigrated request's staged blocks in the pool and
        adopt them into the tree — scheduler thread only, once, before
        the ordinary ``match``/alloc admission runs.  On pool pressure
        (alloc infeasible even with eviction) the request goes back to
        pending via :class:`_BlocksExhausted`, staged data intact."""
        st = getattr(req, "_staged", None)
        if st is None or st["imported"]:
            return
        mgr = self.kv_cache
        n = st["k"].shape[0]
        ids = mgr.alloc(n)
        if ids is None:
            req._pkv_blocked = (mgr.epoch, mgr.free_blocks)
            raise _BlocksExhausted()
        from .kvcache.device import adopt_blocks_into_pages
        bt = mgr.block_tokens
        _sig = _profiling.dispatch_signature(
            "disagg_adopt", batch=n, chunk=bt,
            kv_dtype=self.kv_cache.kv_dtype)
        _t0 = self._prof.begin(_sig)
        self._pk, self._pv = adopt_blocks_into_pages(
            self._pk, self._pv, jax.tree.map(jnp.asarray, st["k"]),
            jax.tree.map(jnp.asarray, st["v"]),
            jnp.asarray(np.asarray(ids, np.int32)))
        self._prof.end(_sig, _t0, out=(self._pk, self._pv),
                       hbm_bytes=n * bt * self._kv_bytes_per_token)
        adopted, lease = mgr.store_shared(req.prompt[:n * bt], ids)
        adopted_set = set(adopted)
        leftovers = [b for b in ids if b not in adopted_set]
        if leftovers:
            # another request's store covered some blocks first: the
            # redundant pages go straight back to the pool (the tree
            # kept the incumbent's copies)
            mgr.free(leftovers)
        if lease is not None:
            # adoption is complete and the pages are tree-owned; the
            # admission's own match() re-pins them on this same thread
            # before any other mutation can evict them
            lease.release()
        st["imported"] = True
        st["k"] = st["v"] = None       # staged host buffers are done
        self.disagg_stats["premigrated_requests"] += 1
        self.disagg_stats["adopted_pages"] += len(adopted)
        self._flight.record("disagg_engine_adopt", blocks=len(adopted),
                            prompt_len=len(req.prompt))

    # ------------------------------------------------------------------
    # live migration (docs/DESIGN.md §18): checkpoint a decoding row out
    # of this engine / adopt one into it

    def export_request(self, rid, *, detach: bool = False,
                       timeout: Optional[float] = 30.0) -> dict:
        """Snapshot everything a decoding row owns — used KV pages
        (verbatim, quantized pools included), emitted tokens/logprobs,
        the sampler rng key, valid length + last token, budget and
        kv_dtype tags — as a host-side checkpoint dict
        :meth:`import_request` resumes from.

        Runs ON the scheduler thread between steps (posted via a
        mailbox; the caller blocks up to ``timeout``), so the snapshot
        is step-consistent: no token is half-recorded and the page
        gather can't race a donated-pool dispatch.

        ``detach=True`` additionally removes the request from the
        engine — slot freed, pages released back to the pool — while
        leaving its ``stream`` OPEN and ``done`` unset: the caller now
        owns delivery (the migration relay feeds the stream from the
        target replica).  Detach is the atomic-handoff freeze point: the
        row decodes up to the step before the snapshot and never after
        it, so the target resuming AT the snapshot replays at most the
        in-flight step — never skips one.

        Speculative rows export at a VERIFY BOUNDARY (exports are
        serviced between dispatches, where no draft is in flight): the
        checkpoint carries per-row adaptive-K state (``spec_k`` +
        acceptance EWMA) but NOT the draft scratch pages or n-gram
        history — the importer rebuilds proposer state from
        prompt+tokens, which is cheap and exact (docs/DESIGN.md §22)."""
        req = rid if isinstance(rid, Request) else self._by_rid.get(rid)
        if req is None:
            raise KeyError(f"unknown request id {rid!r}")
        box = {"req": req, "detach": detach, "ckpt": None, "err": None,
               "claimed": False, "abandoned": False,
               "event": threading.Event()}
        with self._submit_lock:
            if not self._running:
                raise RuntimeError("engine is closed")
            self._export_q.append(box)
            self._queue.put(_WAKE)
        if not box["event"].wait(timeout):
            # a scheduler stalled past the timeout (first-step jit
            # compile, pool-pressure wave) may still service this box
            # LATER — with detach=True that would orphan the request:
            # pages released, stream never fed, no caller left to own
            # delivery.  Abandon the box so a late service is a no-op;
            # if the scheduler claimed it in the race window the export
            # is executing right now, so wait the result out instead.
            with self._submit_lock:
                if not box["claimed"]:
                    box["abandoned"] = True
            if box["abandoned"]:
                raise TimeoutError(
                    "export_request timed out waiting for the "
                    "scheduler; the export was abandoned and the "
                    "request left untouched")
            box["event"].wait()
        if box["err"] is not None:
            raise box["err"]
        return box["ckpt"]

    def _service_exports(self) -> None:
        """Serve queued export_request mailboxes — scheduler thread,
        once per iteration, between steps.  The claimed/abandoned
        handshake (under ``_submit_lock``) makes a timed-out caller's
        box a no-op: servicing it anyway could detach a row nobody
        owns."""
        while self._export_q:
            box = self._export_q.popleft()
            with self._submit_lock:
                if box.get("abandoned"):
                    continue
                box["claimed"] = True
            try:
                box["ckpt"] = self._export_one(box["req"], box["detach"])
            except BaseException as e:
                box["err"] = e
            box["event"].set()

    def _export_one(self, req: Request, detach: bool) -> dict:
        if req.done.is_set():
            raise ValueError(f"request {req.rid!r} already finished")
        if req.cancelled:
            raise ValueError(f"request {req.rid!r} was cancelled")
        slot = next((i for i, r in enumerate(self._slots) if r is req),
                    None)
        mid_adm = ((self._adm is not None and self._adm["req"] is req)
                   or any(a["req"] is req for a in self._adms))
        if slot is None and mid_adm:
            raise ValueError(
                f"request {req.rid!r} is mid-chunked-admission; retry "
                "after its final prefill lands")
        ckpt = {"rid": req.rid,
                "prompt": np.asarray(req.prompt, np.int32),
                "max_new": int(req.max_new),
                "tokens": list(req.tokens), "lps": list(req.lps),
                "kv_dtype": self.kv_dtype,
                "block_tokens": int(self.kv_cache.block_tokens),
                "eos_id": self.eos_id,
                # observability identity rides the checkpoint so the
                # adopting replica's spans/accounting stay attributed
                "tenant": req.tenant, "trace_id": int(req.trace_id),
                "t_submit_wall": float(req.t_submit_wall),
                "migration_pause": float(req.migration_pause)}
        if slot is None:
            # still queued: a cold checkpoint (no pages, nothing
            # emitted) — the importer degrades it to a plain submit
            ckpt.update(length=0, last_tok=0, k=None, v=None, rng=None)
            n_used = 0
        else:
            # KV validity: prefill writes [0, plen) and samples token 1;
            # each decode step writes last_tok's KV at `lengths` then
            # increments — after T emitted tokens lengths = plen + T - 1
            # and KV [0, lengths) is valid.  The partial tail block
            # ships verbatim: its columns past `lengths` hold garbage
            # the stale-slot invariant already covers (decode rewrites
            # them before any query attends).
            length = int(np.asarray(self._lengths)[slot])
            last_tok = int(np.asarray(self._last_tok)[slot])
            bt = self.kv_cache.block_tokens
            n_used = -(-length // bt)
            ids = np.asarray(self._tables[slot][:n_used], np.int32)
            from .kvcache.device import export_blocks_from_pages
            k_run, v_run = export_blocks_from_pages(
                self._pk, self._pv, jnp.asarray(ids))
            ckpt.update(length=length, last_tok=last_tok,
                        k=jax.tree.map(np.asarray, k_run),
                        v=jax.tree.map(np.asarray, v_run),
                        rng=np.asarray(self._rng).copy())
            if self._spec_step is not None or self._pld_step is not None:
                # verify-boundary freeze (§22): adaptive-K state ships;
                # draft scratch / history do not (importer rebuilds)
                ckpt["spec_k"] = int(self._spec_krow[slot])
                ckpt["spec_ewma"] = float(self._spec_ewma[slot])
        self.migration_stats["exported_requests"] += 1
        if detach:
            if slot is not None:
                self._slots[slot] = None
                self._sentinel_slot(slot)
                self._release_request_kv(req)
            else:
                try:
                    self._pending.remove(req)
                except ValueError:
                    pass
            if req.rid is not None and self._by_rid.get(req.rid) is req:
                del self._by_rid[req.rid]
            req._detached = True
            # freeze point: the migration pause runs from here until the
            # first RELAYED token lands on the request's stream (the
            # relay's _on_tok closes it) — the timeline's pause field
            req.migrated = True
            req._pause_t0 = time.perf_counter()
            self.migration_stats["detached_requests"] += 1
        self._flight.record("migration_export", rid=req.rid,
                            tokens=len(req.tokens), blocks=n_used,
                            detach=detach)
        return ckpt

    def import_request(self, ckpt: dict,
                       request_id: Optional[str] = None) -> Request:
        """Adopt an :meth:`export_request` checkpoint: the shipped pages
        land in freshly allocated pool pages (one device scatter, the
        same ``adopt_blocks_into_pages`` join premigrated prefills use),
        whole-PROMPT blocks are adopted into the radix tree (pages
        holding generated tokens stay request-private — `page owned by
        tree xor request` holds verbatim), and decode resumes at the
        checkpointed length with NO prefill dispatch and zero dense-row
        h2d.  Restoring the rng key makes a single-request resume
        sample-exact; greedy streams are bit-identical regardless."""
        rid = request_id if request_id is not None else ckpt.get("rid")
        if not ckpt.get("tokens") or int(ckpt.get("length") or 0) <= 0:
            # cold checkpoint: nothing decoded yet — plain admission
            # (still marked adopted: the source relay owns the client-
            # visible timeline even for a cold handoff)
            req = self.submit(ckpt["prompt"], ckpt["max_new"],
                              request_id=rid,
                              tenant=ckpt.get("tenant"),
                              trace_id=int(ckpt.get("trace_id") or 0))
            req.adopted = True
            return req
        if ckpt.get("kv_dtype", "bf16") != self.kv_dtype:
            raise ValueError(
                f"checkpoint kv_dtype {ckpt.get('kv_dtype')!r} does not "
                f"match this engine's {self.kv_dtype!r} pages")
        bt = self.kv_cache.block_tokens
        if int(ckpt.get("block_tokens", bt)) != bt:
            raise ValueError(
                f"checkpoint block_tokens {ckpt.get('block_tokens')} != "
                f"pool block_tokens {bt}")
        prompt = np.asarray(ckpt["prompt"], np.int32).reshape(-1)
        max_new = int(ckpt["max_new"])
        tokens = [int(t) for t in ckpt["tokens"]]
        if len(tokens) >= max_new:
            raise ValueError("checkpointed request has no budget left")
        check_capacity(self.max_seq, len(prompt), max_new)
        need = -(-(len(prompt) + max_new + self._slack_tokens) // bt)
        if need > self.kv_cache.num_blocks:
            raise ValueError(
                f"checkpoint needs {need} KV blocks but the pool holds "
                f"only {self.kv_cache.num_blocks}")
        length = int(ckpt["length"])
        if length != len(prompt) + len(tokens) - 1:
            raise ValueError(
                f"checkpoint length {length} != prompt {len(prompt)} + "
                f"emitted {len(tokens)} - 1")
        n_used = -(-length // bt)
        n_shipped = jax.tree.leaves(ckpt["k"])[0].shape[0]
        if n_shipped != n_used:
            raise ValueError(
                f"checkpoint ships {n_shipped} blocks; length "
                f"{length} needs {n_used}")
        req = Request(prompt=prompt, max_new=max_new,
                      t_submit=time.perf_counter(),
                      tenant=sanitize_tenant(ckpt.get("tenant")),
                      trace_id=int(ckpt.get("trace_id") or 0),
                      t_submit_wall=float(ckpt.get("t_submit_wall") or 0),
                      migration_pause=float(
                          ckpt.get("migration_pause") or 0),
                      migrated=True, adopted=True)
        req.rid = rid
        req.tokens = tokens
        req.lps = [float(x) for x in (ckpt.get("lps") or [])]
        req.t_first = time.perf_counter()
        req._resume = {"k": ckpt["k"], "v": ckpt["v"], "length": length,
                       "last_tok": int(ckpt["last_tok"]),
                       "rng": ckpt.get("rng"),
                       "spec_k": int(ckpt.get("spec_k") or 0),
                       "spec_ewma": float(ckpt.get("spec_ewma") or 0.0)}
        with self._submit_lock:
            if not self._running:
                raise RuntimeError("engine is closed")
            if rid is not None:
                self._by_rid[rid] = req
            self._queue.put(req)
        return req

    def get_request(self, rid: str) -> Optional[Request]:
        """The live Request registered under ``rid`` (None once it
        finished or was detached) — the migration relay grabs the handle
        BEFORE the detaching export removes the registration."""
        return self._by_rid.get(rid)

    def active_requests(self) -> list:
        """``[(rid, emitted, remaining)]`` for currently decoding slots
        — the migration controller's load view.  Racy read-only snapshot
        (any thread); rows mid-admission or queued are excluded."""
        out = []
        for r in list(self._slots):
            if r is not None and r.rid is not None and not r.cancelled:
                out.append((r.rid, len(r.tokens),
                            r.max_new - len(r.tokens)))
        return out

    def generate(self, prompt_ids: np.ndarray, max_new_tokens: int,
                 seed: int = 0, timeout: Optional[float] = None,
                 logprobs: bool = False, tenant: Optional[str] = None,
                 trace_id: int = 0) -> GenerationResult:
        """Engine-surface convenience: submit each row as its own request
        (they batch with whatever else is in flight) and wait for all.
        ``seed`` is accepted for surface compatibility but not honored —
        see the module docstring.  On ``timeout`` the requests are
        cancelled (slots freed) before TimeoutError propagates.

        ``logprobs=True`` additionally returns each emitted token's raw
        log-softmax probability (the engines' OpenAI-style convention) —
        plain slot decoding only; the speculative proposers' verify
        rounds do not score emitted tokens.  Rows that finished early
        pad logprobs with 0.0 alongside their eos-padded tokens."""
        if logprobs and (self._spec_step is not None
                         or self._pld_step is not None):
            raise ValueError(
                "logprobs are not supported with speculative slot "
                "decoding (draft or prompt-lookup proposers)")
        ids = np.asarray(prompt_ids)
        if ids.ndim == 1:
            ids = ids[None, :]
        t0 = time.perf_counter()
        reqs = self._submit_rows(ids, max_new_tokens, tenant=tenant,
                                 trace_id=trace_id)
        try:
            rows = [r.wait(timeout=timeout) for r in reqs]
        except TimeoutError:
            for r in reqs:
                r.cancel()
            raise
        width = max(len(r) for r in rows)
        pad_id = self.eos_id if self.eos_id is not None else 0
        toks = np.full((len(rows), width), pad_id, np.int32)
        lps = np.zeros((len(rows), width), np.float32) if logprobs else None
        for i, r in enumerate(rows):
            toks[i, :len(r)] = r
            if logprobs:
                lps[i, :len(r)] = reqs[i].lps
        return GenerationResult(tokens=toks, prompt_len=ids.shape[1],
                                num_new=width,
                                seconds=time.perf_counter() - t0,
                                logprobs=lps)

    def _submit_rows(self, ids: np.ndarray, max_new_tokens: int,
                     tenant: Optional[str] = None,
                     trace_id: int = 0) -> list:
        """Submit every row or none: if a later row is shed by the
        admission-depth gate, rows already admitted are cancelled before
        the SchedulerOverloaded propagates — a 503'd multi-row request
        must not leave orphan rows burning slots while the server sheds
        load."""
        reqs = []
        try:
            for row in ids:
                reqs.append(self.submit(row, max_new_tokens,
                                        tenant=tenant, trace_id=trace_id))
        except Exception:
            for r in reqs:
                r.cancel()
            raise
        return reqs

    def generate_stream(self, prompt_ids: np.ndarray, max_new_tokens: int,
                        seed: int = 0, timeout: Optional[float] = None,
                        tenant: Optional[str] = None, trace_id: int = 0,
                        resume: Optional[dict] = None):
        """Yield [batch] token arrays per step (HTTP streaming surface).
        Single-row streaming only batches trivially; multi-row prompts
        stream in lockstep of the slowest admitted row.  An ABANDONED
        stream (client disconnect, or a stop-sequence early exit closing
        the generator) cancels its in-flight requests, freeing their
        slots after the current step instead of decoding to max_new.
        ``timeout``: overall wall-clock deadline — on expiry the
        requests are cancelled (slots freed) and TimeoutError raised,
        so a consumer with a deadline never blocks on a wedged
        scheduler (the --request-timeout contract).

        ``resume``: ``{"delivered_tokens": [...], "rng_step_offset":
        N}`` — gateway-failover resumption (docs/DESIGN.md §23,
        single-row only): the stream yields only the tokens AFTER the
        delivered prefix, which :meth:`submit_resumed` re-derives and
        verifies bit-exactly."""
        ids = np.asarray(prompt_ids)
        if ids.ndim == 1:
            ids = ids[None, :]
        deadline = None if not timeout else time.monotonic() + timeout
        if resume is not None:
            if ids.shape[0] != 1:
                raise ValueError("resume supports a single prompt row")
            delivered = resume.get("delivered_tokens")
            if not isinstance(delivered, (list, tuple)) or not delivered:
                raise ValueError(
                    "resume.delivered_tokens must be a non-empty list")
            off = resume.get("rng_step_offset", len(delivered))
            if int(off) != len(delivered):
                raise ValueError(
                    f"resume.rng_step_offset ({off}) must equal "
                    f"len(delivered_tokens) ({len(delivered)}) — the "
                    "rng schedule is derived from the delivered count")
            reqs = [self.submit_resumed(ids[0], max_new_tokens,
                                        delivered, tenant=tenant,
                                        trace_id=trace_id)]
        else:
            reqs = self._submit_rows(ids, max_new_tokens, tenant=tenant,
                                     trace_id=trace_id)
        fetched = [[] for _ in reqs]
        finished = [False] * len(reqs)   # row's None sentinel was consumed
        try:
            for step_i in range(max_new_tokens):
                out = []
                for i, r in enumerate(reqs):
                    while not finished[i] and len(fetched[i]) <= step_i:
                        try:
                            item = r.stream.get(
                                timeout=None if deadline is None else
                                max(0.0, deadline - time.monotonic()))
                        except queue.Empty:
                            raise TimeoutError(
                                f"request deadline ({timeout}s) "
                                "exceeded") from None
                        if item is None:  # end sentinel: EOS, or failure
                            finished[i] = True
                            if r.error is not None:
                                # a scheduler/device failure must surface
                                # to the streaming consumer, not end the
                                # stream as a cleanly-truncated
                                # generation (siblings cancel in the
                                # finally below)
                                raise r.error
                        else:
                            fetched[i].append(item)
                    out.append(fetched[i][step_i]
                               if step_i < len(fetched[i]) else None)
                if all(o is None for o in out):
                    return
                pad = self.eos_id if self.eos_id is not None else 0
                yield np.asarray([pad if o is None else o for o in out],
                                 np.int32)
        finally:
            for r in reqs:
                if not r.done.is_set():
                    r.cancel()

    def _pending_prefill_tokens(self) -> int:
        """Queued + mid-admission prompt tokens still awaiting prefill —
        the gateway's bounded-load router weighs this BACKLOG, not just
        request counts (one 10k-token prompt loads a replica far more
        than ten 30-token chats, docs/DESIGN.md §19).  Racy snapshot
        reads of scheduler-owned state: a gauge, not an invariant."""
        import copy as _copy
        total = 0
        # queue.Queue's underlying deque: __copy__ is atomic under the
        # GIL (same idiom as the latency reservoirs below); sentinels
        # (_WAKE, shutdown None) are filtered by the isinstance check
        for r in _copy.copy(self._queue.queue):
            if isinstance(r, Request):
                total += len(r.prompt)
        for r in _copy.copy(self._pending):
            total += len(r.prompt)
        adm = self._adm
        if adm is not None:
            total += max(0, len(adm["req"].prompt) - adm["start"])
        for a in list(self._adms):
            total += max(0, len(a["req"].prompt) - a["start"])
        return total

    def _spec_backlog_tokens(self) -> int:
        """Per-iteration speculative token cost of the ACTIVE rows —
        Σ (K_row + 1) · decode_block — the spec twin of the prefill
        backlog above: the gateway's bounded-load router weighs it so a
        replica mid-speculation (whose budget the spec rows are eating)
        stops looking as idle as a plain-decode one (§22).  Racy
        snapshot of scheduler-owned state: a gauge, not an invariant."""
        if self._spec_step is None and self._pld_step is None:
            return 0
        total = 0
        for i, r in enumerate(self._slots):
            if r is not None:
                k = (int(self._spec_krow[i]) if self.spec_adaptive
                     else int(self._spec_buckets[-1]))
                total += (k + 1) * self.decode_block
        return total

    def stats(self) -> dict:
        """Scheduler counters for the HTTP ``/stats`` surface."""
        import copy as _copy

        from .stats import _percentile
        out = {"slots": self.max_batch, "steps": self._step_count,
               "kv_layout": self.kv_layout,
               # live occupancy for the /metrics gauges: submitted-but-
               # unslotted requests vs slots mid-decode (racy reads of
               # scheduler-owned state — gauges, not invariants)
               "queue_depth": self._queue.qsize() + len(self._pending),
               "pending_prefill_tokens": self._pending_prefill_tokens(),
               "active_slots": sum(1 for s in self._slots
                                   if s is not None)}
        if self.kv_cache is not None:
            out["kvcache"] = self.kv_cache.snapshot()
        # dispatch-floor picture (§13): dispatches vs device steps —
        # steps/dispatches ≈ decode_block when fusion is engaging
        out["device_loop"] = dict(self.loop_stats,
                                  decode_block=self.decode_block)
        # completed is the MONOTONIC count; the reservoirs are bounded
        # (the last 512 samples feed the percentiles).  deque.__copy__ is
        # atomic under the GIL — plain iteration would race the
        # scheduler thread's appends and raise "deque mutated".
        lat = {"completed": self._completed}
        for name, res in self._lat.items():
            xs = sorted(_copy.copy(res))   # one sort; _percentile's own
            if xs:                         # sort is then O(n) on sorted
                lat[f"{name}_p50_ms"] = round(_percentile(xs, 50) * 1e3, 3)
                lat[f"{name}_p95_ms"] = round(_percentile(xs, 95) * 1e3, 3)
        out["latency"] = lat
        if self.prefill_chunk is not None:
            cs = self.chunk_stats
            out["chunked_prefill"] = {
                "chunk": self.prefill_chunk,
                "chunks": cs["chunks"],
                "interleaved_steps": cs["interleaved_steps"]}
        if self.mixed_token_budget > 0:
            cs = self.chunk_stats
            out["mixed"] = {
                "token_budget": self.mixed_token_budget,
                "dispatches": cs["mixed_dispatches"],
                "prefill_tokens": cs["mixed_prefill_tokens"],
                # fraction of offered budget actually carried (prefill
                # segment tokens + fused decode tokens per dispatch)
                "budget_utilization": (
                    round(cs["mixed_packed_tokens"]
                          / cs["mixed_budget_tokens"], 4)
                    if cs["mixed_budget_tokens"] else None)}
        if self.disagg_stats["premigrated_requests"]:
            out["disagg"] = dict(self.disagg_stats)
        if self.resume_stats["requests"]:
            out["resumed"] = dict(self.resume_stats)
        if any(self.migration_stats.values()):
            out["migration"] = dict(self.migration_stats)
        # compile ledger (docs/DESIGN.md §20): the recompile_storm
        # detector below reads this fragment, and /stats readers get
        # the per-program compile picture for free
        compile_snap = _profiling.get_compile_tracker().snapshot()
        if compile_snap:
            out["compile"] = compile_snap
        if self._spec_step is not None or self._pld_step is not None:
            s = self.spec_stats
            # per-bucket occupancy of the ACTIVE rows' adaptive K_row —
            # the observable shrink signal (§22): a low-acceptance
            # workload walks mass toward bucket 1
            k_buckets = {
                str(b): int(sum(
                    1 for i, r in enumerate(self._slots)
                    if r is not None and int(self._spec_krow[i]) == b))
                for b in self._spec_buckets}
            out["speculative"] = {
                "proposer": ("prompt_lookup" if self._pld_step is not None
                             else "draft"),
                "num_draft": self.num_draft, "rounds": s["rounds"],
                "drafted": s["drafted"], "accepted": s["accepted"],
                "adaptive": bool(self.spec_adaptive),
                "k_row_buckets": k_buckets,
                "acceptance_rate": (round(s["accepted"] / s["drafted"], 4)
                                    if s["drafted"] else None)}
            out["spec_backlog_tokens"] = self._spec_backlog_tokens()
        # per-tenant SLO rollup (goodput + burn rates) rides the same
        # stats surface: the gateway's health prober stores it per
        # replica (the /debugz fleet summary) and the anomaly layer's
        # slo_burn detector consumes it below
        try:
            out["slo"] = get_slo_ledger().summary()
        except Exception:
            pass
        # anomaly watch rides every stats() reader as well as the
        # scheduler loop: an HTTP /metrics scrape runs on its OWN thread,
        # so the stalled-pipeline watchdog still observes (and fires)
        # when the scheduler thread itself is wedged inside a dispatch.
        # No recursion: the monitor's throttle window swallows the inner
        # observation its own stats() build would trigger.
        self.anomaly.observe(out)
        return out

    def debug_state(self) -> dict:
        """Backend fragment of ``GET /debugz``: anomaly-detector state
        (thresholds, streaks, recent firings, bundles written) + the KV
        cache picture (occupancy, LRU leaves, leased nodes)."""
        out = {"anomaly": self.anomaly.state(),
               "observatory": _profiling.observatory_state()}
        if self.kv_cache is not None:
            out["kvcache"] = self.kv_cache.debug_state()
        if self.disagg_stats["premigrated_requests"]:
            out["disagg"] = dict(self.disagg_stats)
        if any(self.migration_stats.values()):
            out["migration"] = dict(self.migration_stats)
        return out

    def reset_stats(self) -> None:
        self._step_count = 0
        self.loop_stats = {"host_dispatches": 0, "device_loop_steps": 0}
        if self.kv_cache is not None:
            self.kv_cache.reset_stats()
        self.spec_stats = {"rounds": 0, "drafted": 0, "accepted": 0}
        self._reset_chunk_stats()
        self._completed = 0
        for res in self._lat.values():
            res.clear()

    def _reset_chunk_stats(self) -> None:
        """ONE owner of the chunk/mixed counter shape — __init__ and
        reset_stats both call it, so the two sites cannot drift.
        ``mixed_packed_tokens`` counts prefill + decode tokens a mixed
        dispatch actually carried; ``mixed_budget_tokens`` the budget it
        was offered — their ratio is the budget-utilization gauge."""
        self.chunk_stats = {"chunks": 0, "interleaved_steps": 0,
                            "mixed_dispatches": 0,
                            "mixed_prefill_tokens": 0,
                            "mixed_packed_tokens": 0,
                            "mixed_budget_tokens": 0}

    def close(self):
        self._running = False
        self._queue.put(None)              # wake the scheduler
        self._thread.join(timeout=30)
        # the tier dies with its pool: demoted entries reference a page
        # layout the successor engine may not share, and the host ring /
        # mmap'd segment must not outlive the engine that budgeted them
        if self._kv_tier is not None:
            self._kv_tier.close()
        # reset-on-close: this engine's pool owners leave the process
        # watermark ledger (a successor engine's pools start a fresh
        # high-water history; other engines' owners are untouched)
        for owner in self._hbm_owners:
            self._hbm.reset(owner)
        self._hbm_owners.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------
    # scheduler

    def _bucket(self, n: int) -> int:
        for b in self.prompt_buckets:
            if n <= b:
                return b
        return self.max_seq

    def _reserve_pages(self, req: Request) -> int:
        """Paged admission, phase 1: reserve the request's pages and
        build its block table; returns the matched-prefix length m.

        - ``match`` returns page IDS for the matched prefix (pinned by a
          lease held until the request completes: the slot's table will
          reference those shared pages for its whole lifetime);
        - the private remainder — enough pages for prompt + max_new (+
          the speculative modes' fused-overshoot slack) — is allocated
          up front (LRU tree leaves evict under pressure), so decode can
          never run out of pages mid-flight; the draft pool (speculative
          mode) reserves the same span of scratch pages atomically with
          the target's; if even eviction cannot free enough,
          :class:`_BlocksExhausted` sends the request back to pending
          (a completion will free pages);
        - prefill then runs THROUGH the table (paged_prefill /
          _paged_chunk_mid / the mixed slab): a prefix hit reads the
          shared pages in place and writes start at the private
          frontier — zero bytes through the host,
          ``dwt_kvcache_h2d_bytes`` stays 0 on this path by
          construction."""
        mgr = self.kv_cache
        bt = mgr.block_tokens
        plen = len(req.prompt)
        n_total = -(-(plen + req.max_new + self._slack_tokens) // bt)
        # retry gate for a previously blocked admission: only re-attempt
        # once the pool could have changed (a completion frees at least
        # one private page — n_total strictly exceeds the adoptable
        # full-prompt blocks — and stores/evictions bump the epoch).
        # Without this, every scheduler iteration would re-run match(),
        # inflating hit/miss/reuse counters and flooding the flight ring
        # with phantom lookups while the request just waits.
        state = (mgr.epoch, mgr.free_blocks)
        if getattr(req, "_pkv_blocked", None) == state:
            raise _BlocksExhausted()
        # disaggregated join: land migrated blocks + adopt BEFORE the
        # match below, which then finds them as an ordinary prefix hit
        self._import_staged(req)
        # tier promotion (docs/DESIGN.md §21) rides the same seam: a
        # demoted continuation of the prompt's device-covered prefix
        # adopts back into the pool here, so the match below finds it
        # as an ordinary hit.  Best-effort: pool pressure skips it and
        # the suffix prefills (never _BlocksExhausted — a cold prefill
        # beats waiting on a warm one).
        if self._kv_tier is not None:
            from .kvcache import promote_prefix
            self._pk, self._pv, _ = promote_prefix(
                mgr, self._kv_tier, self._pk, self._pv, req.prompt,
                profiler=self._prof)
        lease = mgr.match(req.prompt)
        m = lease.tokens if lease is not None else 0
        n_pref = m // bt
        private = mgr.alloc(n_total - n_pref)
        if private is None:
            if lease is not None:
                lease.release()
            req._pkv_blocked = (mgr.epoch, mgr.free_blocks)
            raise _BlocksExhausted()
        dprivate = None
        if self._dmgr is not None:
            # draft scratch pages, reserved atomically with the
            # target's: a half-reserved admission must not wedge pages
            # while it waits for the other pool
            dprivate = self._dmgr.alloc(n_total)
            if dprivate is None:
                mgr.free(private)
                if lease is not None:
                    lease.release()
                req._pkv_blocked = (mgr.epoch, mgr.free_blocks)
                raise _BlocksExhausted()
        req._pkv_blocked = None
        table = np.full((self._table_width,), self._page_sentinel,
                        np.int32)
        if lease is not None:
            table[:n_pref] = lease.block_ids
        table[n_pref:n_total] = private
        dtable = None
        if dprivate is not None:
            dtable = np.full((self._table_width,), self._dpage_sentinel,
                             np.int32)
            dtable[:n_total] = dprivate
        req._pkv = {"lease": lease, "store_lease": None,
                    "private": private, "adopted": (), "n_pref": n_pref,
                    "table": table, "dprivate": dprivate,
                    "dtable": dtable, "released": False}
        # workload sketch: prefix-hit share = matched / prompt tokens,
        # recorded once per SUCCESSFUL reservation (a _BlocksExhausted
        # retry re-runs match and must not double-count)
        self._sketch.record_prefix(m, plen)
        return m

    def _release_request_kv(self, req: Request) -> None:
        """Return a paged request's KV resources: release its pins
        (matched prefix + stored path), free the private pages the
        tree did not adopt, and free the draft pool's scratch pages
        (never adopted by anything).  Idempotent — completion, cancel,
        failure, and the shutdown drain all funnel here."""
        st = getattr(req, "_pkv", None)
        if st is None or st["released"]:
            return
        st["released"] = True
        if st["lease"] is not None:
            st["lease"].release()
        if st["store_lease"] is not None:
            st["store_lease"].release()
        adopted = set(st["adopted"])
        self.kv_cache.free([b for b in st["private"]
                            if b not in adopted])
        if st["dprivate"] is not None:
            self._dmgr.free(st["dprivate"])

    def _needs_stream(self, req: Request) -> bool:
        """Does this prompt need the one-at-a-time chunk stream, or can
        it admit in a single dispatch?  Classified by the EFFECTIVE
        suffix (a KV-cache hit may shrink a long prompt to one
        dispatch — it must not wait behind an unrelated stream).  Pure
        peek: hit/miss accounting stays with ``_reserve_pages``.

        The decision is memoized on the request (``_stream_cls``),
        validated against the manager's mutation epoch: a blocked
        request is NOT rescanned every scheduler iteration, but a
        store/eviction invalidates the memo — a classification must
        never outlive the cache content it relied on (an evicted prefix
        would otherwise send a long prompt down the one-dispatch path,
        voiding the chunked activation-memory bound; evictions only
        happen inside stores, which bump the epoch)."""
        C = self.prefill_chunk
        if C is None:
            return False
        if getattr(req, "_resume", None) is not None:
            # a live-migration resume never prefills: its checkpoint IS
            # the row state, one adopt scatter regardless of prompt size
            return False
        st = getattr(req, "_staged", None)
        if st is not None and not st["imported"]:
            # premigrated join: the effective suffix after the adopt is
            # at most prompt - n_blocks*bt tokens regardless of what the
            # tree holds right now (the import lands before admission);
            # once imported, the normal peek below sees the adopted
            # prefix in the tree and classifies the same way
            return (len(req.prompt)
                    - st["k"].shape[0] * self.kv_cache.block_tokens) > C
        epoch = self.kv_cache.epoch if self.kv_cache is not None else 0
        cls = getattr(req, "_stream_cls", None)
        if cls is not None and cls[0] == epoch:
            return cls[1]
        needs = len(req.prompt) > C
        if needs and self.kv_cache is not None:
            m = self.kv_cache.peek(req.prompt)
            if m and len(req.prompt) - m <= C:
                needs = False
        req._stream_cls = (epoch, needs)
        return needs

    def _admit_request(self, slot: int, req: Request):
        # scheduler pickup: everything before this is queue wait,
        # everything from here to the first token is prefill (the
        # timeline ledger's TTFT decomposition)
        if req.t_sched == 0.0:
            req.t_sched = time.perf_counter()
        if getattr(req, "_resume", None) is not None:
            self._admit_resume(slot, req)
            return
        start = self._reserve_pages(req)
        self._finish_admission(slot, req, start, req.prompt[start:])

    def _admit_resume(self, slot: int, req: Request) -> None:
        """Adopt a live-migration checkpoint into a free slot (docs/
        DESIGN.md §18): scatter the shipped blocks into freshly
        allocated pages, adopt the whole-PROMPT blocks into the radix
        tree (pages holding generated tokens stay request-private), and
        install the slot state at the checkpointed length/last-token —
        no prefill dispatch, decode resumes exactly where the source
        froze.  Restoring the rng key hands over the sampler state (the
        pre-split order makes the key the whole of it)."""
        rs = req._resume
        mgr = self.kv_cache
        bt = mgr.block_tokens
        plen = len(req.prompt)
        n_total = -(-(plen + req.max_new + self._slack_tokens) // bt)
        # same pool-pressure retry gate as _reserve_pages
        state = (mgr.epoch, mgr.free_blocks)
        if getattr(req, "_pkv_blocked", None) == state:
            raise _BlocksExhausted()
        ids = mgr.alloc(n_total)
        if ids is None:
            req._pkv_blocked = state
            raise _BlocksExhausted()
        dids = None
        if self._dmgr is not None:
            # draft scratch, atomically with the target's pages (same
            # rule as _reserve_pages): the checkpoint does NOT ship
            # draft KV — it is rebuilt below from prompt + tokens
            dids = self._dmgr.alloc(n_total)
            if dids is None:
                mgr.free(ids)
                req._pkv_blocked = state
                raise _BlocksExhausted()
        req._pkv_blocked = None
        length = rs["length"]
        n_used = -(-length // bt)
        from .kvcache.device import adopt_blocks_into_pages
        self._pk, self._pv = adopt_blocks_into_pages(
            self._pk, self._pv, jax.tree.map(jnp.asarray, rs["k"]),
            jax.tree.map(jnp.asarray, rs["v"]),
            jnp.asarray(np.asarray(ids[:n_used], np.int32)))
        adopted, store_lease = (), None
        if plen // bt >= 1:
            adopted, store_lease = mgr.store_shared(
                req.prompt, ids[:plen // bt])
        table = np.full((self._table_width,), self._page_sentinel,
                        np.int32)
        table[:n_total] = ids
        dtable = None
        if dids is not None:
            dtable = np.full((self._table_width,), self._dpage_sentinel,
                             np.int32)
            dtable[:n_total] = dids
        req._pkv = {"lease": None, "store_lease": store_lease,
                    "private": ids, "adopted": tuple(adopted),
                    "n_pref": 0, "table": table, "dprivate": dids,
                    "dtable": dtable, "released": False}
        self._tables[slot] = table
        self._lengths, self._last_tok = self._set_slot_state(
            self._lengths, self._last_tok, jnp.int32(slot),
            jnp.int32(length), jnp.int32(rs["last_tok"]))
        if rs.get("rng") is not None:
            self._rng = jnp.asarray(np.asarray(rs["rng"]))
        if self._spec_step is not None or self._pld_step is not None:
            # §22 verify-boundary resume: the proposers' state is NOT in
            # the checkpoint — rebuild it exactly from prompt + emitted
            # tokens (KV [0, length) = prompt + tokens[:-1]; tokens[-1]
            # is last_tok, whose KV the next round's verify writes)
            hist = np.concatenate(
                [np.asarray(req.prompt, np.int32),
                 np.asarray(req.tokens[:-1], np.int32)])
            if self._spec_step is not None:
                dbucket = self._bucket(length)
                dpad = np.zeros((1, dbucket), np.int32)
                dpad[0, :length] = hist
                drow_k, drow_v = self._dprefill(
                    self.draft_params, jnp.asarray(dpad),
                    *self._zero_row_d())
                self._dpk, self._dpv = self._write_row(
                    self._dpk, self._dpv, drow_k, drow_v,
                    jnp.asarray(dtable))
                self._dtables[slot] = dtable
            if self._pld_step is not None:
                hpad = np.zeros((1, self._bucket(length)), np.int32)
                hpad[0, :length] = hist
                self._history = self._admit_h(
                    self._history, jnp.asarray(hpad), jnp.int32(slot),
                    jnp.int32(length), jnp.int32(rs["last_tok"]))
            k = int(rs.get("spec_k") or 0)
            self._spec_krow[slot] = next(
                (b for b in self._spec_buckets if b >= k),
                self._spec_buckets[-1]) if k > 0 \
                else self._spec_buckets[-1]
            self._spec_ewma[slot] = (float(rs.get("spec_ewma") or 0.0)
                                     or 1.0)
        self._slots[slot] = req
        req._resume = None          # staged host buffers are done
        self.migration_stats["imported_requests"] += 1
        self._flight.record("migration_import", slot=slot, rid=req.rid,
                            length=length, tokens=len(req.tokens),
                            blocks=n_used)

    def _start_admission(self, req: Request) -> bool:
        """Park a chunk-needing prompt as the in-progress admission the
        scheduler advances one dispatch per iteration (chunked admission
        is resumable state, NOT an inline loop: between dispatches the
        loop keeps decoding in-flight rows AND admitting other queued
        requests into free slots, so a long prompt head-blocks
        neither).  Returns False when the paged pool cannot reserve the
        request's pages yet — the caller requeues it (everything else,
        including failure, is handled here)."""
        try:
            start = self._reserve_pages(req)
        except _BlocksExhausted:
            return False
        except BaseException as e:
            self._fail_request(req, e)
            return True
        self._adm = {"req": req, "start": start, "m": start,
                     "suffix": req.prompt[start:]}
        return True

    def _advance_admission(self, free: list) -> None:
        """One dispatch of the in-progress admission: the next C-token
        chunk through the logits-free mid-chunk program, or — once the
        remainder fits one dispatch — the sampling final prefill into a
        free slot (parked until one frees).  Intermediate chunks are
        always full, so the next chunk overwrites the previous
        dispatch's padded tail exactly (stale-slot invariant)."""
        a = self._adm
        if a is None:
            return
        req, C = a["req"], self.prefill_chunk
        if req.cancelled:
            # bound cancel latency to one chunk, same property the
            # interleaving gives decode
            self._adm = None
            self._fail_request(req, None)
            return
        if len(a["suffix"]) > C:
            try:
                head = jnp.asarray(
                    np.asarray(a["suffix"][:C], np.int32)[None])
                _sig = _profiling.dispatch_signature(
                    "paged_chunk_mid", batch=1, chunk=C,
                    kv_dtype=self.kv_cache.kv_dtype)
                _t0 = self._prof.begin(_sig)
                self._pk, self._pv = self._paged_chunk_mid(
                    self.params, self._pk, self._pv, head,
                    jnp.asarray(req._pkv["table"][None]),
                    jnp.int32(a["start"]))
                self._prof.end(_sig, _t0, out=self._pk,
                               hbm_bytes=C * self._kv_bytes_per_token)
            except BaseException as e:
                # a per-request failure fails that request, never the
                # engine — same contract as every other admission
                # dispatch ("surface to the waiter")
                self._adm = None
                self._fail_request(req, e)
                return
            a["start"] += C
            a["suffix"] = a["suffix"][C:]
            self.chunk_stats["chunks"] += 1
        elif free:
            self._adm = None
            try:
                self._finish_admission(free.pop(0), req, a["start"],
                                       a["suffix"], prefix_reused=a["m"])
            except BaseException as e:
                self._fail_request(req, e)

    def _finish_admission(self, slot: int, req: Request, start: int,
                          suffix, prefix_reused: Optional[int] = None
                          ) -> None:
        """The sampling final prefill + slot install, shared by one-shot
        admission and the last dispatch of a chunked one.  The prefill
        runs THROUGH the request's block table straight into its
        reserved pages (no temp row, no scatter round trip); writes
        begin at ``start``, at/past the matched-prefix frontier, so the
        tree-owned shared pages are read-only by construction
        (prepare_kv_chunk's write contract)."""
        plen = len(req.prompt)
        st = req._pkv
        bucket = self._bucket(len(suffix))
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(suffix)] = suffix
        if getattr(req, "_rng_rewind", False):
            # §23 sampled resume: rewind the engine stream to the seed
            # key immediately before this request's token-#1 split, so
            # the replayed split schedule (this admission split, then
            # one decode split per dispatch) re-derives the delivered
            # tokens bit-exactly; the suppress queue verifies each one
            self._rng = jax.random.PRNGKey(self._seed)
            req._rng_rewind = False
        self._rng, sub = jax.random.split(self._rng)
        _sig = _profiling.dispatch_signature(
            "paged_prefill", batch=1, chunk=bucket,
            kv_dtype=self.kv_cache.kv_dtype)
        _t0 = self._prof.begin(_sig)
        self._pk, self._pv, tok, lp0 = self._paged_prefill(
            self.params, self._pk, self._pv, jnp.asarray(padded),
            jnp.asarray(st["table"][None]), jnp.int32(start),
            jnp.int32(len(suffix)), sub)
        self._prof.end(_sig, _t0, out=tok,
                       hbm_bytes=len(suffix) * self._kv_bytes_per_token)
        # store at PREFILL time, by ADOPTION: the tree takes
        # ownership of the full-prompt pages it was missing — the
        # next shared-prefix request block-table-references the
        # very same pages this one decodes against
        if plen // self.kv_cache.block_tokens >= 1:
            adopted, store_lease = self.kv_cache.store_shared(
                req.prompt,
                st["table"][:plen // self.kv_cache.block_tokens])
            st["adopted"] = adopted
            st["store_lease"] = store_lease
        self._tables[slot] = st["table"]
        self._lengths, self._last_tok = self._set_slot_state(
            self._lengths, self._last_tok, jnp.int32(slot),
            jnp.int32(plen), tok.astype(jnp.int32))
        if self._spec_step is not None:
            # draft-side pages: always the FULL prompt (prefix reuse
            # applies to the target cache only; the draft is cheap) —
            # prefilled into a dense temp row, scattered into the
            # request's reserved draft scratch pages, zero D2H
            dbucket = self._bucket(plen)
            dpad = np.zeros((1, dbucket), np.int32)
            dpad[0, :plen] = req.prompt
            drow_k, drow_v = self._dprefill(
                self.draft_params, jnp.asarray(dpad), *self._zero_row_d())
            self._dpk, self._dpv = self._write_row(
                self._dpk, self._dpv, drow_k, drow_v,
                jnp.asarray(st["dtable"]))
            self._dtables[slot] = st["dtable"]
        if self._pld_step is not None:
            # seed the slot's n-gram history: full prompt + first token
            hpad = np.zeros((1, self._bucket(plen)), np.int32)
            hpad[0, :plen] = req.prompt
            self._history = self._admit_h(
                self._history, jnp.asarray(hpad), jnp.int32(slot),
                jnp.int32(plen), tok.astype(jnp.int32))
        # fresh acceptor starts at the widest bucket (adaptive K re-learns
        # from this row's own acceptance; §22)
        self._spec_krow[slot] = self._spec_buckets[-1]
        self._spec_ewma[slot] = 1.0
        self._slots[slot] = req
        self._flight.record("batch_admit", slot=slot, prompt_len=plen,
                            max_new=req.max_new,
                            prefix_reused=(start if prefix_reused is None
                                           else prefix_reused))
        # lps stay empty (not a stale 1-entry list) in the speculative
        # modes, whose drains never score emitted tokens
        plain = self._spec_step is None and self._pld_step is None
        self._record_token(slot, req, int(tok),
                           float(lp0) if plain else None)

    def _record_row_blocks(self, em_np, counts, lps_np=None) -> None:
        """Record per-row emitted token blocks into the slots' requests
        (``counts[i]`` tokens from row i), stopping a row the moment it
        finishes (max_new/eos frees the slot mid-block — the stale-slot
        guard shared by the speculative rounds and the fused
        decode-block path).  ``lps_np``: matching per-token logprobs
        (plain mode; the speculative drains pass none)."""
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            for j in range(int(counts[i])):
                if self._slots[i] is None:
                    break              # row hit max_new or eos mid-block
                self._record_token(
                    i, req, int(em_np[i, j]),
                    None if lps_np is None else float(lps_np[i, j]))

    def _drain_spec_blocks(self, em_np, ns_np, k_vec=None) -> None:
        """Record one speculative round's per-row emitted blocks +
        acceptance stats — shared by the draft-model and prompt-lookup
        step branches.  Both counters come from the slots still OCCUPIED
        at drain time, so rounds after a row finished mid-block (fused
        decode_block) inflate neither drafted nor accepted.  ``k_vec``
        ([B] or None): the mixed dispatch's per-row draft widths —
        adaptive K prices drafted by what each row actually offered."""
        self._step_count += 1
        self.spec_stats["rounds"] += 1
        live = [i for i, r in enumerate(self._slots) if r is not None]
        self.spec_stats["drafted"] += (
            self.num_draft * len(live) if k_vec is None
            else int(sum(int(k_vec[i]) for i in live)))
        self.spec_stats["accepted"] += int(
            sum(int(ns_np[i]) - 1 for i in live))
        self._record_row_blocks(em_np, ns_np)

    def _record_token(self, slot: int, req: Request, tok: int,
                      lp: Optional[float] = None):
        sup = getattr(req, "_suppress", None)
        if sup:
            # §23 resume replay: the regenerated token must match the
            # journal exactly — append it (budget/page math counts it)
            # but never re-stream it.  A mismatch means the survivor's
            # replay diverged (foreign config, or a concurrent stream
            # reordered the rng spend): fail loudly, never emit a
            # silently-wrong suffix.
            expect = sup.popleft()
            if tok != expect:
                self.resume_stats["diverged"] += 1
                self._flight.record("resume_diverged", slot=slot,
                                    expect=expect, got=tok,
                                    replayed=len(req.tokens))
                self._slots[slot] = None
                self._fail_request(req, RuntimeError(
                    f"resume replay diverged at replayed token "
                    f"{len(req.tokens) + 1}: journal says {expect}, "
                    f"survivor regenerated {tok} (engine config/seed or "
                    "rng schedule differs from the original replica)"))
                self._sentinel_slot(slot)
                return
            req.tokens.append(tok)
            if lp is not None:
                req.lps.append(lp)
            if len(req.tokens) == 1:
                req.t_first = time.perf_counter()
            self.resume_stats["replayed_tokens"] += 1
            return
        if req.resumed and req.resume_pause == 0.0 and req.t_first:
            # first VISIBLE token of a resumed stream: the replay
            # window ends here, recorded like a migration pause so the
            # SLO timeline decomposition still sums exactly
            req.resume_pause = max(
                1e-9, time.perf_counter() - req.t_first)
        req.tokens.append(tok)
        if lp is not None:
            req.lps.append(lp)
        if len(req.tokens) == 1:
            req.t_first = time.perf_counter()
        req.stream.put(tok)
        hit_eos = self.eos_id is not None and tok == self.eos_id
        if len(req.tokens) >= req.max_new or hit_eos:
            req.t_done = time.perf_counter()
            self._completed += 1
            self._lat["ttft"].append(req.t_first - req.t_submit)
            self._lat["e2e"].append(req.t_done - req.t_submit)
            if len(req.tokens) > 1:
                self._lat["per_token"].append(
                    (req.t_done - req.t_first) / (len(req.tokens) - 1))
            req.stream.put(None)
            req.done.set()
            # workload sketch: realized decode length at completion
            self._sketch.record_decode(len(req.tokens))
            if req.rid is not None and self._by_rid.get(req.rid) is req:
                del self._by_rid[req.rid]
            self._slots[slot] = None
            # completion frees the pages: pins released, private
            # non-adopted pages back to the pool (target AND draft),
            # the slot's table rows sentineled so post-finish stale
            # writes drop
            self._release_request_kv(req)
            self._sentinel_slot(slot)
            self._flight.record("batch_done", slot=slot,
                                tokens=len(req.tokens),
                                reason="eos" if hit_eos else "length")
            self._close_timeline(req)

    def _fail_request(self, req: Request, err: Optional[BaseException]):
        """Finish a request (with an error, or cleanly for err=None).
        Releases any paged pages/pins the request reserved — cancel,
        failure, and the shutdown drain all reach KV cleanup through
        here (the slot's table row is reset by the callers that own
        one)."""
        self._release_request_kv(req)
        req.error = err
        req.stream.put(None)
        req.done.set()
        if req.rid is not None and self._by_rid.get(req.rid) is req:
            del self._by_rid[req.rid]
        if err is not None:
            get_flight_recorder().record(
                "batch_fail", error=type(err).__name__,
                tokens=len(req.tokens))
        self._close_timeline(
            req, error=(type(err).__name__ if err is not None
                        else ("cancelled" if req.cancelled else None)))

    def _close_timeline(self, req: Request,
                        error: Optional[str] = None) -> None:
        """Close ``req`` into the process SLO ledger exactly once —
        completion, failure, cancel, and the migration relay's fin (on
        the SOURCE replica, which owns the client connection) all funnel
        here.  Adopted (migrated-in) requests are skipped so a tenant's
        tokens are never double-counted across the fleet.  Best-effort:
        accounting must never add a failure to the request path."""
        if req.adopted or getattr(req, "_timeline_closed", False):
            return
        req._timeline_closed = True
        t_done = req.t_done if req.t_done else time.perf_counter()
        t_first = req.t_first if req.t_first else t_done
        t_sched = req.t_sched if req.t_sched else t_first
        try:
            get_slo_ledger().close_request(
                rid=req.rid or "", tenant=req.tenant,
                trace_id=req.trace_id,
                t_submit_wall=req.t_submit_wall,
                queue_wait_s=max(0.0, t_sched - req.t_submit),
                ttft_s=max(0.0, t_first - req.t_submit),
                e2e_s=max(0.0, t_done - req.t_submit),
                tokens=len(req.tokens),
                migration_pause_s=req.migration_pause,
                migrated=req.migrated,
                resume_pause_s=req.resume_pause,
                resumed=req.resumed, replica=self.tracer.proc,
                error=error)
            if req.trace_id:
                # engine spans for the fleet trace stitch: wall-clock
                # starts are reconstructed from the submit wall time +
                # perf_counter offsets, so the spans line up with the
                # gateway's proxy span without mixing clocks mid-span
                base = req.t_submit_wall or (time.time()
                                             - (t_done - req.t_submit))
                self.tracer.record(
                    "engine.prefill", req.trace_id,
                    ts=base + max(0.0, t_sched - req.t_submit),
                    dur=max(0.0, t_first - t_sched),
                    rid=req.rid, tenant=req.tenant)
                if t_done > t_first and len(req.tokens) > 1:
                    self.tracer.record(
                        "engine.decode", req.trace_id,
                        ts=base + max(0.0, t_first - req.t_submit),
                        dur=t_done - t_first, rid=req.rid,
                        tenant=req.tenant, tokens=len(req.tokens))
        except Exception:
            pass

    def register_aux_tracer(self, tracer) -> None:
        """Attach a co-located recorder (e.g. the migration worker's)
        so :meth:`export_trace` drains it with the engine's own spans."""
        self._aux_tracers.append(tracer)

    def export_trace(self) -> dict:
        """Chrome trace of the engine's span sink plus any registered
        auxiliary recorders (the replica ``GET /trace`` surface;
        ``/trace/fleet`` merges these across replicas).  Drains: each
        span exports exactly once."""
        spans = self.tracer.drain()
        for t in self._aux_tracers:
            try:
                spans.extend(t.drain())
            except Exception:
                pass
        return to_chrome_trace(spans)

    def _drain_all(self, err: BaseException):
        """Fail every in-flight slot, mid-admission, backlogged, and
        queued request with ``err``."""
        for i, req in enumerate(self._slots):
            if req is not None:
                self._fail_request(req, err)
                self._slots[i] = None
                self._sentinel_slot(i)
        if self._adm is not None:
            self._fail_request(self._adm["req"], err)
            self._adm = None
        for a in self._adms:
            self._fail_request(a["req"], err)
        self._adms = []
        while self._pending:
            self._fail_request(self._pending.popleft(), err)
        while self._export_q:
            box = self._export_q.popleft()
            box["err"] = err
            box["event"].set()
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            if req is not None and req is not _WAKE:
                self._fail_request(req, err)

    def _sweep_cancelled(self) -> None:
        """Free the slots of requests cancelled mid-flight — run once per
        scheduler iteration and between admission chunks, so a cancel's
        latency is bounded by one step/chunk either way."""
        for i, req in enumerate(self._slots):
            if req is not None and req.cancelled:
                self._fail_request(req, None)
                self._slots[i] = None
                self._sentinel_slot(i)

    def _sentinel_slot(self, slot: int) -> None:
        """Route a freed slot's future writes to nowhere: sentinel its
        block-table row(s) so post-finish stale writes drop."""
        self._tables[slot] = self._page_sentinel
        if self._dmgr is not None:
            self._dtables[slot] = self._dpage_sentinel

    def _eos_scalar(self):
        """eos_id as the traced sentinel scalar (-1 = disabled) — the
        fused loop's on-device eos check (engine.py convention)."""
        return jnp.int32(self.eos_id if self.eos_id is not None else -1)

    def _budget_vec(self) -> jnp.ndarray:
        """[B] remaining-token budget per slot (0 for empty slots): the
        fused loop's on-device row-done bound, so a block whose rows
        all reach max_new at step j < decode_block exits at j."""
        return jnp.asarray(
            [(r.max_new - len(r.tokens)) if r is not None else 0
             for r in self._slots], jnp.int32)

    def _count_loop(self, steps: int) -> None:
        from .engine import count_device_loop
        self.loop_stats["host_dispatches"] += 1
        self.loop_stats["device_loop_steps"] += steps
        count_device_loop(type(self).__name__, steps)

    def _sample_hbm(self) -> None:
        """Feed the HBM watermark ledger one scheduler-iteration sample
        per pool owner.  Pool accounting is host-side integers (no
        device sync); owners are remembered so close() can retire their
        watermarks (reset-on-close)."""
        snap = self.kv_cache.snapshot()
        self._hbm.sample("kv_page_pool",
                         snap.get("device_resident_bytes", 0)
                         + snap.get("quant_scale_bytes", 0))
        self._hbm_owners.add("kv_page_pool")
        if self._dmgr is not None:
            d = self._dmgr.snapshot()
            self._hbm.sample("draft_scratch",
                             d.get("device_resident_bytes", 0)
                             + d.get("quant_scale_bytes", 0))
            self._hbm_owners.add("draft_scratch")
        if self._kv_tier is not None:
            # host RAM, not HBM — but the same ledger answers the same
            # postmortem question ("how big did this pool get"), and
            # reset-on-close retires it with the engine's other owners
            self._hbm.sample("host_tier",
                             self._kv_tier.host_resident_bytes)
            self._hbm_owners.add("host_tier")

    def _decode_kv_bytes(self, active_mask, steps: int) -> int:
        """KV bytes one fused decode dispatch touched (achieved-GB/s
        attribution, SAMPLED dispatches only — the lengths readback
        here is a host sync the unsampled path must never pay): every
        active row re-reads its history each step and writes one
        token per step, priced by the pool's per-token byte math."""
        lens = np.asarray(self._lengths)[active_mask]
        return int((int(lens.sum()) + active_mask.sum())
                   * max(1, steps) * self._kv_bytes_per_token)

    def _step_active(self, rounds: int) -> None:
        """Run up to ``rounds`` lockstep decode steps (plain mode) or
        draft/verify rounds (speculative / prompt-lookup modes) over the
        currently occupied slots and record the emitted tokens.  Shared
        by the scheduler loop and chunked admission's between-chunk
        interleaving (``prefill_chunk``).  The plain fused block may run
        FEWER than ``rounds`` steps (on-device early exit when every
        row eos'd or exhausted its budget); the device-reported step
        count drives the drain."""
        active_mask = np.array([s is not None for s in self._slots])
        self._rng, sub = jax.random.split(self._rng)
        if self._pld_step is not None or self._spec_step is not None:
            if self._pld_step is not None:
                (self._pk, self._pv, self._history, self._lengths,
                 tok, em, ns) = self._pld_step(
                    self.params, self._pk, self._pv, self._history,
                    jnp.asarray(self._tables), self._lengths,
                    self._last_tok, jnp.asarray(active_mask), sub,
                    rounds)
            else:
                (self._pk, self._pv, self._dpk, self._dpv,
                 self._lengths, tok, em, ns) = self._spec_step(
                    self.params, self.draft_params, self._pk,
                    self._pv, self._dpk, self._dpv,
                    jnp.asarray(self._tables),
                    jnp.asarray(self._dtables), self._lengths,
                    self._last_tok, jnp.asarray(active_mask), sub,
                    rounds)
            self._last_tok = tok
            self._count_loop(rounds)
            em_np, ns_np = np.asarray(em), np.asarray(ns)
            for r in range(rounds):
                self._drain_spec_blocks(em_np[r], ns_np[r])
        elif rounds > 1:
            _sig = _profiling.dispatch_signature(
                "paged_multi_step", batch=int(active_mask.sum()),
                chunk=rounds, kv_dtype=self.kv_cache.kv_dtype)
            _t0 = self._prof.begin(_sig)
            (self._pk, self._pv, self._lengths, tok,
             blocks, lps, steps) = self._paged_multi_step(
                self.params, self._pk, self._pv,
                jnp.asarray(self._tables), self._lengths,
                self._last_tok, jnp.asarray(active_mask), sub,
                self._eos_scalar(), self._budget_vec(), rounds)
            self._last_tok = tok
            steps = int(steps)       # the on-device active count
            if _t0 is not None:
                # sampled only (int(steps) above already synced): the
                # dominant KV traffic is each active row re-reading its
                # history every step, plus one written token/row/step
                self._prof.end(_sig, _t0, out=tok,
                               hbm_bytes=self._decode_kv_bytes(
                                   active_mask, steps))
            self._count_loop(steps)
            self._step_count += steps
            self._record_row_blocks(
                np.asarray(blocks), np.full(len(self._slots), steps),
                np.asarray(lps))
        else:
            _sig = _profiling.dispatch_signature(
                "paged_step", batch=int(active_mask.sum()), chunk=1,
                kv_dtype=self.kv_cache.kv_dtype)
            _t0 = self._prof.begin(_sig)
            (self._pk, self._pv, self._lengths, tok,
             lp) = self._paged_step(
                self.params, self._pk, self._pv,
                jnp.asarray(self._tables), self._lengths,
                self._last_tok, jnp.asarray(active_mask), sub)
            self._last_tok = tok
            if _t0 is not None:
                self._prof.end(_sig, _t0, out=tok,
                               hbm_bytes=self._decode_kv_bytes(
                                   active_mask, 1))
            self._count_loop(1)
            tok_np, lp_np = np.asarray(tok), np.asarray(lp)
            self._step_count += 1
            for i, req in enumerate(self._slots):
                if req is not None:
                    self._record_token(i, req, int(tok_np[i]),
                                       float(lp_np[i]))

    def _mixed_iteration(self) -> None:
        """One MIXED-mode scheduler iteration (docs/DESIGN.md §19):
        intake, start concurrent admissions, then ONE token-budget
        dispatch carrying every active row's fused decode block plus
        packed prefill segments.  The serialized loop's per-iteration
        bookkeeping (cancel sweep, export service) rides along at the
        same points."""
        free = [i for i, s in enumerate(self._slots) if s is None]
        # block for work only when truly idle: nothing decoding, no
        # admission mid-stream, nothing waiting to be served
        timeout = (None if not (any(self._slots) or self._adms
                                or self._pending)
                   else 0.0)
        while True:
            try:
                req = self._queue.get(timeout=timeout)
            except queue.Empty:
                break
            timeout = 0.0
            if req is _WAKE:               # export_request nudge
                continue
            if req is None:                # close() sentinel
                break
            self._pending.append(req)
        # serve pending FIFO.  Live-migration resumes adopt straight
        # into a free slot (their checkpoint IS the row state — no
        # prefill to pack); everything else becomes a concurrent
        # admission whose chunks the dispatch packs.  Admissions are
        # capped at the free-slot count (reserving pages for prompts
        # that cannot land yet just wedges the pool), floor 1 so a
        # fully busy batch still streams one prompt's chunks (the
        # serialized path's overlap property).  Serviceable requests
        # pass blocked ones.
        still: "deque[Request]" = deque()
        for req in self._pending:
            if req.cancelled:              # dropped while waiting
                self._fail_request(req, None)
            elif getattr(req, "_resume", None) is not None:
                if free:
                    slot = free.pop(0)
                    try:
                        self._admit_request(slot, req)
                    except _BlocksExhausted:
                        free.insert(0, slot)
                        still.append(req)
                    except BaseException as e:
                        self._fail_request(req, e)
                else:
                    still.append(req)      # waiting for a slot
            elif len(self._adms) < max(1, len(free)):
                try:
                    start = self._reserve_pages(req)
                except _BlocksExhausted:
                    still.append(req)      # retry when pages free up
                    continue
                except BaseException as e:  # surface to the waiter
                    self._fail_request(req, e)
                    continue
                self._adms.append({"req": req, "start": start,
                                   "m": start,
                                   "suffix": req.prompt[start:]})
            else:
                still.append(req)
        self._pending = still
        # drop cancelled admissions between dispatches (cancel latency
        # bounded by one dispatch, the serialized path's property)
        for a in list(self._adms):
            if a["req"].cancelled:
                self._adms.remove(a)
                self._fail_request(a["req"], None)
        self._sweep_cancelled()
        self._service_exports()
        if not any(self._slots) and not self._adms:
            return
        self._dispatch_mixed(
            [i for i, s in enumerate(self._slots) if s is None])

    def _dispatch_mixed(self, free: list) -> None:
        """Build and run ONE mixed token-budget dispatch, then drain it.

        Packing policy (docs/DESIGN.md §19): every active decode row
        contributes its ``decode_block`` fused-loop tokens off the top
        of the budget; the remainder packs C-token prefill segments
        FIFO over the concurrent admissions — each contributes its
        sequential chunks, and its bucket-free FINAL segment (sampling
        token #1, installing the slot in-program) once a free slot
        pops.  At least one segment is always packed when an admission
        is in flight, so a saturated decode batch cannot starve
        prefill.  rng split order: one batch-1 split per packed final
        in pack order, then ONE decode split iff any row decodes —
        exactly the serialized path's spend, which keeps cold-start
        sampled streams bit-identical."""
        B = self.max_batch
        C = self.prefill_chunk
        W = self._table_width
        n_seg = self._mixed_seg_cap
        n_active = sum(1 for s in self._slots if s is not None)
        live0 = [i for i, s in enumerate(self._slots) if s is not None]
        spec_mixed = (self._mixed_pld_step is not None
                      or self._mixed_spec_step is not None)
        if spec_mixed:
            # §22 pricing: a speculative row costs (K_row + 1) tokens
            # per round — K_row drafts + the verify/bonus token — times
            # the decode_block fused rounds.  Adaptive K shrinks a
            # collapsing acceptor toward K_row = 1 (≈ plain decode)
            # so it stops burning budget the prefill slab could use.
            k_vec = (self._spec_krow.copy() if self.spec_adaptive
                     else np.full((B,), self._spec_buckets[-1],
                                  np.int32))
            room = max(0, self.mixed_token_budget - sum(
                (int(k_vec[i]) + 1) * self.decode_block for i in live0))
        else:
            k_vec = None
            room = max(0, self.mixed_token_budget
                       - n_active * self.decode_block)
        want = min(n_seg, max(1, room // C)) if self._adms else 0
        seg_ids = np.zeros((n_seg, C), np.int32)
        seg_tables = np.full((n_seg, W), self._page_sentinel, np.int32)
        seg_starts = np.zeros((n_seg,), np.int32)
        seg_lens = np.ones((n_seg,), np.int32)
        seg_slot = np.full((n_seg,), B, np.int32)
        seg_plen = np.zeros((n_seg,), np.int32)
        seg_keys = np.zeros((n_seg, 2), np.uint32)
        packed = []          # (row, admission, is_final, slot)
        prefill_tokens = 0
        r = 0
        for a in self._adms:
            if r >= want:
                break
            req = a["req"]
            while r < want and len(a["suffix"]) > C:
                seg_ids[r, :] = np.asarray(a["suffix"][:C], np.int32)
                seg_tables[r] = req._pkv["table"]
                seg_starts[r] = a["start"]
                packed.append((r, a, False, -1))
                prefill_tokens += C
                a["start"] += C
                a["suffix"] = a["suffix"][C:]
                self.chunk_stats["chunks"] += 1
                r += 1
            if r >= want or len(a["suffix"]) > C:
                break
            if not free:
                continue     # final parked until a slot frees; later
                             # admissions may still pack their chunks
            slot = free.pop(0)
            n = len(a["suffix"])
            seg_ids[r, :n] = np.asarray(a["suffix"], np.int32)
            seg_tables[r] = req._pkv["table"]
            seg_starts[r] = a["start"]
            seg_lens[r] = n
            seg_slot[r] = slot
            seg_plen[r] = len(req.prompt)
            # the final's batch-1 sampling key: the serialized
            # prefill's exact split, spent in pack order
            if getattr(req, "_rng_rewind", False):
                # §23 sampled resume rewind — same hook as the
                # serialized _finish_admission, mixed-dispatch shape
                self._rng = jax.random.PRNGKey(self._seed)
                req._rng_rewind = False
            self._rng, sub = jax.random.split(self._rng)
            seg_keys[r] = np.asarray(sub)
            # decode inside this dispatch pages through the installed
            # row — its table must be live BEFORE the dispatch; the
            # radix adoption (below) waits until the pages hold data
            self._tables[slot] = req._pkv["table"]
            packed.append((r, a, True, slot))
            prefill_tokens += n
            r += 1
        with_finals = any(f for (_, _, f, _) in packed)
        active_mask = np.array([s is not None for s in self._slots])
        # budget: remaining tokens per pre-existing row; a freshly
        # installed final's row has max_new - 1 left (token #1 came
        # from its prefill logits)
        budget_vec = np.array(
            [(s.max_new - len(s.tokens)) if s is not None else 0
             for s in self._slots], np.int32)
        for (_, a, is_final, slot) in packed:
            if is_final:
                budget_vec[slot] = a["req"].max_new - 1
        if spec_mixed:
            # §22 rng rule: the decode split is spent iff spec rounds
            # run, i.e. iff a row was ALREADY active — a freshly
            # installed final spends only its pack-order batch-1 key
            # this dispatch (its proposer state seeds host-side after),
            # exactly the serialized final-split-then-step-split order.
            num_rounds = self.decode_block if n_active > 0 else 0
            k_disp = (max(int(k_vec[i]) for i in live0) if live0
                      else int(self._spec_buckets[-1]))
            if num_rounds > 0:
                self._rng, dec_sub = jax.random.split(self._rng)
            else:
                dec_sub = jax.random.PRNGKey(0)
        elif n_active > 0 or with_finals:
            # ONE decode split per dispatch that decodes — the
            # serialized loop's spend (it skips the split when no slot
            # is active)
            num_rounds, k_disp = 0, 0
            self._rng, dec_sub = jax.random.split(self._rng)
        else:
            num_rounds, k_disp = 0, 0
            dec_sub = jax.random.PRNGKey(0)   # prefill-only: loop is
                                              # a 0-step no-op
        prog = ("mixed_step" if not spec_mixed else
                "mixed_spec_step" if self._mixed_spec_step is not None
                else "mixed_pld_step")
        _sig = _profiling.dispatch_signature(
            prog, batch=int(active_mask.sum()),
            chunk=self.decode_block, kv_dtype=self.kv_cache.kv_dtype)
        _t0 = self._prof.begin(_sig)
        try:
            if not spec_mixed:
                (self._pk, self._pv, self._lengths, tok, final_toks,
                 final_lps, toks, lps, steps) = self._mixed_step(
                    self.params, self._pk, self._pv,
                    jnp.asarray(seg_ids), jnp.asarray(seg_tables),
                    jnp.asarray(seg_starts), jnp.asarray(seg_lens),
                    jnp.asarray(seg_slot), jnp.asarray(seg_plen),
                    jnp.asarray(seg_keys), jnp.asarray(self._tables),
                    self._lengths, self._last_tok,
                    jnp.asarray(active_mask), dec_sub,
                    self._eos_scalar(), jnp.asarray(budget_vec),
                    self.decode_block, with_finals)
                self._last_tok = tok
            elif self._mixed_spec_step is not None:
                (self._pk, self._pv, self._dpk, self._dpv,
                 self._lengths, self._last_tok, final_toks, final_lps,
                 em, ns) = self._mixed_spec_step(
                    self.params, self.draft_params, self._pk, self._pv,
                    self._dpk, self._dpv, jnp.asarray(seg_ids),
                    jnp.asarray(seg_tables), jnp.asarray(seg_starts),
                    jnp.asarray(seg_lens), jnp.asarray(seg_slot),
                    jnp.asarray(seg_plen), jnp.asarray(seg_keys),
                    jnp.asarray(self._tables),
                    jnp.asarray(self._dtables), self._lengths,
                    self._last_tok, jnp.asarray(active_mask), dec_sub,
                    jnp.asarray(k_vec), k_disp, num_rounds,
                    with_finals)
            else:
                (self._pk, self._pv, self._history, self._lengths,
                 self._last_tok, final_toks, final_lps, em,
                 ns) = self._mixed_pld_step(
                    self.params, self._pk, self._pv, self._history,
                    jnp.asarray(seg_ids), jnp.asarray(seg_tables),
                    jnp.asarray(seg_starts), jnp.asarray(seg_lens),
                    jnp.asarray(seg_slot), jnp.asarray(seg_plen),
                    jnp.asarray(seg_keys), jnp.asarray(self._tables),
                    self._lengths, self._last_tok,
                    jnp.asarray(active_mask), dec_sub,
                    jnp.asarray(k_vec), k_disp, num_rounds,
                    with_finals)
        except BaseException as e:
            # a per-request failure fails the packed requests, never
            # the engine — same contract as the serialized admission
            # dispatches.  A pure-decode failure (nothing packed) IS an
            # engine failure: re-raise into the crash drain.
            if not packed:
                raise
            failed = []
            for (_, a, is_final, slot) in packed:
                if a["req"] not in failed:
                    failed.append(a["req"])
                if is_final:
                    self._tables[slot] = self._page_sentinel
            self._adms = [a for a in self._adms
                          if a["req"] not in failed]
            for req in failed:
                self._fail_request(req, e)
            return
        cs = self.chunk_stats
        cs["mixed_dispatches"] += 1
        cs["mixed_prefill_tokens"] += prefill_tokens
        cs["mixed_budget_tokens"] += self.mixed_token_budget
        # finals first: install host state + radix adoption, record
        # token #1.  The adoption waits until after the dispatch — the
        # tree must never serve pages whose K/V is still in flight.
        if with_finals:
            final_toks_np = np.asarray(final_toks)
            final_lps_np = np.asarray(final_lps)
            for (r0, a, is_final, slot) in packed:
                if not is_final:
                    continue
                req = a["req"]
                self._adms.remove(a)
                st = req._pkv
                plen = len(req.prompt)
                bt = self.kv_cache.block_tokens
                if plen // bt >= 1:
                    adopted, store_lease = self.kv_cache.store_shared(
                        req.prompt, st["table"][:plen // bt])
                    st["adopted"] = adopted
                    st["store_lease"] = store_lease
                if spec_mixed:
                    # the fresh row's proposer state seeds HOST-SIDE,
                    # exactly as _finish_admission does — during the
                    # dispatch its draft table row was all-sentinel (or
                    # its history row untouched: inactive rows scatter
                    # out of bounds), so nothing stale survives
                    if self._spec_step is not None:
                        dbucket = self._bucket(plen)
                        dpad = np.zeros((1, dbucket), np.int32)
                        dpad[0, :plen] = req.prompt
                        drow_k, drow_v = self._dprefill(
                            self.draft_params, jnp.asarray(dpad),
                            *self._zero_row_d())
                        self._dpk, self._dpv = self._write_row(
                            self._dpk, self._dpv, drow_k, drow_v,
                            jnp.asarray(st["dtable"]))
                        self._dtables[slot] = st["dtable"]
                    if self._pld_step is not None:
                        hpad = np.zeros((1, self._bucket(plen)),
                                        np.int32)
                        hpad[0, :plen] = req.prompt
                        self._history = self._admit_h(
                            self._history, jnp.asarray(hpad),
                            jnp.int32(slot), jnp.int32(plen),
                            jnp.int32(int(final_toks_np[r0])))
                    # fresh acceptor: start wide, re-learn
                    self._spec_krow[slot] = self._spec_buckets[-1]
                    self._spec_ewma[slot] = 1.0
                self._slots[slot] = req
                self._flight.record("batch_admit", slot=slot,
                                    prompt_len=plen,
                                    max_new=req.max_new,
                                    prefix_reused=a["m"])
                self._record_token(
                    slot, req, int(final_toks_np[r0]),
                    None if spec_mixed else float(final_lps_np[r0]))
        if spec_mixed:
            em_np, ns_np = np.asarray(em), np.asarray(ns)
            if _t0 is not None:
                self._prof.end(_sig, _t0, out=self._last_tok,
                               hbm_bytes=(
                    prefill_tokens * self._kv_bytes_per_token
                    + self._decode_kv_bytes(
                        active_mask, num_rounds * (k_disp + 1))))
            emitted = int(ns_np[:, live0].sum()) if live0 else 0
            cs["mixed_packed_tokens"] += prefill_tokens + emitted
            if num_rounds > 0:
                self._count_loop(num_rounds)
                for r0 in range(num_rounds):
                    self._drain_spec_blocks(em_np[r0], ns_np[r0],
                                            k_vec=k_vec)
                if self.spec_adaptive:
                    self._update_spec_krow(live0, k_vec, ns_np,
                                           num_rounds)
            if num_rounds > 0 and self._adms:
                cs["interleaved_steps"] += 1
            return
        steps = int(steps)           # the on-device active count
        if _t0 is not None:
            # sampled only (int(steps) above already synced): packed
            # prefill writes + every active row's per-step history read
            self._prof.end(_sig, _t0, out=tok, hbm_bytes=(
                prefill_tokens * self._kv_bytes_per_token
                + self._decode_kv_bytes(active_mask, steps)))
        cs["mixed_packed_tokens"] += (prefill_tokens
                                      + n_active * steps)
        if steps > 0:
            self._count_loop(steps)
            self._step_count += steps
            self._record_row_blocks(
                np.asarray(toks), np.full(len(self._slots), steps),
                np.asarray(lps))
        if steps > 0 and self._adms:
            cs["interleaved_steps"] += 1

    def _update_spec_krow(self, live0, k_vec, ns_np, num_rounds: int
                          ) -> None:
        """EWMA acceptance feedback (docs/DESIGN.md §22): fold one
        dispatch's realized acceptance rate — per row live at dispatch
        START, extra tokens kept over drafts offered — into the row's
        EWMA, then re-bucket K_row to the smallest bucket covering
        ``ewma * num_draft``.  A collapsing acceptor walks down to
        K_row = 1 (plain decode's price); recovery walks it back up."""
        buckets = self._spec_buckets
        alpha = self._spec_ewma_alpha
        for i in live0:
            offered = num_rounds * max(1, int(k_vec[i]))
            kept = int(ns_np[:, i].sum()) - num_rounds
            rate = min(1.0, max(0.0, kept / offered))
            self._spec_ewma[i] = ((1.0 - alpha) * self._spec_ewma[i]
                                  + alpha * rate)
            want = self._spec_ewma[i] * self.num_draft
            self._spec_krow[i] = next(
                (b for b in buckets if b >= want), buckets[-1])

    def _loop(self):
        try:
            self._loop_body()
        except BaseException as e:
            # a failed decode step (device lost, OOM, ...) must not strand
            # every waiter on a dead thread: fail all in-flight and queued
            # requests with the underlying error, then refuse new work.
            # The submit lock orders the drain after any submit that
            # already saw _running True — its request lands before the
            # drain runs, so none can slip past onto the dead thread.
            # The flight ring holds the admissions/steps leading up to
            # the failure; capture them before the drain mutates state.
            self._flight.record("scheduler_crash",
                                error=type(e).__name__, detail=str(e))
            postmortem.trigger(
                "scheduler_crash",
                detail={"error": f"{type(e).__name__}: {e}",
                        "active_slots": sum(1 for s in self._slots
                                            if s is not None),
                        "steps": self._step_count})
            with self._submit_lock:
                self._running = False
                self._drain_all(e)

    def _loop_body(self):
        if self.mixed_token_budget > 0:
            # MIXED mode: one token-budget dispatch per iteration —
            # decode fusion survives admission (no fuse suppression,
            # no one-admission-at-a-time rule).  The serialized loop
            # below is untouched: it is the bit-identity reference and
            # the bench baseline.
            while self._running:
                self.anomaly.observe(self.stats)
                self._sample_hbm()
                self._mixed_iteration()
            self._drain_all(
                RuntimeError("engine closed while request in flight"))
            return
        while self._running:
            # anomaly watch rides the loop (throttled internally; the
            # stats() snapshot is only built when an observation is due)
            self.anomaly.observe(self.stats)
            self._sample_hbm()
            free = [i for i, s in enumerate(self._slots) if s is None]
            # one dispatch of the in-progress chunked admission (if any)
            self._advance_admission(free)
            # block for work only when truly idle: nothing decoding, no
            # admission mid-stream, nothing waiting to be served
            timeout = (None if not (any(self._slots) or self._adm
                                    or self._pending)
                       else 0.0)
            # drain newly queued requests behind the already-pending ones
            while True:
                try:
                    req = self._queue.get(timeout=timeout)
                except queue.Empty:
                    break
                timeout = 0.0
                if req is _WAKE:           # export_request nudge
                    continue
                if req is None:            # close() sentinel
                    break
                self._pending.append(req)
            # serve pending FIFO: a chunk-needing prompt starts streaming
            # slot-FREE (only its final sampling prefill needs a slot, so
            # its chunks overlap busy decode); short prompts admit into
            # free slots.  Serviceable requests pass blocked ones.
            still: "deque[Request]" = deque()
            for req in self._pending:
                if req.cancelled:          # dropped while waiting
                    self._fail_request(req, None)
                elif self._needs_stream(req):
                    if self._adm is None:
                        # consumes no slot; False = paged pool full now,
                        # wait for a completion to free pages
                        if not self._start_admission(req):
                            still.append(req)
                    else:
                        still.append(req)  # one stream at a time
                elif free:
                    slot = free.pop(0)
                    try:
                        self._admit_request(slot, req)
                    except _BlocksExhausted:
                        # give the slot back: a later pending request
                        # whose pages ARE available must take it this
                        # pass (serviceable requests pass blocked ones)
                        free.insert(0, slot)
                        still.append(req)  # retry when pages free up
                    except BaseException as e:  # surface to the waiter
                        self._fail_request(req, e)
                else:
                    still.append(req)      # waiting for a slot
            self._pending = still
            self._sweep_cancelled()
            # serve export checkpoints between steps: state is
            # consistent here (pending drained, cancels swept, no
            # dispatch in flight)
            self._service_exports()
            if not any(self._slots):
                continue

            # fuse a block whenever no admission DISPATCH could land
            # anyway: an admission mid-stream always lands one per
            # iteration, so streaming disables fusing outright (its next
            # chunk must not wait out a fused block — time-to-first-token
            # beats peak decode throughput for the stream's duration);
            # otherwise fuse when nothing is waiting, or when every slot
            # is busy (the saturated regime is exactly where the fused
            # path pays — a backlog must not silently disable it)
            all_busy = all(s is not None for s in self._slots)
            fuse = (self.decode_block > 1 and self._adm is None
                    and (not self._pending or all_busy))
            if self._adm is not None:
                self.chunk_stats["interleaved_steps"] += 1
            self._step_active(self.decode_block if fuse else 1)

        # drain: fail anything still queued or in flight
        self._drain_all(RuntimeError("engine closed while request in flight"))
