"""Distributed pipeline inference: the ring token loop over a transport.

TPU-native redesign of the reference's hot path (``Communication.running``
→ ``multiSteps`` → ``OneStep``, ``Communication.java:389-928``; SURVEY.md
§3.3): header embeds + runs its layer range, hidden states hop stage to
stage, the tail samples, and the token id rides the ring back to the
header.  Differences by design:

- **KV-cached decode** at every stage — each step moves a [b, 1, H] hidden
  row, not a re-run of the whole prefix (the reference re-runs modules
  statelessly and feeds only the last token, defect #3).
- **In-flight samples are tags, not socket sets**: ``pool_size`` requests
  interleave through the same transport edges, each with its own per-stage
  KV cache slot (the reference allocates a socket set per concurrency slot,
  ``Communication.java:930-970``).
- **Sampling fused at the tail** (jit) with a deterministic
  ``fold_in(rid, step)`` rng — no host round-trip for top-k.
- All receives carry timeouts (reference defect #7: indefinite blocking).

Message tags (payloads are wire.py tensor messages):

- ``h:{rid}:{step}``   hidden chunk (step 0 = prefill, else one token row)
- ``tok:{rid}:{step}`` sampled [b] token ids, tail → header
- ``c:{rid}``          classification chunk: [hidden, label_token_ids];
  the tail answers ``ctok:{rid}`` with argmax-over-labels indices (the
  reference's binary-classification variant,
  ``inference.cpp:220-270`` / ``native-lib.cpp:1305-1366``)
- ``end:{rid}``        free the request's cache, forwarded along the chain
- ``stop``             shut down the worker loop, forwarded along the chain
- ``statsreq``         forwarded along the chain; every non-header stage
  replies to the header with a ``statsrep:{device_id}`` JSON snapshot
  (the reference's per-device timer dump, ``Communication.java:650-661``,
  as a pollable message instead of stdout)
"""

from __future__ import annotations

import json
import logging
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..comm import wire
from ..comm.transport import (BaseTransport, TransportTimeout,
                              record_corrupt_frame)
from ..models.base import KVCache, ModelConfig, StageParams, StageSpec
from ..ops.sampling import SamplingParams, sample_logits
from ..telemetry import postmortem
from ..telemetry import profiling as _profiling
from ..telemetry.flightrecorder import get_flight_recorder
from ..telemetry.tracing import SpanClock, TraceRecorder, new_trace_id
from .stats import StageStats

log = logging.getLogger(__name__)

DEFAULT_STEP_TIMEOUT = 120.0  # generous: first jit compile can be slow


class StageRuntime:
    """Jitted compute for one stage + per-request KV cache slots."""

    def __init__(self, cfg: ModelConfig, spec: StageSpec, params: StageParams,
                 max_seq: int, sampling: SamplingParams = SamplingParams(),
                 seed: int = 0, mesh=None, kv_cache_dtype=None,
                 kv_layout=None, kv_dtype=None):
        """``mesh``: a local tp mesh — this stage's layer range then runs
        with Megatron-sliced weights and a kv-head-sharded cache on this
        host's chips (pipeline across hosts x tensor parallelism within
        one, each worker choosing its own tp independently — the
        activations on the wire stay replicated [b, s, H] either way).

        ``kv_cache_dtype``: reduced-precision storage for this stage's
        request cache slots (e.g. "float8_e4m3fn"), same insert-cast /
        read-upcast contract as InferenceEngine's — each pipeline stage
        halves its own cache bytes independently.

        ``kv_layout``: "paged" (the default, docs/DESIGN.md §14) backs
        every request's cache with ONE per-stage page pool: blocks are
        allocated per chunk actually run (a request holding 40 tokens
        holds ceil(40/bt) pages, not a max_seq row) and returned on
        ``end:{rid}``, so concurrent rids (``pool_size`` dynamic
        batching) share the pool instead of each reserving worst-case
        rows.  Pool size: ``DWT_STAGE_KV_BLOCKS`` (default
        ``DWT_STAGE_KV_ROWS`` = 16 rows' worth); exhaustion raises
        loudly rather than silently evicting live KV.  Paged is the
        only layout ("dense" was removed — docs/DESIGN.md §14)."""
        self.cfg = cfg
        self.spec = spec
        self.max_seq = max_seq
        self.sampling = sampling
        self.mesh = mesh
        self.kv_cache_dtype = (jnp.dtype(kv_cache_dtype)
                               if kv_cache_dtype else None)
        from ..ops.quant import resolve_kv_dtype
        self.kv_dtype = resolve_kv_dtype(kv_dtype)
        if self.kv_dtype != "bf16" and self.kv_cache_dtype is not None:
            raise ValueError(
                f"kv_dtype={self.kv_dtype!r} quantizes the stage page "
                "pool and cannot compose with a kv_cache_dtype storage "
                f"cast ({self.kv_cache_dtype}); drop one of the two knobs")
        from .kvcache import resolve_kv_layout
        self.kv_layout = resolve_kv_layout(kv_layout)
        self._rng_base = jax.random.PRNGKey(seed)
        self.caches: Dict[int, KVCache] = {}      # dense layout only

        from ..parallel.tensor import (make_forward_seam,
                                       make_paged_forward_seam)
        take_last = spec.is_last
        if self.kv_layout == "paged":
            import math

            from ..telemetry._env import env_int
            from .kvcache import resolve_kvcache_config
            _, bt = resolve_kvcache_config(None, None)
            g = math.lcm(8, bt)
            S = -(-max_seq // g) * g
            self._bt, self._table_width = bt, S // bt
            rows = env_int("DWT_STAGE_KV_ROWS", 16)
            n_blocks = env_int("DWT_STAGE_KV_BLOCKS",
                               rows * self._table_width)
            fwd, bind, pool_sharding = make_paged_forward_seam(
                cfg, spec, mesh, params, bt)
            self._cache_sharding = pool_sharding
            if pool_sharding is not None:
                from .engine import shard_engine_params
                params = shard_engine_params(params, cfg, mesh)
            self.params = params
            from ..ops.quant import alloc_kv_pages
            page_dtype = self.kv_cache_dtype or cfg.dtype
            self._pk = alloc_kv_pages(
                (spec.num_layers, n_blocks, cfg.num_kv_heads, bt,
                 cfg.head_dim), self.kv_dtype, page_dtype)
            self._pv = jax.tree.map(jnp.zeros_like, self._pk)
            if pool_sharding is not None:
                # single sharding broadcasts over the (possibly
                # quantized) leaf subtree — sidecars shard with pages
                self._pk = jax.device_put(self._pk, pool_sharding.keys)
                self._pv = jax.device_put(self._pv, pool_sharding.values)
            self._sentinel = n_blocks
            self._pool_free = list(range(n_blocks - 1, -1, -1))
            self._tables: Dict[int, np.ndarray] = {}
            self._rid_len: Dict[int, int] = {}
            self._rid_blocks: Dict[int, int] = {}

            @jax.jit
            def forward_p(params, inputs, pk, pv, table, length):
                bind(table)
                cache = KVCache(pk, pv, length)
                b, s = inputs.shape[0], inputs.shape[1]
                pos = length + jnp.broadcast_to(jnp.arange(s), (b, s))
                out, cache = fwd(params, inputs, cache, pos, False)
                return ((out[:, -1] if take_last else out),
                        cache.keys, cache.values)

            @jax.jit
            def forward_sample_p(params, inputs, pk, pv, table, length,
                                 rng):
                """Paged tail hot path: layer range + LM head + in-jit
                sampling in ONE dispatch over the page pool — same rng,
                same sample_logits as the split pair (§13)."""
                bind(table)
                cache = KVCache(pk, pv, length)
                b, s = inputs.shape[0], inputs.shape[1]
                pos = length + jnp.broadcast_to(jnp.arange(s), (b, s))
                out, cache = fwd(params, inputs, cache, pos, False)
                return (sample_logits(out[:, -1], rng, sampling),
                        cache.keys, cache.values)

            self._forward_p = forward_p
            self._forward_sample_p = forward_sample_p
        else:
            fwd, self._cache_sharding = make_forward_seam(cfg, spec,
                                                          mesh, params)
            if self._cache_sharding is not None:
                from .engine import shard_engine_params
                params = shard_engine_params(params, cfg, mesh)
            self.params = params

            @jax.jit
            def forward(params, inputs, cache):
                b, s = inputs.shape[0], inputs.shape[1]
                pos = cache.length + jnp.broadcast_to(jnp.arange(s),
                                                      (b, s))
                out, cache = fwd(params, inputs, cache, pos, False)
                return (out[:, -1] if take_last else out), cache

            @jax.jit
            def forward_sample(params, inputs, cache, rng):
                """Tail hot path: layer range + LM head + in-jit
                sampling fused into ONE program (docs/DESIGN.md §13) —
                halves the tail's per-token host dispatches vs
                forward-then-sample.  Same rng, same sample_logits:
                bit-identical tokens to the split pair by
                construction."""
                b, s = inputs.shape[0], inputs.shape[1]
                pos = cache.length + jnp.broadcast_to(jnp.arange(s),
                                                      (b, s))
                out, cache = fwd(params, inputs, cache, pos, False)
                return sample_logits(out[:, -1], rng, sampling), cache

            self._forward = forward
            self._forward_sample = forward_sample

        @jax.jit
        def sample(last_logits, rng):
            return sample_logits(last_logits, rng, sampling)

        self._sample = sample
        # the socket ring's topology caps the circuit at ONE token (the
        # stage cut severs the token -> embed dependency; §13), so the
        # tail's device-side win is dispatch FUSION, not K-fusion —
        # DWT_RING_FUSED_TAIL=0 restores the split pair (the parity
        # reference the fused program is pinned against)
        from ..telemetry._env import env_int
        self.fused_tail = (spec.is_last
                           and env_int("DWT_RING_FUSED_TAIL", 1) != 0)
        # §20 observatory handles: the tail's fused dispatch is profiled
        # under the "ring_chunk_sample" program class; the stage page
        # pool feeds the HBM watermark ledger per chunk served.
        self._prof = _profiling.get_profiler()
        self._kv_token_bytes = _profiling.kv_dispatch_bytes(
            1, spec.num_layers, cfg.num_kv_heads, cfg.head_dim,
            self.kv_dtype if self.kv_layout == "paged" else None,
            (self.kv_cache_dtype or cfg.dtype))

    def _cache_for(self, rid: int, batch: int) -> KVCache:
        cache = self.caches.get(rid)
        if cache is None:
            cache = KVCache.create(self.cfg, self.spec.num_layers, batch,
                                   self.max_seq,
                                   dtype=self.kv_cache_dtype)
            if self._cache_sharding is not None:
                cache = jax.device_put(cache, self._cache_sharding)
            self.caches[rid] = cache
        return cache

    def _paged_chunk_state(self, rid: int, batch: int, s: int):
        """(table, length) for this rid's next ``s``-token chunk,
        growing its block table from the stage pool first — pages are
        reserved per chunk actually run, never per max_seq row.  Pool
        exhaustion raises loudly (evicting live KV would decode wrong
        tokens); the header's capacity check bounds per-rid growth."""
        tbl = self._tables.get(rid)
        if tbl is None:
            tbl = np.full((batch, self._table_width), self._sentinel,
                          np.int32)
            self._tables[rid] = tbl
        cur = self._rid_len.get(rid, 0)
        need = -(-(cur + s) // self._bt)
        have = self._rid_blocks.get(rid, 0)
        if need > self._table_width:
            raise RuntimeError(
                f"rid {rid} needs {need} KV blocks but the stage table "
                f"is {self._table_width} wide (max_seq {self.max_seq})")
        grow = (need - have) * batch
        if grow > len(self._pool_free):
            # all-or-nothing grow: popping a partial set into the table
            # before raising would leak pages if the chunk is retried
            # (the table entries would be overwritten by fresh pops)
            raise RuntimeError(
                "stage page pool exhausted: raise "
                "DWT_STAGE_KV_BLOCKS (or DWT_STAGE_KV_ROWS) — "
                "refusing to evict live request KV")
        for j in range(have, need):
            for row in range(batch):
                tbl[row, j] = self._pool_free.pop()
        self._rid_blocks[rid] = max(have, need)
        return tbl, cur

    def _sample_stage_hbm(self) -> None:
        """One HBM-watermark sample for this stage's page pool (§20) —
        host-side integer math only, called per chunk served."""
        if self.kv_layout != "paged":
            return
        used = self._sentinel - len(self._pool_free)
        _profiling.get_hbm_watermarks().sample(
            "stage_pool", used * self._bt * self._kv_token_bytes)

    def run_chunk(self, rid: int, inputs: np.ndarray) -> jax.Array:
        """Run this stage on a chunk; updates the request's cache in place.
        Returns hidden [b,s,H] (or last-position logits on the tail)."""
        x = jnp.asarray(inputs)
        if self.kv_layout == "paged":
            tbl, cur = self._paged_chunk_state(rid, x.shape[0],
                                               x.shape[1])
            out, self._pk, self._pv = self._forward_p(
                self.params, x, self._pk, self._pv, jnp.asarray(tbl),
                jnp.int32(cur))
            self._rid_len[rid] = cur + x.shape[1]
            self._sample_stage_hbm()
            return out
        cache = self._cache_for(rid, x.shape[0])
        out, self.caches[rid] = self._forward(self.params, x, cache)
        return out

    def sample_tokens(self, rid: int, step: int,
                      last_logits: jax.Array) -> np.ndarray:
        rng = jax.random.fold_in(jax.random.fold_in(self._rng_base, rid),
                                 step)
        return np.asarray(self._sample(last_logits, rng))

    def run_chunk_sample(self, rid: int, step: int,
                         inputs: np.ndarray) -> np.ndarray:
        """Tail-only fused step: run this stage AND sample in one
        dispatch.  The rng is the same ``fold_in(rid, step)`` stream
        :meth:`sample_tokens` draws, so the fused and split tails emit
        bit-identical tokens."""
        x = jnp.asarray(inputs)
        rng = jax.random.fold_in(jax.random.fold_in(self._rng_base, rid),
                                 step)
        b, s = x.shape[0], x.shape[1]
        _sig = _profiling.dispatch_signature(
            "ring_chunk_sample", batch=b, chunk=s,
            kv_dtype=(self.kv_dtype if self.kv_layout == "paged" else
                      np.dtype(self.kv_cache_dtype or self.cfg.dtype).name))
        _t0 = self._prof.begin(_sig)
        if self.kv_layout == "paged":
            tbl, cur = self._paged_chunk_state(rid, b, s)
            tok, self._pk, self._pv = self._forward_sample_p(
                self.params, x, self._pk, self._pv, jnp.asarray(tbl),
                jnp.int32(cur), rng)
            self._rid_len[rid] = cur + s
            tok = np.asarray(tok)
            if _t0 is not None:
                # the asarray above synced; the chunk attends the rid's
                # whole KV prefix and writes s new tokens
                self._prof.end(_sig, _t0, hbm_bytes=(
                    b * (cur + s) * self._kv_token_bytes))
            self._sample_stage_hbm()
            return tok
        cache = self._cache_for(rid, b)
        tok, self.caches[rid] = self._forward_sample(self.params, x,
                                                     cache, rng)
        tok = np.asarray(tok)
        if _t0 is not None:
            self._prof.end(_sig, _t0, hbm_bytes=(
                b * int(np.asarray(self.caches[rid].length))
                * self._kv_token_bytes))
        return tok

    def free(self, rid: int) -> None:
        self.caches.pop(rid, None)
        if self.kv_layout == "paged":
            tbl = self._tables.pop(rid, None)
            self._rid_len.pop(rid, None)
            self._rid_blocks.pop(rid, None)
            if tbl is not None:
                self._pool_free.extend(
                    int(v) for v in tbl.flat if v != self._sentinel)

    def reset_caches(self) -> None:
        """Drop every request's cache state (reshard/restart): dense
        rows garbage-collect; paged tables hand their pages back to the
        stage pool (clearing the dict alone would leak them)."""
        self.caches.clear()
        if self.kv_layout == "paged":
            for rid in list(self._tables):
                self.free(rid)


def _h_tag(rid: int, step: int) -> str:
    return f"h:{rid}:{step}"


def _tok_tag(rid: int, step: int) -> str:
    return f"tok:{rid}:{step}"


class PipelineWorker:
    """A non-header stage: recv → run layer range → send onward; the tail
    additionally samples and returns tokens to the header (the worker /
    tailer roles of ``OneStep``, ``Communication.java:682-928``)."""

    def __init__(self, runtime: StageRuntime, transport: BaseTransport,
                 next_id: Optional[str], header_id: str,
                 step_timeout: float = DEFAULT_STEP_TIMEOUT):
        self.rt = runtime
        self.transport = transport
        self.next_id = next_id          # None on the tail
        self.header_id = header_id
        self.step_timeout = step_timeout
        role = "tail" if runtime.spec.is_last else "worker"
        self.stats = StageStats(role=role)
        self.tracer = TraceRecorder(f"{role}:{transport.device_id}")
        self.flight = get_flight_recorder()
        self.tail_dispatches = 0   # host dispatches spent sampling (§13)
        self._last_wait: Optional[float] = None  # serve loop's recv wait
        self._last_wait_start: Optional[float] = None  # its wall start
        # per-rid expected next step: the KV cache is append-only, so a
        # DUPLICATED or out-of-order hidden chunk (transport retry, chaos
        # duplicate/reorder) must be dropped, never run twice into the
        # cache.  The first frame of a request (or post-reshard relaunch,
        # where the re-prefill arrives at a mid-stream step) is accepted
        # at any step; after that, steps must advance by exactly one.
        self._next_step: Dict[int, int] = {}

    def _forward_control(self, tag: str, payload: bytes = b"") -> None:
        if self.next_id is not None:
            self.transport.send(self.next_id, tag, payload)

    def _count_tail_dispatches(self, dispatches: int) -> None:
        """Per-token host-dispatch accounting on the tail (the ring's
        share of the dwt_engine_* dispatch-floor series): 1 on the
        fused forward+sample path, 2 on the split reference pair."""
        from .engine import count_device_loop
        self.tail_dispatches += dispatches
        count_device_loop("PipelineWorkerTail", 1, dispatches)

    # tag factories — overridable (the elastic runtime appends a reshard
    # epoch so stale pre-reshard traffic is identifiable and droppable)
    def _make_h_tag(self, rid: int, step: int) -> str:
        return _h_tag(rid, step)

    def _make_tok_tag(self, rid: int, step: int) -> str:
        return _tok_tag(rid, step)

    def serve_forever(self, idle_timeout: Optional[float] = None) -> None:
        """Loop until a ``stop`` message arrives; returns cleanly if
        ``idle_timeout``/step_timeout expires with no traffic at all."""
        while True:
            t0_wall = time.time()       # recv_wait span start (wall clock
            t0 = time.perf_counter()    # captured at open, never derived)
            try:
                tag, payload = self.transport.recv_any(
                    timeout=idle_timeout or self.step_timeout)
            except TransportTimeout:
                log.info("worker %s: idle timeout, exiting",
                         self.transport.device_id)
                return
            wait = time.perf_counter() - t0
            self.stats.record_recv(wait, len(payload))
            self._last_wait = wait      # recv_wait span source (tracing)
            self._last_wait_start = t0_wall
            if not self.handle_message(tag, payload):
                return

    def handle_message(self, tag: str, payload: bytes) -> bool:
        """Process one message; returns False on ``stop``."""
        kind, _, rest = tag.partition(":")
        if kind == "stop":
            self.flight.record("worker_stop",
                               stage=self.transport.device_id)
            self._forward_control(tag)
            return False
        if kind == "end":
            rid = int(rest.split(":")[0])
            self.rt.free(rid)
            self._next_step.pop(rid, None)
            self._forward_control(tag)
            return True
        if kind == "statsreq":
            from ..comm.transport import TransportError
            snap = dict(self.stats.snapshot(include_samples=True),
                        device_id=self.transport.device_id,
                        seq=rest)  # echo the poll sequence id
            spans = None
            if payload == b"spans":
                # trace collection rides the stats poll: spans drain into
                # the reply AT MOST ONCE (a reply missing the header's
                # poll window is dropped there; only a locally failed
                # send re-buffers them for the next poll)
                spans = snap["spans"] = self.tracer.drain()
            try:
                self.transport.send(
                    self.header_id,
                    f"statsrep:{self.transport.device_id}",
                    json.dumps(snap).encode("utf-8"))
            except TransportError:
                if spans:
                    for s in spans:      # keep them for the next poll
                        self.tracer.record(
                            s["name"], s["trace_id"], s["parent_id"],
                            ts=s["ts_us"] / 1e6, dur=s["dur_us"] / 1e6,
                            span_id=s["span_id"], **(s.get("args") or {}))
                raise
            self._forward_control(tag, payload)
            return True
        if kind == "statsreset":
            self.stats.reset()
            self._forward_control(tag)
            return True
        if kind == "c":
            self._run_classify(int(rest.split(":")[0]), payload)
            return True
        if kind != "h":
            log.warning("worker %s: unexpected tag %r",
                        self.transport.device_id, tag)
            return True
        fields = rest.split(":")
        rid, step = int(fields[0]), int(fields[1])
        self._run_and_forward(rid, step, payload)
        return True

    def _record_hop_spans(self, ctx, compute_span: int, t_wall: float,
                          compute_s: float, rid: int, step: int) -> None:
        """recv_wait + compute spans for one traced hop; ``compute_span``
        was minted before serialization so the outbound trailer could
        name it as the downstream parent."""
        trace_id, parent = ctx
        if self._last_wait is not None:
            # the wall start was captured at recv open (serve_forever) —
            # never reconstructed as now-minus-duration across clocks
            start = (self._last_wait_start
                     if self._last_wait_start is not None
                     else t_wall - self._last_wait)
            self.tracer.record("recv_wait", trace_id, parent, ts=start,
                               dur=self._last_wait, rid=rid, step=step)
            self._last_wait = None       # consumed; never double-reported
            self._last_wait_start = None
        self.tracer.record("compute", trace_id, parent, ts=t_wall,
                           dur=compute_s, span_id=compute_span,
                           rid=rid, step=step)

    def _traced_send(self, ctx, compute_span: int, dest: str, tag: str,
                     body: bytes, rid: int, step: int) -> None:
        t_s = SpanClock()
        with t_s:
            self.transport.send(dest, tag, body)
        self.stats.record_send(t_s.seconds, len(body))
        self.flight.record("hop_send", stage=self.transport.device_id,
                           rid=rid, step=step, dest=dest,
                           nbytes=len(body))
        if ctx is not None:
            self.tracer.record("send", ctx[0], compute_span, clock=t_s,
                               rid=rid, step=step, dest=dest)

    def _run_and_forward(self, rid: int, step: int, payload: bytes) -> None:
        expected = self._next_step.get(rid)
        if expected is not None and step != expected:
            # duplicate (retry, chaos) or out-of-order frame: running it
            # would append to the KV cache twice and poison every later
            # token — drop; a genuinely lost frame surfaces as a stall
            # and the elastic reshard retransmits
            self.flight.record("dup_frame_dropped",
                               stage=self.transport.device_id,
                               rid=rid, step=step, expected=expected)
            log.info("worker %s: dropping duplicate/out-of-order frame "
                     "rid=%d step=%d (expected %d)",
                     self.transport.device_id, rid, step, expected)
            return
        self.flight.record("hop_recv", stage=self.transport.device_id,
                           rid=rid, step=step, nbytes=len(payload))
        try:
            tensors, ctx = wire.split_trace_context(
                wire.deserialize_tensors(payload))
        except wire.WireIntegrityError as e:
            # counted + flight-recorded, then DROPPED: the header's
            # step-timeout -> reshard path recovers this step; running a
            # corrupt activation forward would decode a wrong token
            record_corrupt_frame(self.transport.device_id,
                                 self._make_h_tag(rid, step),
                                 len(payload), e)
            return
        t_c = SpanClock()
        with t_c:
            [x] = tensors
            if self.rt.fused_tail:
                # ONE dispatch: layers + head + sample (dispatch-floor
                # fusion, §13); the split pair below is its pinned
                # parity reference
                toks = self.rt.run_chunk_sample(rid, step, x)
                self._next_step[rid] = step + 1
                self._count_tail_dispatches(1)
                result = [toks]
                dest, tag = self.header_id, self._make_tok_tag(rid, step)
            else:
                out = self.rt.run_chunk(rid, x)
                # the cache consumed this chunk: only step+1 may run next
                self._next_step[rid] = step + 1
                if self.rt.spec.is_last:
                    result = [self.rt.sample_tokens(rid, step, out)]
                    self._count_tail_dispatches(2)
                    dest, tag = self.header_id, self._make_tok_tag(rid,
                                                                   step)
                else:
                    result = [np.asarray(out)]
                    dest, tag = self.next_id, self._make_h_tag(rid, step)
            compute_span = self.tracer.next_span_id() if ctx else 0
            body = (wire.serialize_tensors_traced(result, ctx[0],
                                                  compute_span)
                    if ctx else wire.serialize_tensors(result))
        self.stats.record_compute(t_c.seconds)
        if ctx is not None:
            self._record_hop_spans(ctx, compute_span, t_c.ts, t_c.seconds,
                                   rid, step)
        self._traced_send(ctx, compute_span, dest, tag, body, rid, step)

    def _run_classify(self, rid: int, payload: bytes) -> None:
        """Classification hop: payload = [chunk, label_token_ids].  The
        tail answers the header with argmax-over-label-logits indices
        (reference ``inference.cpp:220-270``); other stages forward."""
        self.flight.record("hop_recv", stage=self.transport.device_id,
                           rid=rid, step=0, nbytes=len(payload),
                           classify=True)
        try:
            tensors, ctx = wire.split_trace_context(
                wire.deserialize_tensors(payload))
        except wire.WireIntegrityError as e:
            record_corrupt_frame(self.transport.device_id, f"c:{rid}",
                                 len(payload), e)
            return
        t_c = SpanClock()
        with t_c:
            x, label_ids = tensors
            out = self.rt.run_chunk(rid, x)
            if self.rt.spec.is_last:
                logits = np.asarray(out)        # [b, V] last position
                sub = logits[:, label_ids.astype(np.int64)]
                pred = np.argmax(sub, axis=-1).astype(np.int32)
                result = [pred]
                dest, tag = self.header_id, f"ctok:{rid}"
            else:
                result = [np.asarray(out), label_ids]
                dest, tag = self.next_id, f"c:{rid}"
            compute_span = self.tracer.next_span_id() if ctx else 0
            body = (wire.serialize_tensors_traced(result, ctx[0],
                                                  compute_span)
                    if ctx else wire.serialize_tensors(result))
        self.stats.record_compute(t_c.seconds)
        if ctx is not None:
            self._record_hop_spans(ctx, compute_span, t_c.ts, t_c.seconds,
                                   rid, 0)
        self._traced_send(ctx, compute_span, dest, tag, body, rid, 0)


@dataclass
class _Request:
    rid: int
    prompt: np.ndarray                 # [b, s] int32
    max_new_tokens: int
    tokens: List[np.ndarray] = None    # collected [b] arrays
    step: int = 0
    done: bool = False
    trace_id: int = 0                  # telemetry: ring-propagated id

    def __post_init__(self):
        if self.tokens is None:
            self.tokens = []


class PipelineHeader:
    """The header role: owns stage 0, tokenized inputs, the request window,
    and token collection (``Communication.running``'s driver half)."""

    def __init__(self, runtime: StageRuntime, transport: BaseTransport,
                 next_id: str, eos_id: Optional[int] = None,
                 step_timeout: float = DEFAULT_STEP_TIMEOUT):
        if not runtime.spec.is_first:
            raise ValueError("header must own stage 0")
        self.rt = runtime
        self.transport = transport
        self.next_id = next_id
        self.eos_id = eos_id
        self.step_timeout = step_timeout
        self._next_rid = 0
        self.stats = StageStats(role="header")
        self.tracer = TraceRecorder(f"header:{transport.device_id}")
        self.flight = get_flight_recorder()
        self._sent_at: Dict[tuple, float] = {}  # (rid, step) -> send time
        # (rid, step) -> (trace_id, send span id, epoch ts of send end);
        # the ring_rtt span's start/identity when the token comes back
        self._rtt_ctx: Dict[tuple, tuple] = {}
        self._next_stats_seq = 0

    # -- single-stage degenerate case is the engine's job, not ours --------

    def _make_h_tag(self, rid: int, step: int) -> str:
        return _h_tag(rid, step)

    def _send_hidden(self, rid: int, step: int, hidden,
                     trace_id: int = 0, parent_id: int = 0) -> None:
        send_span = self.tracer.next_span_id() if trace_id else 0
        body = wire.serialize_tensors_traced(
            [np.asarray(hidden)], trace_id or None, send_span)
        t_s = SpanClock()
        with t_s:
            self.transport.send(self.next_id, self._make_h_tag(rid, step),
                                body)
        self.stats.record_send(t_s.seconds, len(body))
        self.flight.record("hop_send", stage=self.transport.device_id,
                           rid=rid, step=step, dest=self.next_id,
                           nbytes=len(body))
        self._sent_at[(rid, step)] = time.perf_counter()
        if trace_id:
            self.tracer.record("send", trace_id, parent_id, clock=t_s,
                               span_id=send_span, rid=rid, step=step)
            self._rtt_ctx[(rid, step)] = (trace_id, send_span, time.time())

    def _prefill_array(self, req: _Request) -> np.ndarray:
        """Stage-0 prefill input for this request — token ids by default;
        the multimodal header substitutes a pre-embedded prefix
        (runtime/multimodal.py)."""
        return req.prompt.astype(np.int32)

    def _launch(self, req: _Request) -> None:
        t_c = SpanClock()
        with t_c:
            hidden = self.rt.run_chunk(req.rid, self._prefill_array(req))
            hidden = np.asarray(hidden)
        self.stats.record_compute(t_c.seconds)
        parent = 0
        if req.trace_id:
            parent = self.tracer.record(
                "compute", req.trace_id, clock=t_c,
                rid=req.rid, step=0, phase="prefill")
        self._send_hidden(req.rid, 0, hidden, req.trace_id, parent)

    def _record_rtt(self, rid: int, step: int) -> None:
        """Token (or classify reply) returned: close the ring-RTT timer
        and its span."""
        sent = self._sent_at.pop((rid, step), None)
        rtt_ctx = self._rtt_ctx.pop((rid, step), None)
        if sent is None:
            return
        dt = time.perf_counter() - sent
        self.stats.record_rtt(dt)
        if rtt_ctx is not None:
            trace_id, send_span, ts0 = rtt_ctx
            self.tracer.record("ring_rtt", trace_id, send_span, ts=ts0,
                               dur=dt, rid=rid, step=step)

    def _advance(self, req: _Request, toks: np.ndarray) -> None:
        """Got step's tokens; either issue the next decode chunk or finish."""
        self._record_rtt(req.rid, req.step)
        req.tokens.append(toks)
        req.step += 1
        if req.step >= req.max_new_tokens or (
                self.eos_id is not None
                and bool(np.all(toks == self.eos_id))):
            req.done = True
            self.transport.send(self.next_id, f"end:{req.rid}", b"")
            self.rt.free(req.rid)
            self._sent_at = {k: v for k, v in self._sent_at.items()
                             if k[0] != req.rid}
            self._rtt_ctx = {k: v for k, v in self._rtt_ctx.items()
                             if k[0] != req.rid}
            return
        t_c = SpanClock()
        with t_c:
            hidden = self.rt.run_chunk(req.rid,
                                       toks[:, None].astype(np.int32))
            hidden = np.asarray(hidden)
        self.stats.record_compute(t_c.seconds)
        parent = 0
        if req.trace_id:
            parent = self.tracer.record(
                "compute", req.trace_id, clock=t_c,
                rid=req.rid, step=req.step, phase="decode")
        self._send_hidden(req.rid, req.step, hidden, req.trace_id, parent)

    def _make_requests(self, prompts: Sequence[np.ndarray],
                       max_new_tokens) -> List[_Request]:
        """Capacity-check every prompt and mint _Requests with fresh rids.

        ``max_new_tokens``: one int for every prompt, or a per-prompt
        sequence (each _Request already carries its own budget — the
        dynamic-batching backend groups requests with different
        lengths into one window)."""
        if isinstance(max_new_tokens, (int, np.integer)):
            per = [max_new_tokens] * len(prompts)
        else:
            per = [int(n) for n in max_new_tokens]
            if len(per) != len(prompts):
                raise ValueError(
                    f"{len(per)} max_new_tokens for {len(prompts)} prompts")
        for p, mn in zip(prompts, per):
            need = p.shape[1] + mn
            if need > self.rt.max_seq:
                raise ValueError(
                    f"prompt ({p.shape[1]}) + new ({mn}) = "
                    f"{need} exceeds KV capacity {self.rt.max_seq}")
        pending = [
            _Request(rid=self._next_rid + i, prompt=np.asarray(p),
                     max_new_tokens=mn, trace_id=new_trace_id())
            for i, (p, mn) in enumerate(zip(prompts, per))]
        self._next_rid += len(pending)
        return pending

    def _stall_postmortem(self, phase: str) -> None:
        """A ring step timed out with work in flight: record the stall
        into the flight ring and capture a postmortem bundle naming the
        requests still awaiting their reply — the offline analyzer
        (``tools/postmortem.py``) pins the offending hop from the
        ``hop_send``/``hop_recv`` events around each stalled (rid,
        step)."""
        in_flight = [[r, s] for r, s in sorted(self._sent_at.keys())]
        self.flight.record("pipeline_stall",
                           stage=self.transport.device_id, phase=phase,
                           in_flight=in_flight,
                           step_timeout_s=self.step_timeout)
        postmortem.trigger(
            "pipeline_stall",
            detail={"stage": self.transport.device_id, "phase": phase,
                    "in_flight": in_flight,
                    "step_timeout_s": self.step_timeout},
            spans=self.tracer.snapshot())

    def generate_many(self, prompts: Sequence[np.ndarray],
                      max_new_tokens: int,
                      pool_size: int = 1,
                      on_token=None) -> List[np.ndarray]:
        """Generate for all prompts with ``pool_size`` requests in flight
        (the reference's corePoolSize microbatching,
        ``Communication.java:425-437``).  Returns [b, new_tokens] arrays in
        prompt order.

        ``on_token(prompt_index, step, tokens)`` fires as each step's
        tokens arrive — the reference's partial-decode streaming to the UI
        (``DataRepository``, ``Communication.java:629-638``) as a hook.
        """
        pending = self._make_requests(prompts, max_new_tokens)
        rid_to_index = {req.rid: i for i, req in enumerate(pending)}
        queue = list(pending)
        in_flight: Dict[int, _Request] = {}

        while queue or in_flight:
            while queue and len(in_flight) < pool_size:
                req = queue.pop(0)
                in_flight[req.rid] = req
                self._launch(req)
            t0 = time.perf_counter()
            try:
                tag, payload = self.transport.recv_any(
                    timeout=self.step_timeout)
            except TransportTimeout:
                self._stall_postmortem("generate")
                raise
            self.stats.record_recv(time.perf_counter() - t0, len(payload))
            kind, _, rest = tag.partition(":")
            if kind != "tok":
                log.warning("header: unexpected tag %r", tag)
                continue
            fields = rest.split(":")
            rid, tok_step = int(fields[0]), int(fields[1])
            req = in_flight.get(rid)
            if req is None or tok_step != req.step:
                continue    # finished request, or a duplicate/stale step
                # (transport retry / chaos duplicate): advancing twice on
                # one step would append the same token twice
            self.flight.record("tok_recv", stage=self.transport.device_id,
                               rid=rid, step=req.step)
            try:
                tensors, _ = wire.split_trace_context(
                    wire.deserialize_tensors(payload))
            except wire.WireIntegrityError as e:
                # dropped: this step's token is lost and the step times
                # out (static pipeline) — never a garbage token appended
                record_corrupt_frame(self.transport.device_id, tag,
                                     len(payload), e)
                continue
            [toks] = tensors
            step = req.step
            self._advance(req, toks)
            if on_token is not None:
                on_token(rid_to_index[rid], step, toks)
            if req.done:
                del in_flight[rid]

        return [np.stack(r.tokens, axis=1) for r in pending]

    def generate(self, prompt_ids: np.ndarray,
                 max_new_tokens: int) -> np.ndarray:
        """Single request; returns [b, new_tokens]."""
        return self.generate_many([prompt_ids], max_new_tokens)[0]

    def classify_many(self, prompts: Sequence[np.ndarray],
                      label_token_ids: Sequence[int],
                      pool_size: int = 1) -> List[np.ndarray]:
        """Classify each prompt batch over the pipeline: one prefill hop,
        the tail argmaxes the last-position logits restricted to
        ``label_token_ids``, and the predicted label index rides back (the
        reference's classification run, ``BackgroundService.java:233-245``
        over ``inference.cpp:220-270``).  Returns [b] int32 label-index
        arrays, prompt order.

        Unlike ``generate_many`` on the elastic header, this loop does NOT
        reshard on failure — a dead worker surfaces as a TransportTimeout
        after ``step_timeout`` and the caller retries.  Classification is
        a single stateless hop per request, so retry-from-outside loses
        nothing (no partial tokens to preserve)."""
        label_ids = np.asarray(label_token_ids, np.int32)
        if label_ids.ndim != 1 or label_ids.size < 2:
            raise ValueError("label_token_ids must be >= 2 token ids")
        if (label_ids < 0).any() or (label_ids
                                     >= self.rt.cfg.vocab_size).any():
            # validated HERE: an out-of-range id reaching the tail would
            # IndexError inside its serve loop and poison the pipeline
            raise ValueError(
                f"label_token_ids out of range [0, "
                f"{self.rt.cfg.vocab_size})")
        for p in prompts:
            if p.shape[1] > self.rt.max_seq:
                raise ValueError(
                    f"prompt ({p.shape[1]}) exceeds KV capacity "
                    f"{self.rt.max_seq}")
        rids = list(range(self._next_rid, self._next_rid + len(prompts)))
        self._next_rid += len(prompts)
        trace_ids = {rid: new_trace_id() for rid in rids}
        results: Dict[int, np.ndarray] = {}
        queue = list(zip(rids, prompts))
        in_flight: Dict[int, int] = {}   # rid -> queue index (for order)

        def launch(rid: int, prompt: np.ndarray) -> None:
            trace_id = trace_ids[rid]
            t_c = SpanClock()
            with t_c:
                hidden = self.rt.run_chunk(rid, prompt.astype(np.int32))
                send_span = self.tracer.next_span_id()
                body = wire.serialize_tensors_traced(
                    [np.asarray(hidden), label_ids], trace_id, send_span)
            self.stats.record_compute(t_c.seconds)
            parent = self.tracer.record(
                "compute", trace_id, clock=t_c,
                rid=rid, step=0, phase="classify")
            t_s = SpanClock()
            with t_s:
                self.transport.send(self.next_id, f"c:{rid}", body)
            self.stats.record_send(t_s.seconds, len(body))
            self.flight.record("hop_send",
                               stage=self.transport.device_id,
                               rid=rid, step=0, dest=self.next_id,
                               nbytes=len(body), classify=True)
            self.tracer.record("send", trace_id, parent, clock=t_s,
                               span_id=send_span, rid=rid, step=0)
            # rtt tracked like generate steps: the tail records one
            # compute sample per classify hop, so the header must record
            # one rtt — otherwise mixed classify+generate workloads skew
            # the index-paired activation-hop estimate (stats.snapshot)
            self._sent_at[(rid, 0)] = time.perf_counter()
            self._rtt_ctx[(rid, 0)] = (trace_id, send_span, time.time())

        while queue or in_flight:
            while queue and len(in_flight) < pool_size:
                rid, prompt = queue.pop(0)
                in_flight[rid] = rid
                launch(rid, np.asarray(prompt))
            t0 = time.perf_counter()
            try:
                tag, payload = self.transport.recv_any(
                    timeout=self.step_timeout)
            except TransportTimeout:
                self._stall_postmortem("classify")
                raise
            self.stats.record_recv(time.perf_counter() - t0, len(payload))
            kind, _, rest = tag.partition(":")
            if kind != "ctok":
                log.warning("header: unexpected tag %r during classify", tag)
                continue
            rid = int(rest.split(":")[0])
            if rid not in in_flight:
                continue
            self.flight.record("tok_recv", stage=self.transport.device_id,
                               rid=rid, step=0, classify=True)
            try:
                tensors, _ = wire.split_trace_context(
                    wire.deserialize_tensors(payload))
            except wire.WireIntegrityError as e:
                record_corrupt_frame(self.transport.device_id, tag,
                                     len(payload), e)
                continue
            self._record_rtt(rid, 0)
            [pred] = tensors
            results[rid] = pred.astype(np.int32)
            self.transport.send(self.next_id, f"end:{rid}", b"")
            self.rt.free(rid)
            del in_flight[rid]

        return [results[r] for r in rids]

    def collect_stats(self, num_stages: int,
                      timeout: float = 10.0,
                      include_spans: bool = False) -> List[dict]:
        """Poll every downstream stage for its stats snapshot.

        Sends ``statsreq`` down the chain; each stage replies directly to
        the header and forwards the request.  Returns the header's own
        snapshot first, then one dict per responding stage (may be fewer
        than ``num_stages - 1`` on timeout).  Call outside of generation —
        replies share the transport with token traffic.

        ``include_spans`` asks every stage to drain its trace spans into
        the reply (the :meth:`collect_trace` path — at-most-once
        delivery: a reply that misses this poll's window loses its
        spans).
        """
        seq = str(self._next_stats_seq)
        self._next_stats_seq += 1
        self.transport.send(self.next_id, f"statsreq:{seq}",
                            b"spans" if include_spans else b"")
        mine = dict(self.stats.snapshot(include_samples=True),
                    device_id=self.transport.device_id)
        # keyed by device_id + filtered by seq: a stale reply from an
        # earlier timed-out poll can neither satisfy nor displace this one
        replies: Dict[str, dict] = {}
        deadline = time.monotonic() + timeout
        want = num_stages - 1
        while len(replies) < want:
            left = deadline - time.monotonic()
            if left <= 0:
                break
            try:
                tag, payload = self.transport.recv_any(timeout=left)
            except TransportTimeout:
                break
            if tag.startswith("statsrep:"):
                snap = json.loads(payload.decode("utf-8"))
                if snap.get("seq") == seq:
                    replies[snap.get("device_id", tag)] = snap
            else:
                log.warning("header: unexpected tag %r during stats poll",
                            tag)
        return [mine] + list(replies.values())

    def collect_trace(self, num_stages: int,
                      timeout: float = 10.0) -> dict:
        """Drain every stage's spans (plus the header's own) and export
        as a Chrome trace-event JSON object (Perfetto-loadable).  Spans
        ride the ``statsreq`` control path, so like :meth:`collect_stats`
        this must run outside of generation.  Draining means consecutive
        calls return disjoint span sets; worker spans are at-most-once
        (a stage whose reply misses the poll timeout loses that batch)."""
        from ..telemetry.tracing import to_chrome_trace
        stats = self.collect_stats(num_stages, timeout,
                                   include_spans=True)
        spans = self.tracer.drain()
        for s in stats:
            spans.extend(s.pop("spans", None) or [])
        return to_chrome_trace(spans)

    def reset_stats(self) -> None:
        """Zero our counters and every downstream stage's (e.g. after a
        compile warmup, so benchmarks report steady state only)."""
        self.stats.reset()
        self._sent_at.clear()
        self._rtt_ctx.clear()
        self.transport.send(self.next_id, "statsreset", b"")

    def shutdown_pipeline(self) -> None:
        """Send ``stop`` down the chain (Finish→Close analogue for the data
        plane)."""
        self.transport.send(self.next_id, "stop", b"")
