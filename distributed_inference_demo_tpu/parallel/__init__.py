from .mesh import MeshConfig, make_mesh
from .sharding import param_shardings, shard_params, cache_shardings

__all__ = ["MeshConfig", "make_mesh", "param_shardings", "shard_params",
           "cache_shardings"]
