from .mesh import MeshConfig, make_mesh
from .sharding import param_shardings, shard_params, cache_shardings
from .sequence import make_sp_generate_fn

__all__ = ["MeshConfig", "make_mesh", "param_shardings", "shard_params",
           "cache_shardings", "make_sp_generate_fn"]
