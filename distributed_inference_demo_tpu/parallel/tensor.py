"""Tensor-parallel stage execution (Megatron layout over the ``tp`` axis).

BASELINE.json config #3: "Llama-3-8B tensor-parallel: attention-head shards
across 8 TPU chips via ICI all-gather".  The forward is
``decoder.stage_forward`` run inside ``jax.shard_map`` with column/row-
sliced weights and explicit psum/all-gather collectives (see
``decoder._layer(tp_axis=...)``); the KV cache lives sharded by kv-head so
each chip only touches its heads' cache lines.
"""

import jax
from jax.sharding import Mesh, PartitionSpec as P

from ..models.base import KVCache, ModelConfig, StageParams, StageSpec
from ..models.decoder import stage_forward
from .sharding import stage_param_spec_tree


def _tp_param_specs(params: StageParams, cfg: ModelConfig) -> StageParams:
    # lm_head is vocab-column-sharded; stage_forward all-gathers the logit
    # shards at the sampling boundary.  embed stays replicated (id gather).
    return stage_param_spec_tree(params, cfg, pp_shard=False, use_tp=True,
                                 vocab_parallel_embed=False)


# head-major cache [layers, batch, nkv, seq, hd]: shard the kv-head axis
_CACHE_SPEC = KVCache(keys=P(None, None, "tp", None, None),
                      values=P(None, None, "tp", None, None),
                      length=P())


def make_tp_stage_fn(cfg: ModelConfig, spec: StageSpec, mesh: Mesh,
                     params_template: StageParams):
    """Jitted fn(params, inputs, cache, positions) -> (out, cache) with the
    stage's weights and KV cache sharded over ``tp``.

    Requires ``cfg.num_kv_heads %% tp == 0`` (cache shards by kv head).
    Activations and logits come back replicated — the caller samples or
    forwards them without caring about the mesh.
    """
    tp = mesh.shape["tp"]
    if tp > 1 and cfg.num_kv_heads % tp:
        raise ValueError(
            f"num_kv_heads={cfg.num_kv_heads} not divisible by tp={tp}")

    p_specs = _tp_param_specs(params_template, cfg)

    def body(p, i, c, pos):
        return stage_forward(p, cfg, spec, i, c, pos, tp_axis="tp")

    def fn(params, inputs, cache, positions):
        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(p_specs, P(), _CACHE_SPEC, P()),
            out_specs=(P(), _CACHE_SPEC),
            check_vma=False,
        )(params, inputs, cache, positions)

    return jax.jit(fn, donate_argnums=(2,))
