"""Tensor-parallel stage execution (Megatron layout over the ``tp`` axis).

BASELINE.json config #3: "Llama-3-8B tensor-parallel: attention-head shards
across 8 TPU chips via ICI all-gather".  The forward is
``decoder.stage_forward`` run inside ``jax.shard_map`` with column/row-
sliced weights and explicit psum/all-gather collectives (see
``decoder._layer(tp_axis=...)``); the KV cache lives sharded by kv-head so
each chip only touches its heads' cache lines.
"""

import jax
from jax.sharding import Mesh, PartitionSpec as P

from ..models.base import KVCache, ModelConfig, StageParams, StageSpec
from ..models.decoder import stage_forward
from .compat import shard_map
from .sharding import stage_param_spec_tree


def _tp_param_specs(params: StageParams, cfg: ModelConfig) -> StageParams:
    # lm_head is vocab-column-sharded; stage_forward all-gathers the logit
    # shards at the sampling boundary.  embed stays replicated (id gather).
    return stage_param_spec_tree(params, cfg, pp_shard=False, use_tp=True,
                                 vocab_parallel_embed=False)


# head-major cache [layers, batch, nkv, seq, hd]: shard the kv-head axis.
# The spec doubles as a pytree PREFIX: a quantized page pool
# (ops.quant.QuantizedKVPages) hangs data/scale/zero leaves under keys/
# values, all keeping the [L, N, H, bt, ·] axis order with a trailing
# singleton on the sidecars — the one rank-5 spec broadcasts over the
# subtree, so scale tensors shard WITH their pages and no quantized
# variant of this spec exists (docs/DESIGN.md §17).
_CACHE_SPEC = KVCache(keys=P(None, None, "tp", None, None),
                      values=P(None, None, "tp", None, None),
                      length=P())


def tp_cache_sharding(mesh: Mesh) -> KVCache:
    """NamedShardings for a KVCache on the tp mesh (kv-head-sharded) —
    for committing fresh cache buffers to their shards up front."""
    from jax.sharding import NamedSharding
    return KVCache(keys=NamedSharding(mesh, _CACHE_SPEC.keys),
                   values=NamedSharding(mesh, _CACHE_SPEC.values),
                   length=NamedSharding(mesh, _CACHE_SPEC.length))


def validate_tp(cfg: ModelConfig, mesh: Mesh) -> int:
    """Check the config can shard over the mesh's tp axis; returns tp."""
    tp = mesh.shape.get("tp", 1)
    if tp > 1 and cfg.num_kv_heads % tp:
        raise ValueError(
            f"num_kv_heads={cfg.num_kv_heads} not divisible by tp={tp}")
    return tp


def make_tp_forward(cfg: ModelConfig, spec: StageSpec, mesh: Mesh,
                    params_template: StageParams, attn_impl=None):
    """``fwd(params, inputs, cache, positions, last_logits_only)`` running
    ``stage_forward`` inside a tp shard_map — the seam every engine builds
    its jits on (runtime/engine.py, speculative.py, prompt_lookup.py,
    batching.py).  Activations/positions/logits are replicated; weights
    and the KV cache stay sharded per this module's specs.

    ``attn_impl`` runs INSIDE the shard (per-rank head counts, local
    kv-head cache plane) — e.g. batching's per-slot scatter impl; None
    uses the default insert-and-attend path."""
    validate_tp(cfg, mesh)
    p_specs = _tp_param_specs(params_template, cfg)

    def fwd(p, inputs, cache, positions, last_logits_only):
        def body(p, i, c, po):
            return stage_forward(p, cfg, spec, i, c, po, tp_axis="tp",
                                 attn_impl=attn_impl,
                                 last_logits_only=last_logits_only)
        return shard_map(
            body, mesh=mesh,
            in_specs=(p_specs, P(), _CACHE_SPEC, P()),
            out_specs=(P(), _CACHE_SPEC),
            check_vma=False)(p, inputs, cache, positions)

    return fwd


def resolve_tp_attn_backend(tp: int, attn_backend: str) -> str:
    """The one rule for attention backends under a tp mesh: force jnp
    (the Pallas kernel is not exercised per-shard), rejecting an explicit
    non-jnp request rather than silently downgrading it.  Shared by every
    engine that takes ``mesh=``."""
    if tp > 1:
        if attn_backend not in ("auto", "jnp"):
            raise ValueError(
                f"attn_backend={attn_backend!r} is incompatible with a tp "
                "mesh (the Pallas kernel is not exercised per-shard); use "
                "'auto' or 'jnp'")
        return "jnp"
    return attn_backend


def make_forward_seam(cfg: ModelConfig, spec: StageSpec, mesh,
                      params_template: StageParams, attn_impl=None):
    """(fwd, cache_sharding) for an engine: the tp shard_map seam when
    ``mesh`` has a tp axis > 1, else a plain ``stage_forward`` closure
    with ``cache_sharding=None``.  The one mesh-dispatch rule shared by
    every engine constructor (engine.py, speculative.py,
    prompt_lookup.py, batching.py)."""
    tp = mesh.shape.get("tp", 1) if mesh is not None else 1
    if tp > 1:
        return (make_tp_forward(cfg, spec, mesh, params_template,
                                attn_impl=attn_impl),
                tp_cache_sharding(mesh))

    def fwd(p, inputs, cache, positions, last_logits_only):
        return stage_forward(p, cfg, spec, inputs, cache, positions,
                             attn_impl=attn_impl,
                             last_logits_only=last_logits_only)

    return fwd, None


def make_paged_forward_seam(cfg: ModelConfig, spec: StageSpec, mesh,
                            params_template: StageParams,
                            block_tokens: int, backend: str = "auto"):
    """``(fwd, bind, pool_sharding)`` for a PAGED-cache engine: the
    forward runs ``ops.paged_attention``'s block-table hook over a page
    pool ``[L, N, H, bt, D]`` standing in for the dense cache buffers.

    ``bind(tables)`` hands the current dispatch's block tables to the
    hook — call it at the top of the caller's jitted body, before the
    first ``fwd``.  Off-mesh, the hook reads the binding by closure (a
    loop constant of the trace).  Under a tp mesh the tables are
    threaded through ``shard_map`` as an explicit replicated argument
    instead — shard_map bodies must not close over traced values — and
    the pool shards by kv head exactly like the dense cache
    (``_CACHE_SPEC``: axis 2 either way), so each chip pages only its
    own head planes.  The one paged-dispatch rule shared by the
    batching scheduler and the ring stage runtimes."""
    from ..ops.paged_attention import make_paged_attn_impl
    tp = mesh.shape.get("tp", 1) if mesh is not None else 1
    if tp <= 1:
        impl, bind = make_paged_attn_impl(block_tokens, backend)

        def fwd(p, inputs, cache, positions, last_logits_only):
            return stage_forward(p, cfg, spec, inputs, cache, positions,
                                 attn_impl=impl,
                                 last_logits_only=last_logits_only)

        return fwd, bind, None
    validate_tp(cfg, mesh)
    p_specs = _tp_param_specs(params_template, cfg)
    bound = {}

    def bind(tables):
        bound["tables"] = tables

    def fwd(p, inputs, cache, positions, last_logits_only):
        def body(p_, i_, c_, po_, tab_):
            # the Pallas kernel is not exercised per-shard (the dense
            # tp rule, resolve_tp_attn_backend) — force the XLA gather
            impl, bind_local = make_paged_attn_impl(block_tokens, "xla")
            bind_local(tab_)
            return stage_forward(p_, cfg, spec, i_, c_, po_,
                                 tp_axis="tp", attn_impl=impl,
                                 last_logits_only=last_logits_only)

        return shard_map(
            body, mesh=mesh,
            in_specs=(p_specs, P(), _CACHE_SPEC, P(), P()),
            out_specs=(P(), _CACHE_SPEC),
            check_vma=False)(p, inputs, cache, positions,
                             bound["tables"])

    return fwd, bind, tp_cache_sharding(mesh)


def make_tp_stage_fn(cfg: ModelConfig, spec: StageSpec, mesh: Mesh,
                     params_template: StageParams):
    """Jitted fn(params, inputs, cache, positions) -> (out, cache) with the
    stage's weights and KV cache sharded over ``tp`` (all-positions logits
    variant of :func:`make_tp_forward`).

    Requires ``cfg.num_kv_heads %% tp == 0`` (cache shards by kv head).
    Activations and logits come back replicated — the caller samples or
    forwards them without caring about the mesh.
    """
    fwd = make_tp_forward(cfg, spec, mesh, params_template)

    def fn(params, inputs, cache, positions):
        return fwd(params, inputs, cache, positions, False)

    return jax.jit(fn, donate_argnums=(2,))
