"""Re-export of the repo's jax compat shims under the historical
``parallel.compat`` name (the shims live in ``.._jax_compat`` so
``models`` can consume them without importing this package)."""

from .._jax_compat import axis_size, shard_map

__all__ = ["axis_size", "shard_map"]
