"""Device mesh construction: the TPU-native replacement for the reference's
device ring.

The reference arranges devices in a TCP ring (header -> workers -> tail ->
header, ``Config.java:111-134``) with hand-rolled port arithmetic
(``Communication.java:937-961``).  Here the topology is a
``jax.sharding.Mesh`` with named axes:

- ``dp``: data parallel (concurrent samples — the reference's
  ``core_pool_size`` in-flight pipelining, ``server.py:1003``)
- ``pp``: pipeline stages (the reference's per-device layer ranges)
- ``tp``: tensor parallel (attention heads / MLP columns; absent in the
  reference — SURVEY.md §2.7)
- ``sp``: sequence/context parallel for long sequences (ring attention;
  absent in the reference — SURVEY.md §5.7)

Expert parallelism for MoE rides the ``tp`` axis (experts are sharded over
the same chips that would otherwise shard heads).

Collectives ride ICI when the mesh maps to a physical slice; across hosts
XLA routes them over DCN.  Axis order is chosen so the innermost (fastest)
mesh dim carries ``tp`` — the axis with the chattiest collectives.
"""

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


AXES = ("dp", "pp", "ep", "sp", "tp")


@dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    pp: int = 1
    ep: int = 1
    tp: int = 1
    sp: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.pp * self.ep * self.tp * self.sp

    def axis_sizes(self) -> dict:
        return {"dp": self.dp, "pp": self.pp, "ep": self.ep,
                "tp": self.tp, "sp": self.sp}


def local_tp_mesh(tp: int):
    """tp mesh over the first ``tp`` local devices, or None for tp <= 1 —
    the one mesh-selection rule shared by the CLI engine builders and the
    worker processes."""
    if tp <= 1:
        return None
    return make_mesh(MeshConfig(tp=tp), jax.devices()[:tp])


def local_sp_mesh(sp: int):
    """sp (sequence/context-parallel) mesh over the first ``sp`` local
    devices, or None for sp <= 1 — the CLI's long-context mesh rule
    (``generate --sp``), mirroring :func:`local_tp_mesh`."""
    if sp <= 1:
        return None
    return make_mesh(MeshConfig(sp=sp), jax.devices()[:sp])


def init_multihost(coordinator: str, num_processes: int, process_id: int,
                   local_device_count: Optional[int] = None) -> None:
    """Join this process to a multi-host JAX runtime (DCN control plane).

    The reference scales across hosts with hand-wired ZMQ sockets and
    port arithmetic (``Communication.java:937-961``); the TPU-native
    equivalent is JAX's distributed runtime: after this call
    ``jax.devices()`` spans every host's chips, ``make_mesh`` builds
    cross-host meshes unchanged, and XLA routes in-mesh collectives over
    ICI within a slice and DCN across slices.  Call before any other JAX
    API touches a backend.  Idempotent-unsafe by JAX design (a second
    call raises) — the CLI invokes it once at startup.
    """
    if num_processes < 1 or not (0 <= process_id < num_processes):
        raise ValueError(
            f"bad process topology: id {process_id} of {num_processes}")
    if local_device_count is not None and local_device_count < 1:
        raise ValueError(
            f"local_device_count must be >= 1, got {local_device_count}")
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=(list(range(local_device_count))
                          if local_device_count is not None else None))


def make_mesh(cfg: MeshConfig, devices: Optional[Sequence] = None) -> Mesh:
    """Build the named mesh.  dp is outermost (DCN-friendly: gradient/batch
    collectives are infrequent), tp innermost (ICI-neighbor heavy)."""
    devices = list(devices if devices is not None else jax.devices())
    need = cfg.num_devices
    if len(devices) < need:
        raise ValueError(
            f"mesh {cfg} needs {need} devices, have {len(devices)}")
    # tp innermost: consecutive physical devices are tp-neighbors (the
    # chattiest collectives — per-layer psums — ride adjacent ICI links);
    # sp next (ring-attention ppermute hops one tp-group over), then ep
    # (per-layer all_to_all, chunky but less frequent), then pp, then dp
    # outermost (infrequent gradient/batch collectives, DCN-ok).
    arr = np.asarray(devices[:need]).reshape(cfg.dp, cfg.pp, cfg.ep,
                                             cfg.sp, cfg.tp)
    return Mesh(arr, ("dp", "pp", "ep", "sp", "tp"))
