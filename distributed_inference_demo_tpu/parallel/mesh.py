"""Device mesh construction: the TPU-native replacement for the reference's
device ring.

The reference arranges devices in a TCP ring (header -> workers -> tail ->
header, ``Config.java:111-134``) with hand-rolled port arithmetic
(``Communication.java:937-961``).  Here the topology is a
``jax.sharding.Mesh`` with named axes:

- ``dp``: data parallel (concurrent samples — the reference's
  ``core_pool_size`` in-flight pipelining, ``server.py:1003``)
- ``pp``: pipeline stages (the reference's per-device layer ranges)
- ``tp``: tensor parallel (attention heads / MLP columns; absent in the
  reference — SURVEY.md §2.7)
- ``sp``: sequence/context parallel for long sequences (ring attention;
  absent in the reference — SURVEY.md §5.7)

Expert parallelism for MoE rides the ``tp`` axis (experts are sharded over
the same chips that would otherwise shard heads).

Collectives ride ICI when the mesh maps to a physical slice; across hosts
XLA routes them over DCN.  Axis order is chosen so the innermost (fastest)
mesh dim carries ``tp`` — the axis with the chattiest collectives.
"""

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


AXES = ("dp", "pp", "ep", "sp", "tp")


@dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    pp: int = 1
    ep: int = 1
    tp: int = 1
    sp: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.pp * self.ep * self.tp * self.sp

    def axis_sizes(self) -> dict:
        return {"dp": self.dp, "pp": self.pp, "ep": self.ep,
                "tp": self.tp, "sp": self.sp}


def make_mesh(cfg: MeshConfig, devices: Optional[Sequence] = None) -> Mesh:
    """Build the named mesh.  dp is outermost (DCN-friendly: gradient/batch
    collectives are infrequent), tp innermost (ICI-neighbor heavy)."""
    devices = list(devices if devices is not None else jax.devices())
    need = cfg.num_devices
    if len(devices) < need:
        raise ValueError(
            f"mesh {cfg} needs {need} devices, have {len(devices)}")
    # tp innermost: consecutive physical devices are tp-neighbors (the
    # chattiest collectives — per-layer psums — ride adjacent ICI links);
    # sp next (ring-attention ppermute hops one tp-group over), then ep
    # (per-layer all_to_all, chunky but less frequent), then pp, then dp
    # outermost (infrequent gradient/batch collectives, DCN-ok).
    arr = np.asarray(devices[:need]).reshape(cfg.dp, cfg.pp, cfg.ep,
                                             cfg.sp, cfg.tp)
    return Mesh(arr, ("dp", "pp", "ep", "sp", "tp"))
