"""Ulysses-style all-to-all sequence parallelism over the ``sp`` axis.

Complements the ring-attention path (``parallel/sequence.py``) — the task's
long-context requirement names both strategies ("ring attention or
all-to-all sequence/context parallelism").  Here activations and QKV/MLP
projections stay SEQUENCE-sharded, and attention itself runs HEAD-sharded
over the full sequence after one ``all_to_all`` each way per layer
(DeepSpeed-Ulysses; PAPERS.md):

- prefill: q/k/v ``[b, s/n, heads, hd]`` → all_to_all (split heads, concat
  seq) → ``[b, s, heads/n, hd]``; plain causal attention per head block;
  reverse all_to_all on the output.
- the KV cache shards by HEAD (``[L, b, nkv/n, max_seq, hd]``) — an n-fold
  cache-memory saving, same as the TP layout.
- decode: the single replicated token needs no seq all_to_all; each rank
  slices its head block, attends over its cache shard, and the head
  outputs are all-gathered — 1 collective per layer per step.

vs ring attention: Ulysses moves activations (2 all_to_alls/layer) instead
of KV blocks around a ring; its comm volume is independent of context
length, at the cost of requiring ``num_heads % sp == 0`` (ring has no head
constraint and keeps the cache sequence-sharded).  Absent entirely in the
reference (SURVEY.md §5.7: max_length=40, no cache).
"""

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh
from .compat import axis_size

from ..models.base import KVCache, ModelConfig, StageSpec
from ..models.decoder import stage_forward
from ..ops.attention import attention, update_kv_cache
from ..ops.sampling import SamplingParams, sample_logits
from .sequence import _decode_scan, _sample_first_token, _wrap_sp_body


def make_ulysses_generate_fn(cfg: ModelConfig, mesh: Mesh, *, max_seq: int,
                             num_new_tokens: int,
                             sampling: Optional[SamplingParams] = None,
                             kv_cache_dtype=None):
    """Build a jitted ``fn(params, prompt_ids, rng) -> tokens``: Ulysses
    prefill + head-sharded-cache decode over ``mesh``'s sp axis.

    Constraints (checked host-side): ``prompt_len % sp == 0``,
    ``num_heads % sp == 0``, ``num_kv_heads % sp == 0``,
    ``prompt_len + num_new_tokens <= max_seq``.  Greedy when ``sampling``
    is None; returns [batch, num_new_tokens] int32.

    ``kv_cache_dtype``: reduced-precision storage for the head-sharded
    cache — Ulysses attention (prefill AND decode) already reads from the
    cache, so the engines' "attend what the cache stores" contract holds
    with no extra rounding step (``update_kv_cache`` casts on insert,
    ``ops.attention`` upcasts on read).
    """
    sp = mesh.shape["sp"]
    if cfg.num_heads % sp or cfg.num_kv_heads % sp:
        raise ValueError(
            f"ulysses needs num_heads ({cfg.num_heads}) and num_kv_heads "
            f"({cfg.num_kv_heads}) divisible by sp={sp}")
    from ..runtime.engine import resolve_cache_dtype_backend
    kv_dtype, _ = resolve_cache_dtype_backend(kv_cache_dtype, "jnp")
    sampling = sampling or SamplingParams(greedy=True)
    prefill_core, step_core = _make_ulysses_cores(cfg, max_seq, sp,
                                                  sampling, kv_dtype)

    def body(params, ids, rng):
        carry, rng = prefill_core(params, ids, rng)
        tok0 = carry[-1]

        def step(c, r):
            return step_core(params, c, r)

        return _decode_scan(step, carry, rng, num_new_tokens, tok0)

    return _wrap_sp_body(body, mesh, sp, max_seq, num_new_tokens)


def _make_ulysses_cores(cfg: ModelConfig, max_seq: int, sp: int,
                        sampling: SamplingParams, kv_dtype):
    """``(prefill_core, step_core)`` — the Ulysses math, shared by the
    fused generate fn and the step-split stream fns (one owner, like the
    ring path's ``_make_ring_cores``).  Decode carry:
    ``(keys, values, length, tok)`` with the cache head-sharded."""
    cache_dtype = kv_dtype if kv_dtype is not None else cfg.dtype
    spec = StageSpec(0, 1, 0, cfg.num_layers)
    body_spec = StageSpec(0, 2, 0, cfg.num_layers)  # no head at prefill
    nh_loc = cfg.num_heads // sp
    nkv_loc = cfg.num_kv_heads // sp
    hd = cfg.head_dim

    def slice_slopes(slopes, idx):
        if slopes is None:
            return None
        return jax.lax.dynamic_slice_in_dim(slopes, idx * nh_loc,
                                            nh_loc, axis=0)

    def prefill_core(params, ids, rng):
        n = axis_size("sp")
        idx = jax.lax.axis_index("sp")
        b, chunk = ids.shape            # local contiguous prompt chunk
        S = n * chunk

        # ---- prefill: all_to_all to head-sharded full-sequence attention
        def prefill_attn(q, k, v, kc, vc, pos, cache_start, slopes):
            # [b, chunk, heads, hd] -> [b, S, heads/n, hd]: split the head
            # axis across ranks, gather every rank's seq chunk (rank order
            # == sequence order — contiguous prompt sharding)
            qf = jax.lax.all_to_all(q, "sp", split_axis=2, concat_axis=1,
                                    tiled=True)
            kf = jax.lax.all_to_all(k, "sp", split_axis=2, concat_axis=1,
                                    tiled=True)
            vf = jax.lax.all_to_all(v, "sp", split_axis=2, concat_axis=1,
                                    tiled=True)
            kc, vc = update_kv_cache(kc, vc, kf, vf, cache_start)
            qpos = jnp.broadcast_to(cache_start + jnp.arange(S), (b, S))
            out = attention(qf, kc, vc, qpos, cache_start + S,
                            slice_slopes(slopes, idx))
            # back to seq-sharded all-heads for the output projection
            out = jax.lax.all_to_all(out, "sp", split_axis=1, concat_axis=2,
                                     tiled=True)
            return out, kc, vc

        shape = (spec.num_layers, b, nkv_loc, max_seq, hd)
        cache = KVCache(keys=jnp.zeros(shape, cache_dtype),
                        values=jnp.zeros(shape, cache_dtype),
                        length=jnp.zeros((), jnp.int32))
        positions = jnp.broadcast_to(idx * chunk + jnp.arange(chunk),
                                     (b, chunk))
        hidden, cache = stage_forward(params, cfg, body_spec, ids, cache,
                                      positions, attn_impl=prefill_attn)

        tok0, rng = _sample_first_token(params, cfg, hidden, idx, n, rng,
                                        sampling)
        return (cache.keys, cache.values, jnp.asarray(S, jnp.int32),
                tok0), rng

    def step_core(params, carry, step_rng):
        # ---- decode: head-sharded cache, all_gather the head outputs ----
        keys, values, length, tok = carry
        idx = jax.lax.axis_index("sp")
        b = tok.shape[0]

        def slice_heads(x, loc):
            return jax.lax.dynamic_slice_in_dim(x, idx * loc, loc, axis=2)

        def dec_attn(q, k, v, kc, vc, pos_, cache_start, slopes):
            q_loc = slice_heads(q, nh_loc)     # [b, 1, nh_loc, hd]
            k_loc = slice_heads(k, nkv_loc)
            v_loc = slice_heads(v, nkv_loc)
            kc, vc = update_kv_cache(kc, vc, k_loc, v_loc, cache_start)
            out = attention(q_loc, kc, vc, pos_, cache_start + 1,
                            slice_slopes(slopes, idx))
            out = jax.lax.all_gather(out, "sp", axis=2, tiled=True)
            return out, kc, vc

        cache = KVCache(keys, values, length)
        pos = jnp.broadcast_to(length, (b, 1))
        logits, cache = stage_forward(params, cfg, spec, tok[:, None],
                                      cache, pos, attn_impl=dec_attn)
        nxt = sample_logits(logits[:, -1, :], step_rng, sampling)
        return (cache.keys, cache.values, length + 1, nxt), nxt

    return prefill_core, step_core


def make_ulysses_stream_fns(cfg: ModelConfig, mesh: Mesh, *, max_seq: int,
                            block: int,
                            sampling: Optional[SamplingParams] = None,
                            kv_cache_dtype=None):
    """Step-split Ulysses programs — ``(prefill_fn, decode_fn)`` with the
    same contract as :func:`parallel.sequence.make_sp_stream_fns` (state
    here: head-sharded cache + length + last token).  One compiled pair
    serves every ``max_new_tokens``; greedy parity with the fused fn."""
    sp = mesh.shape["sp"]
    if cfg.num_heads % sp or cfg.num_kv_heads % sp:
        raise ValueError(
            f"ulysses needs num_heads ({cfg.num_heads}) and num_kv_heads "
            f"({cfg.num_kv_heads}) divisible by sp={sp}")
    if block < 1:
        raise ValueError("block must be >= 1")
    from ..runtime.engine import resolve_cache_dtype_backend
    kv_dtype, _ = resolve_cache_dtype_backend(kv_cache_dtype, "jnp")
    sampling = sampling or SamplingParams(greedy=True)
    prefill_core, step_core = _make_ulysses_cores(cfg, max_seq, sp,
                                                  sampling, kv_dtype)

    from jax.sharding import PartitionSpec as P

    from .sequence import _wrap_stream_fns
    cache_spec = P(None, None, "sp", None, None)    # nkv head-sharded
    state_specs = (cache_spec, cache_spec, P(), P())
    return _wrap_stream_fns(prefill_core, step_core, mesh, state_specs,
                            block)
