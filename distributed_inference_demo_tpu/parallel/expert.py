"""Expert-parallel MoE stage execution over the ``ep`` mesh axis.

BASELINE.json config #4: "Mixtral-8x7B MoE: per-expert shard placement,
router on the server, experts as TPU clients" — done the TPU way (the
reference's closest concept is per-device module placement,
``/root/reference/server.py:893-905``): expert weights live E-sliced over
``ep`` (each chip holds ``E/ep`` experts), tokens are data-parallel over
the same axis, and ``decoder._moe_mlp_ep`` routes tokens to expert owners
with GShard-style capacity dispatch + ``all_to_all`` (PAPERS.md: GShard).

Everything that is not an expert weight — attention, norms, router,
embed/head — runs data-parallel over ``ep`` with replicated weights, so
the only cross-chip traffic is the two all_to_alls per MoE layer.
"""

import jax
from jax.sharding import Mesh, PartitionSpec as P
from .compat import shard_map

from ..models.base import KVCache, ModelConfig, StageParams, StageSpec
from ..models.decoder import stage_forward
from ..ops.quant import QuantizedArray, QuantizedArray4
from .sharding import quant4_specs, quant_scale_spec

# expert stacks [L, E, H, I]: shard E over ep; everything else replicated
_EP_LAYER_SPECS = {
    "w_gate": P(None, "ep", None, None),
    "w_up": P(None, "ep", None, None),
    "w_down": P(None, "ep", None, None),
}

# tokens are data-parallel over ep: batch-shard the cache
# [layers, batch, nkv, seq, hd]
_CACHE_SPEC = KVCache(keys=P(None, "ep", None, None, None),
                      values=P(None, "ep", None, None, None),
                      length=P())


def _ep_param_specs(params: StageParams) -> StageParams:
    def map_layers(layers):
        out = {}
        for k, v in layers.items():
            spec = _EP_LAYER_SPECS.get(k, P())
            if isinstance(v, QuantizedArray):
                out[k] = QuantizedArray(q=spec, scale=quant_scale_spec(spec))
            elif isinstance(v, QuantizedArray4):
                # ep slices the EXPERT axis; int4 packing lives on the
                # input axis (-2), so the two compose (quant4_specs
                # rejects only tp, which cuts the packed axis itself)
                out[k] = quant4_specs(v, spec)
            else:
                out[k] = spec
        return out

    rep = lambda d: None if d is None else {k: P() for k in d}
    return StageParams(layers=map_layers(params.layers),
                       embed=rep(params.embed),
                       final_norm=rep(params.final_norm),
                       lm_head=rep(params.lm_head))


def make_ep_stage_fn(cfg: ModelConfig, spec: StageSpec, mesh: Mesh,
                     params_template: StageParams):
    """Jitted fn(params, inputs, cache, positions) -> (out, cache) with
    expert weights E-sliced over ``ep`` and the batch data-parallel over it.

    Requires ``cfg.num_experts % ep == 0`` and ``batch % ep == 0``.
    Outputs come back batch-sharded (matching the inputs); the caller sees
    globally-shaped arrays either way.
    """
    ep = mesh.shape["ep"]
    if cfg.num_experts == 0:
        raise ValueError("expert parallelism needs a MoE config "
                         "(num_experts > 0)")
    if cfg.num_experts % ep:
        raise ValueError(
            f"num_experts={cfg.num_experts} not divisible by ep={ep}")

    p_specs = _ep_param_specs(params_template)
    data = P("ep")  # batch axis of ids/hidden/positions/logits

    def body(p, i, c, pos):
        return stage_forward(p, cfg, spec, i, c, pos, ep_axis="ep")

    def fn(params, inputs, cache, positions):
        if inputs.shape[0] % ep:
            raise ValueError(
                f"batch={inputs.shape[0]} not divisible by ep={ep} "
                "(tokens are data-parallel over the ep axis)")
        return shard_map(
            body, mesh=mesh,
            in_specs=(p_specs, data, _CACHE_SPEC, data),
            out_specs=(data, _CACHE_SPEC),
            check_vma=False,
        )(params, inputs, cache, positions)

    return jax.jit(fn, donate_argnums=(2,))
