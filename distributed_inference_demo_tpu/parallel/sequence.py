"""Sequence/context parallelism: long-context generation over the ``sp`` axis.

Absent in the reference (SURVEY.md §5.7: ``max_length=40``, no KV cache, no
sequence parallelism).  Here long prompts are first-class: the prompt is
sharded into contiguous chunks over the ``sp`` mesh axis, prefill runs
**ring attention** (ops/ring_attention.py) so no device ever materializes the
full sequence, and the KV cache stays sharded by sequence for the whole
generation — decode combines per-shard partial attention with an exact
log-sum-exp reduction instead of moving KV.

Decode-token placement is stateless round-robin, derived from the carried
global length: the d-th decoded token's K/V lands on rank ``d % sp`` at slot
``chunk + d // sp``, so cache shards stay balanced with no coordination
traffic; the ``kv_pos`` position map (-1 = empty slot) drives causal masking.

The decoder block itself is shared with every other path via the
``attn_impl`` hook of ``models.decoder.stage_forward`` — sequence parallelism
swaps the attention/cache strategy, not the model math.
"""

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from .compat import axis_size, shard_map

from ..models.base import KVCache, ModelConfig, StageSpec
from ..models.decoder import stage_forward
from ..ops.attention import update_kv_cache
from ..ops.norms import layer_norm, rms_norm
from ..ops.ring_attention import ring_self_attention, sp_decode_attention
from ..ops.sampling import SamplingParams, sample_logits


def _dynamic_set1(arr: jnp.ndarray, idx: jnp.ndarray, val: jnp.ndarray):
    """arr[idx] = val for a traced scalar idx (1-element update slice)."""
    return jax.lax.dynamic_update_slice(arr, val[None].astype(arr.dtype),
                                        (idx,))


def _final_logits(params, cfg: ModelConfig, h: jnp.ndarray) -> jnp.ndarray:
    """Final norm + LM head on [b, l, H] hidden (stage_forward's tail,
    applied here to just the selected last position instead of on every
    rank's whole chunk)."""
    if cfg.attn_layernorm:
        h = layer_norm(h, params.final_norm["w"], params.final_norm["b"],
                       cfg.norm_eps)
    else:
        h = rms_norm(h, params.final_norm["w"], cfg.norm_eps)
    head = (params.embed["tokens"].T if cfg.tie_embeddings
            else params.lm_head["w"])
    return jnp.einsum("blh,hv->blv", h, head)


def _sample_first_token(params, cfg, hidden, idx, n, rng, sampling):
    """The global last prompt token lives on rank n-1: broadcast its hidden
    row via psum, run the head ONCE on that single position, sample token 0.
    Shared by the ring (make_sp_generate_fn) and Ulysses generate paths."""
    h_last = jnp.where(idx == n - 1, hidden[:, -1:, :].astype(jnp.float32),
                       0.0)
    h_last = jax.lax.psum(h_last, "sp").astype(cfg.dtype)
    last = _final_logits(params, cfg, h_last)[:, 0, :]
    rng, r0 = jax.random.split(rng)
    return sample_logits(last, r0, sampling), rng


def _decode_scan(step, carry, rng, num_new_tokens, tok0):
    """Scan ``step`` over per-step rngs and assemble [b, num_new] tokens."""
    rngs = jax.random.split(rng, num_new_tokens - 1) \
        if num_new_tokens > 1 else jnp.zeros((0, 2), jnp.uint32)
    _, rest = jax.lax.scan(step, carry, rngs)
    return jnp.concatenate([tok0[:, None], rest.T], axis=1) \
        if num_new_tokens > 1 else tok0[:, None]


def _wrap_sp_body(body, mesh: Mesh, sp: int, max_seq: int,
                  num_new_tokens: int):
    """shard_map + jit + host-side shape validation, shared by both
    sequence-parallel strategies (prompt sharded over sp's seq axis)."""
    sharded = shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(None, "sp"), P()),
        out_specs=P(),
        check_vma=False,
    )

    @jax.jit
    def fn(params, prompt_ids, rng):
        return sharded(params, prompt_ids, rng)

    def checked(params, prompt_ids, rng):
        validate_sp_prompt(prompt_ids.shape[1], sp, max_seq,
                           num_new_tokens)
        return fn(params, prompt_ids, rng)

    return checked


def validate_sp_prompt(plen: int, sp: int, max_seq: int,
                       num_new_tokens: int) -> None:
    """The sp prompt-shape rule, owned here and shared by the generate
    fns' call-time check and any caller that wants to FAIL FAST before
    paying a checkpoint load (cli ``generate --sp``)."""
    if plen % sp:
        raise ValueError(
            f"prompt_len={plen} not divisible by sp={sp}; pad first")
    if plen + num_new_tokens > max_seq:
        raise ValueError(
            f"prompt {plen} + new {num_new_tokens} > max_seq {max_seq}")


def make_sp_generate_fn(cfg: ModelConfig, mesh: Mesh, *, max_seq: int,
                        num_new_tokens: int,
                        sampling: Optional[SamplingParams] = None,
                        kv_cache_dtype=None):
    """Build a jitted ``fn(params, prompt_ids, rng) -> tokens`` that runs
    ring-attention prefill + sp-sharded-cache decode over ``mesh``'s sp axis.

    Constraints (checked host-side): ``prompt_len % sp == 0`` (pad the prompt
    to a chunk multiple before calling) and
    ``prompt_len + num_new_tokens <= max_seq`` with ``max_seq % sp == 0``.
    Returns [batch, num_new_tokens] int32; greedy when ``sampling`` is None.

    ``kv_cache_dtype``: reduced-precision storage for the sequence-sharded
    cache (e.g. "float8_e4m3fn") — at long context the cache IS the memory
    bill, so this is where reduced precision pays most.  Same contract as
    every engine (one owner: runtime/engine.resolve_cache_dtype_backend):
    attention reads what the cache stores, so ring prefill rounds K/V
    through the cache dtype before attending — greedy output matches a
    single-device engine with the same cache dtype.
    """
    sp = mesh.shape["sp"]
    if max_seq % sp:
        raise ValueError(f"max_seq={max_seq} not divisible by sp={sp}")
    from ..runtime.engine import resolve_cache_dtype_backend
    kv_dtype, _ = resolve_cache_dtype_backend(kv_cache_dtype, "jnp")
    s_loc = max_seq // sp
    spec = StageSpec(0, 1, 0, cfg.num_layers)
    sampling = sampling or SamplingParams(greedy=True)
    prefill_core, step_core = _make_ring_cores(cfg, spec, s_loc, sampling,
                                               kv_dtype)

    def body(params, ids, rng):
        carry, rng = prefill_core(params, ids, rng)
        tok0 = carry[-1]

        def step(c, r):
            return step_core(params, c, r)

        return _decode_scan(step, carry, rng, num_new_tokens, tok0)

    return _wrap_sp_body(body, mesh, sp, max_seq, num_new_tokens)


def _make_ring_cores(cfg: ModelConfig, spec: StageSpec, s_loc: int,
                     sampling: SamplingParams, kv_dtype):
    """``(prefill_core, step_core)`` — the ring-sp math, shared by the
    fused generate fn and the step-split stream fns so the two programs
    cannot drift.  Both run INSIDE the sp ``shard_map``.  The decode
    carry is ``(keys, values, kv_pos, plen, length, tok)``: ``plen``
    rides along explicitly so a decode dispatch needs no prompt shape
    (the fused path closes over it; the stream path cannot)."""
    cache_dtype = kv_dtype if kv_dtype is not None else cfg.dtype

    def prefill_core(params, ids, rng):
        n = axis_size("sp")
        idx = jax.lax.axis_index("sp")
        b, chunk = ids.shape

        # ---- prefill: ring attention over the prompt chunks -------------
        def prefill_attn(q, k, v, kc, vc, pos, cache_start, slopes):
            kc, vc = update_kv_cache(kc, vc, k, v, jnp.zeros((), jnp.int32))
            if kv_dtype is not None:
                # attention reads what the cache stores (the engines'
                # reduced-precision contract): round K/V through the
                # cache dtype so prefill attends the same values decode
                # will read back from the fp8 shards
                k = k.astype(kv_dtype).astype(cfg.dtype)
                v = v.astype(kv_dtype).astype(cfg.dtype)
            out = ring_self_attention(q, k, v, "sp", slopes=slopes)
            return out, kc, vc

        shape = (spec.num_layers, b, cfg.num_kv_heads, s_loc, cfg.head_dim)
        cache = KVCache(keys=jnp.zeros(shape, cache_dtype),
                        values=jnp.zeros(shape, cache_dtype),
                        length=jnp.zeros((), jnp.int32))
        positions = jnp.broadcast_to(idx * chunk + jnp.arange(chunk),
                                     (b, chunk))
        # body spec (not last): prefill returns hidden states, and the LM
        # head runs once below on the single selected last position instead
        # of on every rank's whole [b, chunk, vocab] chunk.
        body_spec = StageSpec(0, 2, 0, cfg.num_layers)
        hidden, cache = stage_forward(params, cfg, body_spec, ids, cache,
                                      positions, attn_impl=prefill_attn)
        kv_pos = jnp.where(jnp.arange(s_loc) < chunk,
                           idx * chunk + jnp.arange(s_loc), -1).astype(jnp.int32)
        plen = jnp.asarray(n * chunk, jnp.int32)

        tok0, rng = _sample_first_token(params, cfg, hidden, idx, n, rng,
                                        sampling)
        return (cache.keys, cache.values, kv_pos, plen, plen, tok0), rng

    def step_core(params, carry, step_rng):
        # ---- decode: sharded cache + lse-combined partial attention -----
        kc_all, vc_all, kv_pos, plen, length, tok = carry
        n = axis_size("sp")
        idx = jax.lax.axis_index("sp")
        b = tok.shape[0]
        chunk = plen // n
        # stateless round-robin placement, derived from the carry: the
        # d-th decoded token (d = length - prompt_len) lands on rank
        # d % n at slot chunk + d // n.
        d = length - plen
        is_owner = idx == d % n
        slot = chunk + d // n
        kv_pos_new = jnp.where(
            is_owner, _dynamic_set1(kv_pos, slot, length), kv_pos)
        pos = jnp.broadcast_to(length, (b, 1))

        def dec_attn(q, k, v, kc, vc, pos_, cache_start, slopes):
            # kc/vc: [b, nkv, s_loc, hd] head-major; the new token's
            # k/v arrive as [b, 1, nkv, hd] — transpose to cache layout
            k_t = k.transpose(0, 2, 1, 3).astype(kc.dtype)
            v_t = v.transpose(0, 2, 1, 3).astype(vc.dtype)
            old_k = jax.lax.dynamic_slice(
                kc, (0, 0, slot, 0), (b, kc.shape[1], 1, kc.shape[3]))
            old_v = jax.lax.dynamic_slice(
                vc, (0, 0, slot, 0), (b, vc.shape[1], 1, vc.shape[3]))
            k_ins = jnp.where(is_owner, k_t, old_k)
            v_ins = jnp.where(is_owner, v_t, old_v)
            kc = jax.lax.dynamic_update_slice(kc, k_ins, (0, 0, slot, 0))
            vc = jax.lax.dynamic_update_slice(vc, v_ins, (0, 0, slot, 0))
            out = sp_decode_attention(q, kc, vc, kv_pos_new, pos_, "sp",
                                      slopes=slopes)
            return out, kc, vc

        cache = KVCache(kc_all, vc_all, length)
        logits, cache = stage_forward(params, cfg, spec, tok[:, None],
                                      cache, pos, attn_impl=dec_attn)
        nxt = sample_logits(logits[:, -1, :], step_rng, sampling)
        return ((cache.keys, cache.values, kv_pos_new, plen, length + 1,
                 nxt), nxt)

    return prefill_core, step_core


def make_sp_stream_fns(cfg: ModelConfig, mesh: Mesh, *, max_seq: int,
                       block: int,
                       sampling: Optional[SamplingParams] = None,
                       kv_cache_dtype=None):
    """``(prefill_fn, decode_fn)`` — the step-SPLIT ring-sp programs for
    INCREMENTAL long-context serving (runtime/sp_backend.py streaming):

    - ``prefill_fn(params, prompt_ids, rng) -> (*state, rng)`` runs ring
      prefill and samples token #1 (``state[-1]``); the returned state
      (sequence-sharded cache, kv position map, lengths, last token)
      stays on device, sharded.
    - ``decode_fn(params, *state, rng) -> (*state, toks[b, block])``
      advances ``block`` tokens in one dispatch (cache buffers donated).

    Same math as :func:`make_sp_generate_fn` (one core factory,
    ``_make_ring_cores``) — greedy streams are bit-identical to the
    fused fn.  One compiled pair serves EVERY ``max_new_tokens`` (the
    fused fn bakes its trip count into the program); first-token latency
    is one prefill dispatch instead of the whole generation.  Sampled
    streams draw per-block sub-rngs, so they are equally distributed but
    not sequence-identical to the fused fn (the engines' streaming
    contract).  A final partial block may scan past ``max_new``: the
    surplus steps write only into slots the discarded tokens own
    (the caller takes ``toks[:, :remaining]`` and drops the state)."""
    sp = mesh.shape["sp"]
    if max_seq % sp:
        raise ValueError(f"max_seq={max_seq} not divisible by sp={sp}")
    if block < 1:
        raise ValueError("block must be >= 1")
    from ..runtime.engine import resolve_cache_dtype_backend
    kv_dtype, _ = resolve_cache_dtype_backend(kv_cache_dtype, "jnp")
    s_loc = max_seq // sp
    spec = StageSpec(0, 1, 0, cfg.num_layers)
    sampling = sampling or SamplingParams(greedy=True)
    prefill_core, step_core = _make_ring_cores(cfg, spec, s_loc, sampling,
                                               kv_dtype)

    cache_spec = P(None, None, None, "sp", None)
    state_specs = (cache_spec, cache_spec, P("sp"), P(), P(), P())
    return _wrap_stream_fns(prefill_core, step_core, mesh, state_specs,
                            block)


def _wrap_stream_fns(prefill_core, step_core, mesh: Mesh, state_specs,
                     block: int):
    """shard_map + jit scaffolding shared by BOTH strategies' stream-fn
    factories (one owner, like ``_wrap_sp_body`` for the fused fns):
    a prefill program emitting the sharded decode state, and a
    donated-cache decode program scanning ``block`` steps per dispatch.
    ``state_specs`` lead with the two cache buffers (donated)."""

    def prefill_body(params, ids, rng):
        carry, rng = prefill_core(params, ids, rng)
        return (*carry, rng)

    def decode_body(params, *state_rng):
        state, rng = state_rng[:-1], state_rng[-1]

        def step(c, r):
            return step_core(params, c, r)

        carry, toks = jax.lax.scan(step, state,
                                   jax.random.split(rng, block))
        return (*carry, jnp.swapaxes(toks, 0, 1))       # [b, block]

    prefill_fn = jax.jit(shard_map(
        prefill_body, mesh=mesh,
        in_specs=(P(), P(None, "sp"), P()),
        out_specs=(*state_specs, P()), check_vma=False))
    decode_fn = jax.jit(shard_map(
        decode_body, mesh=mesh,
        in_specs=(P(), *state_specs, P()),
        out_specs=(*state_specs, P()), check_vma=False),
        donate_argnums=(1, 2))
    return prefill_fn, decode_fn
