"""Sharding rules: where every parameter and activation lives on the mesh.

GSPMD path: annotate params with NamedSharding and let XLA insert the
collectives (all-gather for column-parallel outputs, reduce-scatter for
row-parallel) — the "pick a mesh, annotate, let XLA do the rest" recipe.
The manual shard_map pipeline (parallel/pipeline.py) slices the same layout.

Megatron-style TP layout:
- wq/wk/wv  [L, H, heads*hd]   -> shard last axis over tp (column parallel)
- wo        [L, heads*hd, H]   -> shard first non-L axis over tp (row parallel)
- w_gate/up [L, H, I]          -> column parallel
- w_down    [L, I, H]          -> row parallel
- MoE experts [L, E, H, I]     -> shard E over tp (expert parallelism)
- embed [V, H] / lm_head [H, V]-> shard V over tp (vocab parallel); logits
                                  all-gather only at the sampling boundary
- KV cache [Ls, B, S, nkv, hd] -> batch over dp, kv heads over tp, seq over sp
"""

from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.base import ModelConfig, StageParams
from ..ops.quant import QuantizedArray


# per-key PartitionSpec for the stacked layer dict; None entries = replicated
_LAYER_SPECS = {
    "attn_norm_w": P(),
    "attn_norm_b": P(),
    "mlp_norm_w": P(),
    "mlp_norm_b": P(),
    "wq": P(None, None, "tp"),
    "wk": P(None, None, "tp"),
    "wv": P(None, None, "tp"),
    "bq": P(None, "tp"),
    "bk": P(None, "tp"),
    "bv": P(None, "tp"),
    "wo": P(None, "tp", None),
    "bo": P(),
    "w_gate": P(None, None, "tp"),
    "w_up": P(None, None, "tp"),
    "b_up": P(None, "tp"),
    "w_down": P(None, "tp", None),
    "b_down": P(),
    "router": P(),
}

# MoE expert stacks carry an extra E axis at position 1: shard experts.
_MOE_SPECS = {
    "w_gate": P(None, "tp", None, None),
    "w_up": P(None, "tp", None, None),
    "w_down": P(None, "tp", None, None),
}


def layer_spec(key: str, cfg: ModelConfig, pp_shard: bool = False) -> P:
    """PartitionSpec for one stacked-layer weight.  ``pp_shard`` additionally
    splits the leading layer axis over pp (SPMD pipeline layout)."""
    if cfg.num_experts > 0 and key in _MOE_SPECS:
        spec = _MOE_SPECS[key]
    else:
        spec = _LAYER_SPECS.get(key, P())
    if pp_shard:
        spec = P("pp", *spec[1:]) if len(spec) > 0 else P("pp")
    return spec


def _embed_specs(cfg: ModelConfig) -> dict:
    # vocab-parallel embedding: the gather masks out-of-shard ids and psums.
    specs = {"tokens": P("tp", None)}
    if cfg.family == "bloom":
        specs["norm_w"] = P()
        specs["norm_b"] = P()
    return specs


def param_shardings(params: StageParams, cfg: ModelConfig, mesh: Mesh,
                    pp_shard: bool = False) -> StageParams:
    """Alias for :func:`stage_param_shardings` (full model == stage 0 of 1)."""
    return stage_param_shardings(params, cfg, mesh, pp_shard)


def stage_param_shardings(params: StageParams, cfg: ModelConfig, mesh: Mesh,
                          pp_shard: bool = False) -> StageParams:
    """Shardings matching an actual params tree (handles absent embed/head)."""
    def ns(spec):
        return NamedSharding(mesh, spec)

    def map_layers(layers):
        out = {}
        for k, v in layers.items():
            spec = layer_spec(k, cfg, pp_shard)
            if isinstance(v, QuantizedArray):
                scale_spec = P(*([None] * (len(spec) - 1)),
                               spec[-1] if len(spec) else None)
                out[k] = QuantizedArray(q=ns(spec), scale=ns(scale_spec))
            else:
                out[k] = ns(spec)
        return out

    embed = None
    if params.embed is not None:
        embed = {k: ns(s) for k, s in _embed_specs(cfg).items()
                 if k in params.embed}
    final_norm = None
    if params.final_norm is not None:
        final_norm = {k: ns(P()) for k in params.final_norm}
    lm_head = None
    if params.lm_head is not None:
        lm_head = {k: ns(P(None, "tp")) for k in params.lm_head}
    return StageParams(layers=map_layers(params.layers), embed=embed,
                       final_norm=final_norm, lm_head=lm_head)


def shard_params(params: StageParams, cfg: ModelConfig, mesh: Mesh,
                 pp_shard: bool = False) -> StageParams:
    """Place a host-resident params tree onto the mesh."""
    shardings = stage_param_shardings(params, cfg, mesh, pp_shard)
    return jax.tree.map(lambda x, s: jax.device_put(x, s), params, shardings)


def cache_shardings(mesh: Mesh, shard_heads: bool = True,
                    shard_seq: bool = False):
    """NamedShardings for KVCache (keys/values/length).

    [layers, batch, seq, kv_heads, head_dim]: batch over dp, kv heads over
    tp (requires num_kv_heads % tp == 0), seq over sp for long-context.
    """
    from ..models.base import KVCache
    kv = P(None, "dp", "sp" if shard_seq else None,
           "tp" if shard_heads else None, None)
    return KVCache(keys=NamedSharding(mesh, kv),
                   values=NamedSharding(mesh, kv),
                   length=NamedSharding(mesh, P()))
