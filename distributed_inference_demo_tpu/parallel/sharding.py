"""Sharding rules: where every parameter and activation lives on the mesh.

GSPMD path: annotate params with NamedSharding and let XLA insert the
collectives (all-gather for column-parallel outputs, reduce-scatter for
row-parallel) — the "pick a mesh, annotate, let XLA do the rest" recipe.
The manual shard_map pipeline (parallel/pipeline.py) slices the same layout.

Megatron-style TP layout:
- wq/wk/wv  [L, H, heads*hd]   -> shard last axis over tp (column parallel)
- wo        [L, heads*hd, H]   -> shard first non-L axis over tp (row parallel)
- w_gate/up [L, H, I]          -> column parallel
- w_down    [L, I, H]          -> row parallel
- MoE experts [L, E, H, I]     -> shard E over tp (expert parallelism)
- embed [V, H] / lm_head [H, V]-> shard V over tp (vocab parallel); logits
                                  all-gather only at the sampling boundary
- KV cache [Ls, B, S, nkv, hd] -> batch over dp, kv heads over tp, seq over sp
"""

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.base import ModelConfig, StageParams
from ..ops.quant import QuantizedArray, QuantizedArray4


# per-key PartitionSpec for the stacked layer dict; None entries = replicated
_LAYER_SPECS = {
    "attn_norm_w": P(),
    "attn_norm_b": P(),
    "mlp_norm_w": P(),
    "mlp_norm_b": P(),
    "wq": P(None, None, "tp"),
    "wk": P(None, None, "tp"),
    "wv": P(None, None, "tp"),
    "bq": P(None, "tp"),
    "bk": P(None, "tp"),
    "bv": P(None, "tp"),
    "wo": P(None, "tp", None),
    "bo": P(),
    "w_gate": P(None, None, "tp"),
    "w_up": P(None, None, "tp"),
    "b_up": P(None, "tp"),
    "w_down": P(None, "tp", None),
    "b_down": P(),
    "router": P(),
}

# MoE expert stacks carry an extra E axis at position 1: shard experts.
_MOE_SPECS = {
    "w_gate": P(None, "tp", None, None),
    "w_up": P(None, "tp", None, None),
    "w_down": P(None, "tp", None, None),
}


def layer_spec(key: str, cfg: ModelConfig, pp_shard: bool = False) -> P:
    """PartitionSpec for one stacked-layer weight.  ``pp_shard`` additionally
    splits the leading layer axis over pp (SPMD pipeline layout)."""
    if cfg.num_experts > 0 and key in _MOE_SPECS:
        spec = _MOE_SPECS[key]
    else:
        spec = _LAYER_SPECS.get(key, P())
    if pp_shard:
        spec = P("pp", *spec[1:]) if len(spec) > 0 else P("pp")
    return spec


def _embed_specs(cfg: ModelConfig) -> dict:
    # vocab-parallel embedding: the gather masks out-of-shard ids and psums.
    specs = {"tokens": P("tp", None)}
    if cfg.family == "bloom":
        specs["norm_w"] = P()
        specs["norm_b"] = P()
    return specs


def quant4_specs(v: QuantizedArray4, spec: P):
    """Spec tree for a packed-int4 weight given its dense spec.

    Nibble packing only changes SIZES along the input axis, so ``q``
    inherits the dense spec unchanged; the group-wise scale inserts a
    broadcast axis before the output axis (shape ``(..., in/g, 1,
    out)``) and its group axis stays replicated.  Slicing the input or
    output axes themselves (tp) would cut through nibble pairs and
    group boundaries — callers must reject tp before calling."""
    if any(s == "tp" for s in spec):
        raise ValueError(
            "int4 (nibble-packed) weights do not compose with tp meshes "
            "yet — tensor-parallel slicing would cut through the packed "
            "input axis; use int8 for tensor-parallel serving")
    scale = P(*spec[:-2], None, None, spec[-1]) if len(spec) >= 2 else P()
    return QuantizedArray4(q=spec, scale=scale, group=v.group)


def quant_scale_spec(q_spec: P) -> P:
    """Scale spec matching ``quantize_array(stacked=True)`` layout.

    The scale's shape is ``[L, 1, ..., out]`` — only the leading layer axis
    and the final output axis are real, so only those can inherit the q
    array's sharding (the collapsed middle axes are size 1 and must stay
    unsharded; e.g. MoE experts shard q's E axis but the scale broadcasts
    over it).
    """
    if len(q_spec) == 0:
        return P()
    if len(q_spec) == 1:
        return P(q_spec[0])
    return P(q_spec[0], *([None] * (len(q_spec) - 2)), q_spec[-1])


def stage_param_spec_tree(params: StageParams, cfg: ModelConfig, *,
                          pp_shard: bool = False, use_tp: bool = True,
                          vocab_parallel_embed: bool = False) -> StageParams:
    """Raw PartitionSpec tree for a params tree — the single source of truth
    shared by the GSPMD path (wrapped in NamedSharding below) and the manual
    shard_map paths (pipeline.py / tensor.py in_specs).

    ``use_tp=False`` strips tp from layer specs (pipeline-only meshes);
    ``vocab_parallel_embed`` shards the token table over tp (GSPMD path) vs
    replicating it (manual paths, which gather by id locally).
    """
    def strip_tp(spec):
        return P(*(s if s == "pp" else None for s in spec))

    def map_layers(layers):
        out = {}
        for k, v in layers.items():
            spec = layer_spec(k, cfg, pp_shard)
            if not use_tp:
                spec = strip_tp(spec)
            if isinstance(v, QuantizedArray):
                out[k] = QuantizedArray(q=spec, scale=quant_scale_spec(spec))
            elif isinstance(v, QuantizedArray4):
                out[k] = quant4_specs(v, spec)
            else:
                out[k] = spec
        return out

    embed = None
    if params.embed is not None:
        if vocab_parallel_embed and use_tp:
            embed = {k: s for k, s in _embed_specs(cfg).items()
                     if k in params.embed}
        else:
            embed = {k: P() for k in params.embed}
    final_norm = None
    if params.final_norm is not None:
        final_norm = {k: P() for k in params.final_norm}
    lm_head = None
    if params.lm_head is not None:
        lm_head = {k: (P(None, "tp") if use_tp else P())
                   for k in params.lm_head}
    return StageParams(layers=map_layers(params.layers), embed=embed,
                       final_norm=final_norm, lm_head=lm_head)


def param_shardings(params: StageParams, cfg: ModelConfig, mesh: Mesh,
                    pp_shard: bool = False) -> StageParams:
    """Alias for :func:`stage_param_shardings` (full model == stage 0 of 1)."""
    return stage_param_shardings(params, cfg, mesh, pp_shard)


def stage_param_shardings(params: StageParams, cfg: ModelConfig, mesh: Mesh,
                          pp_shard: bool = False,
                          vocab_parallel_embed: bool = True) -> StageParams:
    """NamedShardings matching an actual params tree (GSPMD placement)."""
    specs = stage_param_spec_tree(
        params, cfg, pp_shard=pp_shard,
        vocab_parallel_embed=vocab_parallel_embed)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def shard_params(params: StageParams, cfg: ModelConfig, mesh: Mesh,
                 pp_shard: bool = False,
                 vocab_parallel_embed: bool = True) -> StageParams:
    """Place a host-resident params tree onto the mesh."""
    shardings = stage_param_shardings(params, cfg, mesh, pp_shard,
                                      vocab_parallel_embed)
    return jax.tree.map(lambda x, s: jax.device_put(x, s), params, shardings)


def cache_shardings(mesh: Mesh, shard_heads: bool = True,
                    shard_seq: bool = False):
    """NamedShardings for KVCache (keys/values/length).

    [layers, batch, kv_heads, seq, head_dim] (head-major): batch over dp,
    kv heads over tp (requires num_kv_heads % tp == 0), seq over sp for
    long-context.
    """
    from ..models.base import KVCache
    kv = P(None, "dp", "tp" if shard_heads else None,
           "sp" if shard_seq else None, None)
    return KVCache(keys=NamedSharding(mesh, kv),
                   values=NamedSharding(mesh, kv),
                   length=NamedSharding(mesh, P()))
