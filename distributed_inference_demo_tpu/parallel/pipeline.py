"""SPMD pipeline parallelism over the device mesh.

The reference's pipeline is a TCP ring of processes, each pulling activations
from its predecessor with a "Request Data" handshake per token
(``Communication.java:682-928``).  The TPU-native equivalent is a *circular
collective pipeline*: every pp rank holds a contiguous layer range (the
stacked layer stack sharded on its leading axis), microbatches stream through
a ``lax.scan``, and the inter-stage hop is a single ``lax.ppermute`` over ICI
— no handshake, no serialization; backpressure is the scan's data dependence.

Composes with manual Megatron-style TP (``decoder.stage_forward(tp_axis=)``:
psum after row-parallel matmuls) and manual DP (batch sliced over ``dp``,
gradient psum).  Everything runs inside ONE ``jax.shard_map`` /
``jax.jit``, so XLA schedules collective/compute overlap — the reference's
hand-rolled comm/compute threading (``OneStep`` phases) dissolves into the
compiler schedule.

Gradient correctness rule: a parameter leaf's gradient must be psum-reduced
over every *manual* mesh axis the leaf is replicated on (e.g. embed grads
over pp and tp, norm grads over tp) — sharded leaves are already exact.
``_grad_sync_axes`` encodes this from the sharding specs.
"""

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models.base import KVCache, ModelConfig, StageParams, StageSpec
from .sharding import stage_param_spec_tree


def _pp_in_specs(params: StageParams, cfg: ModelConfig, use_tp: bool):
    """shard_map in_specs for the params tree: layer stack split over pp
    (leading axis) and tp (head/column axes); embed/norms replicated; the
    untied head vocab-column-sharded under TP (head_fn all-gathers logit
    shards by shape)."""
    return stage_param_spec_tree(params, cfg, pp_shard=True, use_tp=use_tp,
                                 vocab_parallel_embed=False)


def _grad_sync_axes(params: StageParams, cfg: ModelConfig, use_tp: bool):
    """For each leaf, the tuple of manual axes to psum its gradient over.

    Covers pp/tp replication only; dp gradients are a *mean* (each dp group
    computed a mean loss over its batch slice) and are pmean'd separately.
    """
    in_specs = _pp_in_specs(params, cfg, use_tp)

    def axes_for(spec):
        named = {ax for part in spec if part is not None
                 for ax in ((part,) if isinstance(part, str) else part)}
        return tuple(ax for ax in ("pp", "tp") if ax not in named)

    return jax.tree.map(axes_for, in_specs,
                        is_leaf=lambda x: isinstance(x, P))


def pipeline_apply(
    cfg: ModelConfig,
    params: StageParams,      # LOCAL shards (inside shard_map)
    ids_mb: jnp.ndarray,      # [M, b, s] microbatched token ids
    targets_mb: jnp.ndarray,  # [M, b, s] next-token targets (-100 = pad)
    tp_axis: Optional[str],
    pp_axis: str = "pp",
) -> jnp.ndarray:
    """Forward + mean cross-entropy through the circular pipeline.

    Runs M + S - 1 scan steps; stage 0 ingests microbatch t at step t, the
    last stage emits microbatch t-(S-1) at step t.  Every rank executes the
    same program (SPMD); first/last-stage roles are data selections, not
    control flow.
    """
    S = jax.lax.axis_size(pp_axis)
    my = jax.lax.axis_index(pp_axis)
    is_first = my == 0
    is_last = my == S - 1
    M, b, s = ids_mb.shape
    T = M + S - 1
    H = cfg.hidden_size
    dt = cfg.dtype

    # every rank carries the full (replicated) embed/head; the pipeline body
    # below masks their *use* by rank role.
    spec_mid = StageSpec(stage_id=1, num_stages=3, layer_start=0,
                         layer_end=0)  # "not first, not last": raw layers

    positions = jnp.broadcast_to(jnp.arange(s), (b, s))

    def embed_fn(ids):
        x = params.embed["tokens"][ids]
        if cfg.family == "bloom":
            from ..ops.norms import layer_norm
            x = layer_norm(x, params.embed["norm_w"], params.embed["norm_b"],
                           cfg.norm_eps)
        return x.astype(dt)

    def head_fn(h):
        from ..ops.norms import layer_norm, rms_norm
        if cfg.attn_layernorm:
            h = layer_norm(h, params.final_norm["w"], params.final_norm["b"],
                           cfg.norm_eps)
        else:
            h = rms_norm(h, params.final_norm["w"], cfg.norm_eps)
        head = (params.embed["tokens"].T if cfg.tie_embeddings
                else params.lm_head["w"])
        logits = jnp.einsum("bsh,hv->bsv", h, head)
        if tp_axis is not None and logits.shape[-1] != cfg.vocab_size:
            logits = jax.lax.all_gather(logits, tp_axis, axis=-1, tiled=True)
        return logits

    from ..models.decoder import stage_forward

    def run_local_layers(x):
        nkv_local = params.layers["wk"].shape[-1] // cfg.head_dim
        L_local = jax.tree.leaves(params.layers)[0].shape[0]
        cache = KVCache(
            keys=jnp.zeros((L_local, b, nkv_local, s, cfg.head_dim), dt),
            values=jnp.zeros((L_local, b, nkv_local, s, cfg.head_dim), dt),
            length=jnp.zeros((), jnp.int32))
        mid_params = StageParams(layers=params.layers)
        out, _ = stage_forward(mid_params, cfg, spec_mid, x, cache, positions,
                               tp_axis=tp_axis)
        return out

    def step(carry, t):
        recv, loss_sum, tok_sum = carry
        m_in = jnp.minimum(t, M - 1)
        ids_t = jax.lax.dynamic_index_in_dim(ids_mb, m_in, 0, keepdims=False)
        x0 = embed_fn(ids_t)
        x = jnp.where(is_first, x0, recv)
        h = run_local_layers(x)

        # last stage: loss for microbatch t-(S-1), valid when t >= S-1
        m_out = jnp.clip(t - (S - 1), 0, M - 1)
        tgt = jax.lax.dynamic_index_in_dim(targets_mb, m_out, 0,
                                           keepdims=False)
        logits = head_fn(h)
        mask = (tgt != -100) & (t >= S - 1) & is_last
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        tok_ll = jnp.take_along_axis(
            logp, jnp.maximum(tgt, 0)[..., None], axis=-1)[..., 0]
        loss_sum = loss_sum - jnp.sum(jnp.where(mask, tok_ll, 0.0))
        tok_sum = tok_sum + jnp.sum(mask)

        # rotate activations one stage forward (ICI neighbor hop)
        send = jax.lax.ppermute(
            h, pp_axis, [(i, (i + 1) % S) for i in range(S)])
        return (send, loss_sum, tok_sum), None

    recv0 = jnp.zeros((b, s, H), dt)
    (_, loss_sum, tok_sum), _ = jax.lax.scan(
        step, (recv0, jnp.float32(0.0), jnp.int32(0)), jnp.arange(T))

    loss_sum = jax.lax.psum(loss_sum, pp_axis)
    tok_sum = jax.lax.psum(tok_sum, pp_axis)
    return loss_sum / jnp.maximum(tok_sum, 1)


def make_pipeline_train_step(cfg: ModelConfig, mesh: Mesh, optimizer,
                             num_microbatches: int):
    """Build a jitted data+pipeline+tensor-parallel training step.

    Returns ``train_step(params, opt_state, ids, targets) ->
    (params, opt_state, loss)`` where ids/targets are
    ``[batch, seq]`` int32 on host; batch must divide by dp*num_microbatches.
    """
    use_tp = mesh.shape.get("tp", 1) > 1
    use_dp = mesh.shape.get("dp", 1) > 1
    axis_names = set(mesh.axis_names)
    assert {"dp", "pp", "tp"} <= axis_names, mesh.axis_names

    def build(params_template):
        in_specs_params = _pp_in_specs(params_template, cfg, use_tp)
        sync_axes = _grad_sync_axes(params_template, cfg, use_tp)

        # Under check_vma=False the transpose of every forward psum (the
        # loss reduction over pp, the row-parallel psums over tp) is itself
        # a psum, so raw grads come back uniformly scaled by pp*tp relative
        # to the single-device gradient (verified empirically on the virtual
        # mesh for pp/tp in {1,2}x{1,2}).  Normalize once here so optimizers
        # that are not scale-invariant (sgd, clipping, weight decay) are
        # correct.
        grad_norm = 1.0 / (mesh.shape.get("pp", 1) * mesh.shape.get("tp", 1))

        def sm_loss_and_grads(params_local, ids_mb, targets_mb):
            def loss_fn(p):
                return pipeline_apply(cfg, p, ids_mb, targets_mb,
                                      "tp" if use_tp else None)
            loss, grads = jax.value_and_grad(loss_fn)(params_local)
            grads = jax.tree.map(
                lambda g, axes: jax.lax.psum(g, axes) if axes else g,
                grads, sync_axes)
            grads = jax.tree.map(lambda g: g * grad_norm, grads)
            if use_dp:
                loss = jax.lax.pmean(loss, "dp")
                grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
            return loss, grads

        data_spec = P(None, "dp")  # [M, batch, seq]: batch over dp
        sharded = jax.shard_map(
            sm_loss_and_grads, mesh=mesh,
            in_specs=(in_specs_params, data_spec, data_spec),
            out_specs=(P(), in_specs_params),
            check_vma=False)
        return sharded

    def train_step(params, opt_state, ids, targets):
        M = num_microbatches
        B, s = ids.shape
        ids_mb = ids.reshape(M, B // M, s)
        targets_mb = targets.reshape(M, B // M, s)
        loss, grads = build(params)(params, ids_mb, targets_mb)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        import optax
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(train_step, donate_argnums=(0, 1))
