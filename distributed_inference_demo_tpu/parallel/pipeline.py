"""SPMD pipeline parallelism over the device mesh.

The reference's pipeline is a TCP ring of processes, each pulling activations
from its predecessor with a "Request Data" handshake per token
(``Communication.java:682-928``).  The TPU-native equivalent is a *circular
collective pipeline*: every pp rank holds a contiguous layer range (the
stacked layer stack sharded on its leading axis), microbatches stream through
a ``lax.scan``, and the inter-stage hop is a single ``lax.ppermute`` over ICI
— no handshake, no serialization; backpressure is the scan's data dependence.

Composes with manual Megatron-style TP (``decoder.stage_forward(tp_axis=)``:
psum after row-parallel matmuls) and manual DP (batch sliced over ``dp``,
gradient psum).  Everything runs inside ONE ``jax.shard_map`` /
``jax.jit``, so XLA schedules collective/compute overlap — the reference's
hand-rolled comm/compute threading (``OneStep`` phases) dissolves into the
compiler schedule.

Gradient correctness rule: a parameter leaf's gradient must be psum-reduced
over every *manual* mesh axis the leaf is replicated on (e.g. embed grads
over pp and tp, norm grads over tp) — sharded leaves are already exact.
``_grad_sync_axes`` encodes this from the sharding specs.
"""

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from .compat import axis_size, shard_map

from ..models.base import KVCache, ModelConfig, StageParams, StageSpec
from .sharding import stage_param_spec_tree


def _pp_in_specs(params: StageParams, cfg: ModelConfig, use_tp: bool):
    """shard_map in_specs for the params tree: layer stack split over pp
    (leading axis) and tp (head/column axes); embed/norms replicated; the
    untied head vocab-column-sharded under TP (head_fn all-gathers logit
    shards by shape)."""
    return stage_param_spec_tree(params, cfg, pp_shard=True, use_tp=use_tp,
                                 vocab_parallel_embed=False)


def _grad_sync_axes(params: StageParams, cfg: ModelConfig, use_tp: bool):
    """For each leaf, the tuple of manual axes to psum its gradient over.

    Covers pp/tp replication only; dp gradients are a *mean* (each dp group
    computed a mean loss over its batch slice) and are pmean'd separately.
    """
    in_specs = _pp_in_specs(params, cfg, use_tp)

    def axes_for(spec):
        named = {ax for part in spec if part is not None
                 for ax in ((part,) if isinstance(part, str) else part)}
        return tuple(ax for ax in ("pp", "tp") if ax not in named)

    return jax.tree.map(axes_for, in_specs,
                        is_leaf=lambda x: isinstance(x, P))


def _embed(params: StageParams, cfg: ModelConfig,
           ids: jnp.ndarray) -> jnp.ndarray:
    """Token embedding, shared by the training and generation pipelines;
    every rank holds the replicated embed table and masks its *use* by
    rank role.  Delegates to ``decoder.embed_tokens`` — the ONE owner of
    the embedding pipeline (bloom's LayerNorm, gemma's sqrt(H) scale) so
    the pipeline path cannot drift from single-stage serving."""
    from ..models.decoder import embed_tokens
    return embed_tokens(params, cfg, ids).astype(cfg.dtype)


def _head(params: StageParams, cfg: ModelConfig, h: jnp.ndarray,
          tp_axis: Optional[str]) -> jnp.ndarray:
    """Final norm + LM head on [b, s, H]; gathers vocab-sharded logit
    shards under TP."""
    from ..ops.norms import layer_norm, rms_norm
    if cfg.attn_layernorm:
        h = layer_norm(h, params.final_norm["w"], params.final_norm["b"],
                       cfg.norm_eps)
    else:
        h = rms_norm(h, params.final_norm["w"], cfg.norm_eps)
    head = (params.embed["tokens"].T if cfg.tie_embeddings
            else params.lm_head["w"])
    logits = jnp.einsum("bsh,hv->bsv", h, head)
    if tp_axis is not None and logits.shape[-1] != cfg.vocab_size:
        logits = jax.lax.all_gather(logits, tp_axis, axis=-1, tiled=True)
    return logits


def pipeline_apply(
    cfg: ModelConfig,
    params: StageParams,      # LOCAL shards (inside shard_map)
    ids_mb: jnp.ndarray,      # [M, b, s] microbatched token ids
    targets_mb: jnp.ndarray,  # [M, b, s] next-token targets (-100 = pad)
    tp_axis: Optional[str],
    pp_axis: str = "pp",
) -> jnp.ndarray:
    """Forward + mean cross-entropy through the circular pipeline.

    Runs M + S - 1 scan steps; stage 0 ingests microbatch t at step t, the
    last stage emits microbatch t-(S-1) at step t.  Every rank executes the
    same program (SPMD); first/last-stage roles are data selections, not
    control flow.
    """
    S = axis_size(pp_axis)
    my = jax.lax.axis_index(pp_axis)
    is_first = my == 0
    is_last = my == S - 1
    M, b, s = ids_mb.shape
    T = M + S - 1
    H = cfg.hidden_size
    dt = cfg.dtype

    # every rank carries the full (replicated) embed/head; the pipeline body
    # below masks their *use* by rank role.
    spec_mid = StageSpec(stage_id=1, num_stages=3, layer_start=0,
                         layer_end=0)  # "not first, not last": raw layers

    positions = jnp.broadcast_to(jnp.arange(s), (b, s))

    def embed_fn(ids):
        return _embed(params, cfg, ids)

    def head_fn(h):
        return _head(params, cfg, h, tp_axis)

    from ..models.decoder import stage_forward

    def run_local_layers(x):
        nkv_local = params.layers["wk"].shape[-1] // cfg.head_dim
        L_local = jax.tree.leaves(params.layers)[0].shape[0]
        cache = KVCache(
            keys=jnp.zeros((L_local, b, nkv_local, s, cfg.head_dim), dt),
            values=jnp.zeros((L_local, b, nkv_local, s, cfg.head_dim), dt),
            length=jnp.zeros((), jnp.int32))
        mid_params = StageParams(layers=params.layers)
        # ys cache layout: this forward is differentiated (the carry
        # layout would be saved per-iteration by the scan VJP)
        out, _ = stage_forward(mid_params, cfg, spec_mid, x, cache, positions,
                               tp_axis=tp_axis, cache_in_carry=False)
        return out

    def step(carry, t):
        recv, loss_sum, tok_sum = carry
        m_in = jnp.minimum(t, M - 1)
        ids_t = jax.lax.dynamic_index_in_dim(ids_mb, m_in, 0, keepdims=False)
        x0 = embed_fn(ids_t)
        x = jnp.where(is_first, x0, recv)
        h = run_local_layers(x)

        # last stage: loss for microbatch t-(S-1), valid when t >= S-1
        m_out = jnp.clip(t - (S - 1), 0, M - 1)
        tgt = jax.lax.dynamic_index_in_dim(targets_mb, m_out, 0,
                                           keepdims=False)
        logits = head_fn(h)
        mask = (tgt != -100) & (t >= S - 1) & is_last
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        tok_ll = jnp.take_along_axis(
            logp, jnp.maximum(tgt, 0)[..., None], axis=-1)[..., 0]
        loss_sum = loss_sum - jnp.sum(jnp.where(mask, tok_ll, 0.0))
        tok_sum = tok_sum + jnp.sum(mask)

        # rotate activations one stage forward (ICI neighbor hop)
        send = jax.lax.ppermute(
            h, pp_axis, [(i, (i + 1) % S) for i in range(S)])
        return (send, loss_sum, tok_sum), None

    recv0 = jnp.zeros((b, s, H), dt)
    (_, loss_sum, tok_sum), _ = jax.lax.scan(
        step, (recv0, jnp.float32(0.0), jnp.int32(0)), jnp.arange(T))

    loss_sum = jax.lax.psum(loss_sum, pp_axis)
    tok_sum = jax.lax.psum(tok_sum, pp_axis)
    return loss_sum / jnp.maximum(tok_sum, 1)


def make_pipeline_generate_fn(cfg: ModelConfig, mesh: Mesh, *,
                              max_seq: int, num_new_tokens: int,
                              sampling=None):
    """SPMD circular-pipeline **decode**: multi-chip pipeline inference in
    ICI-collective form (VERDICT r1 item 6; the reference's socket token
    ring, ``Communication.java:621-651``, as one compiled program).

    Microbatches circulate the pp ring round-robin: at ring step ``g``,
    rank ``s`` works on microbatch ``(g - s) mod M``, every hop is a single
    ``ppermute`` carrying the hidden row plus a token lane (the sampled
    token riding last→first — the reference's commu3 leg), and each rank
    keeps a per-microbatch KV cache for its layer slice.  Pipeline is full
    whenever ``M >= S``: every rank computes every step, so decode
    throughput scales with stages instead of being serialized the way the
    socket ring's request/step loop is.

    Returns ``fn(params, ids_mb, rng) -> tokens``:
      ids_mb  [M, b, prompt_len] int32 (equal-length prompts; pad first),
      tokens  [M, b, num_new_tokens] int32, replicated.

    Composes with TP when the mesh has a tp axis > 1 (Megatron shard_map
    inside each stage).
    """
    from ..models.decoder import stage_forward
    from ..ops.sampling import SamplingParams, sample_logits

    sampling = sampling or SamplingParams(greedy=True)
    S = mesh.shape["pp"]
    if S < 2:
        raise ValueError("pipeline generate needs pp >= 2 (use the "
                         "engine for a single stage)")
    use_tp = mesh.shape.get("tp", 1) > 1
    tp_axis = "tp" if use_tp else None
    N = num_new_tokens
    dt = cfg.dtype
    H = cfg.hidden_size
    # "not first, not last": raw layer stack only (roles are data
    # selections in SPMD, not control flow)
    spec_mid = StageSpec(stage_id=1, num_stages=3, layer_start=0,
                         layer_end=0)

    def body(params, ids_mb, rng):
        s = jax.lax.axis_index("pp")
        is_first = s == 0
        is_last = s == S - 1
        M, b, plen = ids_mb.shape
        if M < S:
            raise ValueError(f"need microbatches M={M} >= stages S={S} "
                             "for a full pipeline")

        nkv_loc = params.layers["wk"].shape[-1] // cfg.head_dim
        L_loc = jax.tree.leaves(params.layers)[0].shape[0]
        cshape = (M, L_loc, b, nkv_loc, max_seq, cfg.head_dim)
        K = jnp.zeros(cshape, dt)
        V = jnp.zeros(cshape, dt)
        mid_params = StageParams(layers=params.layers)

        def run_local(x, kc, vc, length, positions):
            cache = KVCache(kc, vc, length)
            out, newc = stage_forward(mid_params, cfg, spec_mid, x, cache,
                                      positions, tp_axis=tp_axis)
            return out, newc.keys, newc.values

        def tail_sample(h_row, m, k):
            """Head + sampling, gated to the tail rank: non-tail ranks run
            an empty branch instead of burning the [b,1,H]x[H,V] matmul +
            TP all-gather S-1 times out of S (VERDICT r2 weak #6).  Safe
            under SPMD: a tp group lives at ONE pp rank, so every member
            agrees on ``is_last`` and the branch's collective stays
            consistent."""
            def yes(h):
                logits = _head(params, cfg, h, tp_axis)[:, 0]
                return sample_logits(logits, rng_for(m, k), sampling)

            def no(h):
                return jnp.zeros((b,), jnp.int32)

            return jax.lax.cond(is_last, yes, no, h_row)

        def upd(stack, m, new, active):
            old = jax.lax.dynamic_index_in_dim(stack, m, 0, keepdims=False)
            val = jnp.where(active, new, old)
            return jax.lax.dynamic_update_index_in_dim(stack, val, m, 0)

        ring = [(i, (i + 1) % S) for i in range(S)]
        pos_pre = jnp.broadcast_to(jnp.arange(plen), (b, plen))

        def rng_for(m, k):
            return jax.random.fold_in(jax.random.fold_in(rng, m), k)

        # ---- prefill: M + S - 1 ring steps over the prompt chunks -------
        def pre_step(carry, t):
            recv_h, K, V, tok0 = carry
            m = jnp.clip(t - s, 0, M - 1)
            active = (t >= s) & (t - s < M)
            ids_t = jax.lax.dynamic_index_in_dim(ids_mb, m, 0,
                                                 keepdims=False)
            x = jnp.where(is_first, _embed(params, cfg, ids_t), recv_h)
            kc = jax.lax.dynamic_index_in_dim(K, m, 0, keepdims=False)
            vc = jax.lax.dynamic_index_in_dim(V, m, 0, keepdims=False)
            h, nk, nv = run_local(x, kc, vc, jnp.zeros((), jnp.int32),
                                  pos_pre)
            K = upd(K, m, nk, active)
            V = upd(V, m, nv, active)
            tok = tail_sample(h[:, -1:, :], m, 0)
            tok0 = upd(tok0, m, jnp.where(active & is_last, tok, -1),
                       active & is_last)
            send = jax.lax.ppermute(h, "pp", ring)
            return (send, K, V, tok0), None

        tok0 = jnp.full((M, b), -1, jnp.int32)
        (recv_h, K, V, tok0), _ = jax.lax.scan(
            pre_step, (jnp.zeros((b, plen, H), dt), K, V, tok0),
            jnp.arange(M + S - 1))
        # everyone learns the first sampled token of every microbatch
        tok0 = jax.lax.pmax(tok0, "pp")

        lengths = jnp.full((M,), plen, jnp.int32)
        out = jnp.zeros((M, b, N), jnp.int32)
        out = jnp.where(is_last, out.at[:, :, 0].set(tok0), out)

        # ---- decode: S - 1 + (N - 1) * M ring steps ---------------------
        def dec_step(carry, g):
            recv_h, recv_tok, tok_buf, K, V, lengths, out = carry
            m = jnp.mod(g - s, M)
            k = (g - s) // M                  # decode pass index
            active = (g >= s) & (k < N - 1)

            # stage 0: fold the token that arrived on the lane into its
            # buffer BEFORE consuming (the lane is one hop behind the tail)
            m_recv = jnp.mod(g - S, M)
            tok_buf = jnp.where(is_first & (g >= S),
                                upd(tok_buf, m_recv, recv_tok, True),
                                tok_buf)

            tok_m = jax.lax.dynamic_index_in_dim(tok_buf, m, 0,
                                                 keepdims=False)
            length = jax.lax.dynamic_index_in_dim(lengths, m, 0,
                                                  keepdims=False)
            pos = jnp.broadcast_to(length, (b, 1))
            x = jnp.where(is_first,
                          _embed(params, cfg, tok_m[:, None]), recv_h)
            kc = jax.lax.dynamic_index_in_dim(K, m, 0, keepdims=False)
            vc = jax.lax.dynamic_index_in_dim(V, m, 0, keepdims=False)
            h, nk, nv = run_local(x, kc, vc, length, pos)
            K = upd(K, m, nk, active)
            V = upd(V, m, nv, active)
            lengths = jnp.where(active, lengths.at[m].set(length + 1),
                                lengths)

            tok_next = tail_sample(h, m, k + 1)
            out = jnp.where(active & is_last,
                            out.at[m, :, jnp.clip(k + 1, 0, N - 1)]
                            .set(tok_next), out)

            send_h = jax.lax.ppermute(h, "pp", ring)
            send_tok = jax.lax.ppermute(tok_next, "pp", ring)
            return (send_h, send_tok, tok_buf, K, V, lengths, out), None

        G = S - 1 + (N - 1) * M
        carry = (jnp.zeros((b, 1, H), dt), jnp.zeros((b,), jnp.int32),
                 tok0, K, V, lengths, out)
        if N > 1:
            (_, _, _, _, _, _, out), _ = jax.lax.scan(
                dec_step, carry, jnp.arange(G))
        # only the last rank holds real tokens; share them
        out = jax.lax.psum(jnp.where(is_last, out, 0), "pp")
        return out

    def fn(params, ids_mb, rng):
        sharded = shard_map(
            body, mesh=mesh,
            in_specs=(_pp_in_specs(params, cfg, use_tp), P(), P()),
            out_specs=P(),
            check_vma=False)
        return sharded(params, ids_mb, rng)

    return jax.jit(fn)


def make_pipeline_train_step(cfg: ModelConfig, mesh: Mesh, optimizer,
                             num_microbatches: int):
    """Build a jitted data+pipeline+tensor-parallel training step.

    Returns ``train_step(params, opt_state, ids, targets) ->
    (params, opt_state, loss)`` where ids/targets are
    ``[batch, seq]`` int32 on host; batch must divide by dp*num_microbatches.
    """
    use_tp = mesh.shape.get("tp", 1) > 1
    use_dp = mesh.shape.get("dp", 1) > 1
    axis_names = set(mesh.axis_names)
    assert {"dp", "pp", "tp"} <= axis_names, mesh.axis_names

    def build(params_template):
        in_specs_params = _pp_in_specs(params_template, cfg, use_tp)
        sync_axes = _grad_sync_axes(params_template, cfg, use_tp)

        # Derivation of the 1/(pp*tp) normalization.  The loss is made
        # replicated by forward psums (over pp at the loss reduction; over
        # tp inside every row-parallel matmul), and under check_vma=False
        # jax transposes psum to psum — which is exactly the semantics
        # "every device backpropagates its own replicated copy of the
        # loss".  The resulting raw gradient for ANY leaf (after
        # _grad_sync_axes folds in the replicated-copy grads) is therefore
        #     sum over the pp*tp devices of d(loss copy)/d(leaf)
        #       = pp * tp * d(loss)/d(leaf),
        # uniform across leaves because each device's loss copy is the
        # same full-model function of every leaf (the pipeline threads all
        # stages through each device's program).  Verified leaf-by-leaf by
        # tools/grad_scale_probe.py for pp/tp in {1,2,4}x{1,2,4} (property
        # test: tests/test_parallel.py::test_grad_scaling_rule_at_4x4).
        # Normalize once here so optimizers that are not scale-invariant
        # (sgd, clipping, weight decay) are correct.
        grad_norm = 1.0 / (mesh.shape.get("pp", 1) * mesh.shape.get("tp", 1))

        def sm_loss_and_grads(params_local, ids_mb, targets_mb):
            def loss_fn(p):
                return pipeline_apply(cfg, p, ids_mb, targets_mb,
                                      "tp" if use_tp else None)
            loss, grads = jax.value_and_grad(loss_fn)(params_local)
            grads = jax.tree.map(
                lambda g, axes: jax.lax.psum(g, axes) if axes else g,
                grads, sync_axes)
            grads = jax.tree.map(lambda g: g * grad_norm, grads)
            if use_dp:
                loss = jax.lax.pmean(loss, "dp")
                grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
            return loss, grads

        data_spec = P(None, "dp")  # [M, batch, seq]: batch over dp
        sharded = shard_map(
            sm_loss_and_grads, mesh=mesh,
            in_specs=(in_specs_params, data_spec, data_spec),
            out_specs=(P(), in_specs_params),
            check_vma=False)
        return sharded

    def train_step(params, opt_state, ids, targets):
        M = num_microbatches
        B, s = ids.shape
        ids_mb = ids.reshape(M, B // M, s)
        targets_mb = targets.reshape(M, B // M, s)
        loss, grads = build(params)(params, ids_mb, targets_mb)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        import optax
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(train_step, donate_argnums=(0, 1))
