"""TPU-native distributed LLM inference framework.

A from-scratch re-design of the capabilities of PFC-star/distributed_inference_demo
(LinguaLinked-style heterogeneous pipeline inference) as an idiomatic
JAX/XLA/Pallas/pjit system:

- Models are pure functions over parameter pytrees with stacked per-layer weights,
  so a "module" (a contiguous layer range, cf. reference server.py:893-905) is an
  array slice, not an ONNX export.
- KV-cached autoregressive decoding from day one (the reference re-runs modules on
  a single token per step, Communication.java:322-327 — a known defect).
- Parallelism over a jax.sharding.Mesh with axes (dp, pp, tp, sp): tensor-parallel
  attention/MLP shards, pipeline stages via shard_map + ppermute collectives,
  ring-attention sequence parallelism for long context.
- A schema'd msgpack control plane (device pool, heartbeats, lifecycle FSM,
  partition planner) replacing the reference's order-coupled raw ZMQ frames
  (Client.java:69-82).
- A versioned, endian-explicit tensor wire codec for the heterogeneous
  (CPU/edge <-> TPU host) boundary, replacing utils.cpp:124-264.
"""

__version__ = "0.1.0"
