"""Checkpoint/resume: params + train-state persistence (orbax-backed).

The reference has no checkpointing at all (SURVEY.md §5.4) — its closest
mechanisms are plan caching (``ip_module.json`` reload, ``server.py:805-820``
→ ours: planner.save_plan_cache/load_cached_plan), on-device model caching
(``skip_model_transmission``, ``server.py:1009`` → ours: local checkpoint
dirs), and the live session swap (→ runtime/elastic.py).  This module adds
the missing piece: durable, versioned model/optimizer state.

- :func:`save_params` / :func:`load_params` — one-shot parameter trees with
  a JSON metadata sidecar (model name, config echo, user metadata); loading
  validates the model name and restores onto abstract shapes derived from
  the config, so dtypes/shapes survive exactly.
- :class:`TrainCheckpointManager` — step-versioned {params, opt_state}
  checkpoints with retention (``max_to_keep``), ``latest_step`` discovery
  and crash-resume semantics.

Works for quantized trees too: QuantizedArray is a registered pytree, so
int8 weights round-trip without special cases.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional, Tuple

import jax

from .models.base import ModelConfig, StageParams
from .models.decoder import init_full_params

_META = "framework_meta.json"


def _abstract_params(cfg: ModelConfig, seed: int = 0):
    """Shape/dtype skeleton of a full parameter tree, no materialization.
    Mirrors models.loader.load_or_init: int8 configs get the quantized
    tree structure (QuantizedArray leaves)."""
    from .ops.quant import maybe_quantize
    return jax.eval_shape(lambda: maybe_quantize(
        init_full_params(jax.random.PRNGKey(seed), cfg), cfg))


def save_params(path: str, params: StageParams, cfg: ModelConfig,
                model_name: str, metadata: Optional[dict] = None) -> None:
    """Write a parameter checkpoint + metadata sidecar at ``path``."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(os.path.join(path, "params"), params, force=True)
    meta = {
        "model": model_name,
        "quantization": cfg.quantization,
        "num_layers": cfg.num_layers,
        "hidden_size": cfg.hidden_size,
        "vocab_size": cfg.vocab_size,
        "metadata": metadata or {},
    }
    tmp = os.path.join(path, _META + ".tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=2)
    os.replace(tmp, os.path.join(path, _META))


def load_params(path: str, cfg: ModelConfig,
                model_name: Optional[str] = None
                ) -> Tuple[StageParams, dict]:
    """Restore a parameter checkpoint; validates model identity when
    ``model_name`` is given.  Returns (params, metadata dict)."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    with open(os.path.join(path, _META)) as f:
        meta = json.load(f)
    if model_name is not None and meta["model"] != model_name:
        raise ValueError(
            f"checkpoint at {path} is for model {meta['model']!r}, "
            f"not {model_name!r}")
    for field, want in (("num_layers", cfg.num_layers),
                        ("hidden_size", cfg.hidden_size),
                        ("vocab_size", cfg.vocab_size),
                        ("quantization", cfg.quantization)):
        if meta.get(field) != want:
            raise ValueError(
                f"checkpoint {field}={meta.get(field)!r} does not match "
                f"config {field}={want!r}")
    template = _abstract_params(cfg)
    with ocp.PyTreeCheckpointer() as ckptr:
        params = ckptr.restore(os.path.join(path, "params"), item=template)
    return params, meta


class TrainCheckpointManager:
    """Step-versioned {params, opt_state} checkpoints with retention.

    Usage::

        mgr = TrainCheckpointManager(dir, cfg, optimizer, max_to_keep=3)
        step0, params, opt_state = mgr.restore_or_init(seed=0)  # resume
        ...
        mgr.save(step, params, opt_state)
    """

    def __init__(self, directory: str, cfg: ModelConfig, optimizer: Any,
                 max_to_keep: int = 3):
        import orbax.checkpoint as ocp
        self.cfg = cfg
        self.optimizer = optimizer
        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))

    def save(self, step: int, params: StageParams, opt_state: Any,
             wait: bool = True) -> None:
        import orbax.checkpoint as ocp
        self._mgr.save(step, args=ocp.args.PyTreeSave(
            {"params": params, "opt_state": opt_state}))
        if wait:
            self._mgr.wait_until_finished()

    @property
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return list(self._mgr.all_steps())

    def restore(self, step: Optional[int] = None):
        """Restore (params, opt_state) at ``step`` (default: latest)."""
        import orbax.checkpoint as ocp
        step = step if step is not None else self.latest_step
        if step is None:
            raise FileNotFoundError(
                f"no checkpoints under {self.directory}")
        skel = _abstract_params(self.cfg)
        template = {
            "params": skel,
            "opt_state": jax.eval_shape(self.optimizer.init, skel),
        }
        out = self._mgr.restore(step,
                                args=ocp.args.PyTreeRestore(item=template))
        return out["params"], out["opt_state"]

    def restore_or_init(self, seed: int = 0):
        """Crash-resume entry point: (step, params, opt_state) from the
        latest checkpoint, or step 0 with fresh init when none exists."""
        if self.latest_step is not None:
            params, opt_state = self.restore()
            return self.latest_step, params, opt_state
        from .ops.quant import maybe_quantize
        params = maybe_quantize(
            init_full_params(jax.random.PRNGKey(seed), self.cfg), self.cfg)
        return 0, params, self.optimizer.init(params)

    def close(self) -> None:
        self._mgr.close()
