"""Device measurement probes: memory, compute throughput, latency, bandwidth.

TPU-native re-implementation of the reference's device-side measurements:

- memory: ``MonitorService.kt:333-342`` reads ActivityManager; here
  /proc/meminfo (host) + jax device memory stats (accelerator).
- flops: ``inference.cpp:329-354`` times an ONNX probe module with 2 warmups
  + 1 timed run; here a timed bf16 matmul on the local jax backend — the
  shape that actually exercises the MXU.
- latency: ``MonitorService.kt:280-331`` shells out to ``ping``; here a TCP
  connect round-trip (no ICMP privileges needed, measures the same path the
  data plane uses).
- bandwidth: ``MonitorService.kt:398-507`` floods a peer's TCP :55555 for
  0.5 s while the peer counts bytes/ms; here the same flood protocol on an
  ephemeral port with an explicit handshake.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, Optional


def memory_info() -> Dict[str, int]:
    """Total/available host memory in bytes (reference TotalMem/AvailMem)."""
    total = avail = 0
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
    except OSError:  # non-Linux fallback
        pass
    return {"total": total, "available": avail}


def flops_probe(size: int = 2048, warmups: int = 2,
                dtype: str = "bfloat16") -> float:
    """Measured FLOPs/sec of a ``size x size`` matmul on the default jax
    backend (2 warmups + 1 timed run, mirroring ``inference.cpp:329-354``)."""
    import jax
    import jax.numpy as jnp

    x = jnp.ones((size, size), jnp.dtype(dtype))
    f = jax.jit(lambda a: a @ a)
    for _ in range(warmups):
        f(x).block_until_ready()
    t0 = time.perf_counter()
    f(x).block_until_ready()
    dt = time.perf_counter() - t0
    return (2.0 * size ** 3) / max(dt, 1e-9)


def tcp_latency_probe(host: str, port: int, attempts: int = 3,
                      timeout: float = 2.0) -> Optional[float]:
    """Average TCP connect RTT in seconds over ``attempts`` tries (the
    reference averages 3 pings, ``MonitorService.kt:291-331``).  None when
    the peer is unreachable."""
    samples = []
    for _ in range(attempts):
        t0 = time.perf_counter()
        try:
            with socket.create_connection((host, port), timeout=timeout):
                samples.append(time.perf_counter() - t0)
        except OSError:
            continue
    return sum(samples) / len(samples) if samples else None


class BandwidthServer:
    """Receiver side of the bandwidth probe: accepts a flood, counts bytes,
    reports bytes/sec back on the same connection
    (``MonitorService.kt:441-507`` with the measurement returned in-band
    instead of out-of-band)."""

    def __init__(self, bind_host: str = "127.0.0.1", port: int = 0):
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((bind_host, port))
        self._srv.listen(4)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._srv.settimeout(0.2)

        def loop():
            while not self._stop.is_set():
                try:
                    conn, _ = self._srv.accept()
                except socket.timeout:
                    continue
                threading.Thread(target=self._serve_one, args=(conn,),
                                 daemon=True).start()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=f"bw-server-{self.port}")
        self._thread.start()

    def _serve_one(self, conn: socket.socket) -> None:
        # End-of-flood is the client's TCP half-close (shutdown(SHUT_WR)) —
        # an in-band sentinel could be split across recv() boundaries.
        with conn:
            conn.settimeout(5.0)
            total = 0
            t0 = None
            try:
                while True:
                    chunk = conn.recv(1 << 16)
                    if t0 is None:
                        t0 = time.perf_counter()
                    if not chunk:        # EOF: client half-closed
                        break
                    total += len(chunk)
                dt = max(time.perf_counter() - (t0 or 0.0), 1e-9)
                conn.sendall(f"{total / dt:.1f}".encode())
            except OSError:
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        self._srv.close()


def bandwidth_probe(host: str, port: int, duration: float = 0.5,
                    timeout: float = 5.0) -> Optional[float]:
    """Flood ``host:port`` for ``duration`` seconds; return measured
    bytes/sec as counted by the receiver (``MonitorService.kt:398-439``)."""
    payload = b"\xab" * (1 << 16)
    try:
        with socket.create_connection((host, port), timeout=timeout) as s:
            s.settimeout(timeout)
            deadline = time.perf_counter() + duration
            while time.perf_counter() < deadline:
                s.sendall(payload)
            s.shutdown(socket.SHUT_WR)   # signal end-of-flood via half-close
            reply = s.recv(64)
            return float(reply.decode())
    except (OSError, ValueError):
        return None
