"""Server side of the monitor round: ROUTER service + report aggregation.

Re-implements the missing ``SecureConnection.monitor.Monitor`` (inferred at
``server.py:849-858``, SURVEY.md §2.2): has ``start()``, an
``is_monitor_ready`` event, and ``get_monitor_info()`` returning per-device
measurements; pushes the peer graph to devices on handshake and tells them
to stop once every expected device has reported (the reference sends
periodic "signal"/"stop" strings, ``MonitorService.kt:186-205``).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import zmq

from ..control.messages import Envelope, MsgType, make
from ..control.router import RouterService
from ..planner.planner import DeviceProfile

DEFAULT_BANDWIDTH = 1e9       # bytes/sec, assumed when a pair wasn't probed
DEFAULT_LATENCY = 1e-3        # seconds


class MonitorAggregator:
    """Collects per-device reports; ready once all expected devices report."""

    def __init__(self, expected: List[str]):
        self.expected = list(expected)
        self.reports: Dict[str, dict] = {}
        self._lock = threading.Lock()
        self.is_monitor_ready = threading.Event()

    def add_report(self, device_id: str, report: dict) -> None:
        with self._lock:
            self.reports[device_id] = report
            if all(d in self.reports for d in self.expected):
                self.is_monitor_ready.set()

    def get_monitor_info(self) -> Dict[str, dict]:
        with self._lock:
            return dict(self.reports)

    def device_profiles(self, addresses: Dict[str, str],
                        ring_order: Optional[List[str]] = None
                        ) -> List[DeviceProfile]:
        """Fold reports into planner inputs (the ``server.py:858`` tuple).

        ``addresses``: device_id -> data-plane address.  ``ring_order``
        fixes the chain order (defaults to ``expected`` order); each
        device's egress bandwidth/latency is its measurement toward the
        NEXT device in the ring."""
        order = ring_order or self.expected
        info = self.get_monitor_info()
        profiles = []
        for i, dev_id in enumerate(order):
            rep = info.get(dev_id, {})
            nxt = order[(i + 1) % len(order)]
            bw = (rep.get("bandwidth") or {}).get(nxt, DEFAULT_BANDWIDTH)
            lat = (rep.get("latency") or {}).get(nxt, DEFAULT_LATENCY)
            mem = rep.get("memory") or {}
            profiles.append(DeviceProfile(
                device_id=dev_id,
                address=addresses.get(dev_id, ""),
                flops_per_sec=rep.get("flops") or 1e12,
                memory_bytes=int(mem.get("available")
                                 or mem.get("total") or (16 << 30)),
                platform=rep.get("platform", "cpu"),
                chips=int(rep.get("chips", 1)),
                egress_bandwidth=bw or DEFAULT_BANDWIDTH,
                egress_latency=lat if lat is not None else DEFAULT_LATENCY,
            ))
        return profiles


class MonitorService(RouterService):
    """ROUTER endpoint the agents talk to (reference port 34567 role)."""

    name = "monitor"

    def __init__(self, aggregator: MonitorAggregator,
                 bind_host: str = "127.0.0.1", port: int = 0,
                 min_rounds: int = 1,
                 ctx: Optional[zmq.Context] = None):
        super().__init__(bind_host=bind_host, port=port, ctx=ctx)
        self.agg = aggregator
        self.min_rounds = min_rounds
        # device_id -> {host, bw_port} gathered from hellos
        self._peers: Dict[str, dict] = {}
        self._rounds: Dict[str, int] = {}
        self._lock = threading.Lock()

    def _peer_graph(self, dev_id: str) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._peers.items()
                    if k != dev_id}

    def handle(self, dev_id: str, msg: Envelope) -> List[bytes]:
        if msg.type == MsgType.MONITOR_HELLO:
            with self._lock:
                self._peers[dev_id] = {
                    "host": msg.get("host", "127.0.0.1"),
                    "bw_port": msg.get("bw_port", 0),
                }
            return [make(MsgType.MONITOR_GRAPH,
                         peers=self._peer_graph(dev_id))]
        if msg.type == MsgType.MONITOR_REPORT:
            self.agg.add_report(dev_id, msg.get("report", {}))
            with self._lock:
                self._rounds[dev_id] = self._rounds.get(dev_id, 0) + 1
                done = (self.agg.is_monitor_ready.is_set()
                        and self._rounds[dev_id] >= self.min_rounds)
            if done:
                return [make(MsgType.MONITOR_STOP)]
            # refresh the peer graph with anyone who joined since
            return [make(MsgType.MONITOR_GRAPH,
                         peers=self._peer_graph(dev_id))]
        return [make(MsgType.ERROR, reason=f"unexpected {msg.type.value}")]
