"""Monitor/profiler subsystem: device probes feeding the partition planner.

Re-design of the reference's profiling round (``service/MonitorService.kt``
device agent + the missing server-side ``SecureConnection.monitor.Monitor``
aggregator, SURVEY.md §3.4/§2.2): each device measures peer latency, p2p
bandwidth, memory, and compute throughput, and uploads a structured report;
the server aggregates reports into the planner's DeviceProfile inputs
(the ``(ping_latency, bandwidths, TotalMem, AvailMem, flop_speed)`` tuple,
``server.py:858``).
"""

from .probes import (flops_probe, memory_info, tcp_latency_probe,
                     BandwidthServer, bandwidth_probe)
from .agent import MonitorAgent
from .aggregator import MonitorAggregator, MonitorService

__all__ = [
    "flops_probe", "memory_info", "tcp_latency_probe",
    "BandwidthServer", "bandwidth_probe",
    "MonitorAgent", "MonitorAggregator", "MonitorService",
]
