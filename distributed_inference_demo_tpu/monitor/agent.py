"""Device-side monitor agent: the measurement loop.

Mirror of the reference's ``MonitorService.kt`` thread (``:149-225``):
DEALER handshake with the server, receive the peer graph, measure
latency / bandwidth / memory / flops each round, upload a structured
report, loop until the server says stop.  Differences: probes are the
TPU-host versions (probes.py), the report schema is typed msgpack, and the
loop polls with timeouts instead of busy-waiting
(``MonitorService.kt:208-211``, defect #5).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

import zmq

from ..control.messages import MsgType, decode, make
from .probes import (BandwidthServer, bandwidth_probe, flops_probe,
                     memory_info, tcp_latency_probe)

log = logging.getLogger(__name__)


class MonitorAgent:
    """Runs the measurement loop against a MonitorService."""

    def __init__(self, server_address: str, device_id: str,
                 host: str = "127.0.0.1",
                 platform: str = "cpu", chips: int = 1,
                 measure_flops: bool = True,
                 bandwidth_duration: float = 0.1,
                 timeout_ms: int = 5000,
                 ctx: Optional[zmq.Context] = None):
        self._ctx = ctx or zmq.Context.instance()
        self.device_id = device_id
        self.host = host
        self.platform = platform
        self.chips = chips
        self.measure_flops = measure_flops
        self.bandwidth_duration = bandwidth_duration
        self._sock = self._ctx.socket(zmq.DEALER)
        self._sock.setsockopt(zmq.IDENTITY, device_id.encode())
        self._sock.setsockopt(zmq.RCVTIMEO, timeout_ms)
        self._sock.setsockopt(zmq.SNDTIMEO, timeout_ms)
        self._sock.setsockopt(zmq.LINGER, 0)
        self._sock.connect(f"tcp://{server_address}")
        self.bw_server = BandwidthServer(bind_host=host)
        self._flops_cache: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- measurements ------------------------------------------------------

    def measure_round(self, peers: Dict[str, dict]) -> dict:
        """One measurement round against the given peer graph."""
        latency, bandwidth = {}, {}
        for peer_id, info in peers.items():
            host, port = info.get("host"), info.get("bw_port")
            if not host or not port:
                continue
            lat = tcp_latency_probe(host, port)
            if lat is not None:
                latency[peer_id] = lat
            bw = bandwidth_probe(host, port,
                                 duration=self.bandwidth_duration)
            if bw is not None:
                bandwidth[peer_id] = bw
        if self.measure_flops and self._flops_cache is None:
            # measured once; hardware speed doesn't change between rounds
            self._flops_cache = flops_probe()
        report = {
            "latency": latency,
            "bandwidth": bandwidth,
            "memory": memory_info(),
            "flops": self._flops_cache,
            "platform": self.platform,
            "chips": self.chips,
        }
        # mirror the round into this process's /metrics gauges
        # (dwt_monitor_peer_* — the planner's inputs, scrapeable live)
        from ..telemetry.catalog import record_monitor_round
        record_monitor_round(report)
        return report

    # -- protocol loop -----------------------------------------------------

    def run(self, max_rounds: int = 100) -> int:
        """Hello → (measure → report)* → stop.  Returns rounds completed."""
        self.bw_server.start()
        try:
            self._sock.send(make(MsgType.MONITOR_HELLO,
                                 device_id=self.device_id, host=self.host,
                                 bw_port=self.bw_server.port))
            msg = decode(self._sock.recv())
            if msg.type != MsgType.MONITOR_GRAPH:
                raise RuntimeError(
                    f"expected MONITOR_GRAPH, got {msg.type.value}")
            peers = msg.get("peers", {})
            rounds = 0
            while rounds < max_rounds and not self._stop.is_set():
                report = self.measure_round(peers)
                self._sock.send(make(MsgType.MONITOR_REPORT,
                                     device_id=self.device_id,
                                     report=report))
                msg = decode(self._sock.recv())
                rounds += 1
                if msg.type == MsgType.MONITOR_STOP:
                    break
                if msg.type == MsgType.MONITOR_GRAPH:
                    peers = msg.get("peers", peers)
            return rounds
        finally:
            self.bw_server.stop()

    def run_async(self, max_rounds: int = 100) -> threading.Thread:
        self._thread = threading.Thread(
            target=self.run, kwargs={"max_rounds": max_rounds}, daemon=True,
            name=f"monitor-agent-{self.device_id}")
        self._thread.start()
        return self._thread

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._sock.close(linger=0)
